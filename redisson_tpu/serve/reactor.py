"""Reactor front door (ISSUE 11 tentpole) — the Netty-analog rewrite of
the RESP serving layer (PAPER.md L0 transport, ROADMAP next-direction 2).

Thread-per-connection serving (serve/resp.py:_serve_conn) costs one
thread per client and gives an unpipelined client a private
wakeup→parse→dispatch round trip per command.  This module replaces it
with a small FIXED pool of reactor threads built on ``selectors``
(epoll on Linux):

* each reactor tick drains recv buffers across ALL ready connections,
  frames commands incrementally (``_StreamFramer``: the non-blocking
  analog of resp._Reader, native C parser first, pure-Python fallback),
  and feeds ONE merged parse→vectorize→dispatch pass
  (``RespServer._dispatch_merged``) — adjacent same-(object, family)
  ops from DIFFERENT connections fuse into single engine launches, so
  single-command clients get batch economics because the aggregate
  front door is always pipelined;
* per-connection ordering is preserved exactly: a connection's commands
  enter the merged window in arrival order, replies are demuxed back to
  their connection in that order, and a connection whose head command
  was handed off to a worker is frozen until the worker completes;
* writes go through per-connection non-blocking send buffers flushed on
  EPOLLOUT, with the ISSUE 7 slow-client output limits enforced against
  the buffered backlog (hard byte bound after its grace, no-progress
  stall bound, idle-timeout fallback — the same policy
  _ConnCtx._send_bounded applies on the thread path);
* commands that may legitimately block (BLPOP, blocking XREAD, pub/sub
  registration, scripts, WAIT, SAVE, DEBUG) are handed off to a
  dedicated worker thread so one parked client can never stall the
  event loop — the worker-thread population tracks the number of
  BLOCKED clients, not the number of connected ones.

10k mostly-idle connections therefore cost file descriptors instead of
threads, and the thread count is fixed at ``resp_reactor_threads`` (+
one worker per currently-blocked client).  ``resp_reactor=False``
restores the legacy accept loop for differential testing; per-connection
reply streams are byte-identical either way (tests/test_reactor.py).
"""

from __future__ import annotations

import selectors
import socket
import threading
import time
import traceback
from collections import deque

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.serve.resp import (
    ProtocolError,
    _ConnCtx,
    _PIPELINE_STOP,
    _encode_error,
)

# Commands the reactor hands off to a dedicated worker thread instead of
# dispatching inline on the event loop: anything that may park (blocking
# pops/reads), writes push frames itself (pub/sub registration), runs
# arbitrary code (scripts — SCRIPT also rides a worker so SCRIPT KILL
# stays dispatchable while a runaway script owns another worker), or
# performs heavy I/O (WAIT's fsync fence, SAVE's snapshot, DEBUG SLEEP).
# The connection is frozen while its worker runs, so per-connection
# ordering is untouched.
_DETACH = frozenset(_PIPELINE_STOP) | frozenset((
    b"EVAL", b"EVALSHA", b"SCRIPT", b"FCALL", b"FCALL_RO", b"FUNCTION",
    b"WAIT", b"SAVE", b"BGREWRITEAOF", b"DEBUG", b"EXEC",
    # Cluster control plane (ISSUE 12): MIGRATE blocks on a cross-node
    # RESTORE round trip under the move guard — inline it would freeze
    # the whole front door per migrated key (and two nodes migrating
    # toward each other would stall each other's loops); CLUSTER's
    # GETKEYSINSLOT/COUNTKEYSINSLOT scan the full keyspace.
    b"MIGRATE", b"CLUSTER",
    # Replication stream (ISSUE 18): REPLFETCH long-polls (parks up to
    # its timeout-ms when the replica is caught up) and PSYNC's
    # FULLRESYNC branch takes a whole snapshot — both would freeze the
    # event loop inline.
    b"RTPU.PSYNC", b"RTPU.REPLFETCH",
))

# Per-tick bounds: commands taken from one connection, commands in one
# merged window, and the per-connection reply backlog above which the
# reactor stops consuming that connection's commands (TCP backpressure —
# the analog of the thread path's blocking sendall).
_MAX_PER_CONN = 1024
_MAX_PER_TICK = 4096
_OUTBUF_HWM = 4 << 20
_PENDING_HWM = 4096
_TICK_S = 0.1
_SWEEP_S = 1.0
# Gather window: when several connections are attached, a tick that saw
# new events keeps collecting stragglers in short extra selects before
# dispatching — the front-door analog of the coalescer's flush window
# (closed-loop unpipelined clients answer a reply wave within ~an RTT,
# so a sub-ms wait turns N tiny merged passes into one wide one).
# Gathering stops the moment a gather select comes back empty, so the
# total wait tracks the actual straggler stream instead of a fixed
# penalty.  Skipped when the reactor serves ≤2 connections: a lone
# pipelined client should not pay the window on every batch.
_GATHER_S = 0.0003
_GATHER_MAX = 1

# Fusable-family classification (pure-Python mirror of the native
# classifier in resp_codec.c:rtpu_classify — the two MUST agree, or a
# connection's chunking would depend on which parser framed it).
_FAM_BF = frozenset((b"BF.ADD", b"BF.MADD", b"BF.EXISTS", b"BF.MEXISTS"))


def _family_code(cmd) -> int:
    """Family class of one command (0 = non-fusable)."""
    if not cmd:
        return 0
    name = cmd[0].upper()
    if name in _FAM_BF:
        return 1
    if name in (b"SETBIT", b"GETBIT"):
        return 2
    if name in (b"GET", b"MGET"):
        return 3
    if name == b"CMS.QUERY":
        return 4
    return 0


class _StreamFramer:
    """Incremental RESP request framer over a growing byte buffer — the
    non-blocking analog of ``resp._Reader`` (which recv()s inline).
    ``feed()`` bytes as they arrive, ``pop_into()`` every complete
    command; raises ProtocolError on malformed frames (the caller
    replies once and closes, Redis-style)."""

    def __init__(self):
        from redisson_tpu.serve import native_codec

        self._native = native_codec.get_parser()
        self._parse_ok = native_codec.PARSE_OK
        self._buf = b""
        # Chunks accumulate per recv and join ONCE per parse attempt:
        # `bytes +=` per 64 KB recv would copy the whole accumulated
        # buffer every time — quadratic for a multi-MB frame growing
        # across ticks.
        self._chunks: list = []

    @property
    def buffered(self) -> int:
        return len(self._buf) + sum(len(c) for c in self._chunks)

    def at_frame_boundary(self) -> bool:
        return not self._buf and not self._chunks

    def feed(self, data: bytes) -> None:
        self._chunks.append(data)

    def pop_into(self, out: deque) -> None:
        if self._chunks:
            self._buf += b"".join(self._chunks)
            self._chunks.clear()
        while self._buf:
            if self._native is not None:
                frames, consumed, err = self._native.parse(self._buf)
                if frames:
                    self._buf = self._buf[consumed:]
                    out.extend(frames)
                    continue
                if err == self._parse_ok:
                    return  # incomplete frame: wait for more bytes
                # Inline command or malformed frame: the pure-Python
                # path below reproduces the blocking reader's behavior.
            cmd = self._parse_py_one()
            if cmd is None:
                return
            out.append(cmd)

    def _parse_py_one(self):
        """Parse ONE command from the front of the buffer; None when the
        bytes there are still incomplete."""
        buf = self._buf
        nl = buf.find(b"\r\n")
        if nl < 0:
            return None
        line = buf[:nl]
        if not line.startswith(b"*"):
            # Inline command (redis-cli fallback); a blank line parses
            # to [] which the dispatch loop skips with no reply.
            self._buf = buf[nl + 2:]
            return line.split()
        try:
            n = int(line[1:])
        except ValueError:
            raise ProtocolError("invalid multibulk length")
        if n < 0:
            raise ProtocolError("invalid multibulk length")
        pos = nl + 2
        args = []
        for _ in range(n):
            nl2 = buf.find(b"\r\n", pos)
            if nl2 < 0:
                return None
            hdr = buf[pos:nl2]
            if not hdr.startswith(b"$"):
                raise ProtocolError("invalid bulk length")
            try:
                size = int(hdr[1:])
            except ValueError:
                raise ProtocolError("invalid bulk length")
            if size < 0:
                raise ProtocolError("invalid bulk length")
            pos = nl2 + 2
            if len(buf) < pos + size + 2:
                return None
            args.append(buf[pos:pos + size])
            pos += size + 2
        self._buf = buf[pos:]
        return args


class _ReactorCtx(_ConnCtx):
    """Loop-drivable connection ctx: ``send`` enqueues into the
    reactor-managed output buffer instead of blocking on the socket, so
    pub/sub pushes and detached-worker replies from ANY thread land in
    the connection's ordered backlog and the event loop flushes them."""

    def __init__(self, sock, server, rconn):
        super().__init__(sock, server=server)
        self._rconn = rconn

    def send(self, frame: bytes) -> None:
        self._rconn.enqueue(frame)


class _RConn:
    """Per-connection reactor state."""

    def __init__(
        self,
        sock: socket.socket,
        server,
        reactor: "_Reactor",
        peer: bool = False,
    ):
        self.sock = sock
        self.fd = sock.fileno()
        self.reactor = reactor
        self.peer = peer  # in-node handoff leg from a sibling worker
        # Native tick path: the per-connection leftover buffer for
        # rtpu_resp_tick (drain+frame+classify in one native call).  The
        # slow-path framer is built lazily, only when this connection
        # falls off the native path (inline commands, proto errors) or
        # the ticker is unavailable.
        ticker = getattr(reactor, "ticker", None)
        self.tickbuf = ticker.new_buf() if ticker is not None else None
        self.framer = None if self.tickbuf is not None else _StreamFramer()
        self.pending: deque = deque()  # (family, argv) not-yet-dispatched
        # Guards outbuf + progress stamps: enqueue() runs cross-thread
        # (pub/sub pushes, detached workers), flush on the reactor.
        self.wlock = _witness.named(
            threading.Lock(), "resp.reactor.outbuf"
        )
        self.outbuf = bytearray()
        self.backlog_t0 = 0.0  # when outbuf last went empty -> non-empty
        self.last_progress = 0.0
        self.last_activity = time.monotonic()
        self.busy = False  # a detached worker owns the head command
        self.closing = False
        self.closed = False  # teardown completed (idempotence guard)
        self.eof = False  # peer closed its write side
        self.read_paused = False
        self.want_write = False
        self.registered = False
        self.cur_mask = 0  # interest set currently in the selector
        self.ctx = _ReactorCtx(sock, server, self)
        if peer:
            # Sibling-worker legs are pre-trusted (same process tree,
            # unix socket under the node's private rundir) and carry
            # already-authed client traffic.
            self.ctx.is_peer = True
            self.ctx.authed = True

    def at_frame_boundary(self) -> bool:
        if self.tickbuf is not None and self.tickbuf.have:
            return False
        return self.framer is None or self.framer.at_frame_boundary()

    def enqueue(self, frame: bytes) -> None:
        """Append a reply/push frame to the ordered output backlog
        (thread-safe) and wake the event loop to flush it."""
        if not frame:
            return
        with self.wlock:
            if self.closing:
                return
            if not self.outbuf:
                now = time.monotonic()
                self.backlog_t0 = now
                self.last_progress = now
            self.outbuf += frame
            self.want_write = True
        # Flag for the loop's flush sweep (a SET, not an every-conn
        # scan — 5k idle connections must not be walked per tick), then
        # wake it — unless we ARE the loop, which flushes its own
        # enqueues at the end of the pass (a self-directed wakeup would
        # just burn a pipe syscall per frame).
        r = self.reactor
        r.want_flush.add(self)
        if threading.get_ident() != r.tid:
            r.wake()


class _Reactor(threading.Thread):
    """One event-loop thread: a selector over its share of the
    connections, a self-pipe for cross-thread wakeups, and the merged
    dispatch pass."""

    # Dispatch-pass sequence number (ISSUE 13): traced commands
    # annotate which tick carried them, correlating a trace with the
    # cross-connection fusion window it rode.  CLASS attribute (the
    # journal `_rotate_req` idiom) so model-check harnesses that drive
    # _run_pass without __init__ still read 0.
    tick_seq = 0

    def __init__(self, server, idx: int):
        super().__init__(name=f"rtpu-resp-reactor-{idx}", daemon=True)
        self.server = server
        from redisson_tpu.serve import native_codec

        # One native ticker per reactor thread (its descriptor arrays
        # are single-threaded scratch); None degrades every connection
        # to the Python framer path.
        self.ticker = native_codec.get_ticker()
        self.sel = selectors.DefaultSelector()
        self.conns: dict = {}  # fd -> _RConn
        self._new: deque = deque()  # sockets awaiting registration
        self._stopping = False
        self.tid: int = 0  # run()'s thread id (self-wake elision)
        # Connections that may have dispatchable work (framed commands
        # pending, or a worker just un-froze them): the pass iterates
        # THIS set, not every connection — 5k idle connections must not
        # cost 5k eligibility checks per tick.  GIL-atomic set ops;
        # workers add cross-thread.
        self._attention: set = set()
        # Connections with unflushed enqueues (same discipline: a set
        # fed by enqueue(), drained by the loop — never a full scan).
        self.want_flush: set = set()
        self._last_sweep = time.monotonic()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ, None)

    # -- cross-thread surface ------------------------------------------------

    def add_conn(self, sock: socket.socket, peer: bool = False) -> None:
        self._new.append((sock, peer))
        self.wake()

    def wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        # rtpulint: disable=RT013 self-pipe wake channel: no replies ever ride it, and a full pipe already guarantees a pending wakeup — there is nothing to desync or drop
        except (BlockingIOError, OSError):
            pass  # pipe already full: a wakeup is pending anyway

    def stop(self) -> None:
        self._stopping = True
        self.wake()

    # -- event loop ----------------------------------------------------------

    def run(self) -> None:
        self.tid = threading.get_ident()
        while not self._stopping:
            try:
                self._tick()
            except Exception:  # pragma: no cover - defensive
                # A bug in the loop must not silently kill every
                # connection on this reactor; report and keep serving.
                traceback.print_exc()
                time.sleep(0.01)
        # Reactor retired: release selector resources.  Connections are
        # closed by the server's drain (close()) before stop() runs.
        try:
            self.sel.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()

    def _tick(self) -> None:
        timeout = 0.0 if self._work_ready() else _TICK_S
        events = self.sel.select(timeout)
        gathers = _GATHER_MAX if events and len(self.conns) > 2 else 0
        while True:
            now = time.monotonic()
            for key, mask in events:
                rconn = key.data
                if rconn is None:
                    self._drain_wake()
                    continue
                if rconn.closing:
                    self._close_conn(rconn)  # async close: finish it
                    continue
                if mask & selectors.EVENT_WRITE:
                    self._flush(rconn)
                if mask & selectors.EVENT_READ:
                    self._read_ready(rconn, now)
            if gathers <= 0:
                break
            gathers -= 1
            events = self.sel.select(_GATHER_S)
            if not events:
                break
        self._admit_new()
        self._apply_write_interest()
        self._run_pass(now)
        if now - self._last_sweep >= self._sweep_interval():
            self._last_sweep = now
            self._sweep(now)

    def _sweep_interval(self) -> float:
        """Sweep cadence tracks the tightest armed gate (a 0.3 s idle
        timeout must not wait for a 1 s sweep); defaults coarse so 5k
        idle connections aren't rescanned every tick."""
        srv = self.server
        interval = _SWEEP_S
        idle_s = srv.idle_timeout_s or 0.0
        if idle_s:
            interval = min(interval, idle_s / 4.0)
        soft_s = getattr(srv, "output_buffer_soft_seconds", 0.0) or 0.0
        if soft_s:
            interval = min(interval, soft_s / 4.0)
        if getattr(srv, "output_buffer_limit", 0):
            interval = min(interval, 0.25)  # hard-grace is ~1 s
        return max(0.05, interval)

    def _work_ready(self) -> bool:
        """Leftover dispatchable work (requeued tails, worker-released
        queues): the next tick must not sleep on select."""
        # tuple() snapshots the set in one C call (GIL-atomic): workers
        # add() concurrently, and a Python-level iteration racing that
        # add would raise "set changed size during iteration".
        for c in tuple(self._attention):
            if (
                c.pending and not c.busy and not c.closing
                and len(c.outbuf) < _OUTBUF_HWM
            ):
                return True
        return False

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        # rtpulint: disable=RT013 self-pipe wake channel: drained opportunistically, carries no reply stream — a failed drain cannot desync anything
        except (BlockingIOError, OSError):
            pass

    def _admit_new(self) -> None:
        while self._new:
            sock, peer = self._new.popleft()
            try:
                sock.setblocking(False)
                rconn = _RConn(sock, self.server, self, peer=peer)
            except OSError:
                self._teardown_slot(sock)
                continue
            if self.server._requirepass and not peer:
                rconn.ctx.authed = False
            try:
                self.sel.register(sock, selectors.EVENT_READ, rconn)
            except (OSError, ValueError):
                self._teardown_slot(sock)
                continue
            rconn.registered = True
            rconn.cur_mask = selectors.EVENT_READ
            self.conns[rconn.fd] = rconn

    def _read_ready(self, rconn: _RConn, now: float) -> None:
        if rconn.tickbuf is not None:
            self._read_ready_native(rconn, now)
            return
        got = False
        eof = False
        budget = 1 << 20
        try:
            # Drain the socket, bounded PER TICK so one firehose client
            # cannot starve the pass — the framer buffer itself may
            # grow past the budget across ticks (a single 4 MB SET's
            # frame must be able to accumulate; level-triggered select
            # re-fires until the socket is dry).
            while budget > 0:
                data = rconn.sock.recv(1 << 16)
                if not data:
                    eof = True
                    break
                got = True
                budget -= len(data)
                rconn.framer.feed(data)
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            eof = True
        if got:
            rconn.last_activity = now
            if not self._pop_framed(rconn):
                return
            if rconn.pending:
                self._attention.add(rconn)
            if len(rconn.pending) > _PENDING_HWM and not rconn.read_paused:
                rconn.read_paused = True
                self._update_mask(rconn)
        if eof:
            # Peer closed its write side.  Parity with the thread path:
            # commands ALREADY framed still execute and their replies
            # flush (a pipelining client may legitimately half-close
            # after its last request); the connection closes once its
            # queue and backlog drain (_maybe_close_eof).
            rconn.eof = True
            rconn.read_paused = True
            self._update_mask(rconn)
            self._maybe_close_eof(rconn)

    def _pop_framed(self, rconn: _RConn) -> bool:
        """Pop slow-path framer output into pending as (family, argv)
        tuples.  False when the stream desynced and the conn closed."""
        tmp: deque = deque()
        try:
            rconn.framer.pop_into(tmp)
        except ProtocolError as e:
            # Desynced stream: reply once, then close (Redis-style;
            # mirrors _serve_conn's ProtocolError arm).
            rconn.enqueue(_encode_error(f"Protocol error: {e}"))
            self._flush(rconn)
            self._close_conn(rconn)
            return False
        for cmd in tmp:
            rconn.pending.append((_family_code(cmd), cmd))
        return True

    def _read_ready_native(self, rconn: _RConn, now: float) -> None:
        """Native per-tick hot loop: one rtpu_resp_tick call drains the
        fd, frames every complete command, and classifies its family —
        Python sees only the parsed (family, argv) stream."""
        from redisson_tpu.serve import native_codec

        got, eof, err = self.ticker.tick(
            rconn.fd, rconn.tickbuf, rconn.pending
        )
        if got:
            rconn.last_activity = now
        if err != native_codec.PARSE_OK:
            # Inline command, oversized frame, or malformed bytes:
            # retire the native path for this connection and let the
            # slow-path framer reproduce the blocking reader's behavior
            # (including the precise protocol-error message).
            rconn.framer = _StreamFramer()
            rconn.framer.feed(rconn.tickbuf.take())
            rconn.tickbuf = None
            if not self._pop_framed(rconn):
                return
        if rconn.pending:
            self._attention.add(rconn)
            if len(rconn.pending) > _PENDING_HWM and not rconn.read_paused:
                rconn.read_paused = True
                self._update_mask(rconn)
        if eof:
            rconn.eof = True
            rconn.read_paused = True
            self._update_mask(rconn)
            self._maybe_close_eof(rconn)

    def _maybe_close_eof(self, rconn: _RConn) -> None:
        if (
            rconn.eof and not rconn.closing and not rconn.busy
            and not rconn.pending and not rconn.outbuf
        ):
            self._close_conn(rconn)

    # -- merged dispatch pass ------------------------------------------------

    def _needs_detach(self, rconn: _RConn, fam: int, cmd) -> bool:
        ctx = rconn.ctx
        if fam == 0:
            name = cmd[0].upper()
            if ctx.in_multi:
                # Queued-under-MULTI commands just queue (fast, inline);
                # only EXEC executes — and may replay scripts — so it
                # rides a worker.  EXEC's replay re-enters _dispatch per
                # member, so the multicore hook still applies to each.
                return name == b"EXEC"
            if name in _DETACH:
                return True
        elif ctx.in_multi:
            return False  # fusable-family member queueing under MULTI
        # Per-core front door (ISSUE 17): a keyed command owned by a
        # sibling worker rides a worker thread too — its in-node handoff
        # leg blocks on the peer's reply, which must never park the
        # event loop.  Peer legs themselves always execute locally.
        mc = getattr(self.server, "multicore", None)
        return (
            mc is not None
            and not ctx.is_peer
            and mc.needs_handoff(cmd)
        )

    @staticmethod
    def _family_key(fam: int, cmd):
        """Grouping key for cross-connection adjacency: commands of one
        fusable family (and target object) sort together inside a
        round, so the vectorizer's adjacency scan sees them as one run.
        Non-fusable commands share a bucket that preserves arrival
        order (the sort is stable)."""
        if fam in (1, 2, 4):
            return (fam, cmd[1] if len(cmd) > 1 else b"")
        return (fam, b"")

    def _run_pass(self, now: float) -> None:
        server = self.server
        per_conn: list = []  # (rconn, [cmds...]) snapshots, conn order
        handoffs: list = []
        total = 0
        for rconn in sorted(tuple(self._attention), key=lambda c: c.fd):
            if rconn.closing or not rconn.pending:
                self._attention.discard(rconn)
                continue
            if rconn.busy:
                continue  # worker re-adds on completion
            if len(rconn.outbuf) >= _OUTBUF_HWM:
                continue  # backpressure: let the peer read first
            taken: list = []
            while (
                rconn.pending and len(taken) < _MAX_PER_CONN
                and total < _MAX_PER_TICK
            ):
                fam, cmd = rconn.pending[0]
                if not cmd:
                    rconn.pending.popleft()  # empty frame: no reply
                    continue
                if self._needs_detach(rconn, fam, cmd):
                    if not taken:
                        handoffs.append(rconn)
                    break
                taken.append(rconn.pending.popleft())
                total += 1
            if taken:
                per_conn.append((rconn, taken))
            if (
                rconn.read_paused
                and len(rconn.pending) < _PENDING_HWM // 2
            ):
                rconn.read_paused = False
                self._update_mask(rconn)
        # Merged-window layout: each connection's snapshot splits into
        # CHUNKS of consecutive same-(family, object) commands (exactly
        # the spans the vectorizer fuses), then rounds of one chunk per
        # connection are stably grouped by family — commands from
        # different connections carry no mutual ordering contract, so
        # grouping their chunks is free, and it is what turns N
        # single-command clients into one fused engine launch (the
        # tentpole's batch economics).  A connection's own commands
        # stay in arrival order: chunks concatenate in order, and a
        # chunk is an order-preserving slice.
        cmds: list = []
        fams: list = []
        ctxs: list = []
        owners: list = []
        chunked: list = []  # (rconn, [[cmds of chunk 0], [chunk 1], ...])
        for rconn, taken in per_conn:
            chunks: list = []
            key = None
            for fam, cmd in taken:
                k = self._family_key(fam, cmd)
                if key is not None and k == key and fam != 0:
                    chunks[-1][1].append((fam, cmd))
                else:
                    chunks.append((k, [(fam, cmd)]))
                    key = k
            chunked.append((rconn, chunks))
        depth = max((len(ch) for _, ch in chunked), default=0)
        for r in range(depth):
            round_items = [
                (rconn, chunks[r])
                for rconn, chunks in chunked
                if r < len(chunks)
            ]
            if len(round_items) > 1:
                round_items.sort(key=lambda it: it[1][0])
            for rconn, (_k, chunk) in round_items:
                for fam, cmd in chunk:
                    cmds.append(cmd)
                    fams.append(fam)
                    ctxs.append(rconn.ctx)
                    owners.append(rconn)
        if cmds:
            self.tick_seq += 1
            obs = server.obs
            if obs is not None:
                obs.reactor_ticks.inc()
                obs.reactor_ready_conns.inc(
                    (), len({id(o) for o in owners})
                )
            try:
                frames, consumed = server._dispatch_merged(cmds, ctxs)
            except Exception:
                # The dispatch pass died outside any per-command guard:
                # protocol position of every involved connection is
                # unknowable — close them (never desync a stream).
                traceback.print_exc()
                for rconn in set(owners):
                    self._close_conn(rconn)
                return
            # Unconsumed tail (reply-buffer bound) back to the FRONT of
            # each owner's queue, in order.
            for k in range(len(cmds) - 1, consumed - 1, -1):
                owners[k].pending.appendleft((fams[k], cmds[k]))
            for k in range(consumed):
                frame = frames[k]
                if frame:
                    owners[k].enqueue(frame)
                owners[k].last_activity = now
            for rconn in {id(o): o for o in owners}.values():
                if not rconn.closing:
                    self._flush(rconn)
        for rconn in handoffs:
            if rconn.busy or rconn.closing or not rconn.pending:
                continue
            _fam, cmd = rconn.pending.popleft()
            rconn.busy = True
            # One thread PER DETACHED COMMAND (not a pool): a pool
            # bounds concurrency, and blocking pops parked in every
            # slot would deadlock against the LPUSH-ing connections
            # waiting behind them.  The spawn (~100 µs) is paid only by
            # the blocking/script/admin command class — a detach-heavy
            # stream is the one shape the thread-per-connection path
            # served better, and it still works here, just not faster.
            threading.Thread(
                target=self._detached, args=(rconn, cmd),
                name="rtpu-resp-detach", daemon=True,
            ).start()
        # Drained connections leave the attention set (it must track
        # ACTIVE conns only — its size is the per-tick cost).
        for rconn, _taken in per_conn:
            if not rconn.pending:
                self._attention.discard(rconn)
        for rconn in handoffs:
            if not rconn.pending:
                self._attention.discard(rconn)

    def _detached(self, rconn: _RConn, cmd) -> None:
        """Worker-thread dispatch of one potentially-blocking command.
        The connection is frozen (busy) until this completes, so its
        ordering is exactly the thread path's."""
        try:
            frame = self.server._safe_dispatch(cmd, rconn.ctx)
            if frame:
                rconn.ctx.send(frame)
        except BaseException:  # _safe_dispatch already maps everything
            traceback.print_exc()
            self._close_conn_async(rconn)
        finally:
            rconn.last_activity = time.monotonic()
            rconn.busy = False
            self._attention.add(rconn)  # GIL-atomic; loop re-examines
            if rconn.closing and rconn.ctx.subs:
                # The connection died while this worker ran (e.g. a
                # SUBSCRIBE racing a peer reset): drop any listener the
                # close sweep could not see yet.
                self._unsubscribe_all(rconn)
            self.wake()

    # -- writes / slow-client protection ------------------------------------

    def _flush(self, rconn: _RConn) -> None:
        """Send as much of the backlog as the socket accepts (reactor
        thread only)."""
        dead = False
        with rconn.wlock:
            buf = rconn.outbuf
            while buf:
                try:
                    n = rconn.sock.send(memoryview(buf)[: 1 << 18])
                except (BlockingIOError, InterruptedError):
                    break
                except OSError:
                    dead = True
                    break
                if n <= 0:
                    break
                del buf[:n]
                rconn.last_progress = time.monotonic()
            rconn.want_write = bool(buf) and not dead
        if dead:
            self._close_conn(rconn)
            return
        self._update_mask(rconn)
        self._maybe_close_eof(rconn)

    def _apply_write_interest(self) -> None:
        """Flush connections flagged by enqueue() — drains the
        want_flush set (set.pop is GIL-atomic against concurrent
        adds), never a scan over every connection."""
        while self.want_flush:
            try:
                rconn = self.want_flush.pop()
            except KeyError:
                break
            if not rconn.closing:
                self._flush(rconn)

    def _update_mask(self, rconn: _RConn) -> None:
        if rconn.closing or not rconn.registered:
            return
        mask = 0
        if not rconn.read_paused:
            mask |= selectors.EVENT_READ
        if rconn.want_write:
            mask |= selectors.EVENT_WRITE
        if mask == rconn.cur_mask:
            return  # epoll_ctl is a syscall: skip no-op modifies
        try:
            if mask:
                self.sel.modify(rconn.sock, mask, rconn)
                rconn.cur_mask = mask
            else:
                # selectors reject an empty interest set: park the fd
                # out of the selector until interest returns.
                self.sel.unregister(rconn.sock)
                rconn.registered = False
                rconn.cur_mask = 0
        except (KeyError, OSError, ValueError):
            pass

    def _sweep(self, now: float) -> None:
        """Periodic gates: slow-client output limits over the buffered
        backlog (the ISSUE 7 policy _send_bounded enforces inline on
        the thread path) and the idle timeout."""
        server = self.server
        hard = getattr(server, "output_buffer_limit", 0) or 0
        soft_s = getattr(server, "output_buffer_soft_seconds", 0.0) or 0.0
        idle_s = server.idle_timeout_s or 0.0
        stall_s = soft_s or idle_s
        hard_grace = soft_s or 1.0
        for rconn in list(self.conns.values()):
            if rconn.closing:
                continue
            self._maybe_close_eof(rconn)
            if rconn.closing:
                continue
            with rconn.wlock:
                backlog = len(rconn.outbuf)
                t0 = rconn.backlog_t0
                prog = rconn.last_progress
            if backlog:
                if hard and backlog > hard and now - t0 > hard_grace:
                    server._note_slow_client("hard-bytes", backlog)
                    self._close_conn(rconn)
                    continue
                if stall_s and now - prog > stall_s:
                    server._note_slow_client(
                        "soft-seconds" if soft_s else "idle-timeout",
                        backlog,
                    )
                    self._close_conn(rconn)
                    continue
            elif (
                idle_s and not rconn.busy
                and now - rconn.last_activity > idle_s
            ):
                if (
                    (rconn.ctx.subs or rconn.ctx.monitor or rconn.peer)
                    and rconn.at_frame_boundary()
                    and not rconn.pending
                ):
                    # Subscribers/monitors may idle legitimately — but
                    # only at a frame boundary (same exemption as
                    # _serve_conn).  Sibling-worker handoff legs are
                    # pooled and long-lived by design.
                    rconn.last_activity = now
                else:
                    self._close_conn(rconn)
            # Re-park the fd if a paused/unregistered conn regained
            # interest outside the normal paths.
            if (
                not rconn.closing and not rconn.registered
                and (not rconn.read_paused or rconn.want_write)
            ):
                try:
                    mask = 0
                    if not rconn.read_paused:
                        mask |= selectors.EVENT_READ
                    if rconn.want_write:
                        mask |= selectors.EVENT_WRITE
                    self.sel.register(rconn.sock, mask, rconn)
                    rconn.registered = True
                    rconn.cur_mask = mask
                except (OSError, ValueError, KeyError):
                    pass

    # -- teardown ------------------------------------------------------------

    def _unsubscribe_all(self, rconn: _RConn) -> None:
        bus = self.server._client._topic_bus
        for channel, lid in list(rconn.ctx.subs.items()):
            rconn.ctx.subs.pop(channel, None)
            try:
                bus.unsubscribe(channel, lid)
            except Exception:
                pass

    def _close_conn_async(self, rconn: _RConn) -> None:
        """Request a close from a non-reactor thread: shut the socket
        down so the event loop observes it and tears down properly."""
        rconn.closing = True
        try:
            rconn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.wake()

    def _teardown_slot(self, sock: socket.socket) -> None:
        """A connection died before registration: release its slot."""
        try:
            sock.close()
        except OSError:
            pass
        server = self.server
        with server._conn_lock:
            server._nconn -= 1
            server._conns.discard(sock)
            server._conn_idle.notify_all()

    def _close_conn(self, rconn: _RConn) -> None:
        if rconn.closed:
            return
        rconn.closed = True
        rconn.closing = True
        self._attention.discard(rconn)
        self.want_flush.discard(rconn)
        # fd-reuse guard: only drop the table entry if it is still OURS
        # (the fd may already back a newer connection).
        if self.conns.get(rconn.fd) is rconn:
            del self.conns[rconn.fd]
        if rconn.registered:
            try:
                self.sel.unregister(rconn.sock)
            except (KeyError, OSError, ValueError):
                pass
            rconn.registered = False
        self._unsubscribe_all(rconn)
        self.server._monitors.discard(rconn.ctx)
        try:
            rconn.sock.close()
        except OSError:
            pass
        server = self.server
        with server._conn_lock:
            server._nconn -= 1
            server._conns.discard(rconn.sock)
            server._conn_idle.notify_all()


class ReactorPool:
    """The fixed reactor-thread pool fronting one RespServer.  The
    accept loop assigns connections round-robin; each reactor owns its
    share for life (no cross-reactor migration — per-connection state
    stays single-threaded)."""

    def __init__(self, server, nthreads: int = 1):
        self.nthreads = max(1, int(nthreads))
        self._reactors = [
            _Reactor(server, i) for i in range(self.nthreads)
        ]
        self._rr = 0
        for r in self._reactors:
            r.start()

    def assign(self, sock: socket.socket, peer: bool = False) -> None:
        r = self._reactors[self._rr % self.nthreads]
        self._rr += 1
        r.add_conn(sock, peer=peer)

    @property
    def native_tick(self) -> bool:
        """True when the reactors run the fused native drain+frame loop
        (rtpu_resp_tick) — INFO frontdoor surfaces this so the bench's
        mini-A/B can verify which arm it measured."""
        return any(r.ticker is not None for r in self._reactors)

    def connection_count(self) -> int:
        return sum(len(r.conns) for r in self._reactors)

    def close(self, timeout_s: float = 5.0) -> None:
        for r in self._reactors:
            r.stop()
        deadline = time.monotonic() + timeout_s
        for r in self._reactors:
            r.join(timeout=max(0.1, deadline - time.monotonic()))
