"""RESP front door — the serving layer of SURVEY.md §2.4 (comm row):
a RESP2 TCP server over the client engine, so existing Redis clients
(redis-cli, redis-py, a stock Redisson) can drive the framework's
keyspace and sketch objects without the Python API.

Command surface (the subset the north-star objects + grid need):
  PING ECHO  GET SET DEL EXISTS EXPIRE PEXPIRE TTL PTTL PERSIST
  EXPIREAT PEXPIREAT RENAME RENAMENX RANDOMKEY
  TYPE DUMP RESTORE                                 (data-only payloads)
  MGET MSET SETNX SETEX PSETEX GETSET GETDEL APPEND STRLEN
  GETRANGE SETRANGE
  SETBIT GETBIT BITCOUNT BITPOS
  PFADD PFCOUNT PFMERGE
  BF.RESERVE BF.ADD BF.MADD BF.EXISTS BF.MEXISTS BF.INFO (RedisBloom shape)
  CMS.INITBYDIM CMS.INCRBY CMS.QUERY CMS.MERGE CMS.INFO  (RedisBloom CMS)
  TOPK.RESERVE TOPK.ADD TOPK.INCRBY TOPK.QUERY TOPK.COUNT
  TOPK.LIST TOPK.INFO            (RedisBloom Top-K over the CMS engine)
  LPUSH RPUSH LPUSHX RPUSHX LPOP RPOP LLEN LRANGE LINDEX LSET LREM
  LTRIM RPOPLPUSH
  BLPOP BRPOP                                       (condvar blocking pops)
  HSET HGET HDEL HLEN HGETALL HMGET HKEYS HVALS HEXISTS HSETNX HINCRBY
  SADD SREM SISMEMBER SCARD SMEMBERS SMISMEMBER SPOP SRANDMEMBER SMOVE
  SINTER SUNION SDIFF SINTERSTORE SUNIONSTORE SDIFFSTORE
  ZADD ZSCORE ZRANGE ZCARD ZREM ZINCRBY ZRANK ZCOUNT ZRANGEBYSCORE
  ZPOPMIN ZPOPMAX ZREVRANGE ZREVRANK ZREMRANGEBYSCORE
  ZUNIONSTORE ZINTERSTORE ZRANGEBYLEX        (weights/aggregate; lex)
  HSCAN SSCAN ZSCAN                  (tagged resume cursors, MATCH/COUNT)
  INCR INCRBY DECR INCRBYFLOAT
  XADD XLEN XRANGE XREVRANGE XDEL XTRIM XREAD XREADGROUP XGROUP XACK
  XPENDING XCLAIM XAUTOCLAIM XINFO                 (streams + groups/PEL)
  GEOADD GEOPOS GEODIST GEOHASH GEOSEARCH GEOSEARCHSTORE
  EVAL EVALSHA SCRIPT FCALL FCALL_RO FUNCTION  (PYTHON script bodies — no
                                          Lua VM; redis.call bridge)
  PUBLISH SUBSCRIBE UNSUBSCRIBE           (push replies; '>' on RESP3)
  AUTH HELLO CLIENT INFO COMMAND QUIT     (RESP2/RESP3, requirepass auth)
  SELECT RESET CONFIG WAIT OBJECT DEBUG       (stock-client handshakes)
  GETEX COPY LMOVE SINTERCARD LPOS HRANDFIELD ZRANDMEMBER
  MULTI EXEC DISCARD                                (contiguous-exec txn)
  KEYS SCAN DBSIZE FLUSHALL

Values travel as raw bytes (RESP bulk strings) through a ByteArray-style
codec boundary: what a foreign client SETs is exactly what it GETs.
One thread per connection (the serving pool analog); all state lives in
the embedded RedissonTpuClient.
"""

from __future__ import annotations

import random as _random
import socket
import threading
import time
from typing import Optional

import numpy as np

from redisson_tpu import chaos
from redisson_tpu import overload as _overload
from redisson_tpu.analysis import witness as _witness
from redisson_tpu.cluster.slots import command_keys as _command_keys
from redisson_tpu.cluster.slots import key_slot as _key_slot
from redisson_tpu.obs import trace as _trace
from redisson_tpu.executor.failures import (
    DeadlineExceededError,
    TenantThrottledError,
)


class RespError(Exception):
    pass


class ProtocolError(Exception):
    """Unrecoverable wire-format violation: reply once, then close (the
    Redis 'Protocol error' behavior)."""


class ScriptKilledError(BaseException):
    """Raised asynchronously INTO a running script's thread by SCRIPT
    KILL.  BaseException, so a script's blanket ``except Exception``
    cannot swallow the kill."""


def _encode_simple(s: str) -> bytes:
    return b"+" + s.encode() + b"\r\n"


# Error codes that travel verbatim as their own RESP code (Redis sends
# '-BUSYKEY ...', not '-ERR BUSYKEY ...').  An explicit allowlist — a
# shape heuristic would hijack messages that merely START with a command
# name ('EXEC without MULTI' must stay '-ERR EXEC without MULTI').
_ERROR_CODES = (
    "BUSYKEY", "NOPROTO", "WRONGTYPE", "NOSCRIPT", "EXECABORT",
    "NOAUTH", "WRONGPASS", "NOGROUP", "BUSYGROUP", "BUSY", "NOTBUSY",
    # Cluster redirect protocol (ISSUE 12): these travel verbatim so
    # stock cluster clients parse the slot/address payload.
    "MOVED", "ASK", "CROSSSLOT", "TRYAGAIN", "CLUSTERDOWN",
    # Per-core front door (ISSUE 17): a broken in-node handoff leg
    # surfaces with its own code so clients can retry-distinguish it.
    "HANDOFFBROKEN",
    # Replication + failover (ISSUE 18): NOJOURNAL = the primary has no
    # journal to stream; NOBACKLOG = the requested offset fell off every
    # retention tier (replica must FULLRESYNC); READONLY = write against
    # a replica (verbatim Redis code); STALEREAD = the bounded-staleness
    # gate refused a replica read whose lag exceeds the configured bound.
    "NOJOURNAL", "NOBACKLOG", "READONLY", "STALEREAD",
)

# Commands whose bodies execute arbitrary Python server-side; gated
# behind enable_python_scripts (see RespServer.__init__ / _dispatch).
_SCRIPT_CMDS = frozenset(
    ("EVAL", "EVALSHA", "SCRIPT", "FCALL", "FCALL_RO", "FUNCTION")
)

# Commands EXEMPT from ingress shedding (overload control plane, ISSUE
# 7): connection handshake, admin, and introspection — exactly the
# surface an operator needs to SEE and FIX an overload (shedding INFO /
# CONFIG during the incident they diagnose would be self-defeating).
# Everything else is refused with -BUSY once queue pressure crosses the
# admission watermark.
_SHED_EXEMPT = frozenset((
    "PING", "ECHO", "AUTH", "HELLO", "QUIT", "RESET", "SELECT",
    "INFO", "CONFIG", "CLIENT", "COMMAND", "SLOWLOG", "DEBUG",
    "SHUTDOWN", "SCRIPT", "WAIT", "MULTI", "EXEC", "DISCARD",
    "SUBSCRIBE", "UNSUBSCRIBE",
    # Residency introspection (ISSUE 14): OBJECT FREQ/IDLETIME/ENCODING
    # is how an operator reads the tier ladder DURING the overload that
    # heat-based demotion exists to survive.
    "OBJECT",
    # Cluster control plane (ISSUE 12): topology surgery and the
    # per-key migration pump must keep running DURING an overload —
    # resharding is how an operator relieves one.
    "CLUSTER", "ASKING", "MIGRATE",
    # Fleet telemetry plane (ISSUE 13): the trace/latency/monitor
    # surfaces are exactly what an operator reads DURING the overload,
    # and the RTPU.TRACE prelude is metadata, not work.
    "LATENCY", "TRACE", "MONITOR", "RTPU.TRACE",
    # Load-attribution plane (ISSUE 16): HOTKEYS is how an operator
    # finds the key causing the overload being shed.
    "HOTKEYS",
    # Flight recorder (ISSUE 20): the causal event timeline is exactly
    # what an operator replays DURING the incident being shed around.
    "EVENTS",
    # Replication + failover plane (ISSUE 18): the stream, the acks,
    # and the cluster bus must keep flowing DURING an overload — a shed
    # replication fetch turns node pressure into replica lag, and a
    # shed CLUSTERPING turns it into a spurious failover.
    "REPLCONF", "RTPU.PSYNC", "RTPU.REPLFETCH", "RTPU.CLUSTERPING",
    "RTPU.FAILOVER.AUTH", "RTPU.TAKEOVER", "FAILOVER",
))

# -- front-door vectorization tables (ISSUE 6 tentpole) ----------------------

# Commands that may not be dispatched from inside a buffered pipelined
# batch: blocking commands would hold earlier replies hostage; pub/sub
# handlers write to the socket themselves (their pushes must not overtake
# buffered replies).
_PIPELINE_STOP = frozenset((
    b"BLPOP", b"BRPOP", b"XREAD", b"XREADGROUP",
    b"SUBSCRIBE", b"UNSUBSCRIBE",
    # MONITOR (ISSUE 13) turns the connection into a push stream, like
    # SUBSCRIBE — its ack must not overtake buffered replies, and the
    # reactor hands it to a worker through the same _DETACH gate.
    b"MONITOR",
))

# NON-MUTATING commands: dispatching one cannot change any keyspace-read
# result, so it does not bump the server's write epoch (the response
# cache's invalidation clock).  Conservative ALLOWLIST — anything absent
# counts as a write.
_NONMUTATING = frozenset((
    "GET", "MGET", "STRLEN", "GETRANGE", "EXISTS", "TTL", "PTTL", "TYPE",
    "KEYS", "DBSIZE", "RANDOMKEY", "GETBIT", "BITCOUNT", "BITPOS",
    "PFCOUNT", "BF.EXISTS", "BF.MEXISTS", "BF.INFO", "CMS.QUERY",
    "CMS.INFO", "TOPK.QUERY", "TOPK.COUNT", "TOPK.LIST", "TOPK.INFO",
    "LLEN", "LRANGE", "LINDEX", "LPOS", "HGET", "HMGET", "HGETALL",
    "HKEYS", "HVALS", "HLEN", "HEXISTS", "HRANDFIELD", "SCARD",
    "SISMEMBER", "SMISMEMBER", "SMEMBERS", "SRANDMEMBER", "SINTER",
    "SUNION", "SDIFF", "SINTERCARD", "ZSCORE", "ZRANGE", "ZCARD",
    "ZRANK", "ZCOUNT", "ZRANGEBYSCORE", "ZREVRANGE", "ZREVRANK",
    "ZRANGEBYLEX", "ZRANDMEMBER", "XLEN", "XRANGE", "XREVRANGE", "XINFO",
    "XPENDING", "GEOPOS", "GEODIST", "GEOHASH", "GEOSEARCH", "HSCAN",
    "SSCAN", "ZSCAN", "SCAN", "OBJECT", "DUMP", "PING", "ECHO", "SELECT",
    "TIME", "COMMAND", "CLIENT", "INFO", "SLOWLOG", "WAIT", "AUTH",
    "HELLO", "QUIT", "SAVE", "BGSAVE", "LASTSAVE", "BGREWRITEAOF",
    "ASKING", "LATENCY", "TRACE", "MONITOR", "RTPU.TRACE", "HOTKEYS",
    "EVENTS",
    # Replication plane (ISSUE 18): stream/ack/bus verbs never change a
    # keyspace-read result on THIS node (a replica's keyspace changes
    # through the apply path, not through the dispatched verb).
    "REPLCONF", "RTPU.PSYNC", "RTPU.REPLFETCH", "RTPU.CLUSTERPING",
    "RTPU.FAILOVER.AUTH",
))

# Response-CACHEABLE subset: deterministic pure keyspace reads whose
# reply depends only on (argv, keyspace state) — no cursors, no
# randomness, no wall-clock.  Served from the per-connection response
# cache while the write epoch is unmoved.
_CACHEABLE = frozenset((
    "GET", "MGET", "STRLEN", "GETRANGE", "EXISTS", "TYPE", "GETBIT",
    "BITCOUNT", "BITPOS", "PFCOUNT", "BF.EXISTS", "BF.MEXISTS",
    "CMS.QUERY", "LLEN", "LRANGE", "LINDEX", "HGET", "HMGET", "HGETALL",
    "HLEN", "HEXISTS", "SCARD", "SISMEMBER", "SMISMEMBER", "SMEMBERS",
    "ZSCORE", "ZCARD", "ZRANK",
))

# Fusable families: runs of ADJACENT commands in one parsed-ahead batch
# that target the same (object, opcode family) fuse into one engine call.
# name -> (is_add, takes_many_items)
_BF_RUN = {
    b"BF.ADD": (True, False),
    b"BF.MADD": (True, True),
    b"BF.EXISTS": (False, False),
    b"BF.MEXISTS": (False, True),
}
_BIT_RUN = frozenset((b"SETBIT", b"GETBIT"))
_GET_RUN = frozenset((b"GET", b"MGET"))

# Bound on ops one fused run may carry (memory + keeps fused launches in
# the pre-warmed bucket ladder; a longer run simply splits).
_RUN_MAX_OPS = 1 << 14

# Commands a READ-ONLY replica still serves beyond the _NONMUTATING
# read surface (ISSUE 18): admin/topology/replication control.  NOT the
# write surface — a replica's keyspace mutates only through its
# replication link, or the -READONLY contract (and the no-dual-primary
# invariant it underwrites) is fiction.
_REPLICA_ADMIN = frozenset((
    "CONFIG", "DEBUG", "CLUSTER", "REPLCONF", "SHUTDOWN", "RESET",
    "MULTI", "EXEC", "DISCARD", "SUBSCRIBE", "UNSUBSCRIBE", "FAILOVER",
))

# One-shot connection licenses (the RT012 class): per-connection flags a
# prelude command grants for EXACTLY the next command — cluster ASKING
# (serve one command from an IMPORTING slot) and the RTPU.TRACE wire
# prelude (stitch one command into a remote trace).  The preludes
# themselves are transparent to EACH OTHER (the migration pump sends
# RTPU.TRACE + ASKING + RESTORE: the licensed hop is the RESTORE,
# whichever order the preludes arrived in).
_LICENSE_TRANSPARENT = frozenset(("ASKING", "RTPU.TRACE"))


def consume_one_shot_licenses(ctx, name: str) -> None:
    """Burn every one-shot license after a dispatched command.

    Keyed commands consume ASKING inside the cluster door's ``route()``
    and traced commands consume the prelude inside ``_trace_begin`` —
    but keyless commands (a PING between ASKING and the redirected
    command), errored dispatches, and untraceable commands must ALL
    still burn the licenses here, or a license leaks to a later
    unrelated command (the PR 12/13 review class: ASKING leaking past
    PING served a foreign-slot command; netsim's redirect model drives
    this function directly and its mutation guard reverts it).

    Called once per non-queueing dispatch (``_safe_dispatch``) and by
    the netsim node harnesses, so the consumption discipline is ONE
    piece of code on both the serving and the model-checking path."""
    if name in _LICENSE_TRANSPARENT:
        return
    if getattr(ctx, "asking", False):
        ctx.asking = False
    if getattr(ctx, "trace_next", None) is not None:
        ctx.trace_next = None


def _encode_error(s: str) -> bytes:
    if s.split(" ", 1)[0] in _ERROR_CODES:
        return b"-" + s.encode() + b"\r\n"
    return b"-ERR " + s.encode() + b"\r\n"


def _encode_int(n: int) -> bytes:
    return b":" + str(int(n)).encode() + b"\r\n"


def _fmt_score(score: float) -> str:
    """Redis-style double formatting: integral scores print as integers
    ('1', not '1.0'); non-finite as 'inf'/'-inf'/'nan'; everything else
    %.17g (the shortest exact form Redis emits)."""
    import math

    if not math.isfinite(score):
        return repr(score)  # 'inf' / '-inf' / 'nan' — Redis spelling
    if score == int(score) and abs(score) < 1e17:
        return "%d" % int(score)
    return "%.17g" % score


def _encode_bulk(v) -> bytes:
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, str):
        v = v.encode()
    return b"$" + str(len(v)).encode() + b"\r\n" + v + b"\r\n"


_int_encoder = None
_int_encoder_loaded = False


def _encode_array(items) -> bytes:
    global _int_encoder, _int_encoder_loaded
    out = b"*" + str(len(items)).encode() + b"\r\n"
    if len(items) >= 8:
        if not _int_encoder_loaded:
            from redisson_tpu.serve import native_codec

            _int_encoder = native_codec.get_parser()
            _int_encoder_loaded = True
        if _int_encoder is not None:
            if all(type(it) is int for it in items):
                # Batch integer replies (BF.MADD / BF.MEXISTS / CMS.QUERY
                # pipelines) serialize in one native call
                # (rtpu_resp_encode_ints).
                return out + _int_encoder.encode_ints(items)
            if all(it is None or type(it) is bytes for it in items):
                # Batch bulk replies (MGET / HGETALL / LRANGE pipelines):
                # one native call builds every `$len\r\n...\r\n` frame
                # (rtpu_resp_encode_bulks; None on a stale .so).
                enc = _int_encoder.encode_bulks(items)
                if enc is not None:
                    return out + enc
    for it in items:
        if isinstance(it, int):
            out += _encode_int(it)
        else:
            out += _encode_bulk(it)
    return out


def _decode_reply(frame: bytes):
    """Parse ONE RESP reply frame into a Python value (the redis.call
    bridge decoding half: scripts see values, not wire bytes).  Error
    replies raise RespError."""
    if not frame:
        return None  # handler wrote its reply itself (push paths)
    val, _ = _decode_reply_at(frame, 0)
    return val


def _decode_reply_at(buf: bytes, i: int):
    j = buf.index(b"\r\n", i)
    t, body = buf[i : i + 1], buf[i + 1 : j]
    i = j + 2
    if t == b"+":
        return body.decode(), i
    if t == b"-":
        raise RespError(body.decode())
    if t == b":":
        return int(body), i
    if t == b"$":
        n = int(body)
        if n < 0:
            return None, i
        return buf[i : i + n], i + n + 2
    if t in (b"*", b">"):
        n = int(body)
        if n < 0:
            return None, i
        out = []
        for _ in range(n):
            v, i = _decode_reply_at(buf, i)
            out.append(v)
        return out, i
    raise RespError(f"unparseable reply type {t!r}")


class _Reader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""
        # True while a multi-part frame is partially parsed: an idle
        # timeout that fires here must close the connection (continuing
        # would desync the protocol stream), see _serve_conn.
        self.frame_started = False
        # Native batch parser (serve/native_codec.py): one C call frames
        # a whole pipelined recv; parsed-ahead commands queue here.  None
        # → pure-Python slow path (no compiler / RTPU_NO_NATIVE_RESP).
        from collections import deque

        from redisson_tpu.serve import native_codec

        self._native = native_codec.get_parser()
        self._pending: "deque[list[bytes]]" = deque()

    def at_frame_boundary(self) -> bool:
        return not self.frame_started and not self._buf and not self._pending

    def _read_line(self) -> Optional[bytes]:
        while b"\r\n" not in self._buf:
            data = self._sock.recv(65536)
            if not data:
                return None
            self._buf += data
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> Optional[bytes]:
        while len(self._buf) < n + 2:
            data = self._sock.recv(65536)
            if not data:
                return None
            self._buf += data
        out, self._buf = self._buf[:n], self._buf[n + 2 :]
        return out

    def read_command(self) -> Optional[list[bytes]]:
        if self._native is not None:
            return self._read_command_native()
        return self._read_command_py()

    def _read_command_native(self) -> Optional[list[bytes]]:
        from redisson_tpu.serve import native_codec

        while True:
            if self._pending:
                self.frame_started = False
                return self._pending.popleft()
            if self._buf:
                frames, consumed, err = self._native.parse(self._buf)
                if frames:
                    self._buf = self._buf[consumed:]
                    self._pending.extend(frames)
                    continue
                if err != native_codec.PARSE_OK:
                    # Inline command or malformed frame: hand the bytes
                    # to the slow path, which reproduces the Python
                    # behavior exactly (split / RespError path).
                    return self._read_command_py()
                # Incomplete frame: block for more bytes.  Flag it so an
                # idle timeout firing here closes the connection instead
                # of desyncing the stream (see _serve_conn).
                self.frame_started = True
            data = self._sock.recv(65536)
            if not data:
                return None
            self._buf += data

    def _read_command_py(self) -> Optional[list[bytes]]:
        self.frame_started = False
        line = self._read_line()
        if line is None:
            return None
        # Set until the frame parses COMPLETELY — a timeout propagating
        # out mid-frame leaves it set and the caller must close (resuming
        # would desync the stream).
        self.frame_started = True
        if not line.startswith(b"*"):
            # inline command (redis-cli fallback)
            self.frame_started = False
            return line.split()
        try:
            n = int(line[1:])
        except ValueError:
            raise ProtocolError("invalid multibulk length")
        if n < 0:
            # '*-1' etc. would silently desync the stream (mirrors the
            # native parser, which already rejects negative counts).
            raise ProtocolError("invalid multibulk length")
        args = []
        for _ in range(n):
            hdr = self._read_line()
            if hdr is None or not hdr.startswith(b"$"):
                return None
            try:
                size = int(hdr[1:])
            except ValueError:
                raise ProtocolError("invalid bulk length")
            if size < 0:
                # '$-1' reaching _read_exact(-1) would slice buf[:-1]
                # and desync the connection into parsing garbage.
                raise ProtocolError("invalid bulk length")
            data = self._read_exact(size)
            if data is None:
                return None
            args.append(data)
        self.frame_started = False
        return args


class _ConnCtx:
    """Per-connection state: serialized writes (pub/sub pushes interleave
    with replies), this connection's channel subscriptions, and the
    MULTI/EXEC transaction queue."""

    def __init__(self, sock: socket.socket, server: "RespServer" = None):
        self.sock = sock
        self.server = server  # live output-buffer limits (CONFIG SET)
        self.lock = _witness.named(threading.Lock(), "resp.conn.send")
        try:  # for SLOWLOG entries; the peer may already be gone
            peer = sock.getpeername()
            if isinstance(peer, tuple):
                self.addr = "%s:%d" % peer[:2]
            else:  # AF_UNIX peername is a (often empty) path string
                self.addr = "unix:%s" % (peer or "peer")
        except OSError:
            self.addr = ""
        self.subs: dict[str, int] = {}  # channel -> bus listener id
        self.authed = True  # server flips to False when requirepass set
        self.in_multi = False
        self.queued: list = []  # commands queued since MULTI
        self.in_exec = False  # replaying an EXEC (blocking cmds don't block)
        self.proto = 2  # RESP protocol version; HELLO 3 upgrades
        self.client_name: Optional[str] = None
        # Per-connection op-deadline override (CLIENT DEADLINE, ISSUE 7):
        # None = server default (op_deadline_ms), 0 = no deadline.
        self.op_deadline_ms: Optional[int] = None
        # Cluster ASKING handshake (ISSUE 12): one-shot — set by the
        # ASKING command, consumed by the next keyed command's routing
        # decision (lets an ASK-redirected command be served from an
        # IMPORTING slot this node does not own yet).
        self.asking = False
        # Distributed-trace wire prelude (ISSUE 13): one-shot (the
        # ASKING shape) — RTPU.TRACE <trace_id> <span_id> parks the
        # remote parent here; the NEXT command joins that trace (head
        # sampling already happened at the remote hop) and consumes it.
        self.trace_next = None
        # MONITOR mode (ISSUE 13): every dispatched command streams to
        # this connection as a +<ts> [db addr] "CMD" ... push.
        self.monitor = False
        # Per-core front door (ISSUE 17): True on in-node handoff legs
        # from sibling workers — peer legs always execute locally (the
        # no-proxy-loops invariant), skip auth (the unix socket lives in
        # a mode-0700 rundir), and are exempt from the idle sweep.
        self.is_peer = False
        # Replication (ISSUE 18): set by REPLCONF IDENT — this
        # connection belongs to a replica with that id; its ACKs land in
        # the hub's per-replica table under this name.
        self.repl_ident: Optional[str] = None
        self.repl_listening_port = 0

    def _kill(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def send(self, frame: bytes) -> None:
        with self.lock:
            srv = self.server
            hard = getattr(srv, "output_buffer_limit", 0) if srv else 0
            soft_s = (
                getattr(srv, "output_buffer_soft_seconds", 0.0)
                if srv else 0.0
            )
            if not hard and not soft_s:
                try:
                    # rtpulint: disable=RT001 the conn write lock EXISTS to serialize whole-frame socket writes (pub/sub pushes interleave with replies); blocking here is its purpose, and the socket timeout / output-buffer limits bound the stall
                    self.sock.sendall(frame)
                except OSError:
                    # Includes socket.timeout: the connection's timeout
                    # covers sendall too, and a timed-out/failed send may
                    # have written a PARTIAL frame — continuing would
                    # desync the reply stream.  Kill the socket; the read
                    # loop reclaims the slot.
                    self._kill()
                return
            self._send_bounded(frame, srv, hard, soft_s)

    def _send_bounded(self, frame: bytes, srv, hard: int,
                      soft_s: float) -> None:
        """Slow-client protection (the client-output-buffer-limit analog,
        ISSUE 7): replies are written through, so the server-side
        'buffer' is the unsent remainder of the current frame.  A frame
        still holding more than ``hard`` bytes unsent after its grace
        (soft-seconds when set, else ~1 s) — or one making NO progress
        for ``soft_s`` seconds — disconnects the client instead of
        parking a connection thread (and the engine results it holds)
        behind a receiver that never (or barely) reads.

        Waits use select(), NOT settimeout(): the socket's timeout is
        shared state the connection's reader thread relies on
        (idle_timeout_s semantics), and this method runs cross-thread
        for pub/sub pushes."""
        import select

        view = memoryview(frame)
        frame_t0 = last_progress = time.monotonic()
        # No-progress stall bound: soft-seconds when configured, else
        # the socket's own timeout (the idle_timeout_s the legacy
        # sendall path died under) — with only the hard byte limit set,
        # an under-limit stall must NOT loop forever where the old path
        # disconnected.
        stall_s = soft_s or self.sock.gettimeout() or 0.0
        # The hard byte limit gets its OWN time gate (soft-seconds when
        # set, else ~1 s): gating it on continuous stall alone lets a
        # one-byte-per-tick trickler reset the clock forever, and tying
        # it to idle_timeout made it a 300 s (or never, at idle 0) wait.
        hard_grace_s = soft_s or 1.0
        while view:
            now = time.monotonic()
            if (
                hard and len(view) > hard
                and now - frame_t0 > hard_grace_s
            ):
                srv._note_slow_client("hard-bytes", len(view))
                self._kill()
                return
            tick = 1.0
            if stall_s:
                rem = stall_s - (now - last_progress)
                if rem <= 0:
                    srv._note_slow_client(
                        "soft-seconds" if soft_s else "idle-timeout",
                        len(view),
                    )
                    self._kill()
                    return
                tick = min(tick, rem)
            if hard and len(view) > hard:
                tick = min(tick, max(0.01, hard_grace_s - (now - frame_t0)))
            try:
                _r, writable, _x = select.select((), (self.sock,), (), tick)
                if not writable:
                    continue  # loop re-checks the stall / hard gates
                # Blocking socket + select-says-writable: send() takes
                # whatever buffer space exists and returns promptly.
                n = self.sock.send(view)
            except (OSError, ValueError):
                self._kill()
                return
            if n > 0:
                last_progress = time.monotonic()
                view = view[n:]


class RespServer:
    """Embedded RESP2 endpoint over a RedissonTpuClient.

    Bounded (SURVEY §2.1 pub/sub + pools rows): at most
    ``max_connections`` concurrent connections (excess are refused with
    an error, the ``maxclients`` behavior) and an ``idle_timeout_s``
    after which a silent connection is closed — subscriber connections
    are exempt, like Redis's default timeout handling for blocked/
    subscribed clients."""

    def __init__(self, client, host: str = "127.0.0.1", port: int = 0,
                 max_connections: int = 256, idle_timeout_s: float = 300.0,
                 requirepass: Optional[str] = None,
                 enable_python_scripts: Optional[bool] = None):
        self._client = client
        # Auth (SURVEY §2.1 config row): explicit arg wins, else the
        # client Config's requirepass key.  A network-exposed server
        # with FLUSHALL and no auth is not shippable — set one.
        self._requirepass = (
            requirepass
            if requirepass is not None
            else getattr(client.config, "requirepass", None)
        )
        # Scripting (EVAL/EVALSHA/SCRIPT/FUNCTION/FCALL): script bodies
        # are arbitrary PYTHON — remote code execution for anyone who can
        # reach the socket.  OFF unless explicitly enabled, and enabling
        # REFUSES unless the server authenticates (requirepass) or binds
        # loopback-only: an open 0.0.0.0 server with EVAL is an
        # unauthenticated RCE, not a configuration choice.
        want_scripts = (
            enable_python_scripts
            if enable_python_scripts is not None
            else getattr(client.config, "enable_python_scripts", False)
        )
        if want_scripts and not (
            self._requirepass or self._is_loopback(host)
        ):
            raise ValueError(
                "enable_python_scripts on a non-loopback bind requires "
                "requirepass: RESP scripts are arbitrary Python (RCE)"
            )
        self._scripts_enabled = bool(want_scripts)
        # DEBUG INJECT (chaos fault injection) shares the scripting gate
        # exactly: a fault injector on an open unauthenticated socket is
        # a denial-of-service surface, not a debugging convenience.
        self._inject_allowed = bool(
            self._requirepass or self._is_loopback(host)
        )
        # Script watchdog (the busy-reply-threshold analog): while a
        # script has been running longer than script_timeout_ms, other
        # connections get BUSY instead of queueing behind the grid lock;
        # SCRIPT KILL stops the runaway (docs/observability.md hazard).
        self._script_timeout_ms = getattr(
            client.config, "script_timeout_ms", 5000
        )
        self._script_lock = _witness.named(
            threading.Lock(), "resp.script"
        )
        self._script_run = None  # (thread, started_monotonic) while running
        self._script_kill = None  # run record a SCRIPT KILL is targeting
        self.max_connections = max_connections
        self.idle_timeout_s = idle_timeout_s
        # Overload control plane (ISSUE 7).  Deadline default stamped on
        # every command at ingress (CLIENT DEADLINE overrides per
        # connection); ingress shedding once coalescer queue pressure
        # crosses the watermark; slow-client output-buffer limits.  All
        # live-settable via CONFIG SET.
        tsk = getattr(client.config, "tpu_sketch", None)
        self.op_deadline_ms = int(getattr(tsk, "op_deadline_ms", 0) or 0)
        self.admission_watermark = float(
            getattr(tsk, "admission_watermark", 0.9) or 0.9
        )
        self.output_buffer_limit = int(
            getattr(client.config, "client_output_buffer_limit", 0) or 0
        )
        self.output_buffer_soft_seconds = float(
            getattr(client.config, "client_output_buffer_soft_seconds", 0.0)
            or 0.0
        )
        self._ingress_shed = 0  # lifetime commands shed at ingress
        self._slow_client_kills = 0
        # Front-door vectorization (ISSUE 6): fuse runs of adjacent
        # pipelined commands into single engine launches; the response
        # cache serves repeated identical reads inside one pipeline
        # window.  Both live-togglable via attributes (bench A/B).
        self.vectorize = bool(
            getattr(client.config, "resp_vectorize", True)
        )
        self.response_cache_size = int(
            getattr(client.config, "resp_response_cache_size", 64)
        )
        # Write epoch: bumped by every mutating RESP command on ANY
        # connection; response-cache entries serve only while it is
        # unmoved since install.  Guarded — a lost increment would let a
        # stale cached reply outlive the write that obsoleted it.
        self._write_epoch = 0
        self._epoch_lock = _witness.named(
            threading.Lock(), "resp.write_epoch"
        )
        # Observability (ISSUE 1): per-command stats + SLOWLOG record
        # into the CLIENT's bundle (shared with the engine's registry,
        # so one Prometheus endpoint exposes both); a bare client
        # without one gets a private bundle.
        self.obs = getattr(client, "obs", None)
        if self.obs is None:
            from redisson_tpu.obs import Observability

            self.obs = Observability()
        self._started = time.monotonic()
        # MONITOR mode (ISSUE 13): live monitor connections' ctxs.  Read
        # lock-free per command (GIL-atomic set ops; the common case is
        # the empty set — one falsy check).  While any monitor is
        # attached, front-door fusion is disabled so EVERY command flows
        # through _safe_dispatch and feeds the stream (redis documents
        # MONITOR as expensive for the same reason).
        self._monitors: set = set()
        self._conns_accepted = 0
        self._nconn = 0
        self._conn_lock = _witness.named(threading.Lock(), "resp.conns")
        self._conn_idle = threading.Condition(self._conn_lock)
        self._conns: set = set()  # live sockets, for shutdown drain
        # SCAN resume state: cursor id -> last key returned (see _cmd_SCAN).
        self._scan_states: dict[int, str] = {}
        self._scan_next = 0
        self._scan_lock = _witness.named(threading.Lock(), "resp.scan")
        # Per-core front door (ISSUE 17): in worker mode this process is
        # one of K siblings sharing the SAME (host, port) via
        # SO_REUSEPORT — the kernel load-balances accepts across the
        # workers' listen sockets.  __main__ probes availability before
        # spawning workers, so a failed setsockopt here means direct
        # misconfiguration: fail loudly, not at first accept.
        fd_i = getattr(client.config, "frontdoor_index", None)
        fd_k = int(getattr(client.config, "frontdoor_workers", 1) or 1)
        self._fd_workers = fd_k if (fd_k > 1 and fd_i is not None) else 1
        self._fd_index = int(fd_i) if fd_i is not None else 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self._fd_workers > 1:
            try:
                self._sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                )
            except (AttributeError, OSError) as e:
                self._sock.close()
                raise ValueError(
                    "frontdoor worker mode requires SO_REUSEPORT "
                    f"(probe with serve.multicore.reuseport_available): {e}"
                )
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.host, self.port = self._sock.getsockname()
        self._closed = False
        # Cluster mode (ISSUE 12 tentpole): the slot-sharded topology
        # door.  When enabled, every keyed command routes through
        # ClusterDoor.route before its handler — wrong-slot keys get
        # -MOVED/-ASK redirects, cross-slot multi-key ops -CROSSSLOT,
        # and keys in a MIGRATING slot serialize with the per-key
        # migration pump (zero acked-write loss under live reshard).
        self.cluster = None
        if bool(getattr(client.config, "cluster_enabled", False)):
            from redisson_tpu.cluster.door import ClusterDoor

            try:
                self.cluster = ClusterDoor.from_config(
                    self, client.config, obs=self.obs
                )
            except Exception:
                self._sock.close()
                raise
        # Connection-limit refusals (ISSUE 11 satellite): counted so
        # reactor-mode capacity tuning is observable — INFO clients
        # (rejected_connections) + rtpu_resp_ingress_shed{conn_limit}.
        self._conns_refused = 0
        # Load-attribution plane (ISSUE 16): the obs bundle's loadmap
        # gains its serving-side wiring here — cluster flag (per-slot
        # attribution only means something behind the door; standalone
        # degrades to slot 0), the ingress key-sample rate, the exact
        # keyspace hooks on both backends, and a one-scan seed of the
        # per-slot key counters.  `_loadmap_keys_exact` gates the O(1)
        # CLUSTER COUNTKEYSINSLOT: only when BOTH backends report every
        # keyspace change may the counters replace the scan.
        lm = self.obs.loadmap
        self.loadmap = lm
        lm.cluster = self.cluster is not None
        lm.sample_rate = float(
            getattr(client.config, "loadmap_key_sample_rate", 0.01) or 0.0
        )
        self._loadmap_keys_exact = False
        try:
            grid = getattr(client, "_grid", None)
            reg = getattr(
                getattr(client, "_engine", None), "registry", None
            )
            # Slot->key index (ISSUE 19): rides the SAME keyspace hooks
            # as the load map's exact counters — one fan-out closure
            # feeds counts (loadmap) and names (slotindex), so the two
            # planes can never disagree about which writes were seen.
            # Cluster-only: single-node servers have no GETKEYSINSLOT
            # callers and the scan stays fine.
            idx = None
            if self.cluster is not None and (
                    grid is not None and reg is not None):
                from redisson_tpu.cluster.slotindex import SlotKeyIndex

                idx = SlotKeyIndex()

                def _keyspace_note(name, delta, _lm=lm, _idx=idx):
                    _lm.note_key(name, delta)
                    _idx.note(name, delta)
            else:
                _keyspace_note = lm.note_key
            if grid is not None:
                grid.on_keyspace = _keyspace_note
            if reg is not None:
                reg.on_keyspace = _keyspace_note
            if grid is not None or reg is not None:
                lm.seed_keys(client.get_keys().get_keys())
                self._loadmap_keys_exact = (
                    grid is not None and reg is not None
                )
            if idx is not None:
                idx.seed(client.get_keys().get_keys())
                self.cluster.slot_index = idx
        except Exception:
            self._loadmap_keys_exact = False
        # Reactor front door (ISSUE 11 tentpole): a small fixed pool of
        # epoll/selector event-loop threads replaces thread-per-
        # connection serving — each tick drains recv buffers across ALL
        # ready connections and feeds one merged parse→vectorize→
        # dispatch pass, so same-family ops from different connections
        # fuse into single engine launches and idle connections cost a
        # file descriptor, not a thread.  resp_reactor=False keeps the
        # legacy path selectable for differential testing;
        # RTPU_REQUIRE_REACTOR makes a silent fallback a hard error
        # (the CI analog of RTPU_REQUIRE_NATIVE_RESP).
        self.reactor = None
        if bool(getattr(client.config, "resp_reactor", True)):
            import os as _os

            try:
                from redisson_tpu.serve.reactor import ReactorPool

                self.reactor = ReactorPool(
                    self,
                    nthreads=int(
                        getattr(client.config, "resp_reactor_threads", 1)
                        or 1
                    ),
                )
            except Exception:
                if _os.environ.get("RTPU_REQUIRE_REACTOR"):
                    self._sock.close()
                    raise
                self.reactor = None
        # Per-core front door (ISSUE 17 tentpole): the in-node
        # slot→process map.  Keyed commands owned by a sibling worker
        # take a loopback handoff over persistent unix-domain legs —
        # invisible to the client (no MOVED from inside a node).  Must
        # init AFTER the reactor (peer legs are admitted into it) and
        # BEFORE the accept thread (a client command must never race a
        # half-built router).
        self.multicore = None
        if self._fd_workers > 1:
            from redisson_tpu.serve.multicore import MulticoreRouter

            try:
                self.multicore = MulticoreRouter(
                    self, self._fd_workers, self._fd_index,
                    getattr(client.config, "frontdoor_dir", None),
                    obs=self.obs,
                )
            except Exception:
                self._sock.close()
                raise
        if self.obs is not None:
            try:
                self.obs.frontdoor_processes.set((), float(self._fd_workers))
                self.obs.frontdoor_worker_index.set((), float(self._fd_index))
            except AttributeError:
                pass  # obs bundle predates the frontdoor families
        # Replication plane (ISSUE 18 tentpole): the primary-side hub
        # (journal tap → backlog ring → RTPU.REPLFETCH) exists whenever
        # a journal does — a node is a streaming-capable primary by
        # default.  `replica_link` is set when THIS node replicates from
        # a primary (config.replica_of or start_replication_from); the
        # link's presence IS the role bit (role:slave, -READONLY gate,
        # bounded-staleness refusals).  `failover` is the cluster-bus
        # agent (cluster/failover.py) when armed.
        self.repl_hub = None
        self.replica_link = None
        self.failover = None
        # Autonomous rebalancer agent (cluster/rebalancer.py) when
        # armed via --rebalance / config rebalance_enabled; fleet
        # doctor (obs/doctor.py) when armed via --doctor.
        self.rebalancer = None
        self.doctor = None
        # Flight recorder (ISSUE 20): stamp the ring with this node's
        # cluster identity so fleet_events() merges by node id (empty
        # node = standalone process — the ring still works).
        events = getattr(self.obs, "events", None)
        if events is not None and self.cluster is not None:
            events.node = self.cluster.myid
        self._repl_hub()  # eager when the journal is already attached
        self._obs_wire_repl_gauges()
        master = getattr(client.config, "replica_of", None)
        if master:
            host_m, _, port_m = str(master).rpartition(":")
            self.start_replication_from(host_m, int(port_m))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rtpu-resp-accept", daemon=True
        )
        self._accept_thread.start()

    # -- plumbing ----------------------------------------------------------

    @staticmethod
    def _is_loopback(host: str) -> bool:
        return host in ("localhost", "::1") or host.startswith("127.")

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            try:
                # redis-server sets TCP_NODELAY on accepted sockets:
                # small reply frames must not sit behind Nagle.
                conn.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
            with self._conn_lock:
                refused = self._nconn >= self.max_connections
                if not refused:
                    self._nconn += 1
                    self._conns_accepted += 1
                    self._conns.add(conn)
            if refused:
                # Count the refusal (ISSUE 11 satellite): reactor-mode
                # capacity tuning needs conn-limit sheds visible next to
                # the command-level ingress sheds, and INFO clients
                # carries the lifetime total (rejected_connections).
                self._conns_refused += 1
                if self.obs is not None:
                    self.obs.resp_ingress_shed.inc(("conn_limit",))
                # Refusal send OUTSIDE _conn_lock (rtpulint RT001): a
                # stalled rejected peer must not park the accept thread
                # while it holds the lock every disconnecting
                # connection needs for slot teardown.
                try:
                    conn.sendall(
                        b"-ERR max number of clients reached\r\n"
                    )
                except OSError:
                    pass
                finally:
                    # close in finally (RT013): a refusal send that
                    # raises must still release the fd — the old shape
                    # leaked it to GC time.
                    conn.close()
                continue
            if self.reactor is not None:
                self.reactor.assign(conn)
            else:
                threading.Thread(
                    target=self._serve_conn, args=(conn,),
                    name="rtpu-resp-conn", daemon=True,
                ).start()

    def _admit_peer(self, conn: socket.socket) -> None:
        """Admit an in-node handoff leg from a sibling front-door worker
        (ISSUE 17).  Peer legs bypass max_connections — refusing one
        would wedge the sibling's forwarded CLIENT command, turning a
        conn-limit shed into a cross-worker stall — but join the normal
        connection set so the shutdown drain covers them."""
        with self._conn_lock:
            if self._closed:
                conn.close()
                return
            self._nconn += 1
            self._conns_accepted += 1
            self._conns.add(conn)
        if self.obs is not None:
            try:
                self.obs.frontdoor_peer_accepts.inc(())
            except AttributeError:
                pass
        if self.reactor is not None:
            self.reactor.assign(conn, peer=True)
        else:
            threading.Thread(
                target=self._serve_conn, args=(conn, True),
                name="rtpu-resp-peer", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket, peer: bool = False) -> None:
        try:
            reader = _Reader(conn)
            ctx = _ConnCtx(conn, server=self)
            if peer:
                ctx.is_peer = True
                ctx.authed = True
            elif self._requirepass:
                ctx.authed = False
        except Exception:
            # Constructor failure must not leak the connection slot.
            conn.close()
            with self._conn_lock:
                self._nconn -= 1
                self._conns.discard(conn)
                self._conn_idle.notify_all()
            raise
        if self.idle_timeout_s:
            conn.settimeout(self.idle_timeout_s)
        try:
            while True:
                try:
                    cmd = reader.read_command()
                except socket.timeout:
                    # Subscribers (and monitors) may idle legitimately —
                    # but only at a frame boundary; a timeout mid-frame
                    # (or with bytes buffered) would desync the protocol
                    # on resume.
                    if (ctx.subs or ctx.monitor or peer) and \
                            reader.at_frame_boundary():
                        continue
                    return  # reclaim the slot
                except OSError:
                    return  # peer reset/aborted: plain disconnect
                except ProtocolError as e:
                    ctx.send(_encode_error(f"Protocol error: {e}"))
                    return  # desynced stream: close, Redis-style
                if cmd is None:
                    return
                if not cmd:
                    # Empty multibulk ('*0\r\n') / blank inline line:
                    # Redis silently skips these with NO reply — emitting
                    # one would desync a pipelining client's reply count.
                    continue
                # Pipelined batch: commands the reader already parsed
                # ahead reply in ONE sendall (the CommandBatchEncoder
                # role) — syscall count stops scaling with pipeline
                # depth; the vectorizer additionally fuses runs of
                # adjacent same-family commands into single engine
                # launches (ISSUE 6).  Bounded so a huge pipeline cannot
                # buffer unbounded reply bytes.
                pending = reader._pending
                if pending:
                    batch = [cmd]
                    while pending and len(batch) < 1024:
                        # Collect up to the first command that blocks
                        # (BLPOP would hold earlier replies hostage) or
                        # whose handler writes to the socket ITSELF
                        # (SUBSCRIBE's ack would overtake buffered
                        # replies — reply order must be command order).
                        if not pending[0]:
                            # Empty frame in a pipeline: skip, no reply.
                            pending.popleft()
                            continue
                        if pending[0][0].upper() in _PIPELINE_STOP:
                            break
                        batch.append(pending.popleft())
                    frames, consumed = self._dispatch_pipeline(batch, ctx)
                    if consumed < len(batch):
                        # Reply-buffer cap hit: the unprocessed tail goes
                        # back to the FRONT of the parse-ahead queue, in
                        # order, for the next loop pass.
                        pending.extendleft(reversed(batch[consumed:]))
                    ctx.send(b"".join(frames))
                else:
                    ctx.send(self._safe_dispatch(cmd, ctx))
        finally:
            # Drop this connection's subscriptions (and monitor slot)
            # with it.
            for channel, lid in list(ctx.subs.items()):
                self._client._topic_bus.unsubscribe(channel, lid)
            self._monitors.discard(ctx)
            conn.close()
            with self._conn_lock:
                self._nconn -= 1
                self._conns.discard(conn)
                self._conn_idle.notify_all()

    def close(self, drain_timeout_s: float = 10.0) -> None:
        """Stop accepting, force-close live connections, and wait for
        their threads to finish the command in flight.  Ordering matters
        for snapshot-on-shutdown: every reply already on the wire was
        dispatched before its connection thread exits, so a snapshot
        taken AFTER this drain contains every acked write."""
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        import time as _time

        with self._conn_lock:
            for c in list(self._conns):
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            deadline = _time.monotonic() + drain_timeout_s
            while self._nconn > 0:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                self._conn_idle.wait(timeout=remaining)
        # Replication plane down BEFORE the client engine can shut down
        # under it: the link thread applies into the engine, the
        # failover agent dials peers, the hub taps the journal.  The
        # rebalancer first — mid-wave it drives migrations THROUGH the
        # failover-tracked peers.
        doc = getattr(self, "doctor", None)
        if doc is not None:
            doc.stop()
        rb = getattr(self, "rebalancer", None)
        if rb is not None:
            rb.stop()
        fo = getattr(self, "failover", None)
        if fo is not None:
            fo.stop()
        link = getattr(self, "replica_link", None)
        if link is not None:
            self.replica_link = None
            link.stop()
        hub = getattr(self, "repl_hub", None)
        if hub is not None:
            hub.detach()
        # Reactors stop AFTER the drain: they are the threads that
        # observe the shutdowns above and tear each connection down.
        if self.reactor is not None:
            self.reactor.close()
        if self.cluster is not None:
            self.cluster.close()  # cached migration sockets
        mc = getattr(self, "multicore", None)
        if mc is not None:
            mc.close()  # peer listener + pooled handoff legs

    # -- command dispatch ---------------------------------------------------

    def _safe_dispatch(self, cmd: list[bytes], ctx: "_ConnCtx") -> bytes:
        """Dispatch with the error-encoding contract: command errors
        never kill the connection; known codes pass through verbatim.

        Every dispatch is timed here — ONE clock read pair per command
        feeds the per-command counters/latency histogram (INFO
        commandstats / latencystats, the Prometheus families) and the
        SLOWLOG ring when the duration meets the configured threshold.

        Commands merely QUEUED under MULTI are not recorded (EXEC's
        replay re-enters here and records the real execution — counting
        the queue step too would double calls and drag latencystats
        toward the ~microsecond queue time)."""
        t0 = time.perf_counter()
        err = False
        name = cmd[0].decode("latin-1", "replace").upper()
        # Load attribution (ISSUE 16): slot 0 is the standalone /
        # unslotted bucket; the cluster door's route() overwrites it
        # with the real slot (or None on redirects — nothing served, so
        # nothing attributed), and the shed branch clears it too.
        ctx.load_slot = 0
        queueing = ctx.in_multi and name not in (
            "EXEC", "DISCARD", "MULTI", "RESET",
        )
        if self._monitors and not queueing:
            # MONITOR stream (ISSUE 13): fed at dispatch, before
            # execution (redis feeds on command processing).
            self._monitor_feed(name, cmd, ctx)
        # Distributed tracing (ISSUE 13): a remote RTPU.TRACE prelude
        # forces the span into that trace even when this node's own
        # sampling is off (head-based: the first hop's decision binds
        # every downstream hop); otherwise head-sample here.  Off path:
        # two attribute reads.
        tspan = (
            self._trace_begin(name, ctx)
            if not queueing
            and (ctx.trace_next is not None or _trace.ENABLED)
            else None
        )
        try:
            if tspan is None:
                reply = self._dispatch_deadlined(cmd, ctx, name)
            else:
                # Ambient scope: engine submits inside link this span,
                # so the trace stitches through the coalescer's launch
                # lifecycle (client leg → ingress → launch phases).
                with _trace.scope(tspan.ctx()):
                    reply = self._dispatch_deadlined(cmd, ctx, name)
        except ScriptKilledError:
            # SCRIPT KILL's async exception can land AFTER the script
            # body left its guarded block (next bytecode boundary):
            # absorb it here so a completed script's connection survives
            # with an error reply instead of the thread dying
            # (ScriptKilledError is a BaseException on purpose — scripts
            # can't swallow it — so the generic handler below misses it).
            self._script_unregister()  # the clear itself may have died
            err = True
            reply = _encode_error("Script killed by user with SCRIPT KILL...")
        except Exception as e:
            # RespError / TypeError-WRONGTYPE / generic all map through
            # the ONE shared helper the fused-run demux also uses.
            err = True
            reply = self._fused_error_frame(e)
        if not queueing:
            # One-shot licenses (ASKING, trace prelude) burn after ANY
            # dispatched command — see consume_one_shot_licenses (the
            # one copy of the discipline, shared with the netsim
            # protocol models).
            consume_one_shot_licenses(ctx, name)
        if not queueing and name not in _NONMUTATING:
            # Any executed command that may have changed keyspace state
            # retires every response-cache entry (coarse, cheap, safe —
            # the cache's whole window is one parsed-ahead batch).
            self._bump_write_epoch()
        dt = time.perf_counter() - t0
        if tspan is not None:
            tspan.end(error=err)
        obs = self.obs
        if obs is not None and not queueing:
            lm = obs.loadmap
            if lm is not None and lm.enabled:
                slot = getattr(ctx, "load_slot", 0)
                if slot is not None:
                    # O(1) per-slot accounting: lock-free array bumps
                    # (see obs/loadmap.py).  Bytes are the parsed argv
                    # and the encoded reply — wire-close without
                    # re-serializing anything.
                    lm.note_command(
                        slot, name not in _NONMUTATING,
                        sum(map(len, cmd)), len(reply),
                    )
                    rate = lm.sample_rate
                    if rate > 0.0 and _random.random() < rate:
                        keys = _command_keys(cmd)
                        if keys:
                            lm.sample_keys(keys)
            if self._blocked(name, cmd, ctx):
                # Condvar-parked wait is not execution time: a routine
                # `BLPOP q 30` would otherwise file a 30s SLOWLOG entry
                # and drive latencystats to +Inf (Redis also excludes
                # blocked time).  Calls/errors still count.
                obs.resp_commands.inc((name,))
                if err:
                    obs.resp_errors.inc((name,))
            else:
                obs.record_resp_command(name, dt, err)
                if obs.latency.threshold_ms > 0:
                    # LATENCY "command" event (ISSUE 13 parity).
                    obs.latency.record("command", dt * 1e3)
                sl = obs.slowlog
                if 0 <= sl.threshold_us <= dt * 1e6:
                    # Sanitize only for entries that will be kept.  A
                    # sampled command's trace id rides the entry
                    # (slow-trace auto-capture): TRACE GET <id> answers
                    # where the time went.
                    sl.maybe_add(
                        dt, self._slowlog_sanitize(name, cmd), ctx.addr,
                        ctx.client_name or "",
                        trace_id=(
                            tspan.trace_id if tspan is not None else ""
                        ),
                    )
        return reply

    def _dispatch_deadlined(self, cmd: list, ctx: "_ConnCtx",
                            name: str) -> bytes:
        """Deadline attach (ISSUE 7): every command gets its own fresh
        end-to-end deadline — connection override first, else the server
        default; 0/None → no deadline (ops block, the pre-overload
        behavior)."""
        dl_s = self._op_deadline_s(ctx)
        if dl_s is not None:
            with _overload.deadline_scope(dl_s):
                return self._dispatch(cmd, ctx, name)
        return self._dispatch(cmd, ctx, name)

    # -- fleet telemetry plane (ISSUE 13) ----------------------------------

    def _node_label(self) -> str:
        if self.cluster is not None:
            return self.cluster.myid
        return f"{self.host}:{self.port}"

    def _trace_begin(self, name: str, ctx: "_ConnCtx"):
        """Mint one command's ingress span: a parked RTPU.TRACE prelude
        forces it into the remote trace (one-shot consume), else
        head-sample against the live rate.  None = dice missed.
        ASKING never consumes the prelude — it is itself a prelude, and
        the migration pump's RTPU.TRACE + ASKING + RESTORE sequence
        must trace the RESTORE."""
        tr = self.obs.trace
        nxt = ctx.trace_next
        if nxt is not None and name != "ASKING":
            ctx.trace_next = None  # one-shot, like ASKING
            span = tr.start("resp:" + name, nxt[0], nxt[1])
        else:
            span = tr.maybe_start("resp:" + name)
            if span is None:
                return None
        span.annotate("node", self._node_label())
        if ctx.addr:
            span.annotate("addr", ctx.addr)
        rc = getattr(ctx, "_rconn", None)
        if rc is not None:
            # Reactor front door: which event-loop tick carried this
            # command (correlates the span with cross-connection batch
            # fusion inside that tick).
            span.annotate("tick", rc.reactor.tick_seq)
        return span

    def _monitor_feed(self, name: str, cmd: list,
                      ctx: "_ConnCtx") -> None:
        """Stream one dispatched command to every MONITOR connection
        (the redis monitor wire shape: ``+<unix.micros> [0 <addr>]
        "CMD" "arg" ...``).  Credentials are redacted exactly as in the
        slowlog; MONITOR itself and a monitor's own commands are not
        echoed.  Cross-thread sends ride each connection's ordered send
        path (reactor outbuf / conn write lock) — the same mechanism as
        pub/sub pushes."""
        if name == "MONITOR" or ctx.monitor:
            return
        shown = self._slowlog_sanitize(name, cmd)
        args = " ".join(
            '"%s"' % a.decode("latin-1", "replace")
            .replace("\\", "\\\\").replace('"', '\\"')
            for a in shown
        )
        line = (
            "+%.6f [0 %s] %s\r\n" % (time.time(), ctx.addr or "?", args)
        ).encode("latin-1", "replace")
        for mctx in tuple(self._monitors):
            try:
                mctx.send(line)
            except Exception:
                self._monitors.discard(mctx)

    @staticmethod
    def _blocked(name: str, cmd: list, ctx: "_ConnCtx") -> bool:
        """True when this invocation may have parked waiting for data —
        its wall time is wait, not work, so it must not feed latency
        histograms or the slowlog.  Inside EXEC every command runs
        non-blocking (recorded normally), and XREAD/XREADGROUP block
        only with an explicit BLOCK option."""
        if ctx.in_exec:
            return False
        if name in ("BLPOP", "BRPOP"):
            return True
        if name in ("XREAD", "XREADGROUP"):
            return any(a.upper() == b"BLOCK" for a in cmd[1:])
        return False

    # -- overload control plane (ISSUE 7) ----------------------------------

    def _op_deadline_s(self, ctx: "_ConnCtx") -> Optional[float]:
        """Effective op deadline for this connection, in relative
        seconds, or None for no deadline."""
        ms = ctx.op_deadline_ms
        if ms is None:
            ms = self.op_deadline_ms
        return ms / 1000.0 if ms and ms > 0 else None

    def _pressure(self) -> float:
        """Coalescer queue pressure (0 when the fronted engine has no
        coalescer — host engine / direct-dispatch mode)."""
        c = getattr(getattr(self._client, "_engine", None),
                    "coalescer", None)
        return c.pressure() if c is not None else 0.0

    def _pressure_over(self) -> bool:
        w = self.admission_watermark
        return w > 0 and self._pressure() > w

    def _count_ingress_shed(self, reason: str = "pressure") -> None:
        # Commands, not ops: a shed command's engine op count is
        # unknowable pre-parse, and mixing units into the ops-
        # denominated rtpu_shed_ops family would make its total
        # meaningless — ingress has its own command-denominated counter.
        self._ingress_shed += 1
        if self.obs is not None:
            self.obs.resp_ingress_shed.inc((reason,))

    def _ingress_tenant(self, cmd: list) -> Optional[str]:
        """Keyspace→tenant peek for the door (ROADMAP overload item
        (b)): the first argument of a keyed command IS the tenant name
        in this keyspace (per-tenant quotas are object-name-keyed), so
        the door can judge a tenant BEFORE any command parse.  None for
        keyless commands."""
        if len(cmd) < 2:
            return None
        return cmd[1].decode("latin-1", "replace")

    def _shed_at_ingress(self, name: str, cmd: list,
                         ctx: "_ConnCtx") -> Optional[str]:
        """The shed reason when this command must be refused with -BUSY
        (None = admit): exempt commands and in-flight transactions
        always pass (EXEC completes atomically once started; MULTI
        queueing is free — the whole transaction is judged once, at
        EXEC, in _cmdctx_EXEC).

        Tenant-aware shedding comes FIRST (ISSUE 10 satellite / ROADMAP
        overload item (b)): an over-quota tenant — token bucket empty or
        in-flight quota full — is refused at the door before its command
        even parses, so during one tenant's burst the burst is what gets
        shed, not the well-behaved tenants' traffic.  The general
        pressure watermark then sheds everyone non-exempt as before."""
        if name in _SHED_EXEMPT or ctx.in_exec or ctx.in_multi:
            return None
        gov = getattr(
            getattr(self._client, "_engine", None), "governor", None
        )
        if gov is not None and gov.active:
            tenant = self._ingress_tenant(cmd)
            if tenant is not None and gov.peek_over_quota(tenant):
                self._count_ingress_shed("tenant")
                return "tenant"
        if not self._pressure_over():
            return None
        self._count_ingress_shed("pressure")
        return "pressure"

    def _note_slow_client(self, cause: str, pending: int) -> None:
        self._slow_client_kills += 1
        if self.obs is not None:
            self.obs.slow_client_disconnects.inc((cause,))

    # -- front-door vectorization (ISSUE 6 tentpole) -----------------------

    def _bump_write_epoch(self) -> None:
        with self._epoch_lock:
            self._write_epoch += 1

    @staticmethod
    def _fused_error_frame(e: BaseException) -> bytes:
        """THE exception → reply-frame mapping, shared by
        _safe_dispatch's except arms and the fused-run demux — one
        implementation, so a fused run's per-command error bytes can
        never drift from what sequential dispatch would have replied
        (the byte-identical contract).  Kind guards raise TypeError —
        clients key on the WRONGTYPE code (redis-py maps it to a
        dedicated exception class)."""
        if isinstance(e, RespError):
            return _encode_error(str(e))
        if isinstance(e, DeadlineExceededError):
            # Overload control plane (ISSUE 7): deadline sheds surface
            # as the retryable -BUSY family, like redis-server's
            # busy-state refusals.
            return _encode_error(f"BUSY RTPU op deadline exceeded: {e}")
        if isinstance(e, TenantThrottledError):
            return _encode_error(f"BUSY RTPU tenant throttled: {e}")
        if isinstance(e, TypeError):
            return _encode_error(
                "WRONGTYPE Operation against a key holding the wrong kind "
                f"of value ({e})"
            )
        return _encode_error(f"{type(e).__name__}: {e}")

    def _dispatch_pipeline(self, batch, ctx: "_ConnCtx"):
        """Vectorized dispatch of one parsed-ahead batch from ONE
        connection (the thread-per-connection path): every item shares
        the connection's ctx."""
        return self._dispatch_merged(batch, [ctx] * len(batch))

    @staticmethod
    def _ctx_fusable(ctx: "_ConnCtx") -> bool:
        """Whether this connection's items may join a fused run right
        now: an unauthenticated connection must see NOAUTH per command,
        and a MULTI-queued command must queue, not execute."""
        return ctx.authed and not ctx.in_multi

    @classmethod
    def _fuse_compat(cls, head_ctx: "_ConnCtx", ctx: "_ConnCtx") -> bool:
        """Whether ``ctx``'s items may join a run HEADED by
        ``head_ctx``'s: fusable, and carrying the SAME per-connection
        deadline override — the run executes under ONE deadline scope
        (the head's), so a CLIENT DEADLINE connection fused into a
        no-deadline run would silently lose its overload contract.  A
        member carrying a trace prelude never fuses: its ingress span
        (and the prelude's one-shot consume) live on the sequential
        path (ISSUE 13)."""
        return (
            cls._ctx_fusable(ctx)
            and ctx.op_deadline_ms == head_ctx.op_deadline_ms
            # getattr: model-check harnesses drive the collectors with
            # minimal fake ctxs that predate the trace field.
            # rtpulint: disable=RT012 fusion FENCE, not a dispatch: a prelude-carrying command never fuses — it is barriered to the sequential path where _safe_dispatch burns the license via consume_one_shot_licenses
            and getattr(ctx, "trace_next", None) is None
        )

    def _dispatch_merged(self, batch, ctxs):
        """Vectorized dispatch of one command window.  ``batch[i]``
        belongs to connection ``ctxs[i]`` — the thread-per-connection
        path passes one shared ctx, the reactor passes one tick's merged
        cross-connection batch (each connection's items appear in its
        own arrival order, so per-connection ordering is preserved by
        construction).  Scans for runs of adjacent same-family commands
        — ACROSS connection boundaries — and fuses each run into one
        engine call, demuxing the packed result into per-command replies
        in window order; everything else (and every command while its
        connection is in MULTI / unauthenticated / script-BUSY state)
        dispatches sequentially, so per-connection semantics are
        bit-identical to the unfused path.  Returns (frames, consumed):
        ``consumed`` < len(batch) when the bounded reply buffer filled —
        the caller re-queues the tail (``frames[k]`` answers
        ``batch[k]`` for k < consumed)."""
        out: list = []
        size = 0
        i = 0
        n = len(batch)
        # Overload (ISSUE 7): while pressure is over the watermark,
        # skip run fusion so every command flows through _safe_dispatch
        # and the ingress shed check there — a fused run would bypass
        # it.  (Checked once per parsed-ahead batch; the per-command
        # check re-reads live pressure.)
        overloaded = self._pressure_over()
        # Per-window response cache: (name, *argv) -> reply frame, valid
        # while the write epoch is unmoved.  Shared across the window's
        # connections on purpose: entries key on exact argv and the
        # server-wide write epoch only, so a frame one connection
        # computed is exactly the frame any other would compute in the
        # same epoch.
        rc: dict = {}
        rc_cap = self.response_cache_size
        rc_state = [self._write_epoch]
        obs = self.obs
        while i < n:
            if size >= (1 << 20):
                break
            cmd = batch[i]
            ctx = ctxs[i]
            name = cmd[0].decode("latin-1", "replace").upper()
            plain = (
                self.vectorize
                and self._ctx_fusable(ctx)
                and not self._script_busy()
                # Telemetry barriers (ISSUE 13): while a MONITOR is
                # attached every command must flow through
                # _safe_dispatch to feed the stream; a command carrying
                # a trace prelude takes the sequential path so its
                # ingress span (and the one-shot consume) happen there.
                and not self._monitors
                # rtpulint: disable=RT012 fusion FENCE, not a dispatch: the prelude-carrying command falls through to _safe_dispatch below, which burns every license via consume_one_shot_licenses
                and getattr(ctx, "trace_next", None) is None
            )
            if plain and rc_cap > 0 and name in _CACHEABLE:
                hit = self._rc_probe(rc, rc_state, name, cmd)
                if hit is not None:
                    ctx.asking = False  # a served command consumes it
                    out.append(hit)
                    size += len(hit)
                    i += 1
                    continue
            run = (
                self._scan_run(batch, i, ctxs)
                if plain and not overloaded else None
            )
            if run is not None:
                if (
                    self._op_deadline_s(ctx) is None
                    and self._run_readonly(run)
                ):
                    # Submit-ahead span: back-to-back READ-ONLY runs
                    # submit their engine calls first, then resolve in
                    # window order — the launches overlap in the
                    # coalescer instead of the window serializing
                    # behind one .result() at a time.  Read-only only:
                    # a write run's epoch bump lands at resolve, and
                    # submitting past it could let a later member's
                    # cache probe serve a stale pre-write frame.
                    # (Span members skip the loop-top response-cache
                    # probe; the frames a run computes are identical to
                    # what the cache held, so bytes cannot differ.)
                    spans = [(i, run, self._submit_run(run))]
                    jj = run[1]
                    span_conns = {id(c) for c in ctxs[i:jj]}
                    while jj < n and len(spans) < 8:
                        if not (
                            self.vectorize
                            and self._ctx_fusable(ctxs[jj])
                            and self._op_deadline_s(ctxs[jj]) is None
                            and not self._script_busy()
                            and getattr(
                                ctxs[jj], "trace_next", None
                            ) is None
                        ):
                            # (A deadline-carrying connection's run must
                            # execute under its deadline_scope — the
                            # _exec_run path — never as a bare span
                            # member.)
                            break
                        nxt = self._scan_run(batch, jj, ctxs)
                        if nxt is None or not self._run_readonly(nxt):
                            break
                        nxt_conns = {id(c) for c in ctxs[jj:nxt[1]]}
                        if span_conns & nxt_conns:
                            # One in-flight run per CONNECTION: a
                            # connection's later run submitted before
                            # its earlier run's observation point could
                            # show a concurrent writer's effects out of
                            # program order (later command reflecting
                            # OLDER state).  Runs of disjoint
                            # connections carry no mutual ordering
                            # contract — they overlap freely.
                            break
                        spans.append((jj, nxt, self._submit_run(nxt)))
                        span_conns |= nxt_conns
                        jj = nxt[1]
                    for pos, r, sub in spans:
                        frames, rj = self._resolve_run(
                            r, sub, batch, pos, ctxs, rc, rc_state
                        )
                        for c in ctxs[pos:rj]:
                            c.asking = False  # served: license consumed
                        if obs is not None and len(
                            {id(c) for c in ctxs[pos:rj]}
                        ) > 1:
                            obs.cross_conn_fused_ops.inc(
                                (r[0],), self._run_nops(r, pos, rj)
                            )
                        out.extend(frames)
                        size += sum(len(f) for f in frames)
                        i = rj
                        if rj < r[1]:
                            # mget reply-byte cut: the tail (and any
                            # later READ-ONLY span member — re-running
                            # a read is free) re-queues.
                            break
                    continue
                frames, j = self._exec_run(run, batch, i, ctxs, rc, rc_state)
                for c in ctxs[i:j]:
                    c.asking = False  # served: ASKING license consumed
                if obs is not None and len(
                    {id(c) for c in ctxs[i:j]}
                ) > 1:
                    # Cross-connection fusion (ISSUE 11): these ops
                    # launched together with ops from other connections
                    # — single-command clients got batch economics.
                    obs.cross_conn_fused_ops.inc(
                        (run[0],), self._run_nops(run, i, j)
                    )
                out.extend(frames)
                size += sum(len(f) for f in frames)
                i = j
                continue
            frame = self._safe_dispatch(cmd, ctx)
            if (
                plain and rc_cap > 0 and name in _CACHEABLE
                and not frame.startswith(b"-")
                and (
                    self.cluster is None
                    or self.cluster.frame_cacheable(name, cmd)
                )
                # Cluster gate: a frame computed for a migrating/
                # importing slot (an ASKING-served read, a mid-
                # migration value) must not serve a later identical
                # command that would have been redirected.
            ):
                self._rc_install(rc, rc_state, name, cmd, frame)
            out.append(frame)
            size += len(frame)
            i += 1
        return out, i

    @staticmethod
    def _run_nops(run, i: int, end: int) -> int:
        """Engine ops a fused-run descriptor carried — ``end`` is the
        position execution actually reached (an mget run can be cut by
        the reply-byte bound; its requeued tail must not be counted
        here AND again when it re-dispatches)."""
        fam = run[0]
        if fam == "mget":
            return end - i
        return len(run[3])

    # response-cache plumbing: rc_state[0] holds the epoch the window's
    # entries were installed under; any bump wipes the window.

    def _rc_probe(self, rc, rc_state, name, cmd):
        cur = self._write_epoch
        if cur != rc_state[0]:
            rc.clear()
            rc_state[0] = cur
            if self.obs is not None:
                self.obs.resp_cache_misses.inc()
            return None
        hit = rc.get((name, *cmd[1:]))
        obs = self.obs
        if obs is not None:
            if hit is not None:
                obs.resp_cache_hits.inc()
                # The command "executed" from the cache: calls still
                # count (INFO commandstats parity).
                obs.resp_commands.inc((name,))
            else:
                obs.resp_cache_misses.inc()
        return hit

    def _rc_install(self, rc, rc_state, name, cmd, frame) -> None:
        if len(frame) > (8 << 10):  # bound per-entry bytes
            return
        cur = self._write_epoch
        if cur != rc_state[0]:
            # A write landed between this command's probe and now: the
            # window dies — and THIS frame may predate that write, so it
            # must be dropped, never re-homed under the new epoch (a
            # pre-write reply cached under the post-write epoch would
            # outlive the write that obsoleted it).
            rc.clear()
            rc_state[0] = cur
            return
        if len(rc) < self.response_cache_size:
            rc[(name, *cmd[1:])] = frame

    # -- run scanning ------------------------------------------------------

    def _scan_run(self, batch, i, ctxs):
        """A fused-run descriptor starting at ``batch[i]``, or None.
        Runs are maximal spans of adjacent commands of one family (same
        target object for bf/bitset/cms), possibly spanning CONNECTION
        boundaries in a merged window; any non-member — including a
        malformed member whose sequential dispatch would error, or a
        member whose connection is mid-MULTI / unauthenticated — ends
        the run and dispatches sequentially (a run barrier)."""
        first = batch[i][0].upper()
        if self.cluster is not None and (
            first in _BF_RUN or first in _BIT_RUN or first in _GET_RUN
            or first == b"CMS.QUERY"
        ):
            # Cluster mode (ISSUE 12): fusing must never skip a redirect
            # judgment.  bf/bit/cms runs share ONE key, so gating the
            # head covers the whole run; GET/MGET runs mix keys (and so
            # slots) AND resolve under the grid lock — routing them
            # there would add a grid.store -> cluster.move edge against
            # MIGRATE's cluster.move -> grid.store, so they dispatch
            # per-command (the slot-aware scatter/gather client is the
            # cluster-mode batching path).
            if first in _GET_RUN:
                return None
            if len(batch[i]) < 2 or not self.cluster.serves_plainly(
                batch[i][1]
            ):
                return None
        if first in _BF_RUN:
            return self._collect_bf_run(batch, i, ctxs)
        if first in _BIT_RUN:
            return self._collect_bit_run(batch, i, ctxs)
        if first in _GET_RUN:
            return self._collect_get_run(batch, i, ctxs)
        if first == b"CMS.QUERY":
            return self._collect_cms_run(batch, i, ctxs)
        return None

    @classmethod
    def _collect_bf_run(cls, batch, i, ctxs):
        cmd = batch[i]
        if len(cmd) < 3:
            return None
        key = cmd[1]
        items: list = []
        flags: list = []
        shape: list = []  # (upper name str, nops, many) per command
        j = i
        while j < len(batch) and len(items) < _RUN_MAX_OPS:
            c = batch[j]
            spec = _BF_RUN.get(c[0].upper())
            if (
                spec is None or len(c) < 3 or c[1] != key
                or not cls._fuse_compat(ctxs[i], ctxs[j])
            ):
                break
            is_add, many = spec
            ops = c[2:] if many else c[2:3]
            items.extend(ops)
            flags.extend([is_add] * len(ops))
            shape.append(
                (c[0].decode("latin-1", "replace").upper(), len(ops), many)
            )
            j += 1
        if j - i < 2:
            return None
        return ("bloom", j, key, items, flags, shape)

    @classmethod
    def _collect_bit_run(cls, batch, i, ctxs):
        key = batch[i][1] if len(batch[i]) >= 2 else None
        idx: list = []
        kinds: list = []  # 0 = get, 1 = clear, 2 = set
        names: list = []
        j = i
        while j < len(batch) and len(idx) < _RUN_MAX_OPS:
            c = batch[j]
            nm = c[0].upper()
            if not cls._fuse_compat(ctxs[i], ctxs[j]):
                break
            if nm == b"GETBIT":
                if len(c) < 3 or c[1] != key:
                    break
                try:
                    off = int(c[2])
                except ValueError:
                    break
                if off < 0:
                    break
                idx.append(off)
                kinds.append(0)
            elif nm == b"SETBIT":
                if len(c) < 4 or c[1] != key:
                    break
                try:
                    off, val = int(c[2]), int(c[3])
                except ValueError:
                    break
                if off < 0:
                    break
                idx.append(off)
                kinds.append(2 if val else 1)
            else:
                break
            names.append(c[0].decode("latin-1", "replace").upper())
            j += 1
        if j - i < 2:
            return None
        return ("bitset", j, key, idx, kinds, names)

    @classmethod
    def _collect_get_run(cls, batch, i, ctxs):
        j = i
        while j < len(batch):
            c = batch[j]
            if (
                c[0].upper() not in _GET_RUN or len(c) < 2
                or not cls._fuse_compat(ctxs[i], ctxs[j])
            ):
                break
            j += 1
        if j - i < 2:
            return None
        return ("mget", j, None, None, None, None)

    @classmethod
    def _collect_cms_run(cls, batch, i, ctxs):
        """Adjacent CMS.QUERY commands on one sketch fuse into a single
        ``estimate_all`` call (ISSUE 11 satellite / ROADMAP near-cache
        reach): the merged item vector rides the engine's
        ``lookup_batch`` partial-hit split — cached estimates answer
        from the near cache, ONLY the misses ride the coalescer."""
        cmd = batch[i]
        if len(cmd) < 3:
            return None
        key = cmd[1]
        items: list = []
        shape: list = []  # nops per command
        j = i
        while j < len(batch) and len(items) < _RUN_MAX_OPS:
            c = batch[j]
            if (
                c[0].upper() != b"CMS.QUERY" or len(c) < 3 or c[1] != key
                or not cls._fuse_compat(ctxs[i], ctxs[j])
            ):
                break
            items.extend(c[2:])
            shape.append(len(c) - 2)
            j += 1
        if j - i < 2:
            return None
        return ("cms", j, key, items, shape, None)

    # -- run execution -----------------------------------------------------

    def _exec_run(self, run, batch, i, ctxs, rc, rc_state):
        # The fused run is ONE engine call serving many commands: one
        # shared deadline covers it — the run's FIRST connection's
        # deadline, when the run spans connections (per-command scopes
        # re-stamp inside the mget fam's _safe_dispatch calls).
        dl_s = self._op_deadline_s(ctxs[i])
        if dl_s is None:
            return self._exec_run_inner(run, batch, i, ctxs, rc, rc_state)
        with _overload.deadline_scope(dl_s):
            return self._exec_run_inner(run, batch, i, ctxs, rc, rc_state)

    def _exec_run_inner(self, run, batch, i, ctxs, rc, rc_state):
        return self._resolve_run(
            run, self._submit_run(run), batch, i, ctxs, rc, rc_state
        )

    @staticmethod
    def _run_readonly(run) -> bool:
        """True when executing this run cannot mutate keyspace state —
        the submit-ahead span condition (_dispatch_merged): a WRITE
        run's epoch bump lands at resolve time, so submitting past one
        could let a later span member's response-cache probe serve a
        stale pre-write frame."""
        fam = run[0]
        if fam in ("mget", "cms"):
            return True
        if fam == "bloom":
            return not any(run[4])
        return all(k == 0 for k in run[4])  # bitset

    def _submit_run(self, run):
        """Phase 1 of a fused run: build and SUBMIT the engine call(s)
        without waiting; returns an opaque token for _resolve_run.
        Back-to-back read-only runs submit ahead of the first resolve
        (_dispatch_merged), so their launches overlap in the coalescer
        instead of serializing the window behind one .result() at a
        time (ISSUE 11: a reactor tick is the whole front door — a
        blocked tick blocks every connection)."""
        fam = run[0]
        t0 = time.perf_counter()
        if fam == "mget":
            return (t0, None, None)  # host-side: executes at resolve
        if fam == "cms":
            try:
                return (t0, self._cms(run[2]).estimate_all_async(run[3]),
                        None)
            except Exception as e:
                return (t0, None, e)
        if fam == "bloom":
            _, _, key, items, flags, _shape = run
            try:
                bf = self._client.get_bloom_filter(self._s(key))
                if not any(flags):
                    fut = bf.contains_all_async(items)
                elif all(flags):
                    fut = bf.add_all_async(items)
                else:
                    fut = bf.mixed_async(items, np.asarray(flags, bool))
                return (t0, fut, None)
            except Exception as e:
                return (t0, None, e)
        # fam == "bitset"
        _, _, key, idx, kinds, _names = run
        err = None
        groups: list = []  # (start, end, future-or-exception)
        try:
            bs = self._client.get_bit_set(self._s(key))
            p = 0
            while p < len(kinds):
                q = p + 1
                while q < len(kinds) and kinds[q] == kinds[p]:
                    q += 1
                sel = idx[p:q]
                if kinds[p] == 0:
                    groups.append((p, q, bs.get_many_async(sel)))
                else:
                    groups.append(
                        (p, q, bs.set_many_async(sel, kinds[p] == 2))
                    )
                p = q
        except Exception as e:
            # Submit-time failure: nothing later can have applied —
            # every not-yet-grouped op fails with the same error.
            err = e
            done = groups[-1][1] if groups else 0
            groups.append((done, len(kinds), e))
        return (t0, groups, err)

    def _resolve_run(self, run, sub, batch, i, ctxs, rc, rc_state):
        """Phase 2 of a fused run: wait for the submission, demux
        per-command reply frames in window order, feed the response
        cache, bump the write epoch for runs that wrote, and record
        stats."""
        fam, j = run[0], run[1]
        t0, handle, err = sub
        if fam == "mget":
            # One grid pass: the whole read run executes under a single
            # grid-lock hold (handlers re-enter the RLock for free), and
            # repeated identical reads inside the run serve from the
            # response cache.  The run stops early once it has buffered
            # the reply-byte bound — the caller re-queues the tail (same
            # 1 MB discipline the per-command loop enforces).
            frames = []
            size = 0
            grid = self._client._grid
            with grid.lock:
                for k in range(i, j):
                    if size >= (1 << 20):
                        j = k
                        break
                    cmd = batch[k]
                    name = cmd[0].decode("latin-1", "replace").upper()
                    # The run's FIRST command was already probed (and
                    # missed) by the caller — re-probing would double-
                    # count resp_cache_misses.
                    hit = (
                        self._rc_probe(rc, rc_state, name, cmd)
                        if k > i and self.response_cache_size > 0
                        else None
                    )
                    if hit is not None:
                        frames.append(hit)
                        size += len(hit)
                        continue
                    frame = self._safe_dispatch(cmd, ctxs[k])
                    if (
                        self.response_cache_size > 0
                        and not frame.startswith(b"-")
                    ):
                        self._rc_install(rc, rc_state, name, cmd, frame)
                    frames.append(frame)
                    size += len(frame)
            # names=None: each command's stats were recorded by its own
            # _safe_dispatch above (the run is lock-amortization + the
            # response cache, not an engine-call fusion — it still counts
            # toward the "mget" family per the ISSUE's GET/MGET-run
            # definition, so the fusion ratio is interpretable against
            # the per-family breakdown in rtpu_resp_fused_cmds).
            self._count_fused(fam, j - i, j - i, None, 0.0)
            return frames, j
        if fam == "cms":
            # One estimate_all call for the whole run: the merged item
            # vector rides the near cache's lookup_batch partial-hit
            # split, so cached estimates never touch the device and only
            # misses ride the coalescer (ROADMAP near-cache reach).
            _, _, key, items, shape, _ = run
            vals = None
            if err is None:
                try:
                    vals = np.asarray(handle.result())
                except Exception as e:
                    err = e
            frames = []
            pos = 0
            names = []
            for nops in shape:
                names.append("CMS.QUERY")
                if err is not None:
                    frames.append(self._fused_error_frame(err))
                else:
                    frames.append(
                        _encode_array(
                            [int(v) for v in vals[pos : pos + nops]]
                        )
                    )
                pos += nops
            self._install_read_frames(
                rc, rc_state, batch, i, names, frames,
                readable=("CMS.QUERY",), err=err, wrote=False,
            )
            self._count_fused(
                fam, j - i, len(items), names,
                time.perf_counter() - t0, err=err,
            )
            self._note_run_load(run, batch, i, frames, write=False)
            return frames, j
        if fam == "bloom":
            _, _, key, items, flags, shape = run
            vals = None
            any_add = any(flags)
            if err is None:
                try:
                    vals = handle.result()
                except Exception as e:
                    err = e
            if any_add:
                self._bump_write_epoch()
            frames = []
            pos = 0
            names = []
            for nm, nops, many in shape:
                names.append(nm)
                if err is not None:
                    frames.append(self._fused_error_frame(err))
                elif many:
                    frames.append(
                        _encode_array(
                            [int(v) for v in vals[pos : pos + nops]]
                        )
                    )
                else:
                    frames.append(_encode_int(int(vals[pos])))
                pos += nops
            self._install_read_frames(
                rc, rc_state, batch, i, [s[0] for s in shape], frames,
                readable=("BF.EXISTS", "BF.MEXISTS"), err=err,
                wrote=any_add,
            )
            self._count_fused(
                fam, j - i, len(items), names,
                time.perf_counter() - t0, err=err,
            )
            self._note_run_load(run, batch, i, frames, write=any_add)
            return frames, j
        # fam == "bitset"
        _, _, key, idx, kinds, names = run
        any_write = any(k != 0 for k in kinds)
        groups = handle  # (start, end, future-or-exception) spans
        if any_write:
            self._bump_write_epoch()
        frames: list = [None] * len(kinds)
        # Resolve PER GROUP: consecutive groups joined one coalescer
        # segment (one launch), but a terminal failure can still be
        # group-scoped (a migration-split launch, a breaker opening
        # mid-run) — an earlier group's applied writes must answer their
        # real results, only the failed group's commands get the error
        # (the sequential path's granularity).
        for p, q, fut in groups:
            if isinstance(fut, BaseException):
                e = fut
            else:
                try:
                    vals = np.asarray(fut.result()).reshape(-1)
                    for o in range(p, q):
                        frames[o] = _encode_int(int(bool(vals[o - p])))
                    continue
                except Exception as ex:
                    e = ex
            err = err or e
            ef = self._fused_error_frame(e)
            for o in range(p, q):
                frames[o] = ef
        self._install_read_frames(
            rc, rc_state, batch, i, names, frames,
            readable=("GETBIT",), err=err, wrote=any_write,
        )
        self._count_fused(
            fam, j - i, len(idx), names, time.perf_counter() - t0, err=err,
        )
        self._note_run_load(run, batch, i, frames, write=any_write)
        return frames, j

    def _note_run_load(self, run, batch, i, frames, write: bool) -> None:
        """Per-slot accounting for one fused engine run (ISSUE 16): the
        run is ONE O(1) accounting event carrying all its ops — its
        member commands never pass _safe_dispatch.  mget runs are
        excluded (their members DO dispatch through _safe_dispatch,
        which accounts each one; they also only exist standalone).
        The run key stands in for the sampled key stream, weighted by
        the run's op count."""
        lm = self.loadmap
        if lm is None or not lm.enabled:
            return
        key, end = run[2], run[1]
        nops = self._run_nops(run, i, end)
        slot = _key_slot(key) if self.cluster is not None else 0
        lm.note_command(
            slot, write,
            sum(sum(map(len, batch[k])) for k in range(i, end)),
            sum(len(f) for f in frames if f is not None),
            nops=nops,
        )
        rate = lm.sample_rate
        if rate > 0.0 and _random.random() < rate:
            lm.sample_keys([key], nops)

    def _install_read_frames(self, rc, rc_state, batch, i, names, frames,
                             readable, err, wrote) -> None:
        """Feed a fused run's READ replies into the response-cache window
        (a later identical read in this pipeline serves for free).
        ``wrote``: the run contained writes — its read frames may have
        been computed BEFORE a same-key write later in the run, so none
        may be cached (the run's own epoch bump also refuses them in
        _rc_install; this skip is the cheap explicit form)."""
        if err is not None or wrote or self.response_cache_size <= 0:
            return
        for off, nm in enumerate(names):
            if nm in readable:
                self._rc_install(
                    rc, rc_state, nm, batch[i + off], frames[off]
                )

    def _count_fused(self, fam, ncmds, nops, names, dt, err=None) -> None:
        obs = self.obs
        if obs is None:
            return
        obs.resp_fused_runs.inc((fam,))
        obs.resp_fused_cmds.inc((fam,), ncmds)
        obs.resp_fused_ops.inc((fam,), nops)
        if names:
            # Per-command stats parity (INFO commandstats): each fused
            # command counts a call, with the run's wall time amortized.
            per = dt / max(1, ncmds)
            for nm in names:
                obs.record_resp_command(nm, per, err is not None)

    @staticmethod
    def _slowlog_sanitize(name: str, cmd: list) -> list:
        """Credentials must never sit in the slow-op ring (Redis
        obfuscates these the same way): AUTH's arguments and the two
        args after a HELLO ... AUTH token are replaced."""
        if name == "AUTH":
            return [cmd[0]] + [b"(redacted)"] * (len(cmd) - 1)
        if name == "HELLO":
            out = list(cmd)
            for i, a in enumerate(out):
                if i > 0 and a.upper() == b"AUTH":
                    for j in range(i + 1, min(i + 3, len(out))):
                        out[j] = b"(redacted)"
                    break
            return out
        return cmd

    def _dispatch(self, cmd: list[bytes], ctx: "_ConnCtx",
                  name: Optional[str] = None) -> bytes:
        if name is None:  # _safe_dispatch passes the decoded name along
            name = cmd[0].decode().upper()
        kill = self._script_kill
        if kill is not None and kill[0] is threading.current_thread():
            # Cooperative SCRIPT KILL boundary: async-exception delivery
            # (PyThreadState_SetAsyncExc) is LOSSY — an exception that
            # materializes inside a weakref/__del__ callback is reported
            # as "unraisable" and swallowed, never reaching the script.
            # A killed script that issues redis.call dies HERE instead,
            # synchronously and reliably (pure-Python loops are covered
            # by the re-posting reaper in _cmd_SCRIPT).
            with self._script_lock:
                if self._script_kill is kill:
                    self._script_kill = None
                    raise ScriptKilledError()
        if not ctx.authed and name not in ("AUTH", "HELLO", "QUIT", "RESET"):
            # Pre-auth surface is AUTH/HELLO/QUIT/RESET, like Redis
            # (pooled clients RESET connections before authenticating).
            raise RespError("NOAUTH Authentication required.")
        link = self.replica_link
        if link is not None and not ctx.is_peer:
            # Replica role (ISSUE 18): reads-only.  Writes arrive solely
            # over the replication link — a client write accepted here
            # would fork this replica's history from its primary's.
            if (name not in _NONMUTATING and name not in _REPLICA_ADMIN
                    and not name.startswith("RTPU.")):
                raise RespError(
                    "READONLY You can't write against a read only replica."
                )
            bound = int(getattr(
                self._client.config, "repl_max_staleness_ops", 0
            ) or 0)
            if (bound > 0 and name in _NONMUTATING
                    and link.lag_ops() > bound and _command_keys(cmd)):
                # Bounded staleness: a keyed read on a replica that has
                # fallen more than the configured op count behind is
                # refused (retryable) instead of served silently stale.
                events = self._events()
                if events is not None:
                    events.emit("repl.stale_read", severity="warn",
                                lag=link.lag_ops(), bound=bound,
                                cmd=name)
                raise RespError(
                    f"STALEREAD replica is {link.lag_ops()} ops behind "
                    f"its primary (bound {bound}); retry or read the "
                    "primary"
                )
        if name in _SCRIPT_CMDS and not self._scripts_enabled:
            # Script bodies are Python: gated off by default (see
            # __init__).  Checked at dispatch so MULTI-queued scripts hit
            # the same wall at EXEC.
            raise RespError(
                "scripting is disabled (script bodies are Python; enable "
                "with enable_python_scripts=True — requires requirepass "
                "or a loopback bind)"
            )
        if name not in (
            "SCRIPT", "SHUTDOWN", "AUTH", "HELLO", "QUIT", "RESET",
        ) and self._script_busy():
            # A script has exceeded script_timeout_ms on another
            # connection: Redis's busy-script contract — refuse rather
            # than queue invisibly behind the grid lock.
            raise RespError(
                "BUSY Redis is busy running a script. You can only call "
                "SCRIPT KILL or SHUTDOWN NOSAVE."
            )
        shed = self._shed_at_ingress(name, cmd, ctx)
        if shed is not None:
            # Overload control plane (ISSUE 7 + the ISSUE 10 tenant
            # peek): refuse engine-bound work at the door (the -BUSY
            # retryable surface) instead of letting it buy unbounded
            # queue wait.  Strictly pre-dispatch: a shed command was
            # never executed, so no acked state is involved.
            lm = getattr(self, "loadmap", None)
            if lm is not None and lm.enabled:
                # Shed accounting (ISSUE 16): a shed command is demand
                # the node refused — the rebalancer needs it ON the
                # slot (a slot whose load is all shed is the hottest
                # signal there is).  The route point never ran, so
                # hash the keys here; keyless shed lands in slot 0.
                slot = 0
                if self.cluster is not None:
                    keys = _command_keys(cmd)
                    if keys:
                        slot = _key_slot(keys[0])
                lm.note_shed(slot)
                ctx.load_slot = None  # refused, not served: no op bump
            if shed == "tenant":
                raise RespError(
                    "BUSY RTPU tenant over quota: command shed at "
                    "ingress; retry later"
                )
            raise RespError(
                "BUSY RTPU overloaded: command shed at ingress (queue "
                f"pressure {self._pressure():.2f} over watermark "
                f"{self.admission_watermark:g}); retry later"
            )
        if ctx.in_multi and name not in ("EXEC", "DISCARD", "MULTI", "RESET"):
            # Redis MULTI semantics: commands queue (validated for
            # existence only) and run contiguously at EXEC.  Pub/sub
            # commands are rejected like Redis does — their push replies
            # would break the EXEC array framing.
            if name in ("SUBSCRIBE", "UNSUBSCRIBE"):
                ctx.queued = None  # poison: EXEC must abort
                raise RespError(
                    f"{name} is not allowed in transactions"
                )
            if getattr(
                self, "_cmd_" + name.replace(".", "_"), None
            ) is None and getattr(
                self, "_cmdctx_" + name.replace(".", "_"), None
            ) is None:
                ctx.queued = None  # poison: EXEC must abort
                raise RespError(f"unknown command '{name}'")
            if self.cluster is not None:
                # Cluster routing at QUEUE time (Redis semantics): a
                # wrong-slot member surfaces its redirect NOW and
                # poisons the transaction (EXECABORT), so EXEC can
                # never half-apply a transaction whose tail belonged
                # to another node.  (EXEC re-routes each member too —
                # defense against a reshard between queue and EXEC.)
                frame, _ = self.cluster.route(name, cmd, ctx)
                if frame is not None:
                    ctx.queued = None  # poison: EXEC must abort
                    return frame
            if ctx.queued is not None:
                ctx.queued.append(cmd)
            return _encode_simple("QUEUED")
        mc = self.multicore
        if mc is not None:
            # Per-core front door (ISSUE 17): keyed commands owned by a
            # sibling worker take the in-node handoff leg; fan-out
            # commands merge across the workers.  Runs BEFORE the
            # cluster door so a handed-off command is judged by the
            # slot OWNER's door — the in-node map itself never emits
            # -MOVED (redirects describe the cluster, not node guts).
            frame = mc.route(name, cmd, ctx)
            if frame is not None:
                return frame
        if self.cluster is not None:
            # Cluster routing (ISSUE 12): redirect frames short-circuit
            # the handler; commands on a MIGRATING slot run under the
            # move guard WITH a presence re-check — a command that
            # routed "serve locally" while the migration pump was
            # mid-key must not proceed after the key shipped (it would
            # resurrect the key on the source and strand the acked
            # write when the slot finalizes).
            frame, guarded = self.cluster.route(name, cmd, ctx)
            if frame is not None:
                return frame
            if guarded:
                with self.cluster.move_lock:
                    frame = self.cluster.route_recheck(name, cmd)
                    if frame is not None:
                        return frame
                    return self._invoke_handler(name, cmd, ctx)
        return self._invoke_handler(name, cmd, ctx)

    def _invoke_handler(self, name: str, cmd: list, ctx: "_ConnCtx") -> bytes:
        ctx_handler = getattr(self, "_cmdctx_" + name.replace(".", "_"), None)
        if ctx_handler is not None:  # connection-stateful (pub/sub)
            return ctx_handler([c for c in cmd[1:]], ctx)
        handler = getattr(self, "_cmd_" + name.replace(".", "_"), None)
        if handler is None:
            raise RespError(f"unknown command '{name}'")
        return handler([c for c in cmd[1:]])

    @staticmethod
    def _s(b: bytes) -> str:
        return b.decode()

    @staticmethod
    def _raw(obj):
        """Foreign clients speak raw bytes: bypass the configured codec."""
        obj._enc = lambda v: v if isinstance(v, bytes) else str(v).encode()
        obj._dec = lambda v: v
        return obj

    # transactions (→ the reference's REDIS_WRITE_ATOMIC batch mode,
    # SURVEY §3.4: commands queue client-side and execute contiguously
    # at EXEC on this connection's thread)

    def _cmdctx_MULTI(self, args, ctx: _ConnCtx):
        if ctx.in_multi:
            raise RespError("MULTI calls can not be nested")
        ctx.in_multi = True
        ctx.queued = []
        return _encode_simple("OK")

    def _cmdctx_EXEC(self, args, ctx: _ConnCtx):
        if not ctx.in_multi:
            raise RespError("EXEC without MULTI")
        queued, ctx.queued, ctx.in_multi = ctx.queued, [], False
        if queued is None:  # a queue-time error poisons the transaction
            raise RespError(
                "EXECABORT Transaction discarded because of previous errors"
            )
        if queued and self._pressure_over() and any(
            c[0].decode("latin-1", "replace").upper() not in _SHED_EXEMPT
            for c in queued
        ):
            # Overload door for transactions (ISSUE 7): MULTI queueing
            # is free, so the judgment lands HERE, before any queued
            # command executes — otherwise wrapping work in MULTI/EXEC
            # would bypass ingress shedding entirely.  The transaction
            # is consumed (EXECABORT semantics), nothing partial ran.
            self._count_ingress_shed()
            raise RespError(
                "BUSY RTPU overloaded: transaction shed at EXEC (queue "
                f"pressure {self._pressure():.2f} over watermark "
                f"{self.admission_watermark:g}); retry later"
            )
        frames = []
        ctx.in_exec = True  # blocking commands act non-blocking (Redis)
        try:
            for c in queued:
                frames.append(self._safe_dispatch(c, ctx))
        finally:
            ctx.in_exec = False
        return b"*" + str(len(frames)).encode() + b"\r\n" + b"".join(frames)

    def _cmdctx_DISCARD(self, args, ctx: _ConnCtx):
        if not ctx.in_multi:
            raise RespError("DISCARD without MULTI")
        ctx.in_multi = False
        ctx.queued = []
        return _encode_simple("OK")

    # connection/admin

    def _cmd_PING(self, args):
        return _encode_simple("PONG") if not args else _encode_bulk(args[0])

    def _cmd_QUIT(self, args):
        # +OK then the read loop closes on the peer's FIN; also legal
        # pre-auth (part of the Redis unauthenticated surface).
        return _encode_simple("OK")

    def _cmd_SELECT(self, args):
        """One logical database (the engine's keyspace is flat): SELECT 0
        succeeds for stock-client handshakes, other indexes error like a
        databases=1 redis-server."""
        if int(args[0]) != 0:
            raise RespError("DB index is out of range")
        return _encode_simple("OK")

    def _cmdctx_RESET(self, args, ctx: _ConnCtx):
        """→ Redis RESET: abort MULTI, drop subscriptions, revert to
        RESP2 defaults, and de-authenticate when a password is set."""
        ctx.in_multi = False
        ctx.queued = []
        for channel, lid in list(ctx.subs.items()):
            self._client._topic_bus.unsubscribe(channel, lid)
        ctx.subs.clear()
        ctx.proto = 2
        ctx.client_name = None
        ctx.monitor = False  # RESET exits MONITOR mode (Redis parity)
        self._monitors.discard(ctx)
        ctx.trace_next = None
        if self._requirepass:
            ctx.authed = False
        return _encode_simple("RESET")

    # CONFIG: the handful of keys stock clients interrogate on connect.
    # GET answers from this table; SET round-trips into it for the SAME
    # keys only (anything else errors — silently acking unknown tunables
    # would fake capabilities the engine does not have).
    _CONFIG_KEYS = {
        # Client-compat stubs: stock clients interrogate these on
        # connect; they have no live semantics here (writes round-trip
        # through the table, nothing applies), so there is nothing to
        # bounds-validate and no honest INFO line to emit.
        "maxmemory": "0",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        "maxmemory-policy": "noeviction",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        "save": "",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        # appendonly/appendfsync: LIVE on an engine with the durability
        # tier (ISSUE 10) — _config_table_init overrides from the
        # journal state and CONFIG SET toggles it; this static row only
        # serves the host engine (no journal to report).
        "appendonly": "no",  # rtpulint: disable=RT004 live on the TPU engine (overridden in _config_table_init); host-engine stub only
        "databases": "1",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        "timeout": "0",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        "proto-max-bulk-len": "536870912",  # rtpulint: disable=RT004 client-compat stub, no live semantics
        # Applied to the live slowlog ring on CONFIG SET (obs/slowlog.py;
        # same defaults as redis-server).  Surfaced via SLOWLOG GET/LEN
        # and CONFIG GET, not INFO — redis-server parity.
        "slowlog-log-slower-than": "10000",  # rtpulint: disable=RT004 surfaced via SLOWLOG/CONFIG GET, not INFO (redis parity)
        "slowlog-max-len": "128",  # rtpulint: disable=RT004 surfaced via SLOWLOG/CONFIG GET, not INFO (redis parity)
    }

    # Near-cache tunables (ISSUE 4) live-apply to the engine's
    # SketchNearCache on CONFIG SET.  Registered only when the fronted
    # engine HAS a near cache (host engine: unknown option — silently
    # acking would fake a capability).
    def _nearcache(self):
        return getattr(getattr(self._client, "_engine", None),
                       "nearcache", None)

    def _residency(self):
        """The fronted engine's residency manager (ISSUE 14), or None
        on the host engine (no ladder to report or tune)."""
        return getattr(getattr(self._client, "_engine", None),
                       "residency", None)

    def _config_table_init(self) -> dict:
        table = dict(self._CONFIG_KEYS)
        nc = self._nearcache()
        if nc is not None:
            table.update({
                "nearcache": "yes" if nc.enabled else "no",
                "nearcache-max-bytes": str(nc.store.max_bytes),
                "nearcache-tenant-quota-bytes": str(
                    nc.store.tenant_quota_bytes
                ),
                "nearcache-max-batch": str(nc.max_batch),
            })
        # Overload control plane (ISSUE 7): live-settable everywhere the
        # serve layer applies (output-buffer limits, deadline default,
        # watermark); the engine-side knobs (fetch timeout, tenant
        # quotas) register only when the fronted engine HAS a coalescer/
        # governor — acking them on the host engine would fake the
        # capability.
        table.update({
            "op-deadline-ms": str(self.op_deadline_ms),
            "admission-watermark": f"{self.admission_watermark:g}",
            "client-output-buffer-limit": str(self.output_buffer_limit),
            "client-output-buffer-soft-seconds":
                f"{self.output_buffer_soft_seconds:g}",
            # Fleet telemetry plane (ISSUE 13): live head-sampling rate
            # (also settable via TRACE SAMPLE) and the latency-monitor
            # arm threshold (0 = off, redis semantics).
            "trace-sample-rate": f"{self.obs.trace.sample_rate:g}",
            "latency-monitor-threshold":
                str(self.obs.latency.threshold_ms),
        })
        lm = getattr(self, "loadmap", None)
        if lm is not None:
            # Load-attribution plane (ISSUE 16): the key-sampling rate
            # and master switch live-apply to the node's LoadMap.
            table.update({
                "loadmap-key-sample-rate": f"{lm.sample_rate:g}",
                "loadmap-enabled": "yes" if lm.enabled else "no",
            })
        rb = getattr(self, "rebalancer", None)
        if rb is not None:
            # Autonomous rebalancer (ISSUE 19): damping knobs live-apply
            # to the agent/planner; rows register only when the agent is
            # armed (acking them unarmed would fake the capability).
            table.update({
                "rebalance-threshold": f"{rb.planner.threshold:g}",
                "rebalance-interval-ms": str(int(rb.interval_s * 1000)),
                "rebalance-max-moves": str(rb.planner.max_moves),
                "rebalance-pace-ms": str(int(rb.pace_s * 1000)),
                "rebalance-cooldown-ms": str(
                    int(rb.planner.cooldown_s * 1000)
                ),
            })
        rm = self._residency()
        if rm is not None:
            # Tiered residency (ISSUE 14): budgets and the promotion
            # threshold live-apply to the manager (arming a budget
            # starts the maintenance thread).
            table.update({
                "residency-device-rows": str(rm.device_rows),
                "residency-max-host-bytes": str(rm.max_host_bytes),
                "residency-max-disk-bytes": str(rm.max_disk_bytes),
                "residency-promote-heat": f"{rm.promote_heat:g}",
            })
        eng = getattr(self._client, "_engine", None)
        # Durability tier (ISSUE 10): appendonly/appendfsync are LIVE on
        # an engine that carries the journal surface — CONFIG SET
        # enables/disables journaling and switches the fsync policy on
        # the running engine.
        if hasattr(eng, "journal_set_enabled"):
            table["appendonly"] = (
                "yes" if getattr(eng, "journal", None) is not None
                else "no"
            )
            table["appendfsync"] = str(
                getattr(eng.config, "journal_fsync", "everysec")
            )
        c = getattr(eng, "coalescer", None)
        if c is not None:
            table["fetch-timeout-ms"] = str(
                int(c.fetch_timeout_s * 1000)
            )
        gov = getattr(eng, "governor", None)
        if gov is not None:
            table.update({
                "tenant-rate-limit": str(int(gov.rate_limit)),
                "tenant-burst-ops": str(int(gov._burst_cfg)),
                "tenant-max-inflight": str(int(gov.max_inflight)),
            })
        return table

    def _apply_nearcache_config(self, key: str, val: str) -> None:
        nc = self._nearcache()
        if nc is None:  # validated against the table: can't happen
            return
        if key == "nearcache":
            try:
                nc.set_enabled(val.lower() in ("yes", "1", "true", "on"))
            except ValueError as e:  # forced off under multi-host
                raise RespError(str(e)) from e
        elif key == "nearcache-max-bytes":
            nc.store.resize(max_bytes=int(val))
        elif key == "nearcache-tenant-quota-bytes":
            nc.store.resize(tenant_quota_bytes=int(val))
        elif key == "nearcache-max-batch":
            nc.max_batch = int(val)

    # Residency-ladder knobs (ISSUE 14) with bounds validation before
    # apply (the nearcache/overload pattern): budgets are >= 0 ints
    # (0 disables that tier bound), the promote threshold a >= 0 float.
    _RESIDENCY_KEYS = frozenset((
        "residency-device-rows", "residency-max-host-bytes",
        "residency-max-disk-bytes", "residency-promote-heat",
    ))

    def _validate_residency_config(self, key: str, raw: bytes) -> None:
        try:
            fv = float(raw)
            if key != "residency-promote-heat":
                fv = int(raw)
        except ValueError:
            raise RespError(
                f"Invalid argument '{raw.decode()}' for CONFIG SET "
                f"'{key}'"
            )
        if fv < 0:
            raise RespError(
                f"argument must be >= 0 for CONFIG SET '{key}' "
                f"(0 disables this bound)"
            )

    def _apply_residency_config(self, key: str, val: str) -> None:
        rm = self._residency()
        if rm is None:  # validated against the table: can't happen
            return
        if key == "residency-device-rows":
            rm.set_budget(device_rows=int(val))
        elif key == "residency-max-host-bytes":
            rm.set_budget(max_host_bytes=int(val))
        elif key == "residency-max-disk-bytes":
            rm.set_budget(max_disk_bytes=int(val))
        elif key == "residency-promote-heat":
            rm.set_budget(promote_heat=float(val))

    # Overload knobs (ISSUE 7) with bounds validation: CONFIG SET
    # rejects nonsense (negative deadline, zero watermark) instead of
    # applying it — the nearcache-knob pattern.
    _OVERLOAD_KEYS = frozenset((
        "op-deadline-ms", "admission-watermark", "fetch-timeout-ms",
        "tenant-rate-limit", "tenant-burst-ops", "tenant-max-inflight",
        "client-output-buffer-limit", "client-output-buffer-soft-seconds",
    ))

    # Telemetry knobs (ISSUE 13) with bounds validation before apply
    # (the overload-knob pattern): a nonsense rate/threshold must be
    # refused, never acked into the table.
    _TELEMETRY_KEYS = frozenset((
        "trace-sample-rate", "latency-monitor-threshold",
    ))

    def _validate_telemetry_config(self, key: str, raw: bytes) -> None:
        if key == "trace-sample-rate":
            try:
                fv = float(raw)
            except ValueError:
                raise RespError(
                    f"Invalid argument '{raw.decode()}' for CONFIG SET "
                    f"'{key}'"
                )
            if not 0.0 <= fv <= 1.0:
                raise RespError(
                    f"argument must be in [0, 1] for CONFIG SET '{key}'"
                )
        elif key == "latency-monitor-threshold":
            try:
                iv = int(raw)
            except ValueError:
                raise RespError(
                    f"Invalid argument '{raw.decode()}' for CONFIG SET "
                    f"'{key}'"
                )
            if iv < 0:
                raise RespError(
                    f"argument must be >= 0 for CONFIG SET '{key}' "
                    f"(0 disables the latency monitor)"
                )

    def _apply_telemetry_config(self, key: str, val: str) -> None:
        if key == "trace-sample-rate":
            self.obs.trace.set_sample_rate(float(val))
        elif key == "latency-monitor-threshold":
            self.obs.latency.set_threshold_ms(int(val))

    # Load-attribution knobs (ISSUE 16): the key-sampling rate and the
    # master accounting switch, live-applied to the node's LoadMap
    # (same bounds discipline as the telemetry knobs).
    _LOADMAP_KEYS = frozenset((
        "loadmap-key-sample-rate", "loadmap-enabled",
    ))

    def _validate_loadmap_config(self, key: str, raw: bytes) -> None:
        if key == "loadmap-key-sample-rate":
            try:
                fv = float(raw)
            except ValueError:
                raise RespError(
                    f"Invalid argument '{raw.decode()}' for CONFIG SET "
                    f"'{key}'"
                )
            if not 0.0 <= fv <= 1.0:
                raise RespError(
                    f"argument must be in [0, 1] for CONFIG SET '{key}'"
                )
        elif key == "loadmap-enabled":
            if raw.decode("latin-1", "replace").lower() not in (
                    "yes", "no", "1", "0", "true", "false", "on", "off"):
                raise RespError(
                    f"argument must be yes or no for CONFIG SET '{key}'"
                )

    def _apply_loadmap_config(self, key: str, val: str) -> None:
        lm = getattr(self, "loadmap", None)
        if lm is None:
            return
        if key == "loadmap-key-sample-rate":
            lm.sample_rate = float(val)
        elif key == "loadmap-enabled":
            lm.enabled = val.lower() in ("yes", "1", "true", "on")

    _REBALANCE_KEYS = frozenset((
        "rebalance-threshold", "rebalance-interval-ms",
        "rebalance-max-moves", "rebalance-pace-ms",
        "rebalance-cooldown-ms",
    ))

    def _validate_rebalance_config(self, key: str, raw: bytes) -> None:
        if key == "rebalance-threshold":
            try:
                fv = float(raw)
            except ValueError:
                raise RespError(
                    f"Invalid argument '{raw.decode()}' for CONFIG SET "
                    f"'{key}'"
                )
            if fv < 1.0:
                raise RespError(
                    f"argument must be >= 1.0 for CONFIG SET '{key}'"
                )
            return
        try:
            iv = int(raw)
        except ValueError:
            raise RespError(
                f"Invalid argument '{raw.decode()}' for CONFIG SET "
                f"'{key}'"
            )
        floor = 1 if key in (
            "rebalance-interval-ms", "rebalance-max-moves"
        ) else 0
        if iv < floor:
            raise RespError(
                f"argument must be >= {floor} for CONFIG SET '{key}'"
            )

    def _apply_rebalance_config(self, key: str, val: str) -> None:
        rb = getattr(self, "rebalancer", None)
        if rb is None:
            return
        if key == "rebalance-threshold":
            rb.planner.threshold = float(val)
        elif key == "rebalance-interval-ms":
            rb.interval_s = int(val) / 1000.0
        elif key == "rebalance-max-moves":
            rb.planner.max_moves = int(val)
        elif key == "rebalance-pace-ms":
            rb.pace_s = int(val) / 1000.0
        elif key == "rebalance-cooldown-ms":
            rb.planner.cooldown_s = int(val) / 1000.0

    def _validate_overload_config(self, key: str, raw: bytes) -> None:
        def bad(msg: str):
            raise RespError(
                f"argument must be {msg} for CONFIG SET '{key}'"
            )

        if key in ("admission-watermark",
                   "client-output-buffer-soft-seconds",
                   "tenant-rate-limit", "tenant-burst-ops"):
            # Float-valued knobs — validated exactly as wide as the
            # setter applies them (the governor takes fractional
            # rates).
            try:
                fv = float(raw)
            except ValueError:
                raise RespError(
                    f"Invalid argument '{raw.decode()}' for CONFIG SET "
                    f"'{key}'"
                )
            if key == "admission-watermark" and not 0.0 < fv <= 1.0:
                bad("in (0, 1] (use 1 to effectively disable shedding)")
            elif key != "admission-watermark" and fv < 0:
                bad(">= 0")
            return
        try:
            iv = int(raw)
        except ValueError:
            raise RespError(
                f"Invalid argument '{raw.decode()}' for CONFIG SET "
                f"'{key}'"
            )
        if key == "fetch-timeout-ms" and iv <= 0:
            bad("positive")
        if iv < 0:
            bad(">= 0")

    def _apply_overload_config(self, key: str, val: str) -> None:
        eng = getattr(self._client, "_engine", None)
        if key == "op-deadline-ms":
            self.op_deadline_ms = int(val)
        elif key == "admission-watermark":
            self.admission_watermark = float(val)
        elif key == "client-output-buffer-limit":
            self.output_buffer_limit = int(val)
        elif key == "client-output-buffer-soft-seconds":
            self.output_buffer_soft_seconds = float(val)
        elif key == "fetch-timeout-ms":
            c = getattr(eng, "coalescer", None)
            if c is not None:
                c.fetch_timeout_s = int(val) / 1000.0
        elif key in ("tenant-rate-limit", "tenant-burst-ops",
                     "tenant-max-inflight"):
            gov = getattr(eng, "governor", None)
            if gov is not None:
                if key == "tenant-rate-limit":
                    gov.set_limits(rate_limit=float(val))
                elif key == "tenant-burst-ops":
                    gov.set_limits(burst=float(val))
                else:
                    gov.set_limits(max_inflight=int(val))

    def _cmd_CONFIG(self, args):
        import fnmatch

        sub = args[0].decode().upper()
        if not hasattr(self, "_config_table"):
            self._config_table = self._config_table_init()
        if sub == "GET":
            pat = args[1].decode().lower()
            flat = []
            for k, v in sorted(self._config_table.items()):
                if fnmatch.fnmatch(k, pat):
                    flat.extend([k.encode(), v.encode()])
            return _encode_array(flat)
        if sub == "SET":
            pairs = args[1:]
            if not pairs or len(pairs) % 2 != 0:
                raise RespError(
                    "wrong number of arguments for 'config|set' command"
                )
            # Validate EVERY pair before applying any (Redis 7 multi-pair
            # form; acking while silently dropping pairs would fake
            # capabilities).
            for i in range(0, len(pairs), 2):
                key = pairs[i].decode().lower()
                if key not in self._config_table:
                    raise RespError(
                        f"Unknown option or number of arguments for "
                        f"CONFIG SET - '{key}'"
                    )
                if key in self._OVERLOAD_KEYS:
                    self._validate_overload_config(key, pairs[i + 1])
                elif key in self._RESIDENCY_KEYS:
                    self._validate_residency_config(key, pairs[i + 1])
                elif key in self._TELEMETRY_KEYS:
                    self._validate_telemetry_config(key, pairs[i + 1])
                elif key in self._LOADMAP_KEYS:
                    self._validate_loadmap_config(key, pairs[i + 1])
                elif key in self._REBALANCE_KEYS:
                    self._validate_rebalance_config(key, pairs[i + 1])
                elif key == "appendonly":
                    v = pairs[i + 1].decode().lower()
                    if v not in ("yes", "no"):
                        raise RespError(
                            f"Invalid argument '{pairs[i + 1].decode()}' "
                            f"for CONFIG SET 'appendonly'"
                        )
                    eng = getattr(self._client, "_engine", None)
                    if v == "yes" and (
                        not hasattr(eng, "journal_set_enabled")
                        or not getattr(eng.config, "journal_dir", None)
                    ):
                        # Refused BEFORE any table write: GET must never
                        # report yes without a live journal behind it.
                        raise RespError(
                            "appendonly needs Config.journal_dir on an "
                            "engine with the durability tier"
                        )
                elif key == "appendfsync":
                    from redisson_tpu.durability import FSYNC_POLICIES

                    v = pairs[i + 1].decode().lower()
                    if v not in FSYNC_POLICIES:
                        raise RespError(
                            f"argument must be one of "
                            f"{'|'.join(FSYNC_POLICIES)} for CONFIG SET "
                            f"'appendfsync'"
                        )
                elif key.startswith("slowlog-") or (
                    key.startswith("nearcache-")
                ):
                    try:
                        iv = int(pairs[i + 1])
                    except ValueError:
                        raise RespError(
                            f"Invalid argument '{pairs[i + 1].decode()}' "
                            f"for CONFIG SET '{key}'"
                        )
                    # Bounds, like redis-server's out-of-range rejection:
                    # a negative/zero budget or batch cap would silently
                    # kill the cache while acking OK.  Quota 0 is legal
                    # (0 → re-derive the max_bytes/8 default).
                    if key in (
                        "nearcache-max-bytes", "nearcache-max-batch"
                    ) and iv <= 0:
                        raise RespError(
                            f"argument must be positive for CONFIG SET "
                            f"'{key}'"
                        )
                    if key == "nearcache-tenant-quota-bytes" and iv < 0:
                        raise RespError(
                            f"argument must be >= 0 for CONFIG SET "
                            f"'{key}'"
                        )
                elif key == "nearcache":
                    v = pairs[i + 1].decode().lower()
                    if v not in (
                        "yes", "no", "1", "0", "true", "false", "on", "off"
                    ):
                        raise RespError(
                            f"Invalid argument '{pairs[i + 1].decode()}' "
                            f"for CONFIG SET '{key}'"
                        )
                    nc = self._nearcache()
                    if (
                        nc is not None and nc.locked_off
                        and v in ("yes", "1", "true", "on")
                    ):
                        # Refused HERE, before any table write: CONFIG GET
                        # must never report yes while the cache is forced
                        # off (multi-host lockstep).
                        raise RespError(
                            "nearcache is forced off under multi-host "
                            "(a cache hit skips a device dispatch — "
                            "multi-controller lockstep)"
                        )
            for i in range(0, len(pairs), 2):
                key = pairs[i].decode().lower()
                val = pairs[i + 1].decode()
                if key in ("appendonly", "appendfsync"):
                    # APPLY before the table write: journal attach can
                    # fail at runtime (unwritable dir, disk full) even
                    # though validation passed — GET must never report
                    # yes without a live journal behind it.
                    eng = getattr(self._client, "_engine", None)
                    if key == "appendonly":
                        if hasattr(eng, "journal_set_enabled"):
                            try:
                                eng.journal_set_enabled(
                                    val.lower() == "yes"
                                )
                            except (OSError, ValueError) as e:
                                raise RespError(
                                    f"appendonly failed to apply: {e}"
                                ) from e
                    elif hasattr(eng, "journal_set_policy"):
                        eng.journal_set_policy(val.lower())
                    self._config_table[key] = val
                    self._audit_config_set(key, val)
                    continue
                self._config_table[key] = val
                self._audit_config_set(key, val)
                # Live-apply the slowlog/nearcache tunables (validated
                # above).
                if key == "slowlog-log-slower-than":
                    self.obs.slowlog.set_threshold_us(int(val))
                elif key == "slowlog-max-len":
                    self.obs.slowlog.set_max_len(int(val))
                elif key in self._OVERLOAD_KEYS:
                    self._apply_overload_config(key, val)
                elif key in self._RESIDENCY_KEYS:
                    self._apply_residency_config(key, val)
                elif key in self._TELEMETRY_KEYS:
                    self._apply_telemetry_config(key, val)
                elif key in self._LOADMAP_KEYS:
                    self._apply_loadmap_config(key, val)
                elif key in self._REBALANCE_KEYS:
                    self._apply_rebalance_config(key, val)
                elif key.startswith("nearcache"):
                    self._apply_nearcache_config(key, val)
            return _encode_simple("OK")
        if sub == "RESETSTAT":
            # Zero the commandstats/latencystats families, like Redis.
            self.obs.reset_command_stats()
            return _encode_simple("OK")
        raise RespError(f"Unknown CONFIG subcommand {sub}")

    def _audit_config_set(self, key: str, val: str) -> None:
        """The CONFIG SET audit trail (ISSUE 20): every applied pair
        lands in the flight recorder, so a 3 a.m. behavior change is
        attributable to the knob that caused it."""
        events = self._events()
        if events is not None:
            events.emit("config.set", key=key, value=val)

    def _cmd_WAIT(self, args):
        """Standalone server, no replicas: 0 acknowledged replicas is
        the honest Redis answer.  With the durability journal live,
        WAIT is additionally a real JOURNAL-FSYNC FENCE (ISSUE 10): it
        forces an fsync covering every record appended so far — under
        any appendfsync policy — and blocks (up to the command's
        timeout-ms argument) until it lands.  A client that issues
        writes then WAIT gets local durability even under everysec/no.

        With replicas attached (ISSUE 18), WAIT <numreplicas> is a REAL
        replica-ack fence: after the local fsync it blocks until that
        many replicas have ``REPLCONF ACK``ed an offset covering every
        record appended so far, and replies with the count that did."""
        eng = getattr(self._client, "_engine", None)
        fence = getattr(eng, "journal_fence", None)
        timeout_s = None
        if len(args) >= 2:
            ms = int(args[1])
            timeout_s = ms / 1000.0 if ms > 0 else None
        if fence is not None:
            from redisson_tpu.durability import JournalError

            t0 = time.perf_counter()
            try:
                if not fence(timeout=timeout_s):
                    raise RespError(
                        "BUSY RTPU journal fsync fence timed out"
                    )
            except JournalError as e:
                raise RespError(f"journal is broken: {e}") from e
            tctx = _trace.current()
            if tctx is not None and not isinstance(tctx, tuple):
                # Traced WAIT: the fsync fence becomes its own child
                # span, so a trace shows exactly how much of the
                # command was durability wait (ISSUE 13).
                dur = time.perf_counter() - t0
                tctx.tracer.record_span(
                    tctx, "journal_fsync_fence", time.time() - dur, dur,
                )
        hub = self._repl_hub()
        if hub is None:
            return _encode_int(0)
        # Fence offset: everything appended up to now.  Captured AFTER
        # the fsync fence — records appended while we waited are the
        # next WAIT's problem, exactly Redis's WAIT contract.
        fence_seq = hub.journal.last_seq()
        numreplicas = int(args[0]) if args else 0
        if numreplicas <= 0:
            return _encode_int(hub.count_acked(fence_seq))
        acked = hub.wait_acked(
            fence_seq, numreplicas,
            timeout_s if timeout_s is not None else float("inf"),
        )
        if acked < numreplicas:
            events = self._events()
            if events is not None:
                events.emit("repl.wait.timeout", severity="warn",
                            offset=fence_seq, asked=numreplicas,
                            acked=acked)
        return _encode_int(acked)

    # -- replication plane (ISSUE 18 tentpole) -----------------------------

    def _repl_hub(self):
        """The primary-side ReplicationHub over the CURRENT journal —
        rebuilt when the journal object changes (a ``CONFIG SET
        appendonly`` re-attach or a promotion makes a NEW lineage: a
        fresh repl_id, so stale offsets can never partial-resync
        against a different history)."""
        eng = getattr(self._client, "_engine", None)
        j = getattr(eng, "journal", None)
        hub = self.repl_hub
        if j is None:
            if hub is not None:
                hub.detach()
                self.repl_hub = None
            return None
        if hub is None or hub.journal is not j:
            from redisson_tpu.durability.replication import ReplicationHub

            if hub is not None:
                hub.detach()
            hub = self.repl_hub = ReplicationHub(
                j, obs=self.obs,
                backlog_bytes=int(getattr(
                    self._client.config, "repl_backlog_bytes", 4 << 20
                ) or (4 << 20)),
            )
        return hub

    def _events(self):
        """The flight-recorder ring (obs/events.py), or None on a bare
        bundle — every door-side emit point rides this accessor."""
        return getattr(self.obs, "events", None)

    def _repl_offset(self) -> int:
        """This node's replication offset: a replica reports what it
        APPLIED; a primary reports its journal head."""
        link = self.replica_link
        if link is not None:
            return int(link.applied)
        eng = getattr(self._client, "_engine", None)
        j = getattr(eng, "journal", None)
        return int(j.last_seq()) if j is not None else 0

    def _repl_lag(self) -> int:
        link = self.replica_link
        return int(link.lag_ops()) if link is not None else 0

    def _obs_wire_repl_gauges(self) -> None:
        obs = self.obs
        if obs is None:
            return
        try:
            obs.repl_offset_source = self._repl_offset
            obs.repl_lag_source = self._repl_lag
        except AttributeError:
            pass  # obs bundle predates the replication families

    def start_replication_from(self, host: str, port: int,
                               ident: Optional[str] = None,
                               replid: Optional[str] = None):
        """Turn this node into a replica of ``host:port``: start the
        pull link (durability/replica.py).  The link's existence flips
        the role to ``slave`` — the -READONLY gate and the bounded-
        staleness refusals in _dispatch key off it."""
        from redisson_tpu.durability.replica import ReplicaLink

        if ident is None:
            if self.cluster is not None:
                ident = self.cluster.myid
            else:
                import uuid

                ident = uuid.uuid4().hex[:16]
        cfg = self._client.config
        link = ReplicaLink(
            self._client, host, int(port), ident,
            listening_port=self.port, obs=self.obs,
            batch=int(getattr(cfg, "repl_fetch_batch", 512) or 512),
            poll_timeout_ms=int(
                getattr(cfg, "repl_poll_timeout_ms", 500) or 500
            ),
            replid=replid or getattr(cfg, "_repl_bootstrap_id", None),
        )
        self.replica_link = link
        link.start()
        return link

    def promote_to_primary(self, epoch: int = 0) -> None:
        """Failover takeover: stop applying the (dead) primary's
        stream, snapshot the promoted state — the local journal was
        EMPTY while replicating (the apply path never re-journals), so
        the snapshot is what makes this node's own crash recovery
        self-contained — and start a fresh replication lineage for the
        replicas that will re-home here."""
        link, self.replica_link = self.replica_link, None
        if link is not None:
            link.stop()
        eng = getattr(self._client, "_engine", None)
        sdir = getattr(getattr(eng, "config", None), "snapshot_dir", None)
        if sdir and hasattr(eng, "snapshot"):
            try:
                self._client.snapshot(sdir)
            except Exception:  # pragma: no cover — promotion never fails
                pass           # on snapshot IO; LASTSAVE surfaces it
        hub = self.repl_hub
        if hub is not None:
            # Fresh lineage: replicas of the dead primary carry ITS
            # repl_id, which never matches a rebuilt hub — they full-
            # resync against the promoted state instead of splicing
            # foreign offsets into this journal.
            hub.detach()
            self.repl_hub = None
        self._repl_hub()
        self._promote_epoch = int(epoch)
        if self.obs is not None:
            try:
                self.obs.failover_takeovers.inc((), 1)
            except AttributeError:
                pass

    def _cmdctx_REPLCONF(self, args, ctx: "_ConnCtx"):
        if not args:
            raise RespError(
                "wrong number of arguments for 'replconf' command"
            )
        sub = args[0].decode("latin-1", "replace").upper()
        if sub == "IDENT":
            # REPLCONF IDENT <replica-id> [listening-port] — names this
            # connection's replica so its ACKs land in the hub table.
            if len(args) < 2:
                raise RespError(
                    "REPLCONF IDENT <replica-id> [listening-port]"
                )
            ctx.repl_ident = self._s(args[1])
            if len(args) > 2:
                ctx.repl_listening_port = int(args[2])
            return _encode_simple("OK")
        if sub == "LISTENING-PORT":
            ctx.repl_listening_port = int(args[1])
            return _encode_simple("OK")
        if sub == "ACK":
            offset = int(args[1])
            if chaos.ENABLED:
                try:
                    chaos.fire("repl.ack", {"offset": offset})
                except (chaos.FaultInjected, chaos.CorruptionDetected):
                    # A dropped/garbled ack is LOST, not an error — the
                    # replica's next ack supersedes it (acks are
                    # max-merged).  WAIT fences simply see it later.
                    return _encode_simple("OK")
            hub = self._repl_hub()
            if hub is not None and ctx.repl_ident:
                addr = ctx.addr
                if ctx.repl_listening_port and ":" in addr:
                    addr = "%s:%d" % (
                        addr.rsplit(":", 1)[0], ctx.repl_listening_port
                    )
                hub.ack(ctx.repl_ident, offset, addr=addr)
            return _encode_simple("OK")
        if sub == "GETACK":
            return _encode_simple("OK")
        raise RespError(f"Unknown REPLCONF subcommand {sub}")

    def _snapshot_tar(self) -> tuple:
        """FULLRESYNC payload: take a REAL durable snapshot into the
        configured snapshot_dir (engine.snapshot retires journal
        segments, so shipping a temp-dir snapshot would break THIS
        node's crash recovery), then tar the directory.  Returns
        (snapshot's journal cut, tar bytes)."""
        import io
        import json
        import os
        import tarfile

        eng, sdir = self._persist_engine()
        self._client.snapshot(sdir)
        # Exclude concurrent snapshots (BGSAVE / the periodic
        # snapshotter) while reading meta + taring, so the cut seq and
        # the files describe the SAME capture.
        lock = getattr(eng, "_snapshot_lock", None)
        if lock is not None:
            lock.acquire()
        try:
            snap_seq = 0
            meta_path = os.path.join(sdir, "sketch_meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as f:
                    snap_seq = int(json.load(f).get("journal_seq") or 0)
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w") as tf:
                for fn in sorted(os.listdir(sdir)):
                    if fn.endswith(".tmp"):
                        continue  # a concurrent write's scratch files
                    tf.add(os.path.join(sdir, fn), arcname=fn)
        finally:
            if lock is not None:
                lock.release()
        return snap_seq, buf.getvalue()

    def _cmd_RTPU_PSYNC(self, args):
        """RTPU.PSYNC <replid|?> <offset> → [CONTINUE, replid] when the
        (lineage, offset) can partial-resync, else [FULLRESYNC, replid,
        snap_seq, snapshot-tar]."""
        if len(args) < 2:
            raise RespError("RTPU.PSYNC <replid|?> <offset>")
        hub = self._repl_hub()
        if hub is None:
            raise RespError(
                "NOJOURNAL replication requires the op journal "
                "(set Config.journal_dir / appendonly yes)"
            )
        replid = self._s(args[0])
        offset = int(args[1])
        if replid != "?" and hub.can_continue(replid, offset):
            hub.note_partial_resync()
            return b"".join([
                b"*2\r\n", _encode_bulk(b"CONTINUE"),
                _encode_bulk(hub.repl_id.encode()),
            ])
        hub.note_full_resync()
        snap_seq, tar_bytes = self._snapshot_tar()
        return b"".join([
            b"*4\r\n", _encode_bulk(b"FULLRESYNC"),
            _encode_bulk(hub.repl_id.encode()),
            _encode_int(snap_seq), _encode_bulk(tar_bytes),
        ])

    def _cmd_RTPU_REPLFETCH(self, args):
        """RTPU.REPLFETCH <after> [maxn] [timeout-ms] → [replid,
        master_offset, [[seq, crc, payload], ...]] — the stream's pull
        verb.  Long-polls up to timeout-ms when the replica is caught
        up (reactor detaches it like the other blocking commands)."""
        if not args:
            raise RespError("RTPU.REPLFETCH <after> [maxn] [timeout-ms]")
        hub = self._repl_hub()
        if hub is None:
            raise RespError(
                "NOJOURNAL replication requires the op journal "
                "(set Config.journal_dir / appendonly yes)"
            )
        after = int(args[0])
        maxn = int(args[1]) if len(args) > 1 else 512
        timeout_ms = int(args[2]) if len(args) > 2 else 0
        corrupt = False
        if chaos.ENABLED:
            try:
                chaos.fire("repl.stream", {"after": after})
            except chaos.FaultInjected:
                # Dropped batch: the replica sees an empty fetch and
                # re-polls — lost FRAMES are a latency event, never a
                # lost write (the journal retains them).
                return b"".join([
                    b"*3\r\n", _encode_bulk(hub.repl_id.encode()),
                    _encode_int(hub.journal.last_seq()), b"*0\r\n",
                ])
            except chaos.CorruptionDetected:
                corrupt = True  # flip a byte in one OUTGOING payload
        status, frames = hub.fetch(
            after, max_n=maxn, timeout_s=max(0, timeout_ms) / 1000.0
        )
        if status != "CONTINUE":
            raise RespError(
                "NOBACKLOG offset fell off the replication backlog; "
                "FULLRESYNC required"
            )
        if corrupt and frames:
            seq0, crc0, payload0 = frames[0]
            garbled = bytearray(payload0)
            garbled[len(garbled) // 2] ^= 0x40
            frames = [(seq0, crc0, bytes(garbled))] + list(frames[1:])
        out = [
            b"*3\r\n", _encode_bulk(hub.repl_id.encode()),
            _encode_int(hub.journal.last_seq()),
            b"*%d\r\n" % len(frames),
        ]
        for seq, crc, payload in frames:
            out.append(b"*3\r\n")
            out.append(_encode_int(seq))
            out.append(_encode_int(crc))
            out.append(_encode_bulk(payload))
        return b"".join(out)

    def _cmd_FAILOVER(self, args):
        """Manual FAILOVER (operator surface): on a replica, promote it
        to primary immediately (FAILOVER TAKEOVER semantics)."""
        if self.replica_link is None:
            raise RespError("FAILOVER requires a replica role")
        self.promote_to_primary(
            epoch=self.failover.state.current_epoch + 1
            if self.failover is not None else 0
        )
        return _encode_simple("OK")

    def _cmd_RTPU_CLUSTERPING(self, args):
        """Cluster bus liveness probe: RTPU.CLUSTERPING <sender-id>
        <sender-epoch> → [PONG, myid, epoch, offset, role].  Answered
        by every node (armed or not) — liveness is the point."""
        sender = self._s(args[0]) if args else ""
        epoch = int(args[1]) if len(args) > 1 else 0
        fo = self.failover
        my_epoch = epoch
        if fo is not None:
            # A ping from a peer proves the PEER is alive too.
            my_epoch = fo.state.note_ping(sender, epoch, time.monotonic())
        myid = self.cluster.myid if self.cluster is not None else ""
        role = "slave" if self.replica_link is not None else "master"
        return _encode_array([
            b"PONG", myid.encode(), int(my_epoch),
            int(self._repl_offset()), role.encode(),
        ])

    def _cmd_RTPU_FAILOVER_AUTH(self, args):
        """Election vote request: RTPU.FAILOVER.AUTH <candidate-id>
        <epoch> <failed-primary-id> → :1 granted / :0 denied.  Only a
        PRIMARY holding a failover agent may grant, at most once per
        epoch (the no-dual-primary invariant's load-bearing rule)."""
        if len(args) < 3:
            raise RespError(
                "RTPU.FAILOVER.AUTH <candidate-id> <epoch> <failed-id>"
            )
        fo = self.failover
        if fo is None or self.replica_link is not None:
            return _encode_int(0)
        granted = fo.state.grant_vote(
            self._s(args[0]), int(args[1]), self._s(args[2])
        )
        if granted:
            events = self._events()
            if events is not None:
                events.emit("failover.vote",
                            candidate=self._s(args[0]),
                            epoch=int(args[1]),
                            failed_primary=self._s(args[2]))
        return _encode_int(1 if granted else 0)

    def _cmd_RTPU_TAKEOVER(self, args):
        """Takeover broadcast: RTPU.TAKEOVER <new-primary-id>
        <old-primary-id> <epoch> [ranges] — reassign the claimed slots
        to the new primary, per-slot epoch-gated (a STALE takeover from
        a lost election must never un-assign a newer one).  ``ranges``
        is the winner's explicit claim ("0-100,200-300"); without it
        the receiver falls back to whatever ITS map still shows the old
        primary owning (pre-claim wire compatibility)."""
        if len(args) < 3:
            raise RespError(
                "RTPU.TAKEOVER <new-id> <old-id> <epoch> [ranges]"
            )
        new_id, old_id = self._s(args[0]), self._s(args[1])
        epoch = int(args[2])
        if self.cluster is None:
            raise RespError("This instance has cluster support disabled")
        slots = None
        if len(args) > 3 and args[3]:
            slots = []
            for part in self._s(args[3]).split(","):
                a, _, b = part.partition("-")
                slots.append([int(a), int(b or a)])
        moved = self.cluster.slotmap.apply_takeover(
            old_id, new_id, epoch, slots=slots
        )
        fo = self.failover
        if fo is not None:
            fo.state.note_takeover(new_id, old_id, epoch)
        events = self._events()
        if events is not None:
            events.emit("failover.takeover.applied", epoch=epoch,
                        new_primary=new_id, old_primary=old_id,
                        slots_moved=moved)
        return _encode_int(moved)

    # -- persistence commands (ISSUE 10): SAVE family goes live -----------

    def _persist_engine(self):
        eng = getattr(self._client, "_engine", None)
        if eng is None or not hasattr(eng, "snapshot"):
            raise RespError("engine has no snapshot support")
        sdir = getattr(eng.config, "snapshot_dir", None)
        if not sdir:
            raise RespError(
                "snapshot_dir is not configured (set Config.snapshot_dir)"
            )
        return eng, sdir

    def _cmd_SAVE(self, args):
        """Synchronous snapshot (the RDB SAVE analog): returns +OK only
        after the snapshot files are fsynced and renamed in — and, with
        a journal live, after covered segments retired."""
        eng, sdir = self._persist_engine()
        eng.snapshot(sdir)
        return _encode_simple("OK")

    def _bg_snapshot(self, eng, sdir) -> None:
        try:
            eng.snapshot(sdir)
        except Exception:  # pragma: no cover — surfaced via LASTSAVE
            pass

    def _cmd_BGSAVE(self, args):
        eng, sdir = self._persist_engine()
        threading.Thread(
            target=self._bg_snapshot, args=(eng, sdir),
            name="rtpu-bgsave", daemon=True,
        ).start()
        return _encode_simple("Background saving started")

    def _cmd_LASTSAVE(self, args):
        eng = getattr(self._client, "_engine", None)
        return _encode_int(int(getattr(eng, "_last_save_ts", 0.0) or 0))

    def _cmd_BGREWRITEAOF(self, args):
        """The journal's rewrite IS a snapshot: a completed snapshot
        records the journal cut and retires every covered segment
        (mark_snapshot), which is exactly the AOF-rewrite compaction."""
        eng, sdir = self._persist_engine()
        if getattr(eng, "journal", None) is None:
            raise RespError("appendonly is off (no journal to rewrite)")
        threading.Thread(
            target=self._bg_snapshot, args=(eng, sdir),
            name="rtpu-bgrewrite", daemon=True,
        ).start()
        return _encode_simple("Background append only file rewriting started")

    # -- script watchdog helpers (ISSUE 3 satellite) -----------------------

    def _script_busy(self) -> bool:
        """True while a script on ANOTHER connection has been running
        longer than script_timeout_ms (its own redis.call dispatches
        must keep flowing)."""
        run = self._script_run
        if run is None:
            return False
        thread, started = run
        if threading.current_thread() is thread:
            return False
        t = self._script_timeout_ms
        return t > 0 and (time.monotonic() - started) * 1000.0 >= t

    def _script_register(self) -> bool:
        """Claim the watchdog slot for the current thread; False when a
        script on this thread already owns it (nested redis.call)."""
        with self._script_lock:
            if self._script_run is None:
                self._script_run = (
                    threading.current_thread(), time.monotonic()
                )
                # Any kill flag here is stale (its target run is gone —
                # e.g. the killed thread died without unwinding through
                # _script_unregister): it must not fell the new script.
                self._script_kill = None
                return True
            return False

    def _script_unregister(self) -> None:
        """Release the slot if the CURRENT thread owns it.  Also the
        defensive path for a SCRIPT KILL whose async exception landed
        inside the normal clearing code — without it the stale record
        would report BUSY forever and target an innocent later command."""
        with self._script_lock:
            run = self._script_run
            if run is not None and run[0] is threading.current_thread():
                self._script_run = None
                if self._script_kill is run:
                    self._script_kill = None

    def _script_claim(self) -> bool:
        """Claim the watchdog slot BEFORE acquiring the grid lock; True
        when this frame now owns it, False for a nested call whose outer
        frame on this thread already does.  When ANOTHER connection's
        script owns the slot, wait for it rather than run unregistered:
        the caller would serialize on the grid lock anyway, and an
        unregistered runaway would be invisible to BUSY, report NOTBUSY
        to SCRIPT KILL — and leave SCRIPT KILL aimed at the slot owner,
        an innocent thread still queued on the grid lock.  Claiming
        before the lock means the BUSY clock may include queue wait,
        which only makes BUSY (slightly) early, never absent.  Claim
        order (slot, then grid lock) is the same in every script path,
        so the wait cannot deadlock: the slot owner never waits on the
        slot, and nested same-thread frames break out immediately."""
        while True:
            if self._script_register():
                return True
            run = self._script_run
            if run is None:
                continue  # slot freed between register and read: retry
            if run[0] is threading.current_thread():
                return False  # nested call: the outer frame owns the slot
            if not run[0].is_alive():  # owner died mid-script: reclaim
                with self._script_lock:
                    if self._script_run is run:
                        self._script_run = None
                continue
            time.sleep(0.001)

    def _script_reaper(self, run) -> None:
        """Drive one SCRIPT KILL home.  Re-posts the async exception on
        a short period until the target run exits (slot cleared / thread
        dead) or the cooperative dispatch-boundary check consumed the
        kill flag first.  The grace before the first post gives a
        redis.call-ing script time to die cleanly at its next dispatch,
        so the async path (whose landing site is uncontrollable) only
        fires for scripts that spin without calling back in."""
        import ctypes

        while True:
            time.sleep(0.02)
            with self._script_lock:
                if (
                    self._script_kill is not run
                    or self._script_run is not run
                    or not run[0].is_alive()
                ):
                    return
                n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(run[0].ident),
                    ctypes.py_object(ScriptKilledError),
                )
                if n > 1:  # pragma: no cover — CPython contract: undo
                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(run[0].ident), None
                    )
                    return

    def _cmd_DEBUG(self, args):
        sub = args[0].decode().upper()
        if sub == "SLEEP":
            import time as _time

            _time.sleep(float(args[1]))
            return _encode_simple("OK")
        if sub == "RESIDENCY":
            # DEBUG RESIDENCY DEMOTE|PROMOTE|SPILL|LOAD <key> | TICK —
            # the residency ladder's forcing surface (ISSUE 14): soak
            # tests and operators drive exact transitions without
            # waiting out heat decay.  Admin-gated like DEBUG INJECT.
            if not self._inject_allowed:
                raise RespError(
                    "DEBUG RESIDENCY on a non-loopback bind requires "
                    "requirepass (tier forcing is an admin surface)"
                )
            rm = self._residency()
            if rm is None:
                raise RespError(
                    "this engine has no residency manager (host engine)"
                )
            if len(args) < 2:
                raise RespError(
                    "DEBUG RESIDENCY DEMOTE|PROMOTE|SPILL|LOAD <key> "
                    "| TICK"
                )
            verb = args[1].decode().upper()
            if verb == "TICK":
                out = rm.maintain()
                return _encode_array([
                    f"{k} {v}".encode() for k, v in sorted(out.items())
                ])
            if verb not in ("DEMOTE", "PROMOTE", "SPILL", "LOAD") or (
                len(args) < 3
            ):
                raise RespError(
                    "DEBUG RESIDENCY DEMOTE|PROMOTE|SPILL|LOAD <key> "
                    "| TICK"
                )
            fn = {
                "DEMOTE": rm.demote, "PROMOTE": rm.promote,
                "SPILL": rm.spill, "LOAD": rm.load,
            }[verb]
            try:
                ok = fn(self._s(args[2]))
            except (OSError, ValueError) as e:
                raise RespError(f"residency {verb.lower()}: {e}") from e
            return _encode_int(1 if ok else 0)
        if sub == "INJECT":
            # DEBUG INJECT <point> <kind> <rate> [seed] | DEBUG INJECT OFF
            # — the chaos engine's RESP admin surface (docs/robustness.md),
            # gated exactly like scripting (loopback-or-requirepass).
            if not self._inject_allowed:
                raise RespError(
                    "DEBUG INJECT on a non-loopback bind requires "
                    "requirepass (fault injection is an admin surface)"
                )
            if len(args) >= 2 and args[1].decode().upper() == "OFF":
                chaos.clear()
                return _encode_simple("OK")
            if len(args) == 2 and args[1].decode().upper() == "LIST":
                flat = []
                for point, (kind, rate, seed) in sorted(
                    chaos.active().items()
                ):
                    flat.append(
                        f"{point} {kind} {rate:g} seed={seed}".encode()
                    )
                return _encode_array(flat)
            if len(args) < 4:
                raise RespError(
                    "DEBUG INJECT <point> <kind> <rate> [seed] [seconds] "
                    "| OFF | LIST"
                )
            point = args[1].decode()
            kind = args[2].decode().lower()
            try:
                rate = float(args[3])
                seed = int(args[4]) if len(args) > 4 else 0
                # Optional magnitude: latency rules sleep this long,
                # pressure rules (overload.pressure, ISSUE 7) inflate
                # the admission wait estimate by it.
                latency_s = float(args[5]) if len(args) > 5 else 0.001
                chaos.inject(point, kind=kind, rate=rate, seed=seed,
                             latency_s=latency_s)
            except ValueError as e:
                raise RespError(str(e)) from e
            return _encode_simple("OK")
        if sub == "COUNTKEYSINSLOT":
            # ISSUE 16 satellite: the SCAN-based cross-check for the
            # O(1) per-slot key counters behind CLUSTER COUNTKEYSINSLOT
            # — re-hashes every live key name, so tests (and a
            # suspicious operator) can diff the counter against ground
            # truth without trusting the hook coverage.  Explicitly the
            # scan (NOT the ISSUE 19 slot index): this command IS the
            # ground truth both fast paths are diffed against.
            if len(args) < 2:
                raise RespError("DEBUG COUNTKEYSINSLOT <slot>")
            try:
                slot = int(args[1])
            except ValueError:
                raise RespError("value is not an integer or out of range")
            if self.cluster is not None:
                return _encode_int(
                    len(self.cluster.keys_in_slot_scan(slot))
                )
            n = self._client.get_keys().count()
            return _encode_int(n if slot == 0 else 0)
        if sub == "GETKEYSINSLOT":
            # ISSUE 19 satellite: ground-truth twin of the above for
            # key NAMES — the full-keyspace re-hash scan that CLUSTER
            # GETKEYSINSLOT used before the write-time slot index.
            # Index vs scan set-equality is the index's differential
            # test.
            if len(args) < 2:
                raise RespError("DEBUG GETKEYSINSLOT <slot> [count]")
            try:
                slot = int(args[1])
                count = int(args[2]) if len(args) > 2 else None
            except ValueError:
                raise RespError("value is not an integer or out of range")
            if self.cluster is None:
                raise RespError("DEBUG GETKEYSINSLOT requires cluster mode")
            return _encode_array([
                k.encode()
                for k in self.cluster.keys_in_slot_scan(slot, count)
            ])
        raise RespError(f"unsupported DEBUG subcommand {sub}")

    def _cmd_OBJECT(self, args):
        """OBJECT introspection (ISSUE 14 satellite): for sketch
        objects the answers come from the residency ladder's live
        state — FREQ is the decayed access heat (the exact counter the
        demotion/promotion ranking uses), IDLETIME the seconds since
        the last engine-entry touch, and ENCODING reports the
        residency TIER (``device`` | ``host`` | ``disk``) so an
        operator can see where a key lives without DEBUG access.  Grid
        kinds keep the closest Redis encoding name.  Shed-exempt like
        the other introspection commands — it answers during the
        incident it helps debug."""
        sub = args[0].decode().upper()
        if sub == "HELP":
            return _encode_array([
                b"OBJECT ENCODING|REFCOUNT|IDLETIME|FREQ <key>",
            ])
        if sub not in ("ENCODING", "REFCOUNT", "IDLETIME", "FREQ"):
            raise RespError(f"Unknown OBJECT subcommand {sub}")
        if len(args) < 2:
            raise RespError(
                "wrong number of arguments for 'object' command"
            )
        name = self._s(args[1])
        kind = self._kind_of(name)
        if kind is None:
            raise RespError("no such key")
        rm = self._residency()
        sketch_entry = None
        if rm is not None:
            reg = getattr(self._client._engine, "registry", None)
            if reg is not None:
                sketch_entry = reg.lookup(name)
        if sub == "ENCODING":
            if sketch_entry is not None:
                return _encode_bulk(
                    getattr(sketch_entry, "residency", "device").encode()
                )
            enc = {
                "string": "embstr", "list": "quicklist",
                "hash": "hashtable", "set": "hashtable",
                "zset": "skiplist", "stream": "stream",
            }.get(self._TYPE_NAMES.get(kind, kind), "embstr")
            return _encode_bulk(enc.encode())
        if sub == "REFCOUNT":
            return _encode_int(1)
        if sub == "IDLETIME":
            if sketch_entry is not None:
                return _encode_int(int(rm.heat.idle_s(name)))
            return _encode_int(0)
        if sub == "FREQ":
            if sketch_entry is not None:
                import math

                # Redis parity (ISSUE 16 satellite): OBJECT FREQ is an
                # LFU counter on a 0-255 LOGARITHMIC scale, not a raw
                # count.  Map the unbounded decayed heat h through
                # min(255, round(32·log2(1+h))) — 32 points per heat
                # doubling, saturating at h ≈ 255 — so redis-cli
                # --hotkeys (which ranks by OBJECT FREQ) reads sane
                # values.  The raw decayed heat stays inspectable
                # through the residency surfaces (docs/observability.md
                # documents the mapping).
                h = max(0.0, rm.heat.heat(name))
                return _encode_int(
                    min(255, int(round(32.0 * math.log2(1.0 + h))))
                )
            return _encode_int(0)
        raise RespError(f"Unknown OBJECT subcommand {sub}")

    def _cmd_SCAN(self, args):
        """Cursor iteration with the Redis SCAN guarantee (keys present
        for the whole iteration are returned): the integer cursor maps to
        server-side resume state holding the LAST KEY returned, and each
        page lists live keys lexicographically after it — concurrent
        deletes can't shift the position.  State for abandoned cursors is
        evicted LRU (cap 1024)."""
        cursor = int(args[0])
        pattern, count = None, 10
        i = 1
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "MATCH":
                pattern = self._s(args[i + 1])
                i += 2
            elif opt == "COUNT":
                count = int(args[i + 1])
                if count < 1:
                    raise RespError("syntax error")
                i += 2
            else:
                raise RespError("syntax error")
        with self._scan_lock:
            after = None if cursor == 0 else self._scan_states.pop(cursor, None)
            if cursor != 0 and (after is None or not isinstance(after, str)):
                # Unknown/evicted cursor — or one minted by a COLLECTION
                # scan (HSCAN/SSCAN/ZSCAN states are tagged tuples):
                # Redis treats it as terminated.
                return b"*2\r\n" + _encode_bulk("0") + _encode_array([])
        keys = sorted(self._client.get_keys().get_keys(pattern))
        if after is not None:
            import bisect

            start = bisect.bisect_right(keys, after)
        else:
            start = 0
        page = keys[start : start + count]
        if start + count < len(keys):
            with self._scan_lock:
                self._scan_next += 1
                nxt = self._scan_next
                self._scan_states[nxt] = page[-1]
                while len(self._scan_states) > 1024:  # LRU cap
                    self._scan_states.pop(next(iter(self._scan_states)))
        else:
            nxt = 0
        return b"*2\r\n" + _encode_bulk(str(nxt)) + _encode_array(page)

    @staticmethod
    def _parse_scan_opts(args, i):
        pattern, count, novalues = None, 10, False
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "MATCH":
                pattern = args[i + 1].decode("latin-1")
                i += 2
            elif opt == "COUNT":
                count = int(args[i + 1])
                if count < 1:
                    raise RespError("syntax error")
                i += 2
            elif opt == "NOVALUES":
                novalues = True
                i += 1
            else:
                raise RespError("syntax error")
        return pattern, count, novalues

    def _collection_scan(self, tag: str, key: bytes, cursor: int,
                         items: list, pattern, count: int):
        """Shared HSCAN/SSCAN/ZSCAN cursor engine (SURVEY §2.1 iterators
        row): ``items`` is [(sort_bytes, reply_items_tuple)]; resume
        state holds the LAST sort key returned, so members present for
        the whole iteration are always returned even across concurrent
        deletes.  States live in the same LRU table as SCAN's, tagged
        with (command, key) so a cursor replayed against a different
        command or key terminates instead of desyncing."""
        import bisect
        import fnmatch

        with self._scan_lock:
            state = None if cursor == 0 else self._scan_states.pop(cursor, None)
            if cursor != 0 and (
                not isinstance(state, tuple)
                or state[:2] != (tag, key)
            ):
                return b"*2\r\n" + _encode_bulk("0") + _encode_array([])
            after = None if state is None else state[2]
        if pattern is not None:
            items = [
                it for it in items
                if fnmatch.fnmatch(it[0].decode("latin-1"), pattern)
            ]
        items.sort(key=lambda it: it[0])
        start = (
            0 if after is None
            else bisect.bisect_right([it[0] for it in items], after)
        )
        page = items[start : start + count]
        if start + count < len(items):
            with self._scan_lock:
                self._scan_next += 1
                nxt = self._scan_next
                self._scan_states[nxt] = (tag, key, page[-1][0])
                while len(self._scan_states) > 1024:  # LRU cap
                    self._scan_states.pop(next(iter(self._scan_states)))
        else:
            nxt = 0
        flat = [x for _, reply in page for x in reply]
        return b"*2\r\n" + _encode_bulk(str(nxt)) + _encode_array(flat)

    def _cmd_HSCAN(self, args):
        pattern, count, novalues = self._parse_scan_opts(args, 2)
        m = self._map(args[0])
        items = [
            (k, (k,) if novalues else (k, v))
            for k, v in m.entry_set()
        ]
        return self._collection_scan(
            "HSCAN", args[0], int(args[1]), items, pattern, count
        )

    def _cmd_SSCAN(self, args):
        pattern, count, _ = self._parse_scan_opts(args, 2)
        s = self._set(args[0])
        items = [(v, (v,)) for v in s.read_all()]
        return self._collection_scan(
            "SSCAN", args[0], int(args[1]), items, pattern, count
        )

    def _cmd_ZSCAN(self, args):
        pattern, count, _ = self._parse_scan_opts(args, 2)
        z = self._zset(args[0])
        items = [
            (m, (m, _fmt_score(sc).encode()))
            for m, sc in z.entry_range(0, -1)
        ]
        return self._collection_scan(
            "ZSCAN", args[0], int(args[1]), items, pattern, count
        )

    def _zstore(self, args, intersect: bool):
        """ZUNIONSTORE/ZINTERSTORE dest numkeys key... [WEIGHTS w...]
        [AGGREGATE SUM|MIN|MAX] — atomic replace of dest, returns the
        stored cardinality."""
        dest = args[0]
        numkeys = int(args[1])
        keys = args[2 : 2 + numkeys]
        weights = [1.0] * numkeys
        agg = "SUM"
        i = 2 + numkeys
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "WEIGHTS":
                ws = args[i + 1 : i + 1 + numkeys]
                if len(ws) != numkeys:
                    # zip() would silently drop the unweighted keys
                    raise RespError("syntax error")
                weights = [float(a) for a in ws]
                i += 1 + numkeys
            elif opt == "AGGREGATE":
                agg = args[i + 1].decode().upper()
                if agg not in ("SUM", "MIN", "MAX"):
                    raise RespError("syntax error")
                i += 2
            else:
                raise RespError("syntax error")
        with self._client._grid.lock:  # atomic multi-key read + replace
            maps = []
            for k, w in zip(keys, weights):
                entries = {
                    m: sc * w for m, sc in self._zset(k).entry_range(0, -1)
                }
                maps.append(entries)
            if intersect:
                members = set(maps[0]) if maps else set()
                for d in maps[1:]:
                    members &= set(d)
            else:
                members = set()
                for d in maps:
                    members |= set(d)
            out = {}
            for m in members:
                vals = [d[m] for d in maps if m in d]
                out[m] = (
                    sum(vals) if agg == "SUM"
                    else min(vals) if agg == "MIN" else max(vals)
                )
            dz = self._zset(dest)
            dz.delete()
            for m, sc in out.items():
                dz.add(sc, m)
            return _encode_int(len(out))

    def _cmd_ZUNIONSTORE(self, args):
        return self._zstore(args, intersect=False)

    def _cmd_ZINTERSTORE(self, args):
        return self._zstore(args, intersect=True)

    def _cmd_ZRANGEBYLEX(self, args):
        """Lexicographic range over same-score members: '[m' inclusive,
        '(m' exclusive, '-'/'+' unbounded; LIMIT offset count."""
        lo, hi = args[1], args[2]
        offset, count = 0, None
        if len(args) >= 6 and args[3].decode().upper() == "LIMIT":
            offset, count = int(args[4]), int(args[5])

        def bound(b):
            if b in (b"-", b"+"):
                return None, True
            if b[:1] == b"[":
                return b[1:], True
            if b[:1] == b"(":
                return b[1:], False
            raise RespError("min or max not valid string range item")

        lo_v, lo_inc = bound(lo)
        hi_v, hi_inc = bound(hi)

        def in_range(m):
            if lo == b"+" or hi == b"-":
                return False  # inverted/empty ranges match nothing
            if lo != b"-" and (m < lo_v or (m == lo_v and not lo_inc)):
                return False
            if hi != b"+" and (m > hi_v or (m == hi_v and not hi_inc)):
                return False
            return True

        members = sorted(
            m for m, _ in self._zset(args[0]).entry_range(0, -1)
        )
        out = [m for m in members if in_range(m)]
        if count is None or count < 0:
            out = out[offset:]  # Redis: negative count = all remaining
        else:
            out = out[offset : offset + count]
        return _encode_array(out)

    def _cmd_ECHO(self, args):
        return _encode_bulk(args[0])

    def _cmd_GETEX(self, args):
        """GET that also adjusts the key's TTL (exactly ONE of
        EX/PX/EXAT/PXAT/PERSIST; no option = plain GET without touching
        expiry).  TTL mutation rides the GridStore expire helpers — the
        same path EXPIRE/EXPIREAT/PERSIST use."""
        if len(args) > 3:
            raise RespError("syntax error")  # at most one expiry option
        opt = args[1].decode().upper() if len(args) > 1 else None
        operand = args[2] if len(args) > 2 else None
        if opt in ("EX", "PX", "EXAT", "PXAT"):
            if operand is None:
                raise RespError("syntax error")
        elif opt == "PERSIST":
            if operand is not None:
                raise RespError("syntax error")
        elif opt is not None:
            raise RespError("syntax error")
        import time as _time

        grid = self._client._grid
        name = self._s(args[0])
        with grid.lock:
            v = self._str_get(args[0])
            if v is None:
                return _encode_bulk(None)
            if opt == "EX":
                grid.expire(name, float(operand))
            elif opt == "PX":
                grid.expire(name, float(operand) / 1000.0)
            elif opt == "EXAT":
                grid.expire_at(name, float(operand))
            elif opt == "PXAT":
                grid.expire_at(name, float(operand) / 1000.0)
            elif opt == "PERSIST":
                grid.clear_expire(name)
        return _encode_bulk(v)

    def _cmd_COPY(self, args):
        """Grid-keyspace COPY (sketch-backend keys report 0 — their
        state lives in device pools, not copyable entries).  Deep-copies
        the value so the two keys never alias mutations."""
        import copy as _copy

        src, dst = self._s(args[0]), self._s(args[1])
        if src == dst:
            raise RespError(
                "source and destination objects are the same"
            )
        replace = any(a.decode().upper() == "REPLACE" for a in args[2:])
        grid = self._client._grid
        with grid.lock:
            e = grid.get_entry(src)
            if e is None:
                return _encode_int(0)
            if not replace and grid.get_entry(dst) is not None:
                return _encode_int(0)
            ne = grid.put_entry(dst, e.kind, _copy.deepcopy(e.value))
            ne.expire_at = e.expire_at
            return _encode_int(1)

    def _cmd_LMOVE(self, args):
        """LMOVE src dst LEFT|RIGHT LEFT|RIGHT — the RPOPLPUSH
        generalization, atomic under the grid lock."""
        wherefrom = args[2].decode().upper()
        whereto = args[3].decode().upper()
        if wherefrom not in ("LEFT", "RIGHT") or whereto not in ("LEFT", "RIGHT"):
            raise RespError("syntax error")
        src, dst = self._list(args[0]), self._list(args[1])
        grid = self._client._grid
        with grid.lock:
            # Destination kind check BEFORE popping (the pattern
            # poll_last_and_offer_first_to uses): a WRONGTYPE destination
            # discovered after the pop would lose the element.
            de = grid.get_entry(self._s(args[1]))
            if de is not None and de.kind not in ("list", "queue"):
                raise TypeError(
                    f"object {self._s(args[1])!r} holds a {de.kind}, "
                    f"not a list"
                )
            v = (
                src.poll_first() if wherefrom == "LEFT" else src.poll_last()
            )
            if v is None:
                return _encode_bulk(None)
            if whereto == "LEFT":
                dst.add_first(v)
            else:
                dst.add_last(v)
        return _encode_bulk(v)

    def _cmd_SINTERCARD(self, args):
        numkeys = int(args[0])
        keys = args[1 : 1 + numkeys]
        limit = None
        if len(args) > 1 + numkeys:
            if args[1 + numkeys].decode().upper() != "LIMIT":
                raise RespError("syntax error")
            limit = int(args[2 + numkeys])
            if limit < 0:
                raise RespError("LIMIT can't be negative")
        with self._client._grid.lock:
            acc = None
            for k in keys:
                members = set(self._set(k).read_all())
                acc = members if acc is None else (acc & members)
                if not acc:
                    break
        n = 0 if acc is None else len(acc)
        if limit:  # LIMIT 0 = unlimited, like Redis
            n = min(n, limit)
        return _encode_int(n)

    def _cmd_LPOS(self, args):
        """LPOS key element [RANK r] [COUNT c]."""
        rank, count = 1, None
        i = 2
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "RANK":
                rank = int(args[i + 1])
                if rank == 0:
                    raise RespError("RANK can't be zero")
                i += 2
            elif opt == "COUNT":
                count = int(args[i + 1])
                if count < 0:
                    raise RespError("COUNT can't be negative")
                i += 2
            else:
                raise RespError("syntax error")
        items = self._listidx(args[0]).read_all()
        target = args[1]
        matches = [ix for ix, v in enumerate(items) if v == target]
        if rank < 0:
            matches = list(reversed(matches))[(-rank - 1):]
        else:
            matches = matches[(rank - 1):]
        if count is None:
            return (
                _encode_int(matches[0]) if matches else _encode_bulk(None)
            )
        if count == 0:
            return _encode_array(matches)
        return _encode_array(matches[:count])

    def _cmd_HRANDFIELD(self, args):
        import random

        entries = self._map(args[0]).entry_set()
        if len(args) == 1:
            if not entries:
                return _encode_bulk(None)
            return _encode_bulk(random.choice(entries)[0])
        count = int(args[1])
        withvalues = (
            len(args) > 2 and args[2].decode().upper() == "WITHVALUES"
        )
        if count >= 0:  # distinct fields, up to the hash size
            picked = random.sample(entries, min(count, len(entries)))
        else:  # negative: repeats allowed, exactly |count| results
            picked = (
                [random.choice(entries) for _ in range(-count)]
                if entries else []
            )
        flat = []
        for f, v in picked:
            flat.append(f)
            if withvalues:
                flat.append(v)
        return _encode_array(flat)

    def _cmd_ZRANDMEMBER(self, args):
        import random

        entries = self._zset(args[0]).entry_range(0, -1)
        if len(args) == 1:
            if not entries:
                return _encode_bulk(None)
            return _encode_bulk(random.choice(entries)[0])
        count = int(args[1])
        withscores = (
            len(args) > 2 and args[2].decode().upper() == "WITHSCORES"
        )
        if count >= 0:
            picked = random.sample(entries, min(count, len(entries)))
        else:
            picked = (
                [random.choice(entries) for _ in range(-count)]
                if entries else []
            )
        flat = []
        for m, sc in picked:
            flat.append(m)
            if withscores:
                flat.append(_fmt_score(sc).encode())
        return _encode_array(flat)

    def _cmd_KEYS(self, args):
        pattern = self._s(args[0]) if args else "*"
        return _encode_array(self._client.get_keys().get_keys(pattern))

    def _cmd_DBSIZE(self, args):
        return _encode_int(self._client.get_keys().count())

    def _cmd_FLUSHALL(self, args):
        self._client.get_keys().flushall()
        return _encode_simple("OK")

    # strings (raw-bytes bucket)

    def _bucket(self, key: bytes):
        from redisson_tpu.grid.buckets import Bucket

        return self._raw(Bucket(self._s(key), self._client))

    def _str_get(self, key: bytes) -> Optional[bytes]:
        """String-view read: Redis counters ARE string keys, so the
        string read commands must serve atomiclong/atomicdouble entries
        (created via the Python counter API) in their string form
        rather than raising WRONGTYPE — TYPE already reports them as
        "string" (see _cmd_TYPE)."""
        grid = self._client._grid
        with grid.lock:
            e = grid.get_entry(self._s(key))
            if e is not None and e.kind in ("atomiclong", "atomicdouble"):
                v = e.value
                return (
                    _fmt_score(v) if isinstance(v, float) else str(int(v))
                ).encode()
        return self._bucket(key).get()

    def _cmd_SET(self, args):
        key, value = args[0], args[1]
        ttl = None
        i = 2
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "EX":
                ttl = float(args[i + 1])
                i += 2
            elif opt == "PX":
                ttl = float(args[i + 1]) / 1000.0
                i += 2
            else:
                raise RespError(f"unsupported SET option {opt}")
        self._bucket(key).set(value, ttl_seconds=ttl)
        return _encode_simple("OK")

    def _cmd_GET(self, args):
        return _encode_bulk(self._str_get(args[0]))

    def _cmd_MGET(self, args):
        out = []
        for k in args:
            try:
                out.append(self._str_get(k))
            except TypeError:  # WRONGTYPE slot: nil, Redis-style
                out.append(None)
        return _encode_array(out)

    def _cmd_MSET(self, args):
        for i in range(0, len(args), 2):
            self._bucket(args[i]).set(args[i + 1])
        return _encode_simple("OK")

    def _cmd_SETNX(self, args):
        return _encode_int(int(self._bucket(args[0]).set_if_absent(args[1])))

    def _cmd_SETEX(self, args):
        self._bucket(args[0]).set(args[2], ttl_seconds=float(args[1]))
        return _encode_simple("OK")

    def _cmd_PSETEX(self, args):
        self._bucket(args[0]).set(args[2], ttl_seconds=float(args[1]) / 1000)
        return _encode_simple("OK")

    def _cmd_GETSET(self, args):
        with self._client._grid.lock:  # atomic RMW (RLock)
            v = self._str_get(args[0])
            self._bucket(args[0]).set(args[1])
        return _encode_bulk(v)

    def _cmd_GETDEL(self, args):
        with self._client._grid.lock:  # atomic read+delete (RLock)
            v = self._str_get(args[0])
            if v is not None:
                self._client._grid.delete(self._s(args[0]))
        return _encode_bulk(v)

    def _cmd_APPEND(self, args):
        b = self._bucket(args[0])
        with self._client._grid.lock:  # atomic RMW, Redis APPEND contract
            v = (self._str_get(args[0]) or b"") + args[1]
            b.set(v)  # no longer numeric: the bucket kind is honest now
        return _encode_int(len(v))

    def _cmd_STRLEN(self, args):
        v = self._str_get(args[0])
        return _encode_int(0 if v is None else len(v))

    def _cmd_GETRANGE(self, args):
        v = self._str_get(args[0]) or b""
        start, end = int(args[1]), int(args[2])
        if start < 0:
            start = max(0, len(v) + start)
        if end < 0:
            end = max(0, len(v) + end)  # Redis clamps BOTH offsets to 0
        return _encode_bulk(v[start : end + 1])

    def _cmd_SETRANGE(self, args):
        b = self._bucket(args[0])
        off = int(args[1])
        if off < 0:
            raise RespError("offset is out of range")
        with self._client._grid.lock:  # atomic RMW
            v = bytearray(self._str_get(args[0]) or b"")
            if len(v) < off + len(args[2]):
                v.extend(b"\x00" * (off + len(args[2]) - len(v)))
            v[off : off + len(args[2])] = args[2]
            b.set(bytes(v))
        return _encode_int(len(v))

    def _cmd_DEL(self, args):
        return _encode_int(
            self._client.get_keys().delete(*[self._s(a) for a in args])
        )

    def _cmd_EXISTS(self, args):
        return _encode_int(
            self._client.get_keys().count_exists(*[self._s(a) for a in args])
        )

    def _cmd_EXPIRE(self, args):
        ok = self._client.get_keys().expire(self._s(args[0]), float(args[1]))
        return _encode_int(int(ok))

    def _cmd_PEXPIRE(self, args):
        ok = self._client.get_keys().expire(
            self._s(args[0]), float(args[1]) / 1000.0
        )
        return _encode_int(int(ok))

    def _cmd_TTL(self, args):
        ms = self._client.get_keys().remain_time_to_live(self._s(args[0]))
        return _encode_int(ms if ms < 0 else ms // 1000)

    def _cmd_PTTL(self, args):
        return _encode_int(
            self._client.get_keys().remain_time_to_live(self._s(args[0]))
        )

    def _cmd_PERSIST(self, args):
        name = self._s(args[0])
        grid_ok = self._client._grid.clear_expire(name)
        eng = getattr(self._client._engine, "clear_expire", None)
        return _encode_int(int(grid_ok or (eng is not None and eng(name))))

    def _cmd_RENAME(self, args):
        try:
            self._client.get_keys().rename(self._s(args[0]), self._s(args[1]))
        except RuntimeError as e:
            raise RespError(str(e))
        return _encode_simple("OK")

    def _cmd_RENAMENX(self, args):
        if self._exists_any(self._s(args[1])):
            return _encode_int(0)
        self._client.get_keys().rename(self._s(args[0]), self._s(args[1]))
        return _encode_int(1)

    def _cmd_EXPIREAT(self, args):
        import time as _time

        ttl = float(args[1]) - _time.time()
        ok = self._client.get_keys().expire(self._s(args[0]), max(ttl, 0.001))
        return _encode_int(int(ok))

    def _cmd_PEXPIREAT(self, args):
        import time as _time

        ttl = float(args[1]) / 1000.0 - _time.time()
        ok = self._client.get_keys().expire(self._s(args[0]), max(ttl, 0.001))
        return _encode_int(int(ok))

    def _cmd_RANDOMKEY(self, args):
        return _encode_bulk(self._client.get_keys().random_key())

    # server / connection admin

    # Default INFO excludes commandstats/latencystats, like redis-server
    # (they can be wide); 'INFO all'/'everything' or the explicit section
    # name includes them.
    _INFO_DEFAULT = (
        "server", "clients", "memory", "stats", "persistence",
        "replication", "nearcache", "frontdoor", "overload", "cluster",
        "rebalance", "telemetry", "events", "doctor", "loadstats",
        "keyspace",
    )

    def _cmd_INFO(self, args):
        section = args[0].decode().lower() if args else "default"
        if section == "default":
            sections = self._INFO_DEFAULT
        elif section in ("all", "everything"):
            sections = self._INFO_DEFAULT + ("commandstats", "latencystats")
        else:
            sections = (section,)
        obs = self.obs
        lines: list[str] = []
        for s in sections:
            if s == "server":
                lines += [
                    "# Server", "redis_version:7.9.9",
                    "redis_mode:%s" % (
                        "cluster" if self.cluster is not None
                        else "standalone"
                    ),
                    "run_id:redisson-tpu",
                    f"uptime_in_seconds:{int(time.monotonic() - self._started)}",
                ]
            elif s == "clients":
                lines += [
                    "# Clients",
                    f"connected_clients:{self._nconn}",
                    f"maxclients:{self.max_connections}",
                    # Conn-limit refusals (ISSUE 11 satellite): the
                    # accept-loop shed reactor-mode capacity tuning
                    # watches (also rtpu_resp_ingress_shed{conn_limit}).
                    f"rejected_connections:{self._conns_refused}",
                ]
            elif s == "memory":
                from redisson_tpu.serve.metrics import Profiler

                total = sum(
                    (v or {}).get("bytes_in_use") or 0
                    for v in Profiler.device_memory().values()
                )
                lines += [
                    "# Memory",
                    f"used_memory:{total}",  # device-resident pool bytes
                    "maxmemory:0",
                    "maxmemory_policy:noeviction",
                ]
                # Tiered residency (ISSUE 14): where the keyspace
                # actually lives — fast-tier occupancy vs budget, the
                # host-mirror and disk-blob footprints, and lifetime
                # transition counts (the SWAPIN/SWAPOUT view).
                rm = self._residency()
                if rm is not None:
                    st = rm.stats()
                    lines += [
                        f"residency_device_rows:{st['device_rows_used']}",
                        f"residency_device_rows_budget:"
                        f"{st['device_rows_budget']}",
                        f"residency_host_objects:{st['host_objects']}",
                        f"residency_host_bytes:{st['host_bytes']}",
                        f"residency_max_host_bytes:{rm.max_host_bytes}",
                        f"residency_disk_objects:{st['disk_objects']}",
                        f"residency_disk_bytes:{st['disk_bytes']}",
                        f"residency_max_disk_bytes:{rm.max_disk_bytes}",
                        f"residency_promote_heat:{rm.promote_heat:g}",
                        f"residency_promotions:{st['promotions']}",
                        f"residency_demotions:{st['demotions']}",
                        f"residency_spills:{st['spills']}",
                        f"residency_loads:{st['loads']}",
                        f"residency_host_serves:{st['host_serves']}",
                    ]
            elif s == "stats":
                total_cmds = (
                    sum(int(c.value) for _, c in obs.resp_commands.items())
                    if obs is not None else 0
                )
                lines += [
                    "# Stats",
                    f"total_connections_received:{self._conns_accepted}",
                    f"total_commands_processed:{total_cmds}",
                    f"slowlog_len:{0 if obs is None else len(obs.slowlog)}",
                ]
                # Self-healing dispatch (ISSUE 3): the degraded flag —
                # sketches serving from the host golden mirror while a
                # circuit breaker is open.
                health = getattr(
                    getattr(self._client, "_engine", None), "health", None
                )
                if health is not None:
                    mirrors = getattr(self._client._engine, "_mirrors", {})
                    lines += [
                        f"degraded:{1 if health.any_degraded else 0}",
                        f"degraded_objects:{len(mirrors)}",
                        f"breakers_open:{health.board.open_count()}",
                        f"executor_health:{health.state()}",
                    ]
            elif s == "commandstats" and obs is not None:
                lines.append("# Commandstats")
                for cmd, st in sorted(obs.command_stats().items()):
                    lines.append(
                        f"cmdstat_{cmd.lower()}:calls={st['calls']},"
                        f"usec={st['usec']},"
                        f"usec_per_call={st['usec_per_call']},"
                        f"rejected_calls=0,failed_calls={st['errors']}"
                    )
            elif s == "latencystats" and obs is not None:
                lines.append("# Latencystats")
                for cmd, st in sorted(obs.latency_stats().items()):
                    lines.append(
                        f"latency_percentiles_usec_{cmd.lower()}:"
                        f"p50={st['p50_us']:g},p99={st['p99_us']:g},"
                        f"p99.9={st['p999_us']:g}"
                    )
            elif s == "persistence":
                # Durability tier (ISSUE 10): snapshot + journal state —
                # the aof_*/rdb_* vocabulary stock tooling expects, plus
                # the journal-specific seq/lag/segment lines
                # (docs/robustness.md "Persistence & crash recovery").
                eng = getattr(self._client, "_engine", None)
                j = getattr(eng, "journal", None)
                lines += [
                    "# Persistence",
                    "loading:0",
                    f"rdb_last_save_time:"
                    f"{int(getattr(eng, '_last_save_ts', 0.0) or 0)}",
                    f"aof_enabled:{0 if j is None else 1}",
                ]
                if j is not None:
                    st = j.stats()
                    lines += [
                        f"appendfsync:{st['policy']}",
                        f"aof_last_seq:{st['last_seq']}",
                        f"aof_durable_seq:{st['durable_seq']}",
                        f"aof_pending_records:{st['lag_ops']}",
                        f"aof_segments:{st['segments']}",
                        f"aof_bytes_written:{st['bytes_written']}",
                        f"aof_records_written:{st['records_written']}",
                        f"aof_fsyncs:{st['fsyncs']}",
                        f"aof_fsync_ewma_us:{st['fsync_ewma_us']:g}",
                        f"aof_broken:{1 if st['broken'] else 0}",
                        f"aof_replayed_records:"
                        f"{0 if obs is None else int(sum(c.value for _, c in obs.journal_replayed.items()))}",
                    ]
            elif s == "replication":
                # Replication plane (ISSUE 18): role + offsets on BOTH
                # ends — a primary lists its replicas' acked offsets
                # (the WAIT fence's inputs), a replica its applied
                # offset, link state and lag (the staleness bound's
                # input).  Redis vocabulary where one exists.
                link = self.replica_link
                lines += [
                    "# Replication",
                    "role:%s" % ("slave" if link is not None else "master"),
                ]
                if link is not None:
                    lines += [
                        f"master_host:{link.master_host}",
                        f"master_port:{link.master_port}",
                        "master_link_status:%s" % (
                            "up" if link.link_up else "down"
                        ),
                        f"master_replid:{link.replid or '-'}",
                        f"slave_repl_offset:{link.applied}",
                        f"master_repl_offset:{link.master_offset}",
                        f"slave_lag_ops:{link.lag_ops()}",
                        "slave_read_only:1",
                        f"slave_full_resyncs:{link.full_resyncs}",
                        f"slave_partial_resyncs:{link.partial_resyncs}",
                        "connected_slaves:0",
                    ]
                else:
                    hub = self._repl_hub()
                    rows = hub.replica_rows() if hub is not None else []
                    lines.append(f"connected_slaves:{len(rows)}")
                    head = self._repl_offset()
                    for i, (rid, addr, offset, age_s) in enumerate(rows):
                        ip, _, rport = (addr or ":0").rpartition(":")
                        lines.append(
                            f"slave{i}:ip={ip},port={rport},"
                            f"state=online,offset={offset},"
                            f"lag={age_s:.3f},id={rid}"
                        )
                    lines += [
                        "master_replid:%s" % (
                            hub.repl_id if hub is not None else "-"
                        ),
                        f"master_repl_offset:{head}",
                        "repl_backlog_active:%d" % (
                            0 if hub is None else 1
                        ),
                        "repl_full_resyncs:%d" % (
                            0 if hub is None else hub.fullresyncs
                        ),
                        "repl_partial_resyncs:%d" % (
                            0 if hub is None else hub.partial_resyncs
                        ),
                    ]
            elif s == "nearcache":
                # Sketch near cache (ISSUE 4): the epoch-guarded host
                # read tier.  Section absent on the host engine (no tier
                # to report — honesty over empty zeros).
                nc = self._nearcache()
                if nc is not None:
                    st = nc.stats()
                    lines += [
                        "# Nearcache",
                        f"nearcache_enabled:{1 if st['enabled'] else 0}",
                        f"nearcache_hits:{st['hits']}",
                        f"nearcache_misses:{st['misses']}",
                        f"nearcache_hit_rate:{st['hit_rate']}",
                        f"nearcache_bytes:{st['bytes']}",
                        f"nearcache_max_bytes:{st['max_bytes']}",
                        f"nearcache_entries:{st['entries']}",
                        f"nearcache_evictions:{st['evictions']}",
                        f"nearcache_tenants:{st['tenants']}",
                        f"nearcache_tenant_quota_bytes:"
                        f"{st['tenant_quota_bytes']}",
                        f"nearcache_max_batch:{st['max_batch']}",
                    ]
            elif s == "frontdoor" and obs is not None:
                # Front-door vectorization (ISSUE 6): fusion + response-
                # cache effectiveness of the pipelined command stream.
                def _tot(fam):
                    return sum(int(c.value) for _, c in fam.items())

                fused = _tot(obs.resp_fused_cmds)
                total = sum(
                    int(c.value) for _, c in obs.resp_commands.items()
                )
                rch = _tot(obs.resp_cache_hits)
                rcm = _tot(obs.resp_cache_misses)
                lines += [
                    "# Frontdoor",
                    f"frontdoor_vectorize:{1 if self.vectorize else 0}",
                    f"frontdoor_fused_cmds:{fused}",
                    f"frontdoor_fused_ops:{_tot(obs.resp_fused_ops)}",
                    f"frontdoor_fused_runs:{_tot(obs.resp_fused_runs)}",
                    f"frontdoor_fusion_ratio:"
                    f"{round(fused / total, 4) if total else 0.0}",
                    f"frontdoor_response_cache_hits:{rch}",
                    f"frontdoor_response_cache_misses:{rcm}",
                    f"frontdoor_response_cache_hit_rate:"
                    f"{round(rch / (rch + rcm), 4) if rch + rcm else 0.0}",
                ]
                # Reactor front door (ISSUE 11): tick cadence + the
                # cross-connection fusion the merged pass achieved.
                rx = self.reactor
                ticks = _tot(obs.reactor_ticks)
                ready = _tot(obs.reactor_ready_conns)
                lines += [
                    f"frontdoor_reactor:{1 if rx is not None else 0}",
                    f"frontdoor_reactor_threads:"
                    f"{0 if rx is None else rx.nthreads}",
                    f"frontdoor_reactor_ticks:{ticks}",
                    f"frontdoor_reactor_ready_conns_per_tick:"
                    f"{round(ready / ticks, 2) if ticks else 0.0}",
                    f"frontdoor_cross_conn_fused_ops:"
                    f"{_tot(obs.cross_conn_fused_ops)}",
                ]
                # Per-core front door (ISSUE 17): worker identity (bench
                # clients probe this to pin worker-local traffic) + the
                # in-node handoff counters.
                mc = getattr(self, "multicore", None)
                lines += [
                    f"frontdoor_processes:{self._fd_workers}",
                    f"frontdoor_worker_index:{self._fd_index}",
                    "frontdoor_native_tick:"
                    f"{1 if rx is not None and rx.native_tick else 0}",
                ]
                if mc is not None:
                    lines += mc.info_lines()
            elif s == "overload" and obs is not None:
                # Overload control plane (ISSUE 7): deadlines, admission
                # control, tenant quotas, slow-client limits — the
                # operator's one-stop view of what is being shed and why
                # (docs/robustness.md explains each line).
                def _fam_tot(fam):
                    return sum(int(c.value) for _, c in fam.items())

                eng = getattr(self._client, "_engine", None)
                c = getattr(eng, "coalescer", None)
                gov = getattr(eng, "governor", None)
                shed_by = {
                    lv[0]: int(cv.value)
                    for lv, cv in obs.shed_ops.items()
                }
                shed_detail = ",".join(
                    f"{k}={v}" for k, v in sorted(shed_by.items())
                )
                lines += [
                    "# Overload",
                    f"overload_op_deadline_ms:{self.op_deadline_ms}",
                    f"overload_admission_watermark:"
                    f"{self.admission_watermark:g}",
                    f"overload_pressure:{round(self._pressure(), 4)}",
                    f"overload_est_wait_us:"
                    f"{0 if c is None else round(c.last_est_wait_s * 1e6)}",
                    f"overload_fetch_timeout_ms:"
                    f"{0 if c is None else int(c.fetch_timeout_s * 1000)}",
                    f"overload_shed_ops:{sum(shed_by.values())}",
                    f"overload_shed_by_reason:{shed_detail}",
                    f"overload_deadline_exceeded:"
                    f"{_fam_tot(obs.deadline_exceeded)}",
                    f"overload_ingress_shed_commands:{self._ingress_shed}",
                    f"overload_tenant_throttled:"
                    f"{_fam_tot(obs.tenant_throttled)}",
                    f"overload_tenant_rate_limit:"
                    f"{0 if gov is None else gov.rate_limit:g}",
                    f"overload_tenant_burst_ops:"
                    f"{0 if gov is None else gov._burst_cfg:g}",
                    f"overload_tenant_max_inflight:"
                    f"{0 if gov is None else gov.max_inflight}",
                    f"overload_fetch_timeouts:"
                    f"{_fam_tot(obs.fetch_timeouts)}",
                    f"overload_slow_client_disconnects:"
                    f"{self._slow_client_kills}",
                    f"overload_output_buffer_limit:"
                    f"{self.output_buffer_limit}",
                    f"overload_output_buffer_soft_seconds:"
                    f"{self.output_buffer_soft_seconds:g}",
                ]
            elif s == "cluster":
                # Cluster mode (ISSUE 12): slot ownership + migration
                # states + redirect counters (docs/clustering.md).
                lines.append("# Cluster")
                if self.cluster is None:
                    lines.append("cluster_enabled:0")
                else:
                    lines += self.cluster.info_lines()
            elif s == "telemetry" and obs is not None:
                # Fleet telemetry plane (ISSUE 13): the distributed
                # tracer's live knob/ring state and the latency
                # monitor's arm state — what an operator checks before
                # asking "why is TRACE GET empty".
                ts = obs.trace.stats()
                ls = obs.latency.stats()
                lines += [
                    "# Telemetry",
                    f"trace_sample_rate:{ts['sample_rate']:g}",
                    f"trace_spans:{ts['spans']}",
                    f"trace_traces:{ts['traces']}",
                    f"trace_max_spans:{ts['max_spans']}",
                    f"trace_sampled_total:{ts['sampled']}",
                    f"trace_evicted_total:{ts['evicted']}",
                    f"latency_monitor_threshold:{ls['threshold_ms']}",
                    f"latency_events:{ls['events']}",
                    f"latency_samples:{ls['samples']}",
                    f"monitors:{len(self._monitors)}",
                ]
            elif s == "rebalance":
                # Autonomous rebalancer (ISSUE 19): knobs as literals
                # so the served-config coherence pass (RT004) ties the
                # CONFIG SET rows to an operator-visible INFO surface,
                # plus the agent's live wave counters.
                rb = getattr(self, "rebalancer", None)
                lines.append("# Rebalance")
                if rb is None:
                    lines.append("rebalance_enabled:0")
                else:
                    st = rb.status()
                    lines += [
                        "rebalance_enabled:1",
                        f"rebalance_paused:{1 if st['paused'] else 0}",
                        "rebalance_is_coordinator:"
                        f"{1 if st['is_coordinator'] else 0}",
                        f"rebalance_threshold:{st['threshold']:g}",
                        f"rebalance_interval_ms:{st['interval_ms']}",
                        f"rebalance_max_moves:{st['max_moves']}",
                        f"rebalance_pace_ms:{st['pace_ms']}",
                        f"rebalance_cooldown_ms:{st['cooldown_ms']}",
                        "rebalance_imbalance_ratio:"
                        f"{st['imbalance_ratio']:g}",
                        f"rebalance_waves:{st['waves']}",
                        f"rebalance_slots_moved:{st['slots_moved']}",
                        f"rebalance_keys_moved:{st['keys_moved']}",
                        f"rebalance_failures:{st['failures']}",
                    ]
            elif s == "events":
                # Flight recorder (ISSUE 20): ring occupancy, lifetime
                # seq, and evictions — the "is the black box taping"
                # check before an operator trusts EVENTS GET.
                ring = self._events()
                lines.append("# Events")
                if ring is None:
                    lines.append("events_enabled:0")
                else:
                    st = ring.stats()
                    lines += [
                        "events_enabled:1",
                        f"events_len:{st['events']}",
                        f"events_seq:{st['seq']}",
                        f"events_evicted:{st['evicted']}",
                        f"events_max:{st['max_events']}",
                    ]
            elif s == "doctor":
                # Invariant doctor (ISSUE 20): armed state + live sweep
                # and finding counts (the CLUSTER DOCTOR headline rows).
                doc = getattr(self, "doctor", None)
                lines.append("# Doctor")
                if doc is None:
                    lines.append("doctor_enabled:0")
                else:
                    st = doc.status()
                    lines += [
                        "doctor_enabled:1",
                        f"doctor_paused:{1 if st['paused'] else 0}",
                        "doctor_is_coordinator:"
                        f"{1 if st['is_coordinator'] else 0}",
                        f"doctor_interval_ms:{st['interval_ms']}",
                        f"doctor_sweeps:{st['sweeps']}",
                        f"doctor_active_findings:"
                        f"{len(st['active_findings'])}",
                        f"doctor_findings_total:{st['findings_total']}",
                        f"doctor_canary_failures:{st['canary_failures']}",
                    ]
            elif s == "loadstats":
                # Load-attribution plane (ISSUE 16): the loadmap's
                # totals, hottest slots/keys, and the per-tenant
                # device-time shares — the single-node view of what
                # CLUSTER LOADMAP / fleet_loadmap() aggregate.
                lm = self.loadmap
                st = lm.stats()
                lines += ["# Loadstats"] + [
                    f"{k}:{v:g}" if isinstance(v, float) else f"{k}:{v}"
                    for k, v in st.items()
                    # Emitted below as literals so the served-config
                    # coherence pass (RT004) sees the knob names.
                    if k not in ("loadmap_enabled",
                                 "loadmap_key_sample_rate")
                ]
                lines.append("loadmap_top_slots:" + ",".join(
                    f"{s_}={v}" for s_, v in lm.top_slots(8)
                ))
                lines.append("loadmap_hot_keys:" + ",".join(
                    f"{k}={c:g}" for k, c in lm.hot_keys(8)
                ))
                shares = lm.tenant_shares()
                lines.append("loadmap_tenant_shares:" + ",".join(
                    f"{t}={d['share']:g}" for t, d in shares.items()
                ))
                lines.append(
                    "loadmap_keys_exact:"
                    f"{1 if self._loadmap_keys_exact else 0}"
                )
                lines.append(f"loadmap_enabled:{1 if lm.enabled else 0}")
                lines.append(f"loadmap_key_sample_rate:{lm.sample_rate:g}")
            elif s == "keyspace":
                n = self._client.get_keys().count()
                lines += ["# Keyspace", f"db0:keys={n},expires=0,avg_ttl=0"]
        return _encode_bulk("\r\n".join(lines) + "\r\n")

    # SLOWLOG (→ redis-server slowlog.c command surface): entries are
    # recorded by _safe_dispatch against the shared obs bundle.

    def _cmd_SLOWLOG(self, args):
        if not args:
            raise RespError(
                "wrong number of arguments for 'slowlog' command"
            )
        sub = args[0].decode().upper()
        sl = self.obs.slowlog
        if sub == "GET":
            count = int(args[1]) if len(args) > 1 else 10
            entries = sl.entries(count)
            out = b"*" + str(len(entries)).encode() + b"\r\n"
            for e in entries:
                fields = [
                    _encode_int(e.id),
                    _encode_int(e.unix_ts),
                    _encode_int(e.duration_us),
                    _encode_array(list(e.args)),
                    _encode_bulk(e.client_addr),
                    _encode_bulk(e.client_name),
                ]
                if getattr(e, "trace_id", ""):
                    # Slow-trace auto-capture (ISSUE 13): a sampled slow
                    # command carries its trace id as a 7th element
                    # (clients tolerate per-version slowlog arity; the
                    # classic 6-element shape is unchanged when tracing
                    # is off).
                    fields.append(_encode_bulk(e.trace_id))
                out += (
                    b"*" + str(len(fields)).encode() + b"\r\n"
                    + b"".join(fields)
                )
            return out
        if sub == "RESET":
            sl.reset()
            return _encode_simple("OK")
        if sub == "LEN":
            return _encode_int(len(sl))
        if sub == "HELP":
            return _encode_array([
                b"SLOWLOG GET [<count>|-1]",
                b"SLOWLOG LEN",
                b"SLOWLOG RESET",
                b"SLOWLOG HELP",
            ])
        raise RespError(
            f"Unknown SLOWLOG subcommand or wrong number of arguments "
            f"for '{sub.lower()}'"
        )

    # -- fleet telemetry plane (ISSUE 13): TRACE / LATENCY / MONITOR -------

    def _cmdctx_RTPU_TRACE(self, args, ctx: _ConnCtx):
        """Trace-context wire prelude: ``RTPU.TRACE <trace_id>
        <parent_span_id>`` parks the remote parent on the connection;
        the NEXT command joins that trace (head sampling already
        happened at the first hop) and consumes it — one-shot, the
        ASKING shape.  Unknown-command-safe by design: a plain server
        errors on RTPU.TRACE and the traced command still executes,
        just untraced on that hop."""
        if len(args) < 2:
            raise RespError(
                "wrong number of arguments for 'rtpu.trace' command"
            )
        tid = args[0].decode("latin-1", "replace")
        sid = args[1].decode("latin-1", "replace")
        if not (8 <= len(tid) <= 64 and 4 <= len(sid) <= 32):
            raise RespError("RTPU.TRACE trace/span id out of range")
        ctx.trace_next = (tid, sid)
        return _encode_simple("OK")

    def _cmd_TRACE(self, args):
        """TRACE GET [trace_id] | SAMPLE <rate> | RESET | LEN | HELP —
        the distributed-trace ring's RESP surface.  GET replies one JSON
        document per trace (spans grouped by trace id), chosen so a
        cross-node merge is a list concat (cluster client
        fleet_traces)."""
        if not args:
            raise RespError(
                "wrong number of arguments for 'trace' command"
            )
        sub = args[0].decode().upper()
        tr = self.obs.trace
        if sub == "GET":
            tid = args[1].decode() if len(args) > 1 else None
            return _encode_array(
                [d.encode() for d in tr.traces_json(tid)]
            )
        if sub == "SAMPLE":
            if len(args) < 2:
                raise RespError(
                    "wrong number of arguments for 'trace|sample'"
                )
            try:
                tr.set_sample_rate(float(args[1]))
            except ValueError as e:
                raise RespError(str(e)) from e
            if hasattr(self, "_config_table"):
                self._config_table["trace-sample-rate"] = (
                    f"{tr.sample_rate:g}"
                )
            return _encode_simple("OK")
        if sub == "RESET":
            tr.reset()
            return _encode_simple("OK")
        if sub == "LEN":
            return _encode_int(tr.stats()["spans"])
        if sub == "HELP":
            return _encode_array([
                b"TRACE GET [<trace-id>]",
                b"TRACE SAMPLE <rate 0..1>",
                b"TRACE RESET",
                b"TRACE LEN",
                b"TRACE HELP",
            ])
        raise RespError(f"Unknown TRACE subcommand {sub}")

    def _cmd_LATENCY(self, args):
        """LATENCY LATEST | HISTORY <event> | RESET [event ...] |
        DOCTOR | HELP — redis-server's latency monitor surface, fed by
        span phases and the named events (slow-launch, fsync-stall,
        breaker-open, migration, reconcile, command)."""
        if not args:
            raise RespError(
                "wrong number of arguments for 'latency' command"
            )
        sub = args[0].decode().upper()
        lat = self.obs.latency
        if sub == "LATEST":
            rows = []
            for name, ts, ms, mx in lat.latest():
                rows.append(
                    b"*4\r\n" + _encode_bulk(name) + _encode_int(ts)
                    + _encode_int(ms) + _encode_int(mx)
                )
            return (
                b"*" + str(len(rows)).encode() + b"\r\n" + b"".join(rows)
            )
        if sub == "HISTORY":
            if len(args) < 2:
                raise RespError(
                    "wrong number of arguments for 'latency|history'"
                )
            pairs = lat.history(args[1].decode())
            rows = [
                b"*2\r\n" + _encode_int(ts) + _encode_int(ms)
                for ts, ms in pairs
            ]
            return (
                b"*" + str(len(rows)).encode() + b"\r\n" + b"".join(rows)
            )
        if sub == "RESET":
            return _encode_int(
                lat.reset(*[a.decode() for a in args[1:]])
            )
        if sub == "DOCTOR":
            return _encode_bulk(lat.doctor())
        if sub == "HELP":
            return _encode_array([
                b"LATENCY LATEST",
                b"LATENCY HISTORY <event>",
                b"LATENCY RESET [<event> ...]",
                b"LATENCY DOCTOR",
                b"LATENCY HELP",
            ])
        raise RespError(f"Unknown LATENCY subcommand {sub}")

    def _cmd_EVENTS(self, args):
        """EVENTS GET [count] [kind] | LEN | RESET | HELP — the flight
        recorder's RESP surface (ISSUE 20).  GET replies ONE JSON
        document (node id, ring stats, events newest-last) so the
        cluster client's fleet_events() merge is a per-node JSON parse
        + list merge, the CLUSTER LOADMAP shape.  ``kind`` filters by
        exact kind, or a whole control plane with a trailing dot
        (``EVENTS GET 0 doctor.``)."""
        if not args:
            raise RespError(
                "wrong number of arguments for 'events' command"
            )
        sub = args[0].decode().upper()
        ring = self._events()
        if ring is None:
            raise RespError("this process has no flight recorder")
        if sub == "GET":
            count = 0
            kind = ""
            if len(args) > 1:
                try:
                    count = int(args[1])
                except ValueError:
                    raise RespError(
                        "value is not an integer or out of range"
                    )
                if count < 0:
                    raise RespError(
                        "value is not an integer or out of range"
                    )
            if len(args) > 2:
                kind = args[2].decode()
            import json

            doc = dict(ring.stats())
            doc["node"] = ring.node
            doc["events"] = ring.snapshot(count=count, kind=kind)
            return _encode_bulk(json.dumps(doc).encode())
        if sub == "LEN":
            return _encode_int(len(ring))
        if sub == "RESET":
            return _encode_int(ring.reset())
        if sub == "HELP":
            return _encode_array([
                b"EVENTS GET [<count>] [<kind> | <plane.>]",
                b"EVENTS LEN",
                b"EVENTS RESET",
                b"EVENTS HELP",
            ])
        raise RespError(f"Unknown EVENTS subcommand {sub}")

    def _cmd_HOTKEYS(self, args):
        """HOTKEYS [count] (ISSUE 16): the hottest keys by the loadmap's
        dogfooded sketches — a decayed count-min sketch feeding a
        space-saving top-k over the sampled ingress key stream
        (redis-cli --hotkeys parity, without the SCAN+OBJECT FREQ round
        trips).  Flat [key, count, key, count, ...] reply, hottest
        first; counts are decayed CMS estimates scaled by the sample
        rate's inverse would be a lie (the estimate is of the SAMPLED
        stream), so they are reported raw and documented as relative
        weights.  Shed-exempt: finding the hot key IS the overload
        diagnosis."""
        count = 16
        if args:
            try:
                count = int(args[0])
            except ValueError:
                raise RespError("value is not an integer or out of range")
            if count < 0:
                raise RespError("value is not an integer or out of range")
        flat = []
        for key, est in self.loadmap.hot_keys(count):
            flat.append(key.encode())
            flat.append(int(round(est)))
        return _encode_array(flat)

    def _cmdctx_MONITOR(self, args, ctx: _ConnCtx):
        """MONITOR: stream every dispatched command to this connection
        (redis parity).  Rides the reactor's blocking-handoff path (the
        _DETACH set) like SUBSCRIBE; the feed itself is the pub/sub
        push mechanism.  RESET (or disconnect) leaves monitor mode."""
        ctx.monitor = True
        self._monitors.add(ctx)
        return _encode_simple("OK")

    def _cmdctx_CLIENT(self, args, ctx: _ConnCtx):
        sub = args[0].decode().upper() if args else ""
        if sub == "SETNAME":
            ctx.client_name = self._s(args[1])
            return _encode_simple("OK")
        if sub == "GETNAME":
            return _encode_bulk(ctx.client_name)
        if sub == "ID":
            return _encode_int(id(ctx) & 0x7FFFFFFF)
        if sub == "SETINFO":
            # redis-py 5.x sends CLIENT SETINFO lib-name/lib-ver on every
            # connect; acknowledge (the metadata has no server-side use).
            return _encode_simple("OK")
        if sub == "INFO":
            name = ctx.client_name or ""
            return _encode_bulk(
                f"id={id(ctx) & 0x7FFFFFFF} name={name} "
                f"resp={ctx.proto}".encode()
            )
        if sub == "NO-EVICT" or sub == "NO-TOUCH":
            return _encode_simple("OK")
        if sub == "DEADLINE":
            # Overload control plane (ISSUE 7): per-connection override
            # of the server's op_deadline_ms.  CLIENT DEADLINE <ms> sets
            # it, 0 disables deadlines for this connection, a negative
            # value reverts to the server default; with no argument the
            # current setting is returned.
            if len(args) == 1:
                cur = ctx.op_deadline_ms
                return _encode_bulk(
                    b"default" if cur is None else str(cur).encode()
                )
            try:
                v = int(args[1])
            except ValueError:
                raise RespError("value is not an integer or out of range")
            ctx.op_deadline_ms = None if v < 0 else v
            return _encode_simple("OK")
        raise RespError(f"unsupported CLIENT subcommand {sub}")

    def _cmd_COMMAND(self, args):
        return _encode_array([])  # stock-client handshake stub

    # -- cluster protocol (ISSUE 12) ---------------------------------------

    def _cmdctx_ASKING(self, args, ctx: _ConnCtx):
        """One-shot import-side handshake: the NEXT keyed command may be
        served from an IMPORTING slot this node does not own yet."""
        if self.cluster is None:
            raise RespError("This instance has cluster support disabled")
        ctx.asking = True
        return _encode_simple("OK")

    def _cmd_MIGRATE(self, args):
        """Atomic per-key handoff to another node (the migration pump's
        unit of work): dump -> remote ASKING+RESTORE -> local delete,
        one critical section vs writes to the moving key (see
        cluster/door.py)."""
        if self.cluster is None:
            raise RespError("MIGRATE requires cluster mode")
        if len(args) < 5:
            raise RespError("wrong number of arguments for 'migrate' command")
        host, port, key = self._s(args[0]), int(args[1]), args[2]
        timeout_ms = int(args[4])  # args[3] = destination-db (single db)
        try:
            return _encode_simple(
                self.cluster.migrate_key(host, port, key, timeout_ms)
            )
        except OSError as e:
            raise RespError(f"IOERR MIGRATE to {host}:{port} failed: {e}")

    def _cmd_CLUSTER(self, args):
        if not args:
            raise RespError(
                "wrong number of arguments for 'cluster' command"
            )
        from redisson_tpu.cluster.slots import key_slot as _key_slot

        sub = args[0].decode("latin-1", "replace").upper()
        if sub == "KEYSLOT":
            if len(args) != 2:
                raise RespError("CLUSTER KEYSLOT needs exactly one key")
            return _encode_int(_key_slot(args[1]))
        door = self.cluster
        if door is None:
            if sub == "INFO":
                return _encode_bulk("cluster_enabled:0\r\n")
            raise RespError("This instance has cluster support disabled")
        if sub == "MYID":
            return _encode_bulk(door.myid)
        if sub == "INFO":
            return _encode_bulk("\r\n".join(door.info_lines()) + "\r\n")
        if sub == "SLOTS":
            table = door.slotmap.slots_table()
            frames = [b"*%d\r\n" % len(table)]
            for start, end, nid, host, port in table:
                frames.append(b"*3\r\n")
                frames.append(_encode_int(start))
                frames.append(_encode_int(end))
                frames.append(b"*3\r\n")
                frames.append(_encode_bulk(host))
                frames.append(_encode_int(port))
                frames.append(_encode_bulk(nid))
            return b"".join(frames)
        if sub == "SHARDS":
            nodes = door.slotmap.node_ids()
            frames = [b"*%d\r\n" % len(nodes)]
            for nid in nodes:
                host, port = door.slotmap.addr(nid)
                flat = [
                    v
                    for r in door.slotmap.ranges(nid)
                    for v in r
                ]
                frames.append(b"*4\r\n")
                frames.append(_encode_bulk("slots"))
                frames.append(_encode_array(flat))
                frames.append(_encode_bulk("nodes"))
                frames.append(b"*1\r\n" + _encode_array([
                    b"id", nid.encode(), b"endpoint", host.encode(),
                    b"port", port,
                    b"role", door.slotmap.role(nid).encode(),
                ]))
            return b"".join(frames)
        if sub == "NODES":
            lines = []
            for nid in door.slotmap.node_ids():
                host, port = door.slotmap.addr(nid)
                slots = " ".join(
                    ("%d-%d" % (a, b)) if a != b else str(a)
                    for a, b in door.slotmap.ranges(nid)
                )
                me = ",myself" if nid == door.myid else ""
                role = door.slotmap.role(nid)
                flag = "master" if role == "master" else "slave"
                primary = door.slotmap.replica_of(nid) or "-"
                lines.append(
                    f"{nid} {host}:{port}@{port} {flag}{me} {primary} "
                    f"0 0 0 connected {slots}".rstrip()
                )
            return _encode_bulk("\n".join(lines) + "\n")
        if sub == "SETSLOT":
            if len(args) < 3:
                raise RespError("CLUSTER SETSLOT needs a slot and an action")
            slot = int(args[1])
            action = args[2].decode("latin-1", "replace").upper()
            try:
                if action == "IMPORTING":
                    door.slotmap.set_importing(slot, self._s(args[3]))
                elif action == "MIGRATING":
                    door.slotmap.set_migrating(slot, self._s(args[3]))
                elif action == "STABLE":
                    door.slotmap.set_stable(slot)
                elif action == "NODE":
                    closed = door.slotmap.set_owner(slot, self._s(args[3]))
                    if closed["was_importing"] or closed["was_migrating"]:
                        # A finalize that closed a live migration state:
                        # the slot handoff this node took part in.
                        if self.obs is not None:
                            self.obs.cluster_slot_migrations.inc()
                else:
                    raise RespError(
                        f"Invalid CLUSTER SETSLOT action {action}"
                    )
            except KeyError as e:
                raise RespError(f"Unknown node {e.args[0]}")
            return _encode_simple("OK")
        if sub == "MIGRATABLE":
            # Driver pre-flight (cluster/supervisor.py): keys in the
            # slot that MIGRATE would refuse; empty = safe to reshard.
            return _encode_array([
                k.encode() for k in door.undumpable_in_slot(int(args[1]))
            ])
        if sub == "COUNTKEYSINSLOT":
            # O(1) from the load-map per-slot key counters when keyspace
            # hooks are wired; DEBUG COUNTKEYSINSLOT keeps the O(keys)
            # scan as a cross-check.
            slot = int(args[1])
            lm = getattr(self, "loadmap", None)
            if lm is not None and self._loadmap_keys_exact:
                return _encode_int(lm.keys_in_slot(slot))
            return _encode_int(len(door.keys_in_slot(slot)))
        if sub == "LOADMAP":
            # Node-local load snapshot as one JSON bulk: per-slot load
            # vectors (non-zero slots only), hot keys, tenant shares.
            # ClusterClient.fleet_loadmap() merges these across nodes.
            import json

            lm = getattr(self, "loadmap", None)
            if lm is None:
                raise RespError("LOADMAP requires telemetry")
            snap = lm.snapshot()
            snap["node"] = door.myid
            return _encode_bulk(json.dumps(snap).encode())
        if sub == "GETKEYSINSLOT":
            count = int(args[2]) if len(args) > 2 else 10
            return _encode_array([
                k.encode() for k in door.keys_in_slot(int(args[1]), count)
            ])
        if sub == "MEET":
            # Elastic join (ISSUE 19): teach this node a new member's
            # id/address so slots can be SETSLOT'd onto it.  Argument
            # shape is `MEET <id> <host> <port>` — ids are explicit in
            # this cluster (no gossip handshake to mint one).
            if len(args) < 4:
                raise RespError("CLUSTER MEET needs an id, host and port")
            door.slotmap.add_node(
                self._s(args[1]), self._s(args[2]), int(args[3])
            )
            return _encode_simple("OK")
        if sub == "REBALANCE":
            # Autonomous rebalancer surface (ISSUE 19).  STATUS works
            # even unarmed (reports enabled=false) so operators can
            # probe; the verbs require the agent.
            import json

            verb = (
                self._s(args[1]).upper() if len(args) > 1 else "STATUS"
            )
            rb = getattr(self, "rebalancer", None)
            if verb == "STATUS":
                if rb is None:
                    payload = {"enabled": False}
                else:
                    payload = rb.status()
                payload["node"] = door.myid
                return _encode_bulk(json.dumps(payload).encode())
            if rb is None:
                raise RespError(
                    "rebalancer is not armed on this node "
                    "(start with --rebalance)"
                )
            if verb == "PAUSE":
                rb.pause()
                return _encode_simple("OK")
            if verb == "RESUME":
                rb.resume()
                return _encode_simple("OK")
            if verb == "NOW":
                # Synchronous forced tick in this connection's thread:
                # the reply carries how many migrations the wave ran,
                # so scripts can drive rebalancing step by step.
                return _encode_int(rb.tick(force=True))
            if verb == "DRAIN":
                if len(args) < 3:
                    raise RespError("CLUSTER REBALANCE DRAIN needs a node id")
                rb.planner.drain(self._s(args[2]))
                return _encode_simple("OK")
            if verb == "UNDRAIN":
                if len(args) < 3:
                    raise RespError(
                        "CLUSTER REBALANCE UNDRAIN needs a node id"
                    )
                rb.planner.undrain(self._s(args[2]))
                return _encode_simple("OK")
            raise RespError(
                f"Unknown CLUSTER REBALANCE verb '{verb.lower()}'"
            )
        if sub == "MIGRATIONS":
            # This node's in-flight slot states (slot -> peer id), the
            # doctor's stuck-migration probe surface.  JSON bulk, the
            # LOADMAP idiom.
            import json

            with door.slotmap._lock:
                payload = {
                    "node": door.myid,
                    "importing": {
                        str(s): n
                        for s, n in door.slotmap.importing.items()
                    },
                    "migrating": {
                        str(s): n
                        for s, n in door.slotmap.migrating.items()
                    },
                }
            return _encode_bulk(json.dumps(payload).encode())
        if sub == "DOCTOR":
            # Fleet doctor surface (ISSUE 20): bare CLUSTER DOCTOR is
            # the human-readable report (the LATENCY DOCTOR analog);
            # STATUS works even unarmed (enabled=false) so operators
            # can probe; PAUSE/RESUME/NOW require the agent — the
            # CLUSTER REBALANCE contract.
            import json

            verb = (
                self._s(args[1]).upper() if len(args) > 1 else "REPORT"
            )
            doc = getattr(self, "doctor", None)
            if verb == "STATUS":
                if doc is None:
                    payload = {"enabled": False}
                else:
                    payload = doc.status()
                payload["node"] = door.myid
                return _encode_bulk(json.dumps(payload).encode())
            if doc is None:
                if verb == "REPORT":
                    return _encode_bulk(
                        b"Fleet doctor is not armed on this node "
                        b"(start with --doctor)."
                    )
                raise RespError(
                    "fleet doctor is not armed on this node "
                    "(start with --doctor)"
                )
            if verb == "REPORT":
                return _encode_bulk(doc.report().encode())
            if verb == "PAUSE":
                doc.pause()
                return _encode_simple("OK")
            if verb == "RESUME":
                doc.resume()
                return _encode_simple("OK")
            if verb == "NOW":
                # Synchronous forced sweep in this connection's
                # thread; the reply is the active-finding count, so a
                # chaos harness can assert convergence step by step.
                return _encode_int(doc.tick(force=True))
            raise RespError(
                f"Unknown CLUSTER DOCTOR verb '{verb.lower()}'"
            )
        raise RespError(
            f"Unknown CLUSTER subcommand or wrong number of arguments "
            f"for '{sub.lower()}'"
        )

    # TOPK.* (RedisBloom Top-K shape) over the CMS heavy-hitter engine:
    # the candidate-table + device re-estimation design stands in for
    # RedisBloom's HeavyKeeper — same API, same role (BASELINE config 5).

    def _cms(self, key: bytes):
        return self._client.get_count_min_sketch(self._s(key))

    def _cmd_TOPK_RESERVE(self, args):
        k = int(args[1])
        width = int(args[2]) if len(args) > 2 else max(1 << 10, 8 * k)
        depth = int(args[3]) if len(args) > 3 else 4
        # args[4] (decay) accepted, meaningless for exact re-estimation.
        c = self._cms(args[0])
        if not c.try_init(depth, width, track_top_k=k):
            raise RespError("TopK: key already exists")
        return _encode_simple("OK")

    def _cmd_TOPK_ADD(self, args):
        c = self._cms(args[0])
        for item in args[1:]:
            c.add(item)
        # RedisBloom returns the dropped item per slot; exact re-
        # estimation never drops — nil per added item.
        return _encode_array([None] * (len(args) - 1))

    def _cmd_TOPK_INCRBY(self, args):
        c = self._cms(args[0])
        for i in range(1, len(args), 2):
            c.add(args[i], int(args[i + 1]))
        return _encode_array([None] * ((len(args) - 1) // 2))

    def _cmd_TOPK_QUERY(self, args):
        c = self._cms(args[0])
        top = {m for m, _ in c.top_k()}
        return _encode_array([int(item in top) for item in args[1:]])

    def _cmd_TOPK_COUNT(self, args):
        c = self._cms(args[0])
        return _encode_array([int(c.estimate(item)) for item in args[1:]])

    def _cmd_TOPK_LIST(self, args):
        c = self._cms(args[0])
        withcount = any(a.upper() == b"WITHCOUNT" for a in args[1:])
        out = []
        for member, count in c.top_k():
            out.append(member)
            if withcount:
                out.append(int(count))
        return _encode_array(out)

    def _cmd_TOPK_INFO(self, args):
        c = self._cms(args[0])
        k = self._client._engine.topk.track(self._s(args[0]))
        return _encode_array(
            [b"k", int(k), b"width", int(c.get_width()), b"depth",
             int(c.get_depth()), b"decay", b"1"]
        )

    # keyspace type / dump / restore (→ RKeys#getType + RObject#dump/
    # restore riding Redis TYPE / DUMP / RESTORE)

    # Grid KIND -> the type name Redis reports.  Lock/semaphore/counter
    # objects live in plain string keys upstream; geo is a zset.
    _TYPE_NAMES = {
        "bucket": "string", "binarystream": "string",
        "atomiclong": "string", "atomicdouble": "string",
        "longadder": "string", "doubleadder": "string",
        "idgenerator": "string", "lock": "string", "spinlock": "string",
        "fencedlock": "string", "fairlock": "string", "rwlock": "string",
        "semaphore": "string", "xsemaphore": "string",
        "countdownlatch": "string",
        "list": "list", "queue": "list", "delayedqueue": "list",
        "priorityqueue": "list", "ringbuffer": "list",
        "map": "hash", "mapcache": "hash",
        "listmultimap": "hash", "setmultimap": "hash",
        "listmultimapcache": "hash", "setmultimapcache": "hash",
        "set": "set", "setcache": "set",
        "zset": "zset", "sortedset": "zset", "lexset": "zset",
        "geo": "zset", "timeseries": "zset",
        "stream": "stream",
        # sketch kinds (RedisBloom reports module types; HLL/bitmaps are
        # strings in Redis)
        "bloom": "MBbloom--", "cms": "CMSk-TYPE",
        "hll": "string", "bitset": "string",
    }

    def _kind_of(self, name: str) -> Optional[str]:
        eng = self._client._engine
        reg = getattr(eng, "registry", None)
        if reg is not None:  # TPU engine
            if eng.exists(name):
                e = reg.lookup(name)
                if e is not None:
                    return e.kind
        else:  # host golden engine
            with eng._lock:
                o = eng._live(name)
                if o is not None:
                    return o["kind"]
        e = self._client._grid.get_entry(name)
        return None if e is None else e.kind

    def _cmd_TYPE(self, args):
        kind = self._kind_of(self._s(args[0]))
        if kind is None:
            return _encode_simple("none")
        return _encode_simple(self._TYPE_NAMES.get(kind, kind))

    def _cmd_DUMP(self, args):
        """Sketch objects dump their data-only wire blobs (durability
        format); string keys a tagged raw-bytes payload.  Container grid
        kinds are NOT dumpable over RESP: their Python dump() is
        pickle-based, which must never meet an untrusted socket."""
        return _encode_bulk(self._dump_payload(self._s(args[0])))

    def _dump_payload(self, name: str) -> Optional[bytes]:
        """The DUMP blob for one key, or None when absent — shared by
        _cmd_DUMP and the cluster migration pump (cluster/door.py ships
        exactly what DUMP would)."""
        blob = self._client._engine.dump(name)
        if blob is not None:
            return blob
        e = self._client._grid.get_entry(name)
        if e is None:
            return None
        if e.kind == "bucket":
            v = e.value
            if isinstance(v, str):
                v = v.encode()
            return b"RTPS\x00" + v
        raise RespError(f"DUMP unsupported for type {e.kind} over RESP")

    def _cmd_RESTORE(self, args):
        name, ttl_ms, payload = self._s(args[0]), int(args[1]), args[2]
        replace = any(a.upper() == b"REPLACE" for a in args[3:])
        # BUSYKEY/REPLACE semantics span BOTH stores (one logical
        # keyspace): Redis's RESTORE REPLACE deletes the old key whatever
        # its type, so a sketch blob may replace a grid string and vice
        # versa — the per-store foreign-key guards must see a free name.
        if self._exists_any(name):
            if not replace:
                raise RespError("BUSYKEY Target key name already exists.")
            self._client.get_keys().delete(name)
        if payload.startswith(b"RTPS\x00"):
            from redisson_tpu.grid.buckets import Bucket

            self._raw(Bucket(name, self._client)).set(payload[5:])
        else:
            try:
                self._client._engine.restore(name, payload)
            except ValueError as e:
                if "BUSYKEY" in str(e):  # raced with a concurrent creator
                    raise RespError("BUSYKEY Target key name already exists.")
                raise
        if ttl_ms > 0:
            self._client.get_keys().expire(name, ttl_ms / 1000.0)
        return _encode_simple("OK")

    def _exists_any(self, name: str) -> bool:
        return self._client._grid.exists(name) or self._client._engine.exists(
            name
        )

    # bitmaps -> BitSet

    def _cmd_SETBIT(self, args):
        bs = self._client.get_bit_set(self._s(args[0]))
        prev = bs.set(int(args[1]), bool(int(args[2])))
        return _encode_int(int(prev))

    def _cmd_GETBIT(self, args):
        bs = self._client.get_bit_set(self._s(args[0]))
        return _encode_int(int(bs.get(int(args[1]))))

    def _cmd_BITCOUNT(self, args):
        if len(args) > 1:
            # Range form unsupported — error, never silently-wrong data.
            raise RespError("BITCOUNT with ranges is not supported")
        return _encode_int(self._client.get_bit_set(self._s(args[0])).cardinality())

    def _cmd_BITPOS(self, args):
        if len(args) > 2:
            raise RespError("BITPOS with ranges is not supported")
        bs = self._client.get_bit_set(self._s(args[0]))
        target = int(args[1])
        return _encode_int(
            bs.first_set_bit() if target else bs.first_clear_bit()
        )

    # HLL

    def _cmd_PFADD(self, args):
        h = self._client.get_hyper_log_log(self._s(args[0]))
        return _encode_int(int(h.add_all([a for a in args[1:]])))

    def _cmd_PFCOUNT(self, args):
        h = self._client.get_hyper_log_log(self._s(args[0]))
        if len(args) > 1:
            return _encode_int(h.count_with(*[self._s(a) for a in args[1:]]))
        return _encode_int(h.count())

    def _cmd_PFMERGE(self, args):
        h = self._client.get_hyper_log_log(self._s(args[0]))
        h.merge_with(*[self._s(a) for a in args[1:]])
        return _encode_simple("OK")

    # Bloom (RedisBloom command shape)

    def _cmd_BF_RESERVE(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        created = bf.try_init(int(args[2]), float(args[1]))
        if not created:
            raise RespError("item exists")
        return _encode_simple("OK")

    def _cmd_BF_ADD(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        return _encode_int(int(bf.add(args[1])))

    def _cmd_BF_MADD(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        newly = bf.add_all_async([a for a in args[1:]]).result()
        return _encode_array([int(v) for v in newly])

    def _cmd_BF_EXISTS(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        return _encode_int(int(bf.contains(args[1])))

    def _cmd_BF_MEXISTS(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        hits = bf.contains_each([a for a in args[1:]])
        return _encode_array([int(v) for v in hits])

    # CMS (RedisBloom command shape)

    def _cmd_CMS_INITBYDIM(self, args):
        cms = self._client.get_count_min_sketch(self._s(args[0]))
        cms.try_init(int(args[2]), int(args[1]))
        return _encode_simple("OK")

    def _cmd_CMS_INCRBY(self, args):
        cms = self._client.get_count_min_sketch(self._s(args[0]))
        out = []
        for i in range(1, len(args), 2):
            out.append(cms.add(args[i], int(args[i + 1])))
        return _encode_array(out)

    def _cmd_CMS_QUERY(self, args):
        cms = self._client.get_count_min_sketch(self._s(args[0]))
        return _encode_array(
            [int(v) for v in cms.estimate_all([a for a in args[1:]])]
        )

    def _cmd_CMS_MERGE(self, args):
        """CMS.MERGE dest numKeys src [src ...] — RedisBloom OVERWRITE
        semantics: dest becomes the sum of the sources (dest's prior
        counts survive only if dest is itself listed as a source).
        Weights unsupported — error, never silently-wrong data."""
        n = int(args[1])
        dest = self._s(args[0])
        srcs = [self._s(a) for a in args[2 : 2 + n]]
        if len(args) > 2 + n:
            raise RespError("CMS.MERGE WEIGHTS is not supported")
        cms = self._client.get_count_min_sketch(dest)
        if dest not in srcs:
            # Overwrite: zero the counters in place (registry entry and
            # top-K config survive; no delete→reinit window where
            # concurrent CMS.QUERY would see 'not initialized').
            self._client._engine.cms_reset(dest)
        others = [s for s in srcs if s != dest]
        if others:
            cms.merge(*others)
        return _encode_simple("OK")

    def _cmd_CMS_INFO(self, args):
        cms = self._client.get_count_min_sketch(self._s(args[0]))
        return _encode_array(
            [
                "width", cms.get_width(),
                "depth", cms.get_depth(),
                "count", cms.total_count(),
            ]
        )

    def _cmd_BF_INFO(self, args):
        bf = self._client.get_bloom_filter(self._s(args[0]))
        return _encode_array(
            [
                "Capacity", bf.get_expected_insertions(),
                "Size", (bf.get_size() + 7) // 8,  # bits → bytes
                "Number of filters", 1,
                "Number of items inserted", bf.count(),
                "Expansion rate", None,  # non-scaling filter
            ]
        )

    # lists

    def _list(self, key: bytes):
        # Redis lists ARE deques (LPUSH/RPOP both ends).
        from redisson_tpu.grid.queues import Deque

        return self._raw(Deque(self._s(key), self._client))

    def _cmd_RPUSH(self, args):
        lst = self._list(args[0])
        for v in args[1:]:
            lst.offer(v)
        return _encode_int(lst.size())

    def _cmd_LPUSH(self, args):
        lst = self._list(args[0])
        for v in args[1:]:
            lst.add_first(v)
        return _encode_int(lst.size())

    def _cmd_LPUSHX(self, args):
        with self._client._grid.lock:
            if not self._client._grid.exists(self._s(args[0])):
                return _encode_int(0)
            return self._cmd_LPUSH(args)

    def _cmd_RPUSHX(self, args):
        with self._client._grid.lock:
            if not self._client._grid.exists(self._s(args[0])):
                return _encode_int(0)
            return self._cmd_RPUSH(args)

    def _cmd_LPOP(self, args):
        return _encode_bulk(self._list(args[0]).poll_first())

    def _cmd_RPOP(self, args):
        return _encode_bulk(self._list(args[0]).poll_last())

    def _bpop(self, args, first: bool, nonblocking: bool = False) -> bytes:
        """BLPOP/BRPOP: condvar-parked on the grid store (no poll pump) —
        the store's offer() notifies the same condition BlockingQueue
        uses.  Multi-key form checks keys in argument order each wakeup,
        Redis-style.  ``nonblocking``: inside MULTI/EXEC a blocking
        command returns nil immediately (Redis transaction semantics)."""
        import time as _time

        if len(args) < 2:
            raise RespError("wrong number of arguments for 'blpop'")
        *keys, timeout = args
        t = float(timeout)
        qs = [(self._s(k), self._list(k)) for k in keys]
        store = qs[0][1]._store
        deadline = None if t == 0 else _time.monotonic() + t
        with store.cond:
            while True:
                for name, q in qs:
                    v = q.poll_first() if first else q.poll_last()
                    if v is not None:
                        return b"*2\r\n" + _encode_bulk(name) + _encode_bulk(v)
                if nonblocking:
                    return b"*-1\r\n"  # in EXEC: never block
                if deadline is None:
                    store.cond.wait(timeout=1.0)
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return b"*-1\r\n"  # null array: timed out
                    store.cond.wait(timeout=remaining)

    def _cmdctx_BLPOP(self, args, ctx: _ConnCtx):
        return self._bpop(args, first=True, nonblocking=ctx.in_exec)

    def _cmdctx_BRPOP(self, args, ctx: _ConnCtx):
        return self._bpop(args, first=False, nonblocking=ctx.in_exec)

    def _cmd_LLEN(self, args):
        return _encode_int(self._list(args[0]).size())

    def _listidx(self, key: bytes):
        # Index-addressed view of the same "list" store entry (List and
        # Deque share KIND, → RList over one Redis list key).
        from redisson_tpu.grid.collections import List_

        return self._raw(List_(self._s(key), self._client))

    def _cmd_LRANGE(self, args):
        lst = self._listidx(args[0])
        start, end = int(args[1]), int(args[2])
        n = lst.size()
        if start < 0:
            start = max(0, n + start)
        end = n + end if end < 0 else end
        if start > end or start >= n:
            return _encode_array([])
        return _encode_array(lst.sub_list(start, min(end, n - 1) + 1))

    def _cmd_LINDEX(self, args):
        lst = self._listidx(args[0])
        i = int(args[1])
        n = lst.size()
        if i < 0:
            i += n
        if not 0 <= i < n:
            return _encode_bulk(None)
        return _encode_bulk(lst.get(i))

    def _cmd_LSET(self, args):
        lst = self._listidx(args[0])
        i = int(args[1])
        n = lst.size()
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise RespError("index out of range")
        lst.set(i, args[2])
        return _encode_simple("OK")

    def _cmd_LREM(self, args):
        lst = self._listidx(args[0])
        count = int(args[1])
        with self._client._grid.lock:  # atomic scan+remove
            vals = lst.sub_list(0, lst.size())
            idxs = [i for i, v in enumerate(vals) if v == args[2]]
            if count > 0:
                idxs = idxs[:count]  # head-first
            elif count < 0:
                idxs = idxs[count:]  # tail-first, Redis count<0
            for i in reversed(idxs):
                lst.remove_at(i)
        return _encode_int(len(idxs))

    def _cmd_LTRIM(self, args):
        lst = self._listidx(args[0])
        start, end = int(args[1]), int(args[2])
        n = lst.size()
        if start < 0:
            start = max(0, n + start)
        end = n + end if end < 0 else min(end, n - 1)
        if start > end:
            lst.trim(1, 0)  # keep-nothing: Redis empties the list
        else:
            lst.trim(start, end)  # grid trim is [from, to] INCLUSIVE
        return _encode_simple("OK")

    def _cmd_RPOPLPUSH(self, args):
        v = self._list(args[0]).poll_last_and_offer_first_to(
            self._s(args[1])
        )
        return _encode_bulk(v)

    # hashes

    def _map(self, key: bytes):
        from redisson_tpu.grid.maps import Map

        m = self._raw(Map(self._s(key), self._client))
        m._enc_key = m._enc
        m._dec_key = m._dec
        return m

    def _cmd_HSET(self, args):
        m = self._map(args[0])
        n = 0
        for i in range(1, len(args), 2):
            if m.fast_put(args[i], args[i + 1]):
                n += 1
        return _encode_int(n)

    def _cmd_HGET(self, args):
        return _encode_bulk(self._map(args[0]).get(args[1]))

    def _cmd_HDEL(self, args):
        return _encode_int(self._map(args[0]).fast_remove(*args[1:]))

    def _cmd_HLEN(self, args):
        return _encode_int(self._map(args[0]).size())

    def _cmd_HGETALL(self, args):
        flat = []
        for k, v in self._map(args[0]).read_all_map().items():
            flat.extend([k, v])
        return _encode_array(flat)

    def _cmd_HMGET(self, args):
        m = self._map(args[0])
        return _encode_array([m.get(f) for f in args[1:]])

    def _cmd_HKEYS(self, args):
        return _encode_array(self._map(args[0]).key_set())

    def _cmd_HVALS(self, args):
        return _encode_array(self._map(args[0]).values())

    def _cmd_HEXISTS(self, args):
        return _encode_int(int(self._map(args[0]).contains_key(args[1])))

    def _cmd_HSETNX(self, args):
        m = self._map(args[0])
        prev = m.put_if_absent(args[1], args[2])
        return _encode_int(int(prev is None))

    def _cmd_HINCRBY(self, args):
        m = self._map(args[0])
        # Stored values are raw bytes over RESP: interpret as integer.
        with self._client._grid.lock:
            cur = m.get(args[1])
            new = (int(cur) if cur is not None else 0) + int(args[2])
            m.fast_put(args[1], str(new).encode())
        return _encode_int(new)

    # sets

    def _set(self, key: bytes):
        from redisson_tpu.grid.collections import Set_

        return self._raw(Set_(self._s(key), self._client))

    def _cmd_SADD(self, args):
        s = self._set(args[0])
        return _encode_int(sum(int(s.add(v)) for v in args[1:]))

    def _cmd_SREM(self, args):
        s = self._set(args[0])
        return _encode_int(sum(int(s.remove(v)) for v in args[1:]))

    def _cmd_SISMEMBER(self, args):
        return _encode_int(int(self._set(args[0]).contains(args[1])))

    def _cmd_SCARD(self, args):
        return _encode_int(self._set(args[0]).size())

    def _cmd_SMEMBERS(self, args):
        return _encode_array(self._set(args[0]).read_all())

    def _cmd_SMISMEMBER(self, args):
        s = self._set(args[0])
        return _encode_array([int(s.contains(v)) for v in args[1:]])

    def _cmd_SPOP(self, args):
        s = self._set(args[0])
        if len(args) > 1:
            count = int(args[1])
            if count < 0:
                raise RespError("value is out of range, must be positive")
            return _encode_array(s.remove_random(min(count, s.size())))
        out = s.remove_random(1)
        return _encode_bulk(out[0] if out else None)

    def _cmd_SRANDMEMBER(self, args):
        s = self._set(args[0])
        if len(args) > 1:
            count = int(args[1])
            if count < 0:
                # Redis: |count| members, duplicates allowed.
                import random as _random

                vals = s.read_all()
                if not vals:
                    return _encode_array([])
                return _encode_array(_random.choices(vals, k=-count))
            return _encode_array(s.random(min(count, s.size())))
        out = s.random(1)
        return _encode_bulk(out[0] if out else None)

    def _cmd_SMOVE(self, args):
        # Raw-bytes SMOVE: the grid's move() resolves the destination
        # through the client codec; RESP values are raw, so move by hand
        # under the store lock.
        src, dst = self._set(args[0]), self._set(args[1])
        with self._client._grid.lock:
            self._client._grid.get_entry(self._s(args[1]), "set")
            if not src.remove(args[2]):
                return _encode_int(0)
            dst.add(args[2])
        return _encode_int(1)

    # SINTER/SUNION/SDIFF combine via raw per-set reads: the grid's
    # read_intersection/read_union resolve other sets through the
    # CLIENT's codec, but every RESP-stored value is raw bytes.

    def _cmd_SINTER(self, args):
        sets = [set(self._set(a).read_all()) for a in args]
        return _encode_array(sorted(set.intersection(*sets)))

    def _cmd_SUNION(self, args):
        out: set = set()
        for a in args:
            out.update(self._set(a).read_all())
        return _encode_array(sorted(out))

    def _cmd_SDIFF(self, args):
        first = self._set(args[0])
        out = first.read_all()
        others = set()
        for a in args[1:]:
            others.update(self._set(a).read_all())
        return _encode_array([v for v in out if v not in others])

    def _store_set(self, dest: bytes, members) -> bytes:
        with self._client._grid.lock:
            if not members:
                # Redis deletes the destination on an empty result.
                self._client._grid.delete(self._s(dest))
            else:
                self._client._grid.put_entry(
                    self._s(dest), "set", {vb: None for vb in members}
                )
        return _encode_int(len(members))

    def _cmd_SINTERSTORE(self, args):
        sets = [set(self._set(a).read_all()) for a in args[1:]]
        return self._store_set(args[0], sorted(set.intersection(*sets)))

    def _cmd_SUNIONSTORE(self, args):
        out: set = set()
        for a in args[1:]:
            out.update(self._set(a).read_all())
        return self._store_set(args[0], sorted(out))

    def _cmd_SDIFFSTORE(self, args):
        first = self._set(args[1]).read_all()
        others: set = set()
        for a in args[2:]:
            others.update(self._set(a).read_all())
        return self._store_set(args[0], [v for v in first if v not in others])

    # sorted sets

    def _zset(self, key: bytes):
        from redisson_tpu.grid.collections import ScoredSortedSet

        return self._raw(ScoredSortedSet(self._s(key), self._client))

    def _cmd_ZADD(self, args):
        z = self._zset(args[0])
        n = 0
        for i in range(1, len(args), 2):
            n += int(z.add(float(args[i]), args[i + 1]))
        return _encode_int(n)

    def _cmd_ZSCORE(self, args):
        score = self._zset(args[0]).get_score(args[1])
        return _encode_bulk(None if score is None else _fmt_score(score))

    def _cmd_ZRANGE(self, args):
        z = self._zset(args[0])
        withscores = len(args) > 3 and args[3].decode().upper() == "WITHSCORES"
        if not withscores:
            return _encode_array(z.value_range(int(args[1]), int(args[2])))
        flat = []
        for member, score in z.entry_range(int(args[1]), int(args[2])):
            flat.extend([member, _fmt_score(score)])
        return _encode_array(flat)

    def _cmd_ZCARD(self, args):
        return _encode_int(self._zset(args[0]).size())

    def _cmd_ZREM(self, args):
        z = self._zset(args[0])
        return _encode_int(sum(int(z.remove(m)) for m in args[1:]))

    def _cmd_ZINCRBY(self, args):
        new = self._zset(args[0]).add_score(args[2], float(args[1]))
        return _encode_bulk(_fmt_score(new))

    def _cmd_ZRANK(self, args):
        r = self._zset(args[0]).rank(args[1])
        return b"$-1\r\n" if r is None else _encode_int(r)

    @staticmethod
    def _score_bound(raw: bytes):
        """Redis score-bound syntax: '(x' exclusive, -inf/+inf."""
        if raw.startswith(b"("):
            return float(raw[1:]), False
        return float(raw), True

    def _score_filtered(self, z, lo_raw: bytes, hi_raw: bytes):
        lo, lo_inc = self._score_bound(lo_raw)
        hi, hi_inc = self._score_bound(hi_raw)
        out = []
        for m in z.value_range_by_score(lo, hi):
            s = z.get_score(m)
            if (s > lo or (lo_inc and s == lo)) and (
                s < hi or (hi_inc and s == hi)
            ):
                out.append((m, s))
        return out

    def _cmd_ZCOUNT(self, args):
        return _encode_int(
            len(self._score_filtered(self._zset(args[0]), args[1], args[2]))
        )

    def _cmd_ZRANGEBYSCORE(self, args):
        z = self._zset(args[0])
        withscores = False
        offset, count = 0, None
        i = 3
        while i < len(args):
            opt = args[i].upper()
            if opt == b"WITHSCORES":
                withscores = True
                i += 1
            elif opt == b"LIMIT":
                offset, count = int(args[i + 1]), int(args[i + 2])
                i += 3
            else:
                raise RespError(f"syntax error near {args[i].decode()!r}")
        entries = self._score_filtered(z, args[1], args[2])
        if count is not None:
            entries = entries[offset : offset + count if count >= 0 else None]
        elif offset:
            entries = entries[offset:]
        if not withscores:
            return _encode_array([m for m, _ in entries])
        flat = []
        for m, s in entries:
            flat.extend([m, _fmt_score(s)])
        return _encode_array(flat)

    def _zpop(self, args, first: bool):
        z = self._zset(args[0])
        count = int(args[1]) if len(args) > 1 else 1
        flat = []
        with self._client._grid.lock:  # atomic peek+remove per entry
            for _ in range(count):
                entries = (
                    z.entry_range(0, 0) if first else z.entry_range(-1, -1)
                )
                if not entries:
                    break
                member, score = entries[0]
                z.remove(member)
                flat.extend([member, _fmt_score(score)])
        return _encode_array(flat)

    def _cmd_ZPOPMIN(self, args):
        return self._zpop(args, True)

    def _cmd_ZPOPMAX(self, args):
        return self._zpop(args, False)

    def _cmd_ZREVRANGE(self, args):
        z = self._zset(args[0])
        withscores = any(a.upper() == b"WITHSCORES" for a in args[3:])
        start, end = int(args[1]), int(args[2])
        # rev-range indexes count from the HIGHEST score; n derives from
        # the ONE snapshot (a second size() call could race a mutation).
        entries = list(reversed(z.entry_range(0, -1)))
        n = len(entries)
        if start < 0:
            start = max(0, n + start)
        if end < 0:
            end = n + end
            if end < 0:
                return _encode_array([])  # beyond-left end: empty, Redis
        entries = entries[start : end + 1]
        if not withscores:
            return _encode_array([m for m, _ in entries])
        flat = []
        for m, sc in entries:
            flat.extend([m, _fmt_score(sc)])
        return _encode_array(flat)

    def _cmd_ZREVRANK(self, args):
        z = self._zset(args[0])
        r = z.rank(args[1])
        if r is None:
            return b"$-1\r\n"
        return _encode_int(z.size() - 1 - r)

    def _cmd_ZREMRANGEBYSCORE(self, args):
        z = self._zset(args[0])
        with self._client._grid.lock:  # atomic filter+remove (RLock)
            members = [
                m for m, _ in self._score_filtered(z, args[1], args[2])
            ]
            for m in members:
                z.remove(m)
        return _encode_int(len(members))

    # protocol negotiation (→ RESP3's HELLO; the reference speaks
    # RESP2/RESP3 through Netty — SURVEY.md §2.4 comm row)

    def _check_password(self, username: Optional[bytes], password: bytes) -> None:
        """Constant-time password check; only the 'default' user exists
        (the single-password requirepass model, like redis-server
        without ACLs)."""
        import hmac

        if self._requirepass is None:
            raise RespError(
                "Client sent AUTH, but no password is set. Did you mean "
                "AUTH <username> <password>?"
            )
        if username is not None and username != b"default":
            raise RespError(
                "WRONGPASS invalid username-password pair or user is "
                "disabled."
            )
        if not hmac.compare_digest(password, self._requirepass.encode()):
            raise RespError(
                "WRONGPASS invalid username-password pair or user is "
                "disabled."
            )

    def _cmdctx_AUTH(self, args, ctx: _ConnCtx):
        if len(args) == 1:
            self._check_password(None, args[0])
        elif len(args) == 2:
            self._check_password(args[0], args[1])
        else:
            raise RespError("wrong number of arguments for 'auth' command")
        ctx.authed = True
        return _encode_simple("OK")

    def _cmdctx_HELLO(self, args, ctx: _ConnCtx):
        # Validate EVERYTHING before mutating ctx: a failed HELLO must
        # leave the connection on its current protocol (a half-applied
        # upgrade would desync the client — real Redis switches only on
        # success).
        ver = ctx.proto
        name = ctx.client_name
        i = 0
        if args and args[0].isdigit():
            ver = int(args[0])
            if ver not in (2, 3):
                raise RespError(
                    "NOPROTO unsupported protocol version"
                )
            i = 1
        authed = ctx.authed
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "AUTH":
                # HELLO ... AUTH <username> <password>: raises on a bad
                # pair BEFORE any ctx mutation (validate-then-commit).
                self._check_password(args[i + 1], args[i + 2])
                authed = True
                i += 3
                continue
            if opt == "SETNAME":
                name = self._s(args[i + 1])
                i += 2
                continue
            raise RespError(f"unsupported HELLO option {opt}")
        if not authed:
            # HELLO without credentials on a locked server: refused like
            # every other pre-auth command (Redis behavior for HELLO is
            # to answer, but answering leaks server metadata; AUTH-first
            # is the safe strictening and stock clients send AUTH here).
            raise RespError("NOAUTH HELLO must include AUTH when "
                            "requirepass is set.")
        ctx.proto = ver
        ctx.client_name = name
        ctx.authed = authed
        pairs = [
            (b"server", b"redisson-tpu"),
            (b"version", b"4.0.0"),
            (b"proto", ctx.proto),
            (b"id", 1),
            (b"mode", b"standalone"),
            (b"role",
             b"slave" if self.replica_link is not None else b"master"),
            (b"modules", []),
        ]
        if ctx.proto == 3:
            out = b"%" + str(len(pairs)).encode() + b"\r\n"
        else:
            out = b"*" + str(len(pairs) * 2).encode() + b"\r\n"
        for k, v in pairs:
            out += _encode_bulk(k)
            if isinstance(v, int):
                out += _encode_int(v)
            elif isinstance(v, list):
                out += _encode_array(v)
            else:
                out += _encode_bulk(v)
        return out

    # pub/sub (push replies — the SUBSCRIBE protocol shape; RESP3
    # connections get true push frames '>')

    @staticmethod
    def _push_hdr(ctx: _ConnCtx) -> bytes:
        return b">3\r\n" if ctx.proto == 3 else b"*3\r\n"

    def _cmd_PUBLISH(self, args):
        n = self._client._topic_bus.publish(self._s(args[0]), args[1])
        return _encode_int(n)

    def _cmdctx_SUBSCRIBE(self, args, ctx: _ConnCtx):
        if not args:
            raise RespError("wrong number of arguments for 'subscribe'")
        for raw in args:
            channel = self._s(raw)
            already = channel in ctx.subs
            # Ack FIRST, then register: a concurrent PUBLISH must not push
            # its 'message' frame ahead of this channel's 'subscribe' ack.
            ctx.send(
                self._push_hdr(ctx)
                + _encode_bulk(b"subscribe")
                + _encode_bulk(raw)
                + _encode_int(len(ctx.subs) + (0 if already else 1))
            )
            if already:
                continue

            def on_msg(ch, message, _name=raw):
                payload = (
                    message
                    if isinstance(message, bytes)
                    else str(message).encode()
                )
                ctx.send(
                    self._push_hdr(ctx)
                    + _encode_bulk(b"message")
                    + _encode_bulk(_name)
                    + _encode_bulk(payload)
                )

            ctx.subs[channel] = self._client._topic_bus.subscribe(
                channel, on_msg
            )
        return b""  # acks already pushed in order

    def _cmdctx_UNSUBSCRIBE(self, args, ctx: _ConnCtx):
        channels = [self._s(a) for a in args] or list(ctx.subs)
        if not channels:
            # Redis replies even when nothing was subscribed — an empty
            # reply would wedge the client waiting forever.
            return (
                self._push_hdr(ctx)
                + _encode_bulk(b"unsubscribe")
                + _encode_bulk(None)
                + _encode_int(0)
            )
        out = b""
        for channel in channels:
            lid = ctx.subs.pop(channel, None)
            if lid is not None:
                self._client._topic_bus.unsubscribe(channel, lid)
            out += (
                self._push_hdr(ctx)
                + _encode_bulk(b"unsubscribe")
                + _encode_bulk(channel.encode())
                + _encode_int(len(ctx.subs))
            )
        return out

    # counters — one NUMERIC key per name: Redis INCR/INCRBYFLOAT share a
    # string key, so the int and float forms here must interoperate (the
    # entry's kind converts with the operation; INCR on a non-integral
    # value errors like Redis's "not an integer").

    def _numeric_incr(self, key: bytes, delta, is_float: bool):
        grid = self._client._grid
        name = self._s(key)
        with grid.lock:
            e = grid.get_entry(name)
            if e is None:
                cur = 0
            elif e.kind == "bucket":  # Redis counters ARE string keys
                raw = e.value
                if isinstance(raw, str):
                    raw = raw.encode()
                try:
                    cur = int(raw)
                except (TypeError, ValueError):
                    try:
                        cur = float(raw)
                    except (TypeError, ValueError):
                        raise RespError(
                            "value is not a valid float"
                            if is_float
                            else "value is not an integer or out of range"
                        )
            elif e.kind in ("atomiclong", "atomicdouble"):
                cur = e.value  # pre-existing counter kinds stay readable
            else:
                raise TypeError(
                    f"object {name!r} holds a {e.kind}, not a string"
                )
            if is_float:
                new = float(cur) + float(delta)
            else:
                # Exact-int check (float(cur)==int(cur) loses precision
                # past 2**53; Redis counters span full signed 64-bit).
                if isinstance(cur, float) and not cur.is_integer():
                    raise RespError("value is not an integer or out of range")
                new = int(cur) + int(delta)
            # Stored as a plain string key: SET/GET/INCR/INCRBYFLOAT all
            # interoperate on one key, and TYPE reports "string" — EXCEPT
            # when the entry was created via the Python AtomicLong/Double
            # API: rewriting those as "bucket" would make every later
            # Python-API call on the live handle raise WRONGTYPE, so the
            # counter kind is preserved (value stays numeric, not bytes).
            ttl = e.expire_at if e is not None else None
            if e is not None and e.kind in ("atomiclong", "atomicdouble"):
                kind = e.kind
                if kind == "atomiclong" and is_float and not new.is_integer():
                    kind = "atomicdouble"  # int kind can't hold a fraction
                val = int(new) if kind == "atomiclong" else float(new)
                ne = grid.put_entry(name, kind, val)
            else:
                stored = (
                    _fmt_score(new) if is_float else str(new)
                ).encode()
                ne = grid.put_entry(name, "bucket", stored)
            ne.expire_at = ttl
            return new

    def _cmd_INCR(self, args):
        return _encode_int(self._numeric_incr(args[0], 1, False))

    def _cmd_INCRBYFLOAT(self, args):
        return _encode_bulk(
            _fmt_score(self._numeric_incr(args[0], float(args[1]), True))
        )

    def _cmd_INCRBY(self, args):
        return _encode_int(self._numeric_incr(args[0], int(args[1]), False))

    def _cmd_DECR(self, args):
        return _encode_int(self._numeric_incr(args[0], -1, False))

    # streams (→ the reference's RStream command surface over
    # grid/streams.py; reply shapes follow Redis XADD/XRANGE/XREAD/
    # XREADGROUP/XACK/XPENDING/XCLAIM/XAUTOCLAIM)

    def _stream(self, key: bytes):
        from redisson_tpu.grid.streams import Stream

        s = self._raw(Stream(self._s(key), self._client))
        s._enc_key = s._enc
        s._dec_key = s._dec
        return s

    @staticmethod
    def _stream_entries_reply(entries) -> bytes:
        """[(id, {field: value})] → RESP [[id, [f1, v1, ...]], ...]."""
        out = b"*" + str(len(entries)).encode() + b"\r\n"
        for eid, fields in entries:
            flat = []
            for f, v in fields.items():
                flat.extend([f, v])
            out += b"*2\r\n" + _encode_bulk(eid) + _encode_array(flat)
        return out

    def _cmd_XADD(self, args):
        key = args[0]
        i = 1
        nomkstream = False
        maxlen = None
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "NOMKSTREAM":
                nomkstream = True
                i += 1
            elif opt == "MAXLEN":
                i += 1
                if args[i] in (b"~", b"="):  # approximate trim: exact here
                    i += 1
                maxlen = int(args[i])
                i += 1
            else:
                break
        entry_id = self._s(args[i])
        i += 1
        if (len(args) - i) % 2 != 0 or len(args) == i:
            raise RespError("wrong number of arguments for 'xadd' command")
        fields = {args[j]: args[j + 1] for j in range(i, len(args), 2)}
        try:
            new_id = self._stream(key).add(
                fields, entry_id, maxlen=maxlen, nomkstream=nomkstream
            )
        except ValueError as e:
            # Distinguish ordering violations from unparseable ids — a
            # client debugging 'notanid' must not be pointed at ordering.
            if "greater than" in str(e):
                raise RespError(
                    "The ID specified in XADD is equal or smaller than "
                    "the target stream top item"
                ) from e
            raise RespError(
                "Invalid stream ID specified as stream command argument"
            ) from e
        return _encode_bulk(None if new_id is None else new_id)

    def _cmd_XLEN(self, args):
        return _encode_int(self._stream(args[0]).size())

    def _cmd_XRANGE(self, args):
        count = None
        if len(args) >= 5 and args[3].decode().upper() == "COUNT":
            count = int(args[4])
        entries = self._stream(args[0]).range(
            self._s(args[1]), self._s(args[2]), count
        )
        return self._stream_entries_reply(entries)

    def _cmd_XREVRANGE(self, args):
        count = None
        if len(args) >= 5 and args[3].decode().upper() == "COUNT":
            count = int(args[4])
        entries = self._stream(args[0]).rev_range(
            self._s(args[1]), self._s(args[2]), count
        )
        return self._stream_entries_reply(entries)

    def _cmd_XDEL(self, args):
        return _encode_int(
            self._stream(args[0]).remove(*[self._s(a) for a in args[1:]])
        )

    def _cmd_XTRIM(self, args):
        i = 1
        if args[i].decode().upper() != "MAXLEN":
            raise RespError("syntax error")
        i += 1
        if args[i] in (b"~", b"="):
            i += 1
        return _encode_int(self._stream(args[0]).trim(int(args[i])))

    @staticmethod
    def _parse_xread_opts(args, want_group: bool):
        """Shared XREAD/XREADGROUP option walk → (group, consumer,
        count, block_s, keys, ids, noack)."""
        group = consumer = None
        count = block_s = None
        noack = False
        i = 0
        if want_group:
            if args[i].decode().upper() != "GROUP":
                raise RespError("syntax error")
            group, consumer = args[i + 1].decode(), args[i + 2].decode()
            i += 3
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "COUNT":
                count = int(args[i + 1])
                i += 2
            elif opt == "BLOCK":
                # BLOCK 0 = wait indefinitely (the Redis contract); the
                # wait loop still wakes each second, so a closed server
                # unsticks at shutdown.
                block_s = int(args[i + 1]) / 1000.0 or float("inf")
                i += 2
            elif opt == "NOACK":
                noack = True
                i += 1
            elif opt == "STREAMS":
                i += 1
                break
            else:
                raise RespError("syntax error")
        rest = args[i:]
        if not rest or len(rest) % 2 != 0:
            raise RespError(
                "Unbalanced XREAD list of streams: for each stream key "
                "an ID or '$' must be specified."
            )
        half = len(rest) // 2
        return group, consumer, count, block_s, rest[:half], rest[half:], noack

    @staticmethod
    def _xread_reply(out) -> bytes:
        if not out:
            return b"*-1\r\n"  # nil: nothing new
        reply = b"*" + str(len(out)).encode() + b"\r\n"
        for k, entries in out:
            reply += (
                b"*2\r\n" + _encode_bulk(k)
                + RespServer._stream_entries_reply(entries)
            )
        return reply

    def _cmdctx_XREAD(self, args, ctx: _ConnCtx):
        import time as _time

        _, _, count, block_s, keys, ids, _ = self._parse_xread_opts(
            args, False
        )
        if ctx.in_exec:
            block_s = None  # like Redis: no blocking inside MULTI/EXEC
        # Resolve '$' ONCE, before any waiting: a blocked read must see
        # entries added after THIS call, not chase the advancing tail.
        starts = []
        for k, sid in zip(keys, ids):
            s_ = self._s(sid)
            if s_ == "$":
                s_ = self._stream(k).last_id()
            starts.append(s_)
        deadline = (
            None if block_s is None else _time.monotonic() + block_s
        )
        grid = self._client._grid
        while True:
            out = []
            for k, start in zip(keys, starts):
                entries = self._stream(k).read(start, count)
                if entries:
                    out.append((k, entries))
            if out or deadline is None:
                return self._xread_reply(out)
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return self._xread_reply([])
            with grid.cond:  # woken by any XADD (store-wide notify)
                grid.cond.wait(timeout=min(remaining, 1.0))

    def _cmdctx_XREADGROUP(self, args, ctx: _ConnCtx):
        import time as _time

        group, consumer, count, block_s, keys, ids, noack = (
            self._parse_xread_opts(args, True)
        )
        if ctx.in_exec:
            block_s = None
        starts = [self._s(sid) for sid in ids]
        # Redis shape rules: '>' streams with nothing new are OMITTED;
        # explicit-id streams always appear (possibly with an empty
        # array) and make the command non-blocking.
        any_explicit = any(s_ != ">" for s_ in starts)
        deadline = (
            None
            if block_s is None or any_explicit
            else _time.monotonic() + block_s
        )
        grid = self._client._grid
        while True:
            out = []
            got_new = False
            for k, start in zip(keys, starts):
                try:
                    entries = self._stream(k).read_group(
                        group, consumer, count, start, noack=noack
                    )
                except ValueError as e:
                    if "NOGROUP" not in str(e):
                        # e.g. an unparseable start id, not a missing group
                        raise RespError(
                            "Invalid stream ID specified as stream "
                            "command argument"
                        ) from e
                    raise RespError(
                        f"NOGROUP No such consumer group '{group}' for "
                        f"key name '{self._s(k)}'"
                    ) from e
                if start == ">":
                    if entries:
                        out.append((k, entries))
                        got_new = True
                else:
                    out.append((k, entries))
            if got_new or any_explicit or deadline is None:
                return self._xread_reply(out)
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return self._xread_reply([])
            with grid.cond:
                grid.cond.wait(timeout=min(remaining, 1.0))

    def _cmd_XGROUP(self, args):
        sub = args[0].decode().upper()
        if sub == "CREATE":
            key, group, from_id = args[1], args[2].decode(), self._s(args[3])
            mkstream = any(
                a.decode().upper() == "MKSTREAM" for a in args[4:]
            )
            try:
                self._stream(key).create_group(
                    group, from_id, mkstream=mkstream
                )
            except ValueError as e:
                if "already exists" not in str(e):
                    # unparseable start id — NOT a duplicate group (a
                    # client treating BUSYGROUP as 'proceed' would then
                    # hit NOGROUP, a state impossible on real Redis)
                    raise RespError(
                        "Invalid stream ID specified as stream command "
                        "argument"
                    ) from e
                raise RespError(
                    "BUSYGROUP Consumer Group name already exists"
                ) from e
            except RuntimeError as e:
                raise RespError(
                    "The XGROUP subcommand requires the key to exist. Note "
                    "that for CREATE you may want to use the MKSTREAM "
                    "option to create an empty stream automatically."
                ) from e
            return _encode_simple("OK")
        if sub == "DESTROY":
            return _encode_int(
                int(self._stream(args[1]).remove_group(args[2].decode()))
            )
        raise RespError(f"Unknown XGROUP subcommand {sub}")

    def _cmd_XACK(self, args):
        try:
            return _encode_int(
                self._stream(args[0]).ack(
                    args[1].decode(), *[self._s(a) for a in args[2:]]
                )
            )
        except ValueError:
            return _encode_int(0)  # Redis: XACK on a missing group is 0

    def _cmd_XPENDING(self, args):
        s = self._stream(args[0])
        group = args[1].decode()
        if len(args) == 2:  # summary form
            try:
                p = s.pending(group)
            except ValueError as e:
                raise self._nogroup(args[0], group, e) from e
            consumers = [
                [c.encode(), str(n).encode()]
                for c, n in p["consumers"].items()
            ]
            out = (
                b"*4\r\n" + _encode_int(p["total"])
                + _encode_bulk(p["lowest_id"])
                + _encode_bulk(p["highest_id"])
            )
            if consumers:
                out += b"*" + str(len(consumers)).encode() + b"\r\n"
                for pair in consumers:
                    out += _encode_array(pair)
            else:
                out += b"*-1\r\n"
            return out
        # range form: [IDLE ms] start end count [consumer] — the int
        # parses stay OUTSIDE the NOGROUP mapping (a malformed count on
        # a live group is a value error, not a missing group).
        i = 2
        min_idle_ms = 0
        if args[i].decode().upper() == "IDLE":
            min_idle_ms = int(args[i + 1])
            i += 2
        start, end, count = (
            self._s(args[i]), self._s(args[i + 1]), int(args[i + 2])
        )
        consumer = args[i + 3].decode() if len(args) > i + 3 else None
        try:
            rows = s.pending_range(group, start, end, count, consumer)
        except ValueError as e:
            raise self._nogroup(args[0], group, e) from e
        if min_idle_ms:
            rows = [r for r in rows if r["idle_ms"] >= min_idle_ms]
        out = b"*" + str(len(rows)).encode() + b"\r\n"
        for r in rows:
            out += (
                b"*4\r\n" + _encode_bulk(r["id"])
                + _encode_bulk(r["consumer"].encode())
                + _encode_int(int(r["idle_ms"]))
                + _encode_int(r["delivered"])
            )
        return out

    def _nogroup(self, key: bytes, group: str, e: Exception) -> RespError:
        """Map the grid's NOGROUP ValueError to the -NOGROUP code every
        stock client keys on (the create-group-on-NOGROUP pattern)."""
        if "NOGROUP" not in str(e):
            raise e
        return RespError(
            f"NOGROUP No such consumer group '{group}' for key name "
            f"'{self._s(key)}'"
        )

    def _cmd_XCLAIM(self, args):
        s = self._stream(args[0])
        try:
            claimed = s.claim(
                args[1].decode(), args[2].decode(), int(args[3]),
                *[self._s(a) for a in args[4:]],
            )
        except ValueError as e:
            raise self._nogroup(args[0], args[1].decode(), e) from e
        return self._stream_entries_reply(claimed)

    def _cmd_XAUTOCLAIM(self, args):
        s = self._stream(args[0])
        count = 100
        justid = False
        i = 5
        while i < len(args):
            opt = args[i].decode().upper()
            if opt == "COUNT":
                count = int(args[i + 1])
                i += 2
            elif opt == "JUSTID":
                justid = True
                i += 1
            else:
                raise RespError("syntax error")
        try:
            cursor, claimed, deleted = s.auto_claim(
                args[1].decode(), args[2].decode(), int(args[3]),
                self._s(args[4]), count, with_cursor=True, justid=justid,
            )
        except ValueError as e:
            raise self._nogroup(args[0], args[1].decode(), e) from e
        # 7.0 reply: [next-cursor, entries, deleted-ids].  The cursor is
        # '0-0' only when the whole PEL was examined — a COUNT-truncated
        # sweep returns the id to continue from (clients loop until 0-0).
        # The third element names the ids the sweep dropped from the PEL
        # because their entries were deleted from the stream.
        body = (
            _encode_array([eid for eid, _ in claimed])
            if justid  # bare ids, per the JUSTID contract
            else self._stream_entries_reply(claimed)
        )
        return (
            b"*3\r\n" + _encode_bulk(cursor.encode()) + body
            + _encode_array([d.encode() for d in deleted])
        )

    def _cmd_XINFO(self, args):
        sub = args[0].decode().upper()
        s = self._stream(args[1])
        if sub == "STREAM":
            flat = [
                b"length", s.size(),
                b"last-generated-id", s.last_id().encode(),
                b"groups", len(s.list_groups()),
            ]
            return _encode_array(flat)
        if sub == "GROUPS":
            groups = s.list_groups()
            out = b"*" + str(len(groups)).encode() + b"\r\n"
            for g in groups:
                out += _encode_array([
                    b"name", g["name"].encode(),
                    b"consumers", g["consumers"],
                    b"pending", g["pending"],
                    b"last-delivered-id", g["last_delivered_id"].encode(),
                ])
            return out
        if sub == "CONSUMERS":
            try:
                rows = s.list_consumers(args[2].decode())
            except ValueError as e:
                raise self._nogroup(args[1], args[2].decode(), e) from e
            out = b"*" + str(len(rows)).encode() + b"\r\n"
            for r in rows:
                out += _encode_array([
                    b"name", r["name"].encode(), b"pending", r["pending"],
                ])
            return out
        raise RespError(f"Unknown XINFO subcommand {sub}")

    # geo (→ RGeo over grid/geo.py; GEOSEARCH option grammar follows
    # Redis 6.2)

    def _geo(self, key: bytes):
        from redisson_tpu.grid.geo import Geo

        return self._raw(Geo(self._s(key), self._client))

    def _cmd_GEOADD(self, args):
        # [NX|XX] [CH] flags precede the lon/lat/member triples — a
        # coordinate position can never BE a flag, so this walk is safe.
        i = 1
        nx = xx = ch = False
        while i < len(args):
            opt = args[i].decode("latin-1").upper()
            if opt == "NX":
                nx = True
            elif opt == "XX":
                xx = True
            elif opt == "CH":
                ch = True
            else:
                break
            i += 1
        if nx and xx:
            raise RespError(
                "XX and NX options at the same time are not compatible"
            )
        if (len(args) - i) % 3 != 0 or len(args) == i:
            raise RespError("syntax error")
        entries = [
            (float(args[j]), float(args[j + 1]), args[j + 2])
            for j in range(i, len(args), 3)
        ]
        geo = self._geo(args[0])
        try:
            if not (nx or xx or ch):
                return _encode_int(geo.add_entries(*entries))
            added = changed = 0
            with self._client._grid.lock:
                for lon, lat, m in entries:
                    existed = geo.pos(m) != {}
                    if (nx and existed) or (xx and not existed):
                        continue
                    before = geo.pos(m).get(m)
                    added += geo.add(lon, lat, m)
                    if before != geo.pos(m).get(m):
                        changed += 1
            return _encode_int(changed if ch else added)
        except ValueError as e:
            raise RespError(f"invalid longitude,latitude pair ({e})") from e

    def _cmd_GEOPOS(self, args):
        pos = self._geo(args[0]).pos(*args[1:])
        out = b"*" + str(len(args) - 1).encode() + b"\r\n"
        for m in args[1:]:
            p = pos.get(m)
            if p is None:
                out += b"*-1\r\n"
            else:
                out += _encode_array([
                    f"{p[0]:.17g}".encode(), f"{p[1]:.17g}".encode(),
                ])
        return out

    def _cmd_GEODIST(self, args):
        unit = args[3].decode().lower() if len(args) > 3 else "m"
        d = self._geo(args[0]).dist(args[1], args[2], unit)
        return _encode_bulk(None if d is None else f"{d:.4f}".encode())

    def _cmd_GEOHASH(self, args):
        hashes = self._geo(args[0]).hash(*args[1:])
        return _encode_array([
            hashes.get(m, "").encode() or None for m in args[1:]
        ])

    @staticmethod
    def _parse_geosearch(args, i, allow_storedist: bool = False):
        """GEOSEARCH option walk from index ``i`` → (search kwargs,
        with-flags).  Option words are only recognized at option
        POSITIONS — operand slots (the FROMMEMBER member) are consumed
        raw, so a member whose bytes spell an option name stays a
        member."""
        kw = {}
        with_coord = with_dist = with_hash = False
        n = len(args)
        while i < n:
            try:
                opt = args[i].decode().upper()
            except UnicodeDecodeError:
                raise RespError("syntax error")  # binary junk in options
            if opt == "FROMMEMBER":
                kw["member"] = args[i + 1]
                i += 2
            elif opt == "FROMLONLAT":
                kw["longitude"] = float(args[i + 1])
                kw["latitude"] = float(args[i + 2])
                i += 3
            elif opt == "BYRADIUS":
                kw["radius"] = float(args[i + 1])
                kw["unit"] = args[i + 2].decode().lower()
                i += 3
            elif opt == "BYBOX":
                kw["width"] = float(args[i + 1])
                kw["height"] = float(args[i + 2])
                kw["unit"] = args[i + 3].decode().lower()
                i += 4
            elif opt in ("ASC", "DESC"):
                kw["order"] = opt.lower()
                i += 1
            elif opt == "COUNT":
                kw["count"] = int(args[i + 1])
                if kw["count"] <= 0:
                    # hits[:0] / hits[:-n] would silently drop members
                    raise RespError("COUNT must be > 0")
                i += 2
                if i < n and args[i].decode().upper() == "ANY":
                    kw["count_any"] = True
                    i += 1
            elif opt == "WITHCOORD":
                with_coord = True
                i += 1
            elif opt == "WITHDIST":
                with_dist = True
                i += 1
            elif opt == "WITHHASH":
                with_hash = True
                i += 1
            elif allow_storedist and opt == "STOREDIST":
                kw["storedist"] = True
                i += 1
            else:
                raise RespError("syntax error")
        return kw, with_coord, with_dist, with_hash

    def _cmd_GEOSEARCH(self, args):
        kw, wc, wd, wh = self._parse_geosearch(args, 1)
        try:
            rows = self._geo(args[0]).search(
                with_coord=wc, with_dist=wd, with_hash=wh, **kw
            )
        except ValueError as e:
            raise RespError(str(e)) from e
        if not (wc or wd or wh):
            return _encode_array(rows)
        out = b"*" + str(len(rows)).encode() + b"\r\n"
        for r in rows:
            parts = [_encode_bulk(r["member"])]
            if wd:
                parts.append(_encode_bulk(f"{r['dist']:.4f}".encode()))
            if wh:
                parts.append(_encode_int(r["hash"]))
            if wc:
                parts.append(_encode_array([
                    f"{r['coord'][0]:.17g}".encode(),
                    f"{r['coord'][1]:.17g}".encode(),
                ]))
            out += b"*" + str(len(parts)).encode() + b"\r\n" + b"".join(parts)
        return out

    def _cmd_GEOSEARCHSTORE(self, args):
        dest, src = self._s(args[0]), args[1]
        # STOREDIST parses POSITIONALLY inside the option walk (a member
        # named 'storedist' must stay a member, not become the flag).
        kw, _, _, _ = self._parse_geosearch(args, 2, allow_storedist=True)
        store_dist = kw.pop("storedist", False)
        unit = kw.pop("unit", "m")
        try:
            n = self._geo(src).search_and_store(
                dest, store_dist=store_dist, unit=unit, **kw
            )
        except ValueError as e:
            raise RespError(str(e)) from e
        return _encode_int(n)

    # scripting (→ RScript/RFunction over grid/services.py).  Script
    # bodies are PYTHON source — there is deliberately no Lua VM
    # (ScriptService's design note): scripts see KEYS (str list), ARGV
    # (bytes list) and ``redis.call(...)``, which dispatches through this
    # server's own command table and decodes the reply.  A script runs
    # under the grid lock — the Lua-script atomicity contract.

    class _ScriptCtx:
        """Connection-independent ctx for redis.call dispatch: scripts
        cannot touch connection state (no MULTI, no pub/sub pushes —
        the Lua rules), and blocking commands run non-blocking."""

        in_multi = False
        in_exec = True
        proto = 2
        client_name = None
        # Scripts run server-side: the CONNECTION that invoked EVAL was
        # already auth-gated, so the bridge context is always authed.
        authed = True

        def __init__(self):
            self.subs = {}

    def _run_script(self, source: str, keys: list, argv: list):
        server = self
        sctx = self._ScriptCtx()

        class _Bridge:
            @staticmethod
            def call(*parts):
                cmd = [
                    p if isinstance(p, bytes) else str(p).encode()
                    for p in parts
                ]
                return _decode_reply(server._dispatch(cmd, sctx))

            # redis.pcall: errors come back as values, not raises
            @staticmethod
            def pcall(*parts):
                try:
                    return _Bridge.call(*parts)
                except Exception as e:
                    return e

        ns = {"KEYS": list(keys), "ARGV": list(argv), "redis": _Bridge}
        # Compile BEFORE taking the grid lock (ISSUE 3 satellite): a slow
        # or malformed compile must not stall every other connection
        # behind the Lua-atomicity lock.
        try:
            code = compile(source, "<eval>", "eval")
            is_expr = True
        except SyntaxError:
            code = compile(source, "<eval>", "exec")
            is_expr = False
        # Claim the watchdog slot BEFORE the grid lock (see _script_claim
        # — registering after the lock let an EVAL that won the lock race
        # against a slot-holding FCALL run unregistered, with SCRIPT KILL
        # aimed at the FCALL thread still queued on the lock).  The
        # OUTERMOST script on this thread owns the record (a script
        # EVALing another via redis.call re-enters here).
        started_here = self._script_claim()
        try:
            with self._client._grid.lock:  # Lua atomicity contract
                try:
                    if is_expr:
                        out = eval(code, ns)
                    else:
                        exec(code, ns)
                        out = ns.get("result")
                    self._client._grid.cond.notify_all()
                finally:
                    if started_here:
                        self._script_unregister()
        except ScriptKilledError:
            # Only the OUTERMOST frame converts the kill to a (catchable)
            # RespError: converting in a nested frame would let the outer
            # script's blanket `except Exception` swallow the kill and
            # keep looping — the BaseException must ride through script
            # code until the frame that owns the watchdog slot.
            if not started_here:
                raise
            # The kill may have landed INSIDE the finally above, aborting
            # the clear — release the slot defensively or every later
            # connection sees BUSY forever.
            self._script_unregister()
            # _encode_error prepends the ERR code for unknown tokens.
            raise RespError(
                "Script killed by user with SCRIPT KILL..."
            ) from None
        return out

    @staticmethod
    def _script_reply(v) -> bytes:
        """Python script result → RESP (the Lua conversion table shape:
        int → integer, str/bytes → bulk, list → array, None → nil,
        True → 1, False → nil; floats travel as bulk strings — a
        documented deviation from Lua's truncation)."""
        if v is None or v is False:
            return _encode_bulk(None)
        if v is True:
            return _encode_int(1)
        if isinstance(v, int):
            return _encode_int(v)
        if isinstance(v, float):
            return _encode_bulk(_fmt_score(v).encode())
        if isinstance(v, (bytes, str)):
            return _encode_bulk(v if isinstance(v, bytes) else v.encode())
        if isinstance(v, (list, tuple)):
            return b"*" + str(len(v)).encode() + b"\r\n" + b"".join(
                RespServer._script_reply(x) for x in v
            )
        if isinstance(v, dict):
            flat = []
            for k2, v2 in v.items():
                flat.extend([k2, v2])
            return RespServer._script_reply(flat)
        if isinstance(v, Exception):
            return _encode_error(str(v))
        return _encode_bulk(str(v).encode())

    @staticmethod
    def _check_numkeys(numkeys: int, available: int) -> None:
        if numkeys < 0:
            raise RespError("Number of keys can't be negative")
        if numkeys > available:
            # a silent truncation would shift every ARGV by the deficit
            raise RespError(
                "Number of keys can't be greater than number of args"
            )

    def _eval_common(self, source: str, args):
        numkeys = int(args[0])
        self._check_numkeys(numkeys, len(args) - 1)
        keys = [self._s(a) for a in args[1 : 1 + numkeys]]
        argv = list(args[1 + numkeys :])
        return self._script_reply(self._run_script(source, keys, argv))

    def _register_script(self, body: bytes) -> str:
        """Cache a script body under sha1(body) — shared by EVAL (Redis
        registers on first EVAL) and SCRIPT LOAD.  The script also
        becomes invokable via script_service.eval(sha, ...)."""
        import hashlib

        source = body.decode()
        sha = hashlib.sha1(body).hexdigest()
        svc = self._client.get_script()
        if not hasattr(svc, "_sources"):
            svc._sources = {}
        if sha not in svc._sources:
            svc._sources[sha] = source
            svc.register(
                sha,
                lambda client, keys, a, _src=source: self._run_script(
                    _src, keys, a
                ),
            )
        return sha

    def _cmd_EVAL(self, args):
        # Register sha1(body) BEFORE executing, like redis-server: EVAL
        # followed by EVALSHA of the same body must hit.
        self._register_script(args[0])
        return self._eval_common(args[0].decode(), args[1:])

    def _cmd_EVALSHA(self, args):
        sha = args[0].decode().lower()
        svc = self._client.get_script()
        src = getattr(svc, "_sources", {}).get(sha)
        if src is None:
            raise RespError(
                "NOSCRIPT No matching script. Please use EVAL."
            )
        return self._eval_common(src, args[1:])

    def _cmd_SCRIPT(self, args):
        sub = args[0].decode().upper()
        svc = self._client.get_script()
        if not hasattr(svc, "_sources"):
            svc._sources = {}
        if sub == "LOAD":
            return _encode_bulk(self._register_script(args[1]).encode())
        if sub == "EXISTS":
            return _encode_array([
                int(a.decode().lower() in svc._sources) for a in args[1:]
            ])
        if sub == "FLUSH":
            # Unregister from the ScriptService too — a flushed sha must
            # not stay invokable through the Python API.
            with svc._lock:
                for sha in list(svc._sources):
                    svc._fns.pop(sha, None)
            svc._sources.clear()
            return _encode_simple("OK")
        if sub == "KILL":
            # Stop a runaway script.  Delivery is two-pronged because a
            # single PyThreadState_SetAsyncExc is LOSSY (an exception
            # materializing inside a weakref/__del__ callback is
            # swallowed as "unraisable"): (1) a kill flag the script
            # thread checks synchronously at every redis.call dispatch
            # boundary, and (2) a reaper that re-posts the async
            # ScriptKilledError until the script actually exits —
            # covering tight pure-Python loops that never call redis.
            # Unlike Redis we cannot tell read-only scripts from
            # writers, so KILL is always permitted — the hazard is
            # documented in docs/observability.md.
            with self._script_lock:
                run = self._script_run
                if run is None or not run[0].is_alive():
                    raise RespError(
                        "NOTBUSY No scripts in execution right now."
                    )
                self._script_kill = run
            threading.Thread(
                target=self._script_reaper, args=(run,),
                name="rtpu-script-kill", daemon=True,
            ).start()
            return _encode_simple("OK")
        raise RespError(f"Unknown SCRIPT subcommand {sub}")

    def _cmd_FUNCTION(self, args):
        sub = args[0].decode().upper()
        svc = self._client.get_function()
        if sub == "LOAD":
            i = 1
            replace = False
            if args[i].decode().upper() == "REPLACE":
                replace = True
                i += 1
            source = args[i].decode()
            first, _, body = source.partition("\n")
            if not first.startswith("#!python"):
                raise RespError(
                    "Missing library metadata: the engine runs PYTHON "
                    "libraries — start with '#!python name=<library>' "
                    "(there is deliberately no Lua VM)"
                )
            lib = None
            for tok in first.split():
                if tok.startswith("name="):
                    lib = tok[5:]
            if not lib:
                raise RespError("Missing library name")
            collected: dict = {}
            ro_names: list = []

            def register_function(name, fn, flags=()):
                collected[name] = (
                    lambda client, keys, a, _fn=fn: _fn(keys, a)
                )
                if "no-writes" in flags:
                    ro_names.append(name)

            server = self

            class _Bridge:
                @staticmethod
                def call(*parts):
                    cmd = [
                        p if isinstance(p, bytes) else str(p).encode()
                        for p in parts
                    ]
                    return _decode_reply(
                        server._dispatch(cmd, server._ScriptCtx())
                    )

            ns = {"register_function": register_function, "redis": _Bridge}
            exec(compile(body, f"<function:{lib}>", "exec"), ns)
            if not collected:
                raise RespError(
                    "No functions registered: call "
                    "register_function(name, fn) in the library body"
                )
            try:
                svc.load(
                    lib, collected, replace=replace, no_writes=tuple(ro_names)
                )
            except ValueError as e:
                raise RespError(str(e)) from e
            return _encode_bulk(lib.encode())
        if sub == "DELETE":
            try:
                svc.delete(args[1].decode())
            except KeyError as e:
                raise RespError(str(e)) from e
            return _encode_simple("OK")
        if sub == "FLUSH":
            svc.flush()
            return _encode_simple("OK")
        if sub == "LIST":
            pat = None
            if len(args) >= 3 and args[1].decode().upper() == "LIBRARYNAME":
                pat = args[2].decode()
            libs = svc.list(pat)
            out = b"*" + str(len(libs)).encode() + b"\r\n"
            for lib in libs:
                out += (
                    b"*6\r\n"
                    + _encode_bulk(b"library_name")
                    + _encode_bulk(lib["library_name"].encode())
                    + _encode_bulk(b"engine")
                    + _encode_bulk(b"PYTHON")
                    + _encode_bulk(b"functions")
                    + _encode_array(
                        [f["name"].encode() for f in lib["functions"]]
                    )
                )
            return out
        raise RespError(f"Unknown FUNCTION subcommand {sub}")

    def _fcall(self, args, readonly: bool):
        svc = self._client.get_function()
        name = args[0].decode()
        numkeys = int(args[1])
        self._check_numkeys(numkeys, len(args) - 2)
        keys = [self._s(a) for a in args[2 : 2 + numkeys]]
        argv = list(args[2 + numkeys :])
        # Function bodies are the same RCE-gated Python family as EVAL
        # and run under the grid lock (FunctionService takes it
        # internally) — claim the script watchdog slot so a runaway
        # function surfaces BUSY and is SCRIPT KILLable too.
        started_here = self._script_claim()
        try:
            out = (
                svc.call_ro(name, keys, argv)
                if readonly
                else svc.call(name, keys, argv)
            )
        except ScriptKilledError:
            # Nested frame (function called from a script): re-raise the
            # BaseException so the outer script cannot catch it — only
            # the outermost frame converts (see _run_script).  The gated
            # finally below releases the slot either way.
            if not started_here:
                raise
            raise RespError(
                "Script killed by user with SCRIPT KILL..."
            ) from None
        except KeyError as e:
            raise RespError(f"Function not found ({e})") from e
        except ValueError as e:
            raise RespError(str(e)) from e
        finally:
            if started_here:
                self._script_unregister()
        return self._script_reply(out)

    def _cmd_FCALL(self, args):
        return self._fcall(args, False)

    def _cmd_FCALL_RO(self, args):
        return self._fcall(args, True)
