"""Client-side RESP wire helpers shared by the bench and the test
harnesses (one copy of the reply-frame walker — a framing fix applied to
a private duplicate would leave the other silently wrong).

These are deliberately simple and allocation-light: the bench's reply
counter calls ``skip_reply_frame`` per frame on the hot loop.
"""

from __future__ import annotations


def wire_command(args) -> bytes:
    """Encode one command as a RESP multibulk request frame."""
    out = b"*%d\r\n" % len(args)
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def skip_reply_frame(buf: bytes, i: int) -> int:
    """End offset of the RESP reply frame starting at ``i``.

    Raises IndexError when the frame is incomplete (read more bytes and
    retry) and ValueError on an unparseable frame type — callers must
    treat the latter as a corrupt stream, never silently resync."""
    j = buf.find(b"\r\n", i)
    if j < 0:
        raise IndexError("incomplete header")
    t, body = buf[i : i + 1], buf[i + 1 : j]
    i = j + 2
    if t in (b"+", b"-", b":"):
        return i
    if t == b"$":
        n = int(body)
        if n < 0:
            return i
        if len(buf) < i + n + 2:
            raise IndexError("incomplete bulk")
        return i + n + 2
    if t in (b"*", b">"):
        for _ in range(max(0, int(body))):
            i = skip_reply_frame(buf, i)
        return i
    raise ValueError(f"bad reply frame type {t!r}")


class ReplyError(Exception):
    """A RESP error reply (``-...``), decoded but NOT raised by
    ``decode_reply`` — scatter/gather callers must be able to place
    per-command errors positionally without unwinding the batch."""

    @property
    def code(self) -> str:
        """Leading word of the error ('MOVED', 'ASK', 'ERR', ...)."""
        return str(self).split(" ", 1)[0]


def decode_reply(buf: bytes, i: int = 0):
    """Decode ONE RESP reply frame at ``i`` into (value, end_offset).

    simple string -> bytes, integer -> int, bulk -> bytes|None,
    array/push -> list, error -> a ReplyError INSTANCE (returned, not
    raised).  IndexError/ValueError signal an incomplete frame, like
    ``skip_reply_frame``.
    """
    j = buf.index(b"\r\n", i)
    t, body = buf[i : i + 1], buf[i + 1 : j]
    i = j + 2
    if t == b"+":
        return body, i
    if t == b"-":
        return ReplyError(body.decode("latin-1", "replace")), i
    if t == b":":
        return int(body), i
    if t == b"$":
        n = int(body)
        if n < 0:
            return None, i
        if len(buf) < i + n + 2:
            raise IndexError("incomplete bulk")
        return buf[i : i + n], i + n + 2
    if t in (b"*", b">"):
        n = int(body)
        if n < 0:
            return None, i
        out = []
        for _ in range(n):
            v, i = decode_reply(buf, i)
            out.append(v)
        return out, i
    raise ValueError(f"bad reply frame type {t!r}")


def decode_command(buf: bytes, i: int = 0):
    """Decode ONE RESP multibulk REQUEST frame at ``i`` into
    (argv list of bytes, end_offset) — the inverse of
    :func:`wire_command`, for harnesses that play the SERVER side of
    the wire (the netsim protocol models' node actors).
    IndexError/ValueError signal an incomplete frame, like
    ``decode_reply``; a frame that is complete but not a multibulk
    command raises ValueError (corrupt stream, never resync)."""
    j = buf.index(b"\r\n", i)
    if buf[i : i + 1] != b"*":
        raise ValueError(
            f"bad command frame type {buf[i:i + 1]!r} (want multibulk)"
        )
    n = int(buf[i + 1 : j])
    i = j + 2
    out: list = []
    for _ in range(n):
        j = buf.index(b"\r\n", i)
        if buf[i : i + 1] != b"$":
            raise ValueError("command args must be bulk strings")
        ln = int(buf[i + 1 : j])
        i = j + 2
        if len(buf) < i + ln + 2:
            raise IndexError("incomplete bulk")
        out.append(buf[i : i + ln])
        i += ln + 2
    return out, i


def encode_reply(v) -> bytes:
    """Encode one decoded-reply-shaped value back into a RESP frame —
    the server half the netsim node harnesses speak.  The mapping is
    ``decode_reply``'s inverse: int -> ``:``, bytes -> bulk, None ->
    nil bulk, list -> array, ReplyError -> ``-``, str -> simple
    string (use bytes for data, str only for ``+OK``-class acks)."""
    if isinstance(v, bool):
        return b":%d\r\n" % int(v)
    if isinstance(v, int):
        return b":%d\r\n" % v
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, ReplyError):
        return b"-" + str(v).encode("latin-1", "replace") + b"\r\n"
    if isinstance(v, str):
        return b"+" + v.encode("latin-1", "replace") + b"\r\n"
    if isinstance(v, (bytes, bytearray)):
        v = bytes(v)
        return b"$%d\r\n%s\r\n" % (len(v), v)
    if isinstance(v, (list, tuple)):
        return b"*%d\r\n" % len(v) + b"".join(encode_reply(x) for x in v)
    raise TypeError(f"cannot encode reply value of type {type(v)!r}")


def exchange(sock, cmds) -> list:
    """One pipelined request/response cycle on a CONNECTED socket:
    ship ``cmds`` in one sendall, decode exactly ``len(cmds)`` replies
    in order (error replies as ReplyError instances, never raised).

    The one copy of the client-side framing loop (this module's
    founding rule): the cluster client's pooled connections, the
    supervisor's control requests, and the migration pump all ride it.
    Raises OSError when the peer closes mid-reply — after which the
    socket is DESYNCED and must be discarded, never reused."""
    sock.sendall(b"".join(wire_command(c) for c in cmds))
    buf = b""
    out: list = []
    pos = 0
    while len(out) < len(cmds):
        chunk = sock.recv(1 << 16)
        if not chunk:
            raise OSError("peer closed mid-reply")
        buf += chunk
        while len(out) < len(cmds):
            try:
                val, pos = decode_reply(buf, pos)
            except (IndexError, ValueError):
                break  # incomplete frame: recv more
            out.append(val)
    return out
