"""Client-side RESP wire helpers shared by the bench and the test
harnesses (one copy of the reply-frame walker — a framing fix applied to
a private duplicate would leave the other silently wrong).

These are deliberately simple and allocation-light: the bench's reply
counter calls ``skip_reply_frame`` per frame on the hot loop.
"""

from __future__ import annotations


def wire_command(args) -> bytes:
    """Encode one command as a RESP multibulk request frame."""
    out = b"*%d\r\n" % len(args)
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def skip_reply_frame(buf: bytes, i: int) -> int:
    """End offset of the RESP reply frame starting at ``i``.

    Raises IndexError when the frame is incomplete (read more bytes and
    retry) and ValueError on an unparseable frame type — callers must
    treat the latter as a corrupt stream, never silently resync."""
    j = buf.index(b"\r\n", i)
    t, body = buf[i : i + 1], buf[i + 1 : j]
    i = j + 2
    if t in (b"+", b"-", b":"):
        return i
    if t == b"$":
        n = int(body)
        if n < 0:
            return i
        if len(buf) < i + n + 2:
            raise IndexError("incomplete bulk")
        return i + n + 2
    if t in (b"*", b">"):
        for _ in range(max(0, int(body))):
            i = skip_reply_frame(buf, i)
        return i
    raise ValueError(f"bad reply frame type {t!r}")
