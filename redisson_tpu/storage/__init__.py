"""Tiered sketch storage (ISSUE 14): the heat-based residency ladder.

Device rows become a CACHE over host golden mirrors over per-object
disk blobs — the addressable tenant population is bounded by host+disk,
not HBM.  ``heat.py`` tracks decayed access heat per object;
``residency.py`` drives demotion/promotion/spill/load against a
device-rows budget.
"""

from redisson_tpu.storage.heat import HeatTracker
from redisson_tpu.storage.residency import (
    DEVICE,
    DISK,
    HOST,
    ROW_NONE,
    ResidencyManager,
)

__all__ = [
    "HeatTracker",
    "ResidencyManager",
    "DEVICE",
    "HOST",
    "DISK",
    "ROW_NONE",
]
