"""Decayed access-heat tracking (ISSUE 14 tentpole, part 1).

One ``HeatTracker`` per engine scores every named sketch by an
exponentially-decayed access counter: each touch adds 1 after decaying
the stored value by ``2^(-dt/half_life)``.  The score is the residency
ladder's ONLY ranking signal (coldest demote first, hottest promote
first), and it feeds the RESP introspection surface directly:
``OBJECT FREQ`` is the decayed heat, ``OBJECT IDLETIME`` the seconds
since the last touch.

Fed from the engine's entry-point lookups (``_lookup_kind`` /
``hll_ensure`` / ``bitset_ensure`` — the same choke points the
near-cache epoch hooks mark), so every read AND write of every op path
counts exactly once per API call.

The clock is injectable (tests drive a fake clock instead of
``DEBUG SLEEP``-style real waits), and the table is bounded: past
``max_entries`` the coldest half is folded away — a pruned name that
returns simply restarts from zero heat, which only delays its next
promotion by a touch or two.
"""

from __future__ import annotations

import math
import threading
import time

from redisson_tpu.analysis import witness as _witness


class HeatTracker:
    def __init__(self, half_life_s: float = 10.0, *,
                 max_entries: int = 1 << 17, clock=time.monotonic):
        self.half_life_s = float(half_life_s)
        self.max_entries = int(max_entries)
        self._clock = clock
        self._lock = _witness.named(threading.Lock(), "storage.heat")
        # name -> (heat_at_stamp, stamp).  Decay is lazy: applied on
        # touch and on read, so an idle tracker costs nothing.
        self._heat: dict[str, tuple] = {}

    def _decayed(self, heat: float, stamp: float, now: float) -> float:
        dt = now - stamp
        if dt <= 0.0:
            return heat
        hl = self.half_life_s
        if hl <= 0.0 or dt > 64.0 * hl:
            return 0.0
        return heat * math.pow(2.0, -dt / hl)

    def touch(self, name: str, n: int = 1) -> None:
        """Lock-free on purpose: this runs on EVERY engine op's entry
        point, ladder armed or not (it feeds OBJECT FREQ/IDLETIME).
        Individual dict probes/stores are GIL-atomic; a concurrent
        touch of the same name can lose one bump and a racing prune's
        table swap can drop one — both benign for an advisory ranking
        signal (heat ±1 never flips a tier decision that the next
        touch wouldn't flip back).  Structural ops (prune / drop /
        rename / snapshot / reads) still serialize on the lock."""
        d = self._heat
        now = self._clock()
        ent = d.get(name)
        if ent is None:
            d[name] = (float(n), now)
            if len(d) > self.max_entries:
                with self._lock:
                    if len(self._heat) > self.max_entries:
                        self._prune_locked(now)
            return
        heat, stamp = ent
        d[name] = (self._decayed(heat, stamp, now) + n, now)

    def heat(self, name: str) -> float:
        """Current decayed heat (0.0 for never-touched names)."""
        now = self._clock()
        with self._lock:
            ent = self._heat.get(name)
            if ent is None:
                return 0.0
            return self._decayed(ent[0], ent[1], now)

    def idle_s(self, name: str) -> float:
        """Seconds since the last touch (0.0 for never-touched names —
        a fresh object has by definition just been created)."""
        with self._lock:
            ent = self._heat.get(name)
            if ent is None:
                return 0.0
            return max(0.0, self._clock() - ent[1])

    def snapshot(self) -> dict:
        """{name: decayed_heat} — ONE lock hold, used by the residency
        thread's ranking pass."""
        now = self._clock()
        with self._lock:
            # list() is one C-level call (atomic under the GIL) — the
            # per-item Python work below must not iterate the live
            # dict, which lock-free touches keep mutating.
            items = list(self._heat.items())
        return {n: self._decayed(h, s, now) for n, (h, s) in items}

    def drop(self, name: str) -> None:
        with self._lock:
            self._heat.pop(name, None)

    def rename(self, old: str, new: str) -> None:
        with self._lock:
            ent = self._heat.pop(old, None)
            if ent is not None:
                self._heat[new] = ent

    def _prune_locked(self, now: float) -> None:
        """Fold away the coldest half — bounds the table for name-churn
        workloads (the nearcache `_epochs` discipline; see module doc
        for why losing a cold name's heat is benign)."""
        scored = sorted(
            list(self._heat.items()),  # atomic copy vs lock-free touch
            key=lambda kv: self._decayed(kv[1][0], kv[1][1], now),
            reverse=True,
        )
        self._heat = dict(scored[: self.max_entries // 2])

    def __len__(self) -> int:
        with self._lock:
            return len(self._heat)
