"""Heat-based residency ladder (ISSUE 14 tentpole).

Device rows become a CACHE over host golden mirrors over per-object
disk blobs: every sketch is in exactly one of three residency states —

- ``DEVICE`` — a size-class pool row; the fast tier, bounded by the
  ``residency_device_rows`` budget.
- ``HOST``   — a golden-model mirror (objects/degraded.py codecs, the
  exact bidirectional conversion the breaker failover already uses).
  Demoted is NOT degraded: no breaker, no flags — reads and writes
  serve from the mirror at host speed through the same
  ``_serve_degraded`` boundary every engine method already crosses.
- ``DISK``   — a CRC-framed per-object blob (the engine's data-only
  dump format inside the snapshot tier's frame discipline: tmp file →
  fsync → rename, so a kill -9 mid-spill never publishes a torn blob).

Transition protocol (why no schedule loses an acked write or serves a
stale read):

- Every transition holds the engine's JOURNAL GATE.  All mutating
  engine methods hold the gate across their entire
  check-residency → submit window, so no write can be in flight
  between "the op decided device" and "the row moved".
- Demotion drains the coalescer before reading the row (queued ops
  land first), installs the mirror under the mirror lock (serving
  atomically switches to the mirror), and bumps ``_mirror_epoch`` so a
  concurrent breaker seeder discards its possibly-stale row snapshot.
- The freed device row is QUARANTINED, not recycled: readers do not
  hold the gate, so a read that captured the row before the mirror
  install may still flush against it — the row keeps its (bit-
  identical) pre-demotion contents until a later cycle has drained the
  coalescer again, only then is it zeroed and returned to the pool.
- Promotion allocates through the prewarmed size-class pools
  (``SizeClassPool.alloc_row`` — the jit ladder is already warm, so
  promotion never compiles), writes the mirror's encoding, repoints
  ``entry.row`` BEFORE dropping the mirror (a reader racing the drop
  falls through ``_mirror_call``'s None to a fully-written row), and
  bumps ``_mirror_epoch``.
- Spill serializes the mirror while holding the gate (writers
  excluded; degraded-path reads never mutate mirror state), publishes
  the blob durably, and only then drops the mirror.

Snapshot interplay: blobs are versioned ``obj-<h>-<seq>.rts`` files; a
snapshot records the exact filename + CRC per DISK tenant, and a blob
is garbage-collected only when the LATEST durable snapshot no longer
references it — so restore-from-snapshot + journal-tail replay can
never find a blob that was overwritten with post-snapshot state (the
replay would double-apply).

Born-cold creation: when the device budget is full, ``try_create``
skips the row alloc entirely (``TenantRegistry.alloc_gate``) and the
first access installs a zero-seeded mirror — the fast tier holds the
working set, not the keyspace, so pool arrays never grow past the
budget just because the tenant COUNT did.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import struct
import threading
import time
import zlib
from typing import Optional

import numpy as np

from redisson_tpu import chaos as _chaos
from redisson_tpu.analysis import witness as _witness

DEVICE = "device"
HOST = "host"
DISK = "disk"

# Sentinel for "no device row" (HOST/DISK residency).  Everything that
# enumerates an entry's rows must treat row < 0 as "none".
ROW_NONE = -1

_BLOB_MAGIC = b"RTPB"
_BLOB_HDR = struct.Struct("<II")  # payload_len, crc32


def _frame_blob(payload: bytes) -> bytes:
    return _BLOB_MAGIC + _BLOB_HDR.pack(
        len(payload), zlib.crc32(payload)
    ) + payload


def _unframe_blob(data: bytes) -> bytes:
    """CRC-checked payload, or ValueError (a torn/corrupt blob must
    refuse loudly, never install garbage state)."""
    if len(data) < 12 or data[:4] != _BLOB_MAGIC:
        raise ValueError("not a residency blob (bad magic)")
    plen, crc = _BLOB_HDR.unpack(data[4:12])
    payload = data[12:12 + plen]
    if len(payload) != plen or zlib.crc32(payload) != crc:
        raise ValueError("residency blob failed its CRC check")
    return payload


def _parse_dump_row(payload: bytes) -> np.ndarray:
    """The device-row array out of an engine dump blob (the spill
    payload IS the dump format — kind/params ride in the header for
    debuggability, but load only needs the row: the live registry
    entry is authoritative for everything else)."""
    import io
    import struct as _struct

    from redisson_tpu.objects.durability import _DUMP_MAGIC, safe_load_npy

    if len(payload) < 8 or payload[:4] != _DUMP_MAGIC:
        raise ValueError("residency blob payload is not a sketch dump")
    (hlen,) = _struct.unpack("<I", payload[4:8])
    return np.asarray(safe_load_npy(io.BytesIO(payload[8 + hlen:])))


class ResidencyManager:
    """One per TpuSketchEngine.  Owns the heat tracker, the background
    demotion/promotion thread, the disk-blob index, and the quarantine
    of freed device rows."""

    def __init__(self, engine, cfg, *, obs=None, clock=time.monotonic):
        from redisson_tpu.storage.heat import HeatTracker

        self._eng = engine
        self.obs = obs
        self._clock = clock
        self.device_rows = int(getattr(cfg, "residency_device_rows", 0))
        self.max_host_bytes = int(
            getattr(cfg, "residency_max_host_bytes", 0)
        )
        self.max_disk_bytes = int(
            getattr(cfg, "residency_max_disk_bytes", 0)
        )
        self.promote_heat = float(
            getattr(cfg, "residency_promote_heat", 4.0)
        )
        self.interval_s = (
            float(getattr(cfg, "residency_interval_ms", 200)) / 1000.0
        )
        self.directory = getattr(cfg, "residency_dir", None)
        self.heat = HeatTracker(
            half_life_s=float(
                getattr(cfg, "residency_heat_half_life_s", 10.0)
            ),
            clock=clock,
        )
        self._lock = _witness.named(
            threading.Lock(), "storage.residency"
        )
        self._host_nbytes: dict[str, int] = {}   # HOST mirrors, by name
        self._disk: dict[str, dict] = {}         # name -> {file, crc, nbytes}
        self._snapshot_refs: set[str] = set()    # blob files the latest snapshot names
        self._gc: set[str] = set()               # retired blob files awaiting GC
        self._quarantine: list[tuple] = []       # (pool, row, topology_epoch)
        self._spill_seq = 0
        # Lifetime transition counters (INFO memory tier breakdown).
        self.promotions = 0
        self.demotions = 0
        self.spills = 0
        self.loads = 0
        self.host_serves = 0  # ops served from HOST mirrors (not degraded)
        self._thread: Optional[tuple] = None

    # -- heat feed (the engine's entry-point lookups) ----------------------

    def touch(self, name: str, n: int = 1) -> None:
        self.heat.touch(name, n)

    # -- tier accounting ---------------------------------------------------

    def host_objects(self) -> int:
        return len(self._host_nbytes)

    def host_bytes(self) -> int:
        with self._lock:
            return sum(self._host_nbytes.values())

    def disk_objects(self) -> int:
        return len(self._disk)

    def disk_bytes(self) -> int:
        with self._lock:
            return sum(d["nbytes"] for d in self._disk.values())

    def device_rows_used(self) -> int:
        return sum(
            p.used_rows() for p in self._eng.registry.pools()
        )

    def device_full(self) -> bool:
        """The registry's alloc gate: True ⇒ try_create births the
        tenant HOST-resident instead of growing a pool past the
        budget."""
        b = self.device_rows
        return b > 0 and self.device_rows_used() >= b

    def stats(self) -> dict:
        return {
            "device_rows_budget": self.device_rows,
            "device_rows_used": self.device_rows_used(),
            "host_objects": self.host_objects(),
            "host_bytes": self.host_bytes(),
            "disk_objects": self.disk_objects(),
            "disk_bytes": self.disk_bytes(),
            "promotions": self.promotions,
            "demotions": self.demotions,
            "spills": self.spills,
            "loads": self.loads,
            "host_serves": self.host_serves,
            "quarantined_rows": len(self._quarantine),
        }

    # -- observability helpers ---------------------------------------------

    def _note(self, kind: str, name: str, t0: float) -> None:
        """Counter + LATENCY event + trace span for one transition."""
        obs = self.obs
        if obs is None:
            return
        fam = {
            "promote": getattr(obs, "residency_promotions", None),
            "demote": getattr(obs, "residency_demotions", None),
            "spill": getattr(obs, "residency_spills", None),
            "load": getattr(obs, "residency_loads", None),
        }.get(kind)
        if fam is not None:
            fam.inc()
        events = getattr(obs, "events", None)
        if events is not None:
            # The flight recorder records tier TRANSITIONS (promote /
            # demote / spill); disk loads are read-path volume, not a
            # control-plane decision.  One literal per branch: RT015
            # requires every emitted kind to be a registered literal.
            ms = round((self._clock() - t0) * 1e3, 3)
            if kind == "promote":
                events.emit("residency.promote", object=name, ms=ms)
            elif kind == "demote":
                events.emit("residency.demote", object=name, ms=ms)
            elif kind == "spill":
                events.emit("residency.spill", object=name, ms=ms)
        lat = getattr(obs, "latency", None)
        if lat is not None and lat.threshold_ms > 0:
            lat.record(
                f"residency-{kind}", (self._clock() - t0) * 1e3
            )

    def _span(self, kind: str, name: str):
        """Per-transition span in the tracing plane (nullcontext on the
        off path — the chaos/trace.ENABLED discipline)."""
        from redisson_tpu.obs import trace as _trace

        obs = self.obs
        if obs is None or not _trace.ENABLED:
            return contextlib.nullcontext()
        # rtpulint: disable=RT011 the scope is handed off: _Annotated delegates __enter__/__exit__ to it verbatim, so the span always reaches end/abandon through the with-statement below
        scope = obs.trace.span_scope(f"residency:{kind}")

        class _Annotated:
            def __enter__(_s):
                sp = scope.__enter__()
                if sp is not None:
                    sp.annotate("object", name)
                return sp

            def __exit__(_s, *exc):
                return scope.__exit__(*exc)

        return _Annotated()

    # -- transitions -------------------------------------------------------

    def demote(self, name: str) -> bool:
        """DEVICE → HOST: the entry's row contents move into an exact
        golden mirror; the row is quarantined for deferred reclaim.
        See the module doc for the full write/read race argument."""
        from redisson_tpu.objects.degraded import mirror_for_entry

        eng = self._eng
        t0 = self._clock()
        with self._span("demote", name), eng._journal_gate:
            entry = eng._live_lookup(name)
            if entry is None or entry.row < 0 or entry.replica_rows:
                return False
            if eng.health.degraded_kind(entry.kind):
                # A breaker owns this kind's mirror lifecycle right now
                # (and the device read below would be failing anyway).
                return False
            if entry.name in eng._mirrors:
                return False
            # Queued coalesced ops (every writer held the gate at
            # submit, so all accepted writes are either applied or
            # queued) land on the row before the capture read.
            eng._drain()
            try:
                row = np.array(
                    eng.executor.read_row(entry.pool, entry.row)
                )
            except Exception:
                return False
            mirror = mirror_for_entry(entry, row)
            mirror.residency = HOST
            with eng._mirror_lock:
                if entry.name in eng._mirrors:
                    return False  # breaker seeder won the install race
                if eng.health.degraded_kind(entry.kind):
                    return False
                eng._mirrors[entry.name] = mirror
                # Device row about to be retired under any in-flight
                # breaker seeder: its row snapshot is stale.
                eng._mirror_epoch += 1
                pool, old_row = entry.pool, entry.row
                entry.row = ROW_NONE
                entry.residency = HOST
            with self._lock:
                self._quarantine.append(
                    (pool, old_row, pool.topology_epoch)
                )
                self._host_nbytes[name] = int(row.nbytes)
            self.demotions += 1
        self._note("demote", name, t0)
        return True

    def promote(self, name: str) -> bool:
        """HOST (or DISK, via an implicit load) → DEVICE through the
        prewarmed size-class pools — the ladder is already warm, so
        promotion never compiles."""
        eng = self._eng
        t0 = self._clock()
        with self._span("promote", name), eng._journal_gate:
            entry = eng._live_lookup(name)
            if entry is None or entry.row >= 0:
                return False
            if eng.health.degraded_kind(entry.kind):
                return False  # device failing: stay host-resident
            if entry.residency == DISK and not self._load_gated(entry):
                return False
            with eng._mirror_lock:
                mirror = eng._mirrors.get(name)
                if mirror is None or getattr(
                    mirror, "residency", None
                ) != HOST:
                    return False
                row = entry.pool.alloc_row()
                try:
                    # rtpulint: disable=RT001 the write-back MUST hold the mirror lock: a mirror op interleaving between encode and the mirror drop would apply to a mirror about to be discarded (lost acked write) — the reconcile write-back discipline
                    eng.executor.write_row(
                        entry.pool, row,
                        np.asarray(mirror.encode(entry.pool.row_units)),
                    )
                except Exception:
                    try:
                        # rtpulint: disable=RT001 same atomic window as the write above
                        eng.executor.zero_row(entry.pool, row)
                        entry.pool.free_row(row)
                    except Exception:  # pragma: no cover — device failing
                        pass  # leak one row rather than recycle it dirty
                    return False
                # Row first, THEN drop the mirror: a reader racing the
                # drop falls through _mirror_call's None onto a row
                # that is already fully written.
                entry.row = row
                entry.residency = DEVICE
                del eng._mirrors[name]
                eng._mirror_epoch += 1
            with self._lock:
                self._host_nbytes.pop(name, None)
            self.promotions += 1
        self._note("promote", name, t0)
        return True

    def spill(self, name: str) -> bool:
        """HOST → DISK: the mirror serializes into a CRC-framed blob
        (durable before the mirror drops) and the host bytes free."""
        eng = self._eng
        if not self.directory:
            return False
        t0 = self._clock()
        with self._span("spill", name), eng._journal_gate:
            entry = eng._live_lookup(name)
            if entry is None or entry.row >= 0:
                return False
            with eng._mirror_lock:
                mirror = eng._mirrors.get(name)
                if mirror is None or getattr(
                    mirror, "residency", None
                ) != HOST:
                    return False
            # Queued coalesced chunks that serve from this mirror at
            # FLUSH time (the bitset mixed path) land before the
            # capture; new writers are excluded by the gate (we hold
            # it) — after the drain the dump below is a stable capture.
            # (Gate-free READ chunks can still enqueue post-drain; the
            # flush path reloads the mirror for those stragglers.)
            eng._drain()
            payload = eng.dump(name)
            if payload is None:
                return False
            framed = _frame_blob(payload)
            if self.max_disk_bytes > 0 and (
                self.disk_bytes() + len(framed) > self.max_disk_bytes
            ):
                return False  # disk cap: entry stays HOST
            if _chaos.ENABLED:
                _chaos.fire("storage.spill")
            fname = self._write_blob(name, framed)
            with eng._mirror_lock:
                # The gate made the mirror stable; drop it and flip the
                # tier only after the blob is durable on disk.
                eng._mirrors.pop(name, None)
                entry.residency = DISK
            with self._lock:
                self._host_nbytes.pop(name, None)
                old = self._disk.get(name)
                if old is not None:
                    self._retire_blob_locked(old["file"])
                self._disk[name] = {
                    "file": fname,
                    "crc": zlib.crc32(payload),
                    "nbytes": len(framed),
                }
            self.spills += 1
        self._note("spill", name, t0)
        return True

    def load(self, name: str) -> bool:
        """DISK → HOST (also the born-cold first touch): rebuild the
        mirror from the blob (CRC-checked) or, for a tenant created
        past the device budget, from zeros."""
        eng = self._eng
        t0 = self._clock()
        with self._span("load", name), eng._journal_gate:
            entry = eng._live_lookup(name)
            if entry is None or entry.row >= 0:
                return False
            ok = self._load_gated(entry)
        if ok:
            self._note("load", name, t0)
        return ok

    def load_nowait(self, entry) -> bool:
        """Gate-NON-BLOCKING mirror load for the coalescer FLUSH path:
        a transition holding the gate may be draining — i.e. waiting
        on the very flush that is asking — so blocking here would be
        an AB-BA (flush→gate vs gate→drain).  False when the gate is
        contended; the caller retries or fails the chunk typed."""
        eng = self._eng
        if not eng._journal_gate.acquire(blocking=False):
            return False
        try:
            return self._load_gated(entry)
        finally:
            eng._journal_gate.release()

    def install_host(self, entry, row=None, mirror=None) -> None:
        """Install ``entry`` as HOST-resident from a row array or a
        ready-made mirror — the snapshot-restore / journal-writeback
        install path (engine init, or under the journal gate).  The
        manager owns the mirror install AND the host-bytes accounting,
        so the two can never drift (the SpanRecorder.reset lesson)."""
        from redisson_tpu.objects.degraded import mirror_for_entry

        eng = self._eng
        if mirror is None:
            mirror = mirror_for_entry(entry, np.asarray(row))
        mirror.residency = HOST
        with eng._mirror_lock:
            eng._mirrors[entry.name] = mirror
            entry.row = ROW_NONE
            entry.residency = HOST
        with self._lock:
            self._host_nbytes[entry.name] = int(
                entry.pool.row_units
                * np.dtype(entry.pool.spec.dtype).itemsize
            )

    def _load_gated(self, entry) -> bool:
        """Install ``entry``'s HOST mirror from its blob (or zeros for
        a born-cold tenant).  Caller holds the journal gate."""
        from redisson_tpu.objects.degraded import mirror_for_entry

        eng = self._eng
        name = entry.name
        with eng._mirror_lock:
            if name in eng._mirrors:
                entry.residency = HOST
                return True  # raced another loader
        with self._lock:
            info = dict(self._disk.get(name) or {})
        if info:
            if _chaos.ENABLED:
                _chaos.fire("storage.load")
            path = os.path.join(self.directory, info["file"])
            with open(path, "rb") as f:
                payload = _unframe_blob(f.read())
            row = _parse_dump_row(payload)
        else:
            # Born cold (created while the device budget was full):
            # fresh state is all-zeros in every kind's row layout.
            row = np.zeros(
                entry.pool.row_units, entry.pool.spec.dtype
            )
        if row.shape[0] < entry.pool.row_units:
            # The entry migrated to a larger size class while spilled
            # (bitset grow repoints the pool without a row) — pad; the
            # golden models treat trailing zeros as absent bits.
            padded = np.zeros(
                entry.pool.row_units, entry.pool.spec.dtype
            )
            padded[: row.shape[0]] = row
            row = padded
        mirror = mirror_for_entry(entry, row)
        mirror.residency = HOST
        with eng._mirror_lock:
            if name in eng._mirrors:
                entry.residency = HOST
                return True
            eng._mirrors[name] = mirror
            entry.residency = HOST
        with self._lock:
            self._host_nbytes[name] = int(row.nbytes)
            if info:
                # The mirror will accumulate writes: the blob is stale
                # the moment serving resumes.  Retire it (GC keeps any
                # file the latest snapshot still references).
                self._disk.pop(name, None)
                self._retire_blob_locked(info["file"])
        if info:
            self.loads += 1
        return True

    # -- blob files --------------------------------------------------------

    def _write_blob(self, name: str, framed: bytes) -> str:
        from redisson_tpu.durability.journal import _fsync_dir

        os.makedirs(self.directory, exist_ok=True)
        with self._lock:
            self._spill_seq += 1
            seq = self._spill_seq
        h = hashlib.sha1(name.encode("utf-8", "replace")).hexdigest()[:16]
        fname = f"obj-{h}-{seq}.rts"
        tmp = os.path.join(self.directory, fname + ".tmp")
        with open(tmp, "wb") as f:
            f.write(framed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, fname))
        _fsync_dir(self.directory)
        return fname

    def _retire_blob_locked(self, fname: str) -> None:
        self._gc.add(fname)

    def note_snapshot_refs(self, refs) -> None:
        """The latest durable snapshot references exactly these blob
        files — everything retired and unreferenced may now delete."""
        with self._lock:
            self._snapshot_refs = set(refs)

    def gc_blobs(self) -> int:
        """Delete retired blobs the latest snapshot no longer names."""
        with self._lock:
            dead = [f for f in self._gc if f not in self._snapshot_refs]
            for f in dead:
                self._gc.discard(f)
        n = 0
        for f in dead:
            try:
                os.unlink(os.path.join(self.directory, f))
                n += 1
            except OSError:  # pragma: no cover — already gone
                pass
        return n

    def adopt_blob(self, name: str, fname: str, crc: int,
                   nbytes: int) -> None:
        """Snapshot-restore installs a DISK tenant: the blob must
        exist — a missing file would silently lose the object."""
        path = os.path.join(self.directory or "", fname)
        if not self.directory or not os.path.exists(path):
            raise ValueError(
                f"residency blob {fname!r} for {name!r} is missing "
                f"(residency_dir={self.directory!r})"
            )
        with self._lock:
            self._disk[name] = {
                "file": fname, "crc": int(crc), "nbytes": int(nbytes),
            }
            self._snapshot_refs.add(fname)

    def disk_index(self) -> dict:
        with self._lock:
            return {n: dict(d) for n, d in self._disk.items()}

    # -- lifecycle hooks (delete / rename / expiry) ------------------------

    def drop(self, name: str) -> None:
        self.heat.drop(name)
        with self._lock:
            self._host_nbytes.pop(name, None)
            info = self._disk.pop(name, None)
            if info is not None:
                self._retire_blob_locked(info["file"])

    def rename(self, old: str, new: str) -> None:
        self.heat.rename(old, new)
        with self._lock:
            if old in self._host_nbytes:
                self._host_nbytes[new] = self._host_nbytes.pop(old)
            dest = self._disk.pop(new, None)
            if dest is not None:
                self._retire_blob_locked(dest["file"])
            src = self._disk.pop(old, None)
            if src is not None:
                self._disk[new] = src

    # -- quarantine reclaim ------------------------------------------------

    def reclaim(self) -> int:
        """Zero + free quarantined rows from EARLIER cycles.  A drain
        first: any read that captured a quarantined row pre-demotion
        has flushed against its (intact) contents by the time the row
        recycles — the no-stale-reads half of the protocol."""
        with self._lock:
            pending, self._quarantine = self._quarantine, []
        if not pending:
            return 0
        eng = self._eng
        eng._drain()
        n = 0
        for pool, row, epoch in pending:
            with pool._dispatch_lock:
                if pool.topology_epoch != epoch:
                    continue  # a reshard already rebuilt the free list
                try:
                    # rtpulint: disable=RT001 zero-then-free must be atomic vs reallocation (the _reap_rows discipline): releasing between would hand out a dirty row
                    eng.executor.zero_row(pool, row)
                except Exception:
                    continue  # leak one row rather than recycle it dirty
                pool.free_row(row)
                n += 1
        return n

    # -- the background residency thread -----------------------------------

    def maintain(self) -> dict:
        """One maintenance cycle: reclaim, enforce the device-rows
        budget (demote coldest), promote the hot set (admission-aware),
        enforce the host-bytes cap (spill coldest), GC blobs.  Returns
        a {action: count} summary (tests drive this synchronously)."""
        out = {"reclaimed": self.reclaim(), "demoted": 0,
               "promoted": 0, "spilled": 0}
        eng = self._eng
        budget = self.device_rows
        if budget <= 0 and self.max_host_bytes <= 0:
            return out
        heat = self.heat.snapshot()
        entries = eng.registry.entries()

        def _heat(e):
            return heat.get(e.name, 0.0)

        if budget > 0:
            device_e = sorted(
                (e for e in entries if e.row >= 0 and not e.replica_rows),
                key=_heat,
            )
            used = self.device_rows_used()
            # 1. budget enforcement: coldest rows demote first.
            while used > budget and device_e:
                e = device_e.pop(0)
                if self.demote(e.name):
                    out["demoted"] += 1
                    used -= 1
            # 2. promotion, admission-aware: no promotion storm may
            #    push queue pressure past the watermark.
            if not self._admission_blocked():
                cands = sorted(
                    (
                        e for e in entries
                        if e.row < 0 and _heat(e) >= self.promote_heat
                    ),
                    key=_heat, reverse=True,
                )
                for cand in cands:
                    if self._admission_blocked():
                        break
                    if used < budget:
                        if self.promote(cand.name):
                            out["promoted"] += 1
                            used += 1
                        continue
                    # Budget full: swap in only against a clearly
                    # colder victim (2x hysteresis — no thrash at the
                    # boundary).
                    victim = device_e[0] if device_e else None
                    if victim is None or _heat(victim) * 2.0 >= _heat(cand):
                        break
                    if self.demote(victim.name):
                        device_e.pop(0)
                        out["demoted"] += 1
                        used -= 1
                        if self.promote(cand.name):
                            out["promoted"] += 1
                            used += 1
        if self.max_host_bytes > 0 and self.directory:
            # 3. host-bytes cap: coldest HOST mirrors spill to disk.
            host_e = sorted(
                (e for e in entries if e.residency == HOST and e.row < 0),
                key=_heat,
            )
            for e in host_e:
                if self.host_bytes() <= self.max_host_bytes:
                    break
                if self.spill(e.name):
                    out["spilled"] += 1
        self.gc_blobs()
        return out

    def _admission_blocked(self) -> bool:
        """True while coalescer queue pressure sits past the admission
        watermark — promotions (which cost device writes) wait."""
        eng = self._eng
        c = getattr(eng, "coalescer", None)
        if c is None:
            return False
        pressure = getattr(c, "pressure", None)
        if pressure is None:
            return False
        wm = float(
            getattr(eng.config.tpu_sketch, "admission_watermark", 0.9)
        )
        try:
            return pressure() >= wm
        except Exception:  # pragma: no cover — defensive
            return False

    def start(self) -> None:
        """Arm the background thread (idempotent; started lazily when
        a budget first becomes non-zero — CONFIG SET included)."""
        if self._thread is not None:
            return
        stop = threading.Event()

        def loop():
            while not stop.wait(self.interval_s):
                try:
                    self.maintain()
                except Exception:  # pragma: no cover — keep maintaining
                    pass

        t = threading.Thread(
            target=loop, name="rtpu-residency", daemon=True
        )
        self._thread = (t, stop)
        t.start()

    def shutdown(self) -> None:
        th = self._thread
        if th is not None:
            th[1].set()
            self._thread = None

    def set_budget(self, device_rows: Optional[int] = None,
                   max_host_bytes: Optional[int] = None,
                   max_disk_bytes: Optional[int] = None,
                   promote_heat: Optional[float] = None) -> None:
        """Live CONFIG SET surface; arming a budget starts the thread."""
        if device_rows is not None:
            self.device_rows = int(device_rows)
        if max_host_bytes is not None:
            self.max_host_bytes = int(max_host_bytes)
        if max_disk_bytes is not None:
            self.max_disk_bytes = int(max_disk_bytes)
        if promote_heat is not None:
            self.promote_heat = float(promote_heat)
        if self.device_rows > 0 or self.max_host_bytes > 0:
            self.start()
