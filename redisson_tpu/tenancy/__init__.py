"""Multi-tenant sketch storage: size-class pools + name registry.

This is L3 of the build plan (SURVEY.md §7): the TPU analog of Redis's
keyspace for sketch objects.  Thousands of tenants' sketches live as rows of
stacked device arrays so a mixed batch is one vectorized kernel launch
(BASELINE.json: "multi-tenant by construction").
"""

from redisson_tpu.tenancy.registry import (
    PoolKind,
    SizeClassPool,
    TenantEntry,
    TenantRegistry,
)

__all__ = ["PoolKind", "SizeClassPool", "TenantEntry", "TenantRegistry"]
