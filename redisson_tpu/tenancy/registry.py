"""Tenant registry and size-class pools.

Role parity: the registry is the analog of Redis's keyspace + the
``{name}:config`` hash RedissonBloomFilter keeps next to each bitmap
(→ org/redisson/RedissonBloomFilter.java tryInit/readConfig, SURVEY.md
§2.2) — name-addressed objects with per-object parameters, honoring
tryInit-once semantics.

Heterogeneous tenant sizes (SURVEY.md §7 hard part #3) are handled with
**size-class pools**: a bloom filter needing m bits lands in the pool whose
per-row word count is the next power of two ≥ ceil(m/32); all tenants of a
class share one stacked ``uint32[T*W + 1]`` device array (trailing scratch
word, see ops/bitops.py).  Pools grow by doubling row capacity; freed rows
are zeroed and recycled.

Thread-safety: all registry mutations happen under one lock; kernels only
see pool state through the executor's single dispatch path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from redisson_tpu.analysis import witness as _witness
from redisson_tpu.ops.golden import HLL_M


class PoolKind:
    BLOOM = "bloom"
    BITSET = "bitset"
    HLL = "hll"
    CMS = "cms"


def _pow2ceil(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def class_words_for_bits(m: int) -> int:
    """Size class for an m-bit bitmap: pow2 words ≥ ceil(m/32), min 128.

    The 128-word minimum keeps every pool's word count a multiple of 128 so
    kernels can view state as [R, 128] lanes (the TPU-efficient gather
    shape, see ops/bitops.gather_bits).
    """
    return max(128, _pow2ceil(-(-m // 32)))


@dataclass
class PoolSpec:
    kind: str
    class_key: tuple  # (words,) for bloom/bitset, () for hll, (d, w) for cms
    row_units: int  # array elements per tenant row
    dtype: Any

    @property
    def key(self) -> tuple:
        return (self.kind, *self.class_key)


def spec_for(kind: str, class_key: tuple) -> PoolSpec:
    if kind in (PoolKind.BLOOM, PoolKind.BITSET):
        (words,) = class_key
        return PoolSpec(kind, class_key, words, np.uint32)
    if kind == PoolKind.HLL:
        return PoolSpec(kind, (), HLL_M, np.uint8)
    if kind == PoolKind.CMS:
        d, w = class_key
        # Row padded to a 128-multiple: kernels need (pool words) % 128 == 0
        # for the [R, 128] lane view; the tail cells are never probed.
        return PoolSpec(kind, class_key, -(-d * w // 128) * 128, np.uint32)
    raise ValueError(f"unknown pool kind: {kind}")


class SizeClassPool:
    """One stacked device array holding all tenants of a size class."""

    def __init__(self, spec: PoolSpec, capacity: int, factory, dispatch_lock=None):
        self.spec = spec
        # The factory (the executor) owns state layout: flat [T*W+1] on one
        # device, [S, local] row-sharded over a mesh, or [S, words/S]
        # m-sharded for giant bitmaps.  This layer only hands out row
        # numbers and never touches array internals.
        self._factory = factory
        self.capacity = factory.round_capacity(
            capacity, row_units=spec.row_units, kind=spec.kind
        )
        # Growth swaps self.state; a concurrently flushing coalesced write
        # donates the same buffer and reassigns state with the old-shaped
        # output, losing the growth (or hitting use-after-donate).  Taking
        # the executor's dispatch lock around the read-concat-swap makes
        # growth atomic w.r.t. every dispatch.
        self._dispatch_lock = dispatch_lock or threading.RLock()
        self.state = factory.make_pool_state(
            self.capacity, spec.row_units, spec.dtype, kind=spec.kind
        )
        self._free: list[int] = list(range(self.capacity - 1, -1, -1))
        self.generation = 0  # bumped on every growth (jit cache key part)
        # Optional growth callback (set by the engine's BucketPrewarmer):
        # growth changes the state shape and with it every jit key, so
        # the warm ladder must re-run against the new layout.
        self.on_grow = None
        # Bumped (under the dispatch lock) by a live change_topology,
        # which rebuilds the free list wholesale: reap sequences that
        # detached an entry BEFORE the swap must not zero/free the row
        # again afterwards (engines._reap_rows checks this epoch).
        self.topology_epoch = 0

    @property
    def row_units(self) -> int:
        return self.spec.row_units

    def alloc_row(self) -> int:
        # Both the grow and the pop sit inside the dispatch lock: alloc_row
        # is reachable without the registry lock (bitset size-class
        # migration), so two near-simultaneous allocators racing on one
        # remaining free row must serialize end-to-end.
        with self._dispatch_lock:
            if not self._free:
                self._grow()
            return self._free.pop()

    def free_row(self, row: int) -> None:
        # Caller (executor) must zero the row on device before recycling.
        self._free.append(row)

    def alloc_row_with_residue(self, residue: int, S: int) -> int:
        """Allocate a row with ``row % S == residue`` — replica placement
        needs one copy resident on each mesh shard."""
        with self._dispatch_lock:
            while True:
                for i in range(len(self._free) - 1, -1, -1):
                    if self._free[i] % S == residue:
                        return self._free.pop(i)
                self._grow()

    def _grow(self) -> None:
        old_cap = self.capacity
        new_cap = old_cap * 2
        self.state = self._factory.grow_pool_state(
            self.state, old_cap, new_cap, self.spec.row_units, self.spec.dtype,
            kind=self.spec.kind,
        )
        self.capacity = new_cap
        self.generation += 1
        self._free.extend(range(new_cap - 1, old_cap - 1, -1))
        cb = self.on_grow
        if cb is not None:
            try:
                cb(self)
            except Exception:  # pragma: no cover — warm-path best effort
                pass

    def used_rows(self) -> int:
        return self.capacity - len(self._free)


class _TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``;
    ``take(n)`` consumes or refuses atomically (caller holds the
    governor lock)."""

    __slots__ = ("tokens", "stamp")

    def __init__(self, burst: float, now: float):
        self.tokens = burst  # start full: a fresh tenant gets its burst
        self.stamp = now

    def take(self, n: int, rate: float, burst: float, now: float) -> bool:
        self.tokens = min(burst, self.tokens + (now - self.stamp) * rate)
        self.stamp = now
        # A FULL bucket admits even an oversize n (tokens go negative:
        # debt repaid by refill) — otherwise a single bulk submit larger
        # than the burst could never pass at any rate.
        if self.tokens >= n or self.tokens >= burst:
            self.tokens -= n
            return True
        return False


class TenantGovernor:
    """Per-tenant fair-load-shedding quotas (overload control plane,
    ISSUE 7): a token-bucket RATE limit plus a queued+in-flight op
    quota, enforced at the engine's submit boundary — an over-quota
    tenant is shed there (TenantThrottledError, strictly pre-dispatch)
    BEFORE its ops can build the queue wait every other tenant would
    share.  Within-quota tenants never trip this layer, which is the
    fairness guarantee: during one tenant's burst, the burst is what
    gets shed.

    Limits are live-settable (CONFIG SET tenant-rate-limit /
    tenant-max-inflight); rate/quota of 0 disables that check.  All
    state is host-side and O(active tenants)."""

    def __init__(self, *, rate_limit: float = 0.0, burst: float = 0.0,
                 max_inflight: int = 0, obs=None,
                 clock=time.monotonic):
        self._lock = _witness.named(
            threading.Lock(), "tenancy.governor"
        )
        self._clock = clock
        self._buckets: dict[str, _TokenBucket] = {}
        self._inflight: dict[str, int] = {}
        self.obs = obs
        self.throttled_ops = 0  # lifetime shed ops (INFO overload)
        self.set_limits(rate_limit=rate_limit, burst=burst,
                        max_inflight=max_inflight)

    @property
    def active(self) -> bool:
        return self.rate_limit > 0 or self.max_inflight > 0

    def set_limits(self, rate_limit: Optional[float] = None,
                   burst: Optional[float] = None,
                   max_inflight: Optional[int] = None) -> None:
        """Apply new limits; buckets AND in-flight charges reset so a
        limit change takes effect immediately (a tenant throttled under
        the old limits starts the new ones clean — generous, never
        unfair).  The in-flight reset matters for a disable/re-enable
        cycle: release() is skipped while max_inflight is 0, so charges
        left from before the disable would otherwise throttle the
        tenant forever once re-enabled (stale releases after the reset
        are harmless — release() clamps at zero)."""
        with self._lock:
            if rate_limit is not None:
                self.rate_limit = max(0.0, float(rate_limit))
            if burst is not None:
                self._burst_cfg = max(0.0, float(burst))
            if max_inflight is not None:
                self.max_inflight = max(0, int(max_inflight))
            self.burst = (
                self._burst_cfg if self._burst_cfg > 0
                else 2.0 * self.rate_limit
            )
            self._buckets.clear()
            self._inflight.clear()

    def admit(self, tenant: str, n: int) -> None:
        """Charge ``n`` ops to ``tenant``; raises TenantThrottledError
        when a quota refuses.  On success the tenant's in-flight count
        is raised — pair with release() when the ops resolve."""
        from redisson_tpu.executor.failures import TenantThrottledError

        with self._lock:
            if self.max_inflight > 0:
                cur = self._inflight.get(tenant, 0)
                # An oversize single submit is admitted when the tenant
                # has NOTHING in flight (the same carve-out the token
                # bucket and the coalescer queue bound make) — without
                # it a bulk op larger than the quota could never
                # succeed at any retry rate.
                if cur > 0 and cur + n > self.max_inflight:
                    self._note_shed(tenant, n)
                    raise TenantThrottledError(
                        tenant, "inflight",
                        f"{cur} queued+in-flight + {n} > quota "
                        f"{self.max_inflight}",
                    )
            if self.rate_limit > 0:
                now = self._clock()
                b = self._buckets.get(tenant)
                if b is None:
                    b = self._buckets[tenant] = _TokenBucket(
                        self.burst, now
                    )
                if not b.take(n, self.rate_limit, self.burst, now):
                    self._note_shed(tenant, n)
                    raise TenantThrottledError(
                        tenant, "rate",
                        f"{n} ops over the {self.rate_limit:g} ops/s "
                        f"bucket (burst {self.burst:g})",
                    )
            if self.max_inflight > 0:
                self._inflight[tenant] = self._inflight.get(tenant, 0) + n

    def peek_over_quota(self, tenant: str) -> bool:
        """Non-consuming quota probe for the RESP ingress door (ROADMAP
        overload item (b)): True when ``tenant`` would be refused right
        now — its token bucket is empty after refill, or its in-flight
        quota is full.  Reads only; no tokens are taken and no shed is
        counted here (the DOOR counts its own command-denominated shed),
        so a peek can never penalize a tenant that then doesn't submit."""
        if not self.active:
            return False
        with self._lock:
            if self.max_inflight > 0:
                if self._inflight.get(tenant, 0) >= self.max_inflight:
                    return True
            if self.rate_limit > 0:
                b = self._buckets.get(tenant)
                if b is None:  # fresh tenant: full burst available
                    return False
                now = self._clock()
                tokens = min(
                    self.burst,
                    b.tokens + (now - b.stamp) * self.rate_limit,
                )
                # Mirrors take(): a FULL bucket admits anything; below
                # full, at least one token must be available.
                return tokens < 1.0 and tokens < self.burst
        return False

    def release(self, tenant: str, n: int) -> None:
        """Return ``n`` in-flight ops (the submit's futures resolved —
        success or failure, both free the quota)."""
        if self.max_inflight <= 0:
            return
        with self._lock:
            cur = self._inflight.get(tenant, 0) - n
            if cur > 0:
                self._inflight[tenant] = cur
            else:
                self._inflight.pop(tenant, None)

    def _note_shed(self, tenant: str, n: int) -> None:
        self.throttled_ops += n
        if self.obs is not None:
            self.obs.tenant_throttled.inc((tenant,), n)
            self.obs.shed_ops.inc(("tenant",), n)

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate_limit": self.rate_limit,
                "burst": self.burst,
                "max_inflight": self.max_inflight,
                "throttled_ops": self.throttled_ops,
                "tenants_tracked": max(
                    len(self._buckets), len(self._inflight)
                ),
            }


@dataclass
class TenantEntry:
    """One named sketch object's placement + parameters (the `{name}:config`
    analog).  ``expire_at``: absolute monotonic-free wall-clock deadline
    (time.time()) after which the object no longer exists — the
    RedissonExpirable analog; None = no TTL."""

    name: str
    kind: str
    pool: SizeClassPool
    row: int
    params: dict = field(default_factory=dict)
    expire_at: Optional[float] = None
    # Read replication (SURVEY §2.4 replication row): one row per mesh
    # shard (index s holds the copy with row % S == s); None = single copy.
    replica_rows: Optional[list] = None
    # Residency ladder (ISSUE 14, storage/residency.py): "device" —
    # ``row`` is live; "host" — row is ROW_NONE (-1) and the truth is a
    # golden mirror; "disk" — row is ROW_NONE and the truth is a blob.
    residency: str = "device"


class TenantRegistry:
    def __init__(self, factory, initial_capacity: int = 8, dispatch_lock=None):
        self._factory = factory
        self._initial_capacity = initial_capacity
        self._dispatch_lock = dispatch_lock
        self._lock = _witness.named(threading.RLock(), "tenancy.registry")
        self._tenants: dict[str, TenantEntry] = {}
        self._pools: dict[tuple, SizeClassPool] = {}
        # Residency alloc gate (ISSUE 14): when set and True at create
        # time, try_create births the tenant HOST-resident (row -1, a
        # zero-seeded mirror installs on first touch) instead of
        # growing a pool past the device-rows budget — HBM holds the
        # working set, not the keyspace.
        self.alloc_gate = None
        # Load-attribution reach (ISSUE 16): wired by the serve layer
        # to the loadmap's exact per-slot key counters.  Called as
        # ``on_keyspace(name, +1/-1)`` wherever the set of live tenant
        # names changes, UNDER ``self._lock`` — must be leaf-safe.
        self.on_keyspace = None

    def _note_keyspace(self, name: str, delta: int) -> None:
        hook = self.on_keyspace
        if hook is not None:
            hook(name, delta)

    def lookup(self, name: str) -> Optional[TenantEntry]:
        with self._lock:
            return self._tenants.get(name)

    def pool_for(self, kind: str, class_key: tuple) -> SizeClassPool:
        with self._lock:
            spec = spec_for(kind, class_key)
            pool = self._pools.get(spec.key)
            if pool is None:
                pool = SizeClassPool(
                    spec,
                    self._initial_capacity,
                    self._factory,
                    dispatch_lock=self._dispatch_lock,
                )
                self._pools[spec.key] = pool
            return pool

    def try_create(self, name: str, kind: str, class_key: tuple, params: dict):
        """tryInit semantics: create if absent → (entry, True); if present
        → (existing, False) regardless of params (reference behavior:
        tryInit returns false when config already exists)."""
        with self._lock:
            entry = self._tenants.get(name)
            if entry is not None:
                if entry.kind != kind:
                    # Redis WRONGTYPE analog: a name holds one object kind.
                    raise TypeError(
                        f"object {name!r} holds a {entry.kind}, not a {kind}"
                    )
                return entry, False
            pool = self.pool_for(kind, class_key)
            gate = self.alloc_gate
            if gate is not None and gate():
                # Born cold: device budget full — no row; the engine's
                # first-touch load installs a zero-seeded host mirror.
                entry = TenantEntry(
                    name, kind, pool, -1, dict(params),
                    residency="host",
                )
            else:
                entry = TenantEntry(
                    name, kind, pool, pool.alloc_row(), dict(params)
                )
            self._tenants[name] = entry
            self._note_keyspace(name, +1)
            return entry, True

    def detach(self, name: str) -> Optional[TenantEntry]:
        """Atomically remove the name WITHOUT freeing the row — the caller
        zeroes the row on device and then frees it.  This ordering makes
        concurrent delete/expiry safe: only one caller wins the pop, and
        the row cannot be reallocated (and then wrongly zeroed) while a
        stale deleter still holds it."""
        with self._lock:
            entry = self._tenants.pop(name, None)
            if entry is not None:
                self._note_keyspace(name, -1)
            return entry

    def detach_if(self, name: str, entry: TenantEntry) -> Optional[TenantEntry]:
        """detach() guarded on entry identity: a no-op if the name was
        deleted and re-created since the caller captured ``entry`` (expiry
        reapers must never remove a fresh successor object)."""
        with self._lock:
            if self._tenants.get(name) is not entry:
                return None
            popped = self._tenants.pop(name)
            self._note_keyspace(name, -1)
            return popped

    def rename_detach_dest(self, old: str, new: str):
        """Atomic rename; the displaced destination entry (if any) is
        returned WITHOUT freeing its row, so the caller can zero it before
        reuse.  Returns (renamed, displaced_dest | None) — if ``old`` is
        gone (e.g. expired between the caller's check and this call), the
        destination is left untouched (Redis RENAME with a missing source
        errors without side effects)."""
        with self._lock:
            entry = self._tenants.pop(old, None)
            if entry is None:
                return False, None
            dest = self._tenants.pop(new, None)
            entry.name = new
            self._tenants[new] = entry
            self._note_keyspace(old, -1)
            if dest is None:  # overwrite transfers the displaced +1
                self._note_keyspace(new, +1)
            return True, dest

    def names(self, kind: Optional[str] = None) -> list[str]:
        with self._lock:
            return [
                n for n, e in self._tenants.items() if kind is None or e.kind == kind
            ]

    def pools(self) -> list[SizeClassPool]:
        with self._lock:
            return list(self._pools.values())

    def stats(self) -> dict:
        """Occupancy snapshot for the observability gauges (obs package):
        tenant counts by kind and per-pool row capacity/usage.  One lock
        hold, no device access — safe to call from a scrape handler."""
        with self._lock:
            tenants: dict[str, int] = {}
            for e in self._tenants.values():
                tenants[e.kind] = tenants.get(e.kind, 0) + 1
            pools = {
                key: {"capacity": p.capacity, "used_rows": p.used_rows()}
                for key, p in self._pools.items()
            }
        return {"tenants_by_kind": tenants, "pools": pools}

    def entries(self) -> list[TenantEntry]:
        with self._lock:
            return list(self._tenants.values())
