"""Host-side infrastructure helpers (hashing, futures, misc).

Role parity with org/redisson/misc/ (promise glue, hashing, async
semaphores) — see SURVEY.md §2.1 "Misc/infra".
"""
