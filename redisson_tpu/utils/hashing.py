"""Batched 128-bit hashing with bit-identical NumPy / JAX twins.

Role parity: org/redisson/misc/Hash.java — Redisson hashes codec-encoded
bytes to 128 bits (HighwayHash upstream, version-dependent), then derives
Kirsch–Mitzenmacher double-hash indexes ``index_i = (h1 + i*h2) mod m``
(→ org/redisson/RedissonBloomFilter.java, SURVEY.md §2.2).

TPU-first design choice: we use a MurmurHash3 **x86_128** variant because it
is built entirely from 32-bit multiplies/rotates — it runs on the TPU VPU
without 64-bit emulation, and vectorizes over a batch axis in both NumPy
(host/golden path) and jax.numpy (device path).  Deviation from canonical
Murmur3: each key's zero-padded tail bytes (up to its own whole-16-byte
block count) go through the main block mix instead of the scalar tail
path, and the true byte length is mixed into finalization.  Blocks beyond
a key's own count are MASKED out of the mix, so a key's hash never
depends on the batch it rides in (a key hashes identically alone and in
any mixed-length batch — round-3 fix: the unmasked version made
estimates/membership silently miss across differently-shaped batches).
The hash differs from reference Murmur3 vectors but keeps the same mixing
structure and uniformity — FPP parity only requires a uniform 128-bit
hash plus the same (m, k) formulas (SURVEY.md §7 hard part #4).

The NumPy and JAX implementations share one code path parameterized by the
array namespace ``xp``; tests assert bit-identical outputs.
"""

from __future__ import annotations

import numpy as np

# Murmur3 x86_128 block constants.
_C1 = np.uint32(0x239B961B)
_C2 = np.uint32(0xAB0E9789)
_C3 = np.uint32(0x38B34AE5)
_C4 = np.uint32(0xA1E38B93)
# Per-lane post-mix adds.
_N1 = np.uint32(0x561CCD1B)
_N2 = np.uint32(0x0BCAA747)
_N3 = np.uint32(0x96CD1C35)
_N4 = np.uint32(0x32AC3B17)
# fmix32 constants.
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)

_FIVE = np.uint32(5)
DEFAULT_SEED = np.uint32(0x9747B28C)


def _rotl32(x, r: int):
    r = np.uint32(r)
    return (x << r) | (x >> np.uint32(32 - int(r)))


def _fmix32(h):
    h = h ^ (h >> np.uint32(16))
    h = h * _F1
    h = h ^ (h >> np.uint32(13))
    h = h * _F2
    h = h ^ (h >> np.uint32(16))
    return h


def murmur3_x86_128(blocks, lengths, xp=np, seed=DEFAULT_SEED):
    """Batched 128-bit hash.

    Args:
      blocks: ``uint32[B, 4*nblocks]`` little-endian 32-bit lanes of the
        zero-padded key bytes (see ``encode_bytes_batch``).
      lengths: ``uint32[B]`` true byte lengths (mixed into finalization).
      xp: array namespace — ``numpy`` (golden/host) or ``jax.numpy`` (device).
      seed: uint32 seed.

    Returns:
      Tuple ``(c0, c1, c2, c3)`` of ``uint32[B]`` — the 128-bit digest as
      four 32-bit lanes.
    """
    nlanes = blocks.shape[-1]
    if nlanes % 4 != 0:
        raise ValueError(f"blocks last dim must be a multiple of 4, got {nlanes}")
    shape = blocks.shape[:-1]
    seed = np.uint32(seed)
    h1 = xp.full(shape, seed, dtype=np.uint32)
    h2 = xp.full(shape, seed, dtype=np.uint32)
    h3 = xp.full(shape, seed, dtype=np.uint32)
    h4 = xp.full(shape, seed, dtype=np.uint32)

    ln32 = lengths.astype(np.uint32)
    # Whole-16-byte blocks each key owns (min 1); blocks past a key's own
    # count must not perturb its lanes (batch-shape independence).
    nblocks_key = xp.maximum(
        np.uint32(1), (ln32 + np.uint32(15)) >> np.uint32(4)
    )
    n_blk = nlanes // 4
    for blk in range(n_blk):
        k1 = blocks[..., 4 * blk + 0]
        k2 = blocks[..., 4 * blk + 1]
        k3 = blocks[..., 4 * blk + 2]
        k4 = blocks[..., 4 * blk + 3]

        k1 = _rotl32(k1 * _C1, 15) * _C2
        n1 = h1 ^ k1
        n1 = _rotl32(n1, 19) + h2
        n1 = n1 * _FIVE + _N1

        k2 = _rotl32(k2 * _C2, 16) * _C3
        n2 = h2 ^ k2
        n2 = _rotl32(n2, 17) + h3
        n2 = n2 * _FIVE + _N2

        k3 = _rotl32(k3 * _C3, 17) * _C4
        n3 = h3 ^ k3
        n3 = _rotl32(n3, 15) + h4
        n3 = n3 * _FIVE + _N3

        k4 = _rotl32(k4 * _C4, 18) * _C1
        n4 = h4 ^ k4
        n4 = _rotl32(n4, 13) + n1  # chains through the UPDATED h1
        n4 = n4 * _FIVE + _N4

        if n_blk == 1:
            h1, h2, h3, h4 = n1, n2, n3, n4
        else:
            active = np.uint32(blk) < nblocks_key
            h1 = xp.where(active, n1, h1)
            h2 = xp.where(active, n2, h2)
            h3 = xp.where(active, n3, h3)
            h4 = xp.where(active, n4, h4)

    h1 = h1 ^ ln32
    h2 = h2 ^ ln32
    h3 = h3 ^ ln32
    h4 = h4 ^ ln32

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1
    h3 = h3 + h1
    h4 = h4 + h1

    h1 = _fmix32(h1)
    h2 = _fmix32(h2)
    h3 = _fmix32(h3)
    h4 = _fmix32(h4)

    h1 = h1 + h2 + h3 + h4
    h2 = h2 + h1
    h3 = h3 + h1
    h4 = h4 + h1
    return h1, h2, h3, h4


def hash128_np(blocks: np.ndarray, lengths: np.ndarray, seed=DEFAULT_SEED):
    """Host path: returns ``(H1, H2)`` as ``uint64[B]`` (two 64-bit halves).

    Mirrors Hash.hash128's (h1, h2) pair used for Kirsch–Mitzenmacher
    expansion (→ org/redisson/RedissonBloomFilter.java#hash).
    """
    c0, c1, c2, c3 = murmur3_x86_128(blocks, lengths, xp=np, seed=seed)
    h1 = c0.astype(np.uint64) | (c1.astype(np.uint64) << np.uint64(32))
    h2 = c2.astype(np.uint64) | (c3.astype(np.uint64) << np.uint64(32))
    return h1, h2


def km_reduce_mod(h1: np.ndarray, h2: np.ndarray, m: int):
    """Reduce 64-bit double-hash pair mod ``m`` for device-side expansion.

    The device kernel expands ``index_i = (h1m + i*h2m) mod m`` with pure
    uint32 adds (requires ``m <= 2**31`` so ``a + b < 2**32``).  The exact
    64-bit mod happens here on the host where uint64 is cheap.
    """
    if not 0 < m <= (1 << 31):
        raise ValueError(f"m must be in (0, 2**31], got {m}")
    mm = np.uint64(m)
    return (h1 % mm).astype(np.uint32), (h2 % mm).astype(np.uint32)


# --------------------------------------------------------------------------
# Batch byte encoding: python bytes -> fixed-shape uint32 lane arrays.
# --------------------------------------------------------------------------


def pad_block_lanes(nbytes: int) -> int:
    """Number of uint32 lanes after padding to a whole 16-byte block."""
    nblocks = max(1, -(-nbytes // 16))
    return nblocks * 4


def encode_bytes_batch(items) -> tuple[np.ndarray, np.ndarray]:
    """Encode a list of ``bytes`` into ``(uint32[B, L4], uint32[B])``.

    Zero-pads every key to the batch-wide max whole-16-byte block count.
    """
    n = len(items)
    if n == 0:
        return np.zeros((0, 4), np.uint32), np.zeros((0,), np.uint32)
    lengths = np.fromiter((len(b) for b in items), dtype=np.uint32, count=n)
    lanes = pad_block_lanes(int(lengths.max()))
    buf = np.zeros((n, lanes * 4), dtype=np.uint8)
    for i, b in enumerate(items):
        if b:
            buf[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return buf.view("<u4"), lengths


def encode_uint64_batch(arr) -> tuple[np.ndarray, np.ndarray]:
    """Fast path for integer keys: ``uint64[B] -> (uint32[B, 4], 8)``.

    Matches LongCodec's 8-byte little-endian encoding zero-padded into one
    16-byte block — bit-identical to routing the same keys through
    ``encode_bytes_batch``.
    """
    a = np.ascontiguousarray(arr, dtype="<u8")
    n = a.shape[0]
    blocks = np.zeros((n, 4), dtype=np.uint32)
    blocks[:, :2] = a.view("<u4").reshape(n, 2)
    return blocks, np.full((n,), 8, dtype=np.uint32)
