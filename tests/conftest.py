"""Test harness setup.

Mirrors the reference's "many redis-servers on localhost" trick for testing
distribution without a real cluster (SURVEY.md §4): we force 8 virtual CPU
devices so every Mesh/shard_map test runs the real multi-chip code path on
one host.  Must run before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_comp_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
