"""Test harness setup.

Mirrors the reference's "many redis-servers on localhost" trick for testing
distribution without a real cluster (SURVEY.md §4): we force 8 virtual CPU
devices so every Mesh/shard_map test runs the real multi-chip code path on
one host.  Must run before jax is imported anywhere.
"""

import os
import sys

# The session env pins JAX_PLATFORMS=axon (the tunneled TPU) and a
# sitecustomize imports jax at interpreter startup, so env vars set here are
# too late — override through jax.config instead (backends initialize
# lazily, so this still takes effect).  Set RTPU_TEST_PLATFORM to run the
# suite against another backend explicitly.
_platform = os.environ.get("RTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (after env setup on purpose)

jax.config.update("jax_platforms", _platform)
# Persistent compile cache: must also go through jax.config (the env vars
# were read at jax import time, which already happened via sitecustomize).
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _lock_witness_guard():
    """Lock-order witness (ISSUE 8): under RTPU_LOCK_WITNESS=1 every
    test fails if it produced a lock-order cycle or a blocking call
    under a witness-named lock — the report carries the offending
    stack pairs.  Free when the witness is off (active() is False
    until the first lock is wrapped)."""
    yield
    from redisson_tpu.analysis import witness

    if witness.active():
        vs = witness.take_violations()
        if vs:
            pytest.fail(
                "lock-order witness found %d violation(s):\n\n%s"
                % (len(vs), "\n\n".join(v.format() for v in vs)),
                pytrace=False,
            )
