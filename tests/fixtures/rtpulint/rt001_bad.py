# rtpulint: role=dispatch
"""RT001 known-bad corpus: blocking work while holding a lock.

Each marked line reproduces a defect class a review round actually
caught (the in-place retry sleep that stalled every queue, PR 3; the
mirror-seed drain under the mirror lock, PR 3 round 2)."""

import select
import threading
import time

_MODULE_LOCK = threading.Lock()


class Dispatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def retry_sleeps_in_place(self):
        with self._lock:
            time.sleep(0.05)  # rtpulint-expect: RT001

    def fetch_result_under_lock(self, fut):
        with self._lock:
            return fut.result()  # rtpulint-expect: RT001

    def send_between_acquire_release(self, sock, data):
        self._lock.acquire()
        sock.sendall(data)  # rtpulint-expect: RT001
        self._lock.release()

    def select_under_module_lock(self, socks):
        with _MODULE_LOCK:
            return select.select(socks, (), (), 0.1)  # rtpulint-expect: RT001

    def ship_under_lock(self, jax, arr):
        with self._lock:
            return jax.device_put(arr)  # rtpulint-expect: RT001

    def seed_mirror_under_lock(self, coalescer, executor, pool, row):
        with self._lock:
            coalescer.drain()  # rtpulint-expect: RT001
            return executor.read_row(pool, row)  # rtpulint-expect: RT001
