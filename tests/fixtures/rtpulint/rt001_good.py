# rtpulint: role=dispatch
"""RT001 known-good corpus: the idioms the codebase actually uses."""

import threading
import time


class Dispatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def condition_wait_is_fine(self):
        # wait() RELEASES the lock while blocked: the correct idiom.
        with self._cv:
            self._cv.wait(timeout=0.1)

    def stage_under_lock_block_outside(self, fut):
        with self._lock:
            staged = 1
        fut.result()
        return staged

    def closure_defined_under_lock(self, fut):
        # DEFINING deferred work under a lock is not executing it there.
        with self._lock:
            def later():
                return fut.result()
        return later

    def release_before_blocking(self, sock, data):
        self._lock.acquire()
        self._lock.release()
        sock.sendall(data)

    def suppressed_with_reason(self):
        with self._lock:
            # rtpulint: disable=RT001 fixture: a documented by-design critical-section block
            time.sleep(0.0)
