# rtpulint: role=serve
"""RT002 known-bad corpus: settimeout() on a shared-state socket (the
PR 7 third-round finding: a cross-thread pub/sub push shrank the
subscriber reader's idle timeout and killed a healthy connection)."""


class ConnCtx:
    def __init__(self, sock):
        self.sock = sock

    def tighten_for_send(self, tick):
        self.sock.settimeout(tick)  # rtpulint-expect: RT002


def push_cross_thread(ctx, tick):
    ctx.sock.settimeout(tick)  # rtpulint-expect: RT002
