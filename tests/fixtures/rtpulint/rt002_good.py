# rtpulint: role=serve
"""RT002 known-good corpus: a socket sets its OWN timeout where it is
still a local (single owner), and cross-thread waits use select()."""

import select
import socket


def serve_conn(conn, idle_s):
    # The reader thread configuring the connection it owns: fine.
    conn.settimeout(idle_s)


def dial(host, port):
    sock = socket.create_connection((host, port))
    sock.settimeout(1.0)
    return sock


def bounded_send_wait(ctx, tick):
    # Cross-thread wait WITHOUT touching the shared timeout.
    return select.select((), (ctx.sock,), (), tick)
