"""RT003 known-bad corpus: function-level chaos imports (per-call
sys.modules lookups on the DISABLED path — the PR 3 round-2 finding in
prewarm/durability) and unguarded fire() (breaks the zero-overhead-
when-disabled contract)."""

from redisson_tpu import chaos as _chaos


def dispatch(point):
    _chaos.fire(point)  # rtpulint-expect: RT003


def lazy_import():
    from redisson_tpu import chaos  # rtpulint-expect: RT003

    return chaos.active()


def lazy_import_module():
    import redisson_tpu.chaos  # rtpulint-expect: RT003

    return redisson_tpu.chaos.ENABLED
