"""RT003 known-good corpus: module-top import, both guard shapes, and
unguarded control-plane calls (management needs no guard)."""

from redisson_tpu import chaos as _chaos


def dispatch(point):
    if _chaos.ENABLED:
        _chaos.fire(point)


def dispatch_early_return(point):
    if not _chaos.ENABLED:
        return
    _chaos.fire(point)


def dispatch_compound_guard(point, extra):
    if _chaos.ENABLED and extra:
        _chaos.fire(point)


def control_plane():
    _chaos.clear()
    return _chaos.active()
