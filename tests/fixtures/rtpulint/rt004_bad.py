# rtpulint: role=serve
"""RT004 known-bad corpus: served config keys missing their validation
arm and/or INFO mention (the PR 7 class: tenant-burst-ops was settable
and applied but invisible in INFO overload)."""


class MiniServer:
    _CONFIG_KEYS = {
        "shiny-knob": "0",  # rtpulint-expect: RT004
        "half-knob": "1",  # rtpulint-expect: RT004
        "good-knob": "2",
    }

    def _validate_mini_config(self, key, raw):
        if key in ("good-knob", "half-knob") and int(raw) < 0:
            raise ValueError(">= 0 required")

    def _cmd_INFO(self, args):
        return "good_knob:2"
