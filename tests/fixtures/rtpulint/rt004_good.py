# rtpulint: role=serve
"""RT004 known-good corpus: every served key has a validation arm and
an INFO line; a deliberate compat stub rides a reasoned suppression;
a prefix family ("window-") validates via startswith."""


class MiniServer:
    _CONFIG_KEYS = {
        "flush-window-us": "200",
        "window-min-us": "100",
        "compat-stub": "0",  # rtpulint: disable=RT004 fixture compat stub, no live semantics
    }

    _TUNABLE_KEYS = frozenset(("merge-cap",))

    def _config_table_init(self):
        table = dict(self._CONFIG_KEYS)
        table["merge-cap"] = "0"
        return table

    def _validate_mini_config(self, key, raw):
        if key == "flush-window-us" and int(raw) <= 0:
            raise ValueError("positive required")
        if key.startswith("window-") and int(raw) < 0:
            raise ValueError(">= 0 required")

    def _cmd_INFO(self, args):
        window = 200
        cap = 0
        return (
            f"flush_window_us:{window}\r\n"
            f"window_min_us:{100}\r\n"
            f"merge_cap:{cap}"
        )
