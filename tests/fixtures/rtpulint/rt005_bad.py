"""RT005 known-bad corpus: dynamically-composed metric label values
and a Family built outside the registry helpers (both defeat the
per-family bounded-cardinality cap)."""


class Recorder:
    def __init__(self, fam):
        self.fam = fam

    def record(self, tenant, op):
        self.fam.inc((f"tenant:{tenant}", op))  # rtpulint-expect: RT005
        self.fam.inc(("op-" + op,))  # rtpulint-expect: RT005
        self.fam.observe(("{}:{}".format(tenant, op),), 0.01)  # rtpulint-expect: RT005


def rogue_family():
    from redisson_tpu.obs.registry import Family

    return Family("rogue_total", "", "counter")  # rtpulint-expect: RT005
