"""RT005 known-good corpus: raw values ride their own label
dimensions through the registry helpers (the cap collapses overflow
into one sentinel child)."""


class Recorder:
    def __init__(self, registry):
        self.ops = registry.counter(
            "rtpu_fixture_ops", "per-tenant ops", labelnames=("tenant", "op")
        )
        self.lat = registry.histogram(
            "rtpu_fixture_latency", "dispatch latency", labelnames=("op",)
        )

    def record(self, tenant, op, seconds):
        self.ops.inc((tenant, op))
        self.lat.observe((op,), seconds)
