"""RT006 known-bad corpus: a module-level dict growing under
name keys with no prune path (the PR 4/5 class: _epochs and the
_MapCacheHub gens both leaked one entry per name ever seen until the
rising-floor prune was retrofitted)."""

_EPOCHS: dict = {}  # rtpulint-expect: RT006

_WATCHERS = {}  # rtpulint-expect: RT006


def note_write(name):
    _EPOCHS[name] = _EPOCHS.get(name, 0) + 1


def watch(name, fn):
    _WATCHERS.setdefault(name, []).append(fn)
