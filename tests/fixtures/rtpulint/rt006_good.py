"""RT006 known-good corpus: the rising-floor idiom, an explicit
delete path, and constant-keyed tables (which cannot leak)."""

_EPOCHS: dict = {}
_FLOOR = 0

_SESSIONS = {}

_BY_CODE = {0: "zero", 1: "one"}  # constant keys: bounded by source


def note_write(name):
    _EPOCHS[name] = _EPOCHS.get(name, _FLOOR) + 1
    if len(_EPOCHS) > 1024:
        _prune_epochs()


def _prune_epochs():
    # Rising floor: fold dead names into the floor; pruned names can
    # neither serve nor install stale state.
    global _FLOOR
    _FLOOR = max(_EPOCHS.values(), default=_FLOOR)
    _EPOCHS.clear()


def open_session(sid, conn):
    _SESSIONS[sid] = conn


def close_session(sid):
    _SESSIONS.pop(sid, None)
