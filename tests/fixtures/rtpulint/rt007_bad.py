# rtpulint: role=engine
"""RT007 known-bad corpus: a deadline accepted but dropped mid-path.

The PR 7 bug class: the caller attached a budget, some layer between
it and the device forgot to thread it through, and the op waited out
the 120 s fetch timeout behind a deadline everyone thought was live."""


class Engine:
    def __init__(self, coalescer):
        self.coalescer = coalescer

    def submit_drops_budget(self, key, arrays, nops, deadline):
        return self.coalescer.submit(key, None, arrays, nops)  # rtpulint-expect: RT007

    def wrapper_drops_budget(self, fut, deadline):
        return HintedFuture(fut, self.coalescer)  # rtpulint-expect: RT007

    def unbounded_wait(self, fut, deadline):
        return fut.result()  # rtpulint-expect: RT007

    def unbounded_cond_wait(self, cv, deadline):
        with cv:
            cv.wait()  # rtpulint-expect: RT007


class HintedFuture:
    def __init__(self, fut, coalescer, deadline=None):
        self._fut = fut
