# rtpulint: role=engine
"""RT007 known-good corpus: the budget rides every hop."""


class HintedFuture:
    def __init__(self, fut, coalescer, deadline=None):
        self._fut = fut
        self._deadline = deadline


class Engine:
    def __init__(self, coalescer):
        self.coalescer = coalescer

    def submit_threads_deadline(self, key, arrays, nops, deadline):
        fut = self.coalescer.submit(
            key, None, arrays, nops, deadline=deadline
        )
        return HintedFuture(fut, self.coalescer, deadline=deadline)

    def positional_reference_counts(self, key, arrays, nops, deadline):
        # The budget is visibly threaded even without the kwarg form.
        return self.coalescer.submit(key, arrays, nops, deadline)

    def bounded_wait(self, fut, deadline, now):
        return fut.result(timeout=deadline - now)

    def no_deadline_param_is_out_of_scope(self, fut):
        # Without a deadline parameter there is no budget to drop.
        return fut.result()
