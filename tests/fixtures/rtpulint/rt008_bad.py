# rtpulint: role=engine
"""RT008 known-bad corpus: epoch bumps not paired entry+exit.

The near-cache correctness argument (cache/nearcache.py module doc)
needs BOTH bumps: entry retires stale serving the moment the write is
in flight, exit retires installs whose reads were captured inside the
entry->submit window.  One bare bump next to a submit re-opens the
window; a discarded guard never bumps at all."""


class Engine:
    def __init__(self, nearcache, coalescer):
        self.nearcache = nearcache
        self.coalescer = coalescer

    def _nc_mutate(self, name):
        return object()

    def add_bumps_once(self, name, arrays):
        self.nearcache.note_write(name)  # rtpulint-expect: RT008
        return self.coalescer.submit(("add", name), None, arrays, 1)

    def clear_discards_guard(self, name, arrays):
        self._nc_mutate(name)  # rtpulint-expect: RT008
        return self.coalescer.submit(("clear", name), None, arrays, 1)
