# rtpulint: role=engine
"""RT008 known-good corpus: the entry+exit discipline, in its three
legitimate shapes."""


class Engine:
    def __init__(self, nearcache, coalescer):
        self.nearcache = nearcache
        self.coalescer = coalescer

    def _nc_mutate(self, name):
        return object()

    def add_under_guard(self, name, arrays):
        # The canonical form: the guard bumps on __enter__ AND __exit__.
        with self._nc_mutate(name):
            return self.coalescer.submit(("add", name), None, arrays, 1)

    def manual_pairing(self, name, arrays):
        self.nearcache.note_write(name)
        fut = self.coalescer.submit(("add", name), None, arrays, 1)
        self.nearcache.note_write(name)
        return fut

    def host_only_drop(self, name):
        # No device submit: a single structural bump is the whole story
        # (drop_object's shape — nothing rides the coalescer).
        self.nearcache.note_structural(name)

    def read_path_no_bump(self, name, arrays):
        # Reads never bump; nothing to pair.
        return self.coalescer.submit(("read", name), None, arrays, 1)
