# rtpulint: role=dispatch
"""RT009 known-bad corpus: futures stranded on some path.

The PR 7 class: a future someone is (or will be) waiting on is created
but an exit path — including an except arm — forgets it, and the
waiter blocks until the fetch timeout."""

from concurrent.futures import Future


class Dispatcher:
    def __init__(self):
        self.queue = []

    def created_and_dropped(self, op):
        fut = Future()  # rtpulint-expect: RT009
        if op is None:
            return None
        return None

    def swallowing_except_arm(self, results):
        fut = Future()
        self.queue.append(fut)
        try:
            fut.set_result(results.pop())
        except Exception:  # rtpulint-expect: RT009
            pass
        return fut
