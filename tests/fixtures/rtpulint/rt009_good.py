# rtpulint: role=dispatch
"""RT009 known-good corpus: every created future is resolved,
returned, or handed off — exception arms included."""

from concurrent.futures import Future


class Dispatcher:
    def __init__(self):
        self.queue = []

    def returned_to_caller(self, op):
        fut = Future()
        self.queue.append((op, fut))
        return fut

    def resolved_locally(self, value):
        fut = Future()
        fut.set_result(value)
        return fut

    def except_arm_resolves(self, results):
        fut = Future()
        try:
            fut.set_result(results.pop())
        except Exception as e:
            fut.set_exception(e)
        return fut

    def except_arm_reraises(self, results):
        fut = Future()
        self.queue.append(fut)
        try:
            fut.set_result(results.pop())
        except Exception:
            raise
        return fut

    def handed_off_in_tuple(self, op):
        # Escape through a container argument (the coalescer's
        # seg.futures.append((fut, start, n, ...)) shape).
        fut = Future()
        self.queue.append((fut, 0, 1, None))
