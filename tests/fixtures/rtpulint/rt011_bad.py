# rtpulint: role=dispatch
"""RT011 known-bad corpus: spans begun and stranded on some path.

The ISSUE 13 class: an OpSpan / trace span someone begins is dropped on
an exit path (or its end lives in a try whose except swallows), so the
launch records no phases and the trace silently loses the hop."""


class Recorder:
    def __init__(self, obs, tracer):
        self.obs = obs
        self.tracer = tracer

    def begun_and_dropped(self, op):
        span = self.obs.spans.start(op)  # rtpulint-expect: RT011
        if op is None:
            return None
        return None

    def trace_begun_and_dropped(self, name):
        span = self.tracer.maybe_start(name)  # rtpulint-expect: RT011
        if span is None:
            return False
        return True

    def forced_span_dropped(self, tid):
        span = self.tracer.start("hop", tid)  # rtpulint-expect: RT011
        self.counter = (self.counter or 0) + 1
        return self.counter

    def swallowing_except_arm(self, op, work):
        span = self.obs.spans.start(op)
        try:
            work()
            span.finish()
        except Exception:  # rtpulint-expect: RT011
            pass
        return True
