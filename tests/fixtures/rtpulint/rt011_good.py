# rtpulint: role=dispatch
"""RT011 known-good corpus: every begun span is finished/ended/
abandoned, returned, or handed off — exception arms included."""


class Recorder:
    def __init__(self, obs, tracer):
        self.obs = obs
        self.tracer = tracer
        self.segments = []

    def finished_locally(self, op, work):
        span = self.obs.spans.start(op)
        try:
            work()
            span.finish()
        except Exception:
            span.finish(error=True)
            raise
        return True

    def ended_trace_span(self, name):
        span = self.tracer.maybe_start(name)
        if span is None:
            return None
        span.annotate("k", 1)
        span.end()
        return span.trace_id

    def abandoned_on_merge(self, op):
        span = self.obs.spans.start(op)
        span.abandon()
        return None

    def escaped_by_store(self, op, seg):
        # The coalescer shape: the segment owns the span's lifecycle.
        span = self.obs.spans.start(op)
        seg.span = span
        return seg

    def escaped_by_return(self, name):
        span = self.tracer.start_child(self.root, name)
        return span

    def handed_off_in_call(self, op):
        span = self.obs.spans.start(op)
        self.segments.append(span)
        return True

    def plain_thread_start_is_not_a_span(self, thread):
        # `.start()` on things that are not span sources must not fire.
        worker = thread.start()
        return worker
