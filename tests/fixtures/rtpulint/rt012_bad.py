# rtpulint: role=serve
"""RT012 known-bad corpus: one-shot connection licenses read on a
dispatch path without being burned (the PR 12/13 review class: ASKING
leaking past PING, the trace prelude surviving an errored dispatch)."""


def serve_importing_slot(door, name, cmd, ctx):
    # Reads the license to decide serving, never burns it: the NEXT
    # command on this connection inherits it.
    if ctx.asking and door.is_importing(cmd):  # rtpulint-expect: RT012
        return door.serve(name, cmd)
    return door.redirect(name, cmd)


def cache_hit_path(server, rc, ctx, name, cmd):
    # The cache-hit shape: a served-from-cache command is still a
    # dispatch — skipping the burn leaks the license past the hit.
    hit = rc.get((name, tuple(cmd)))
    if hit is not None and getattr(ctx, "asking", False):  # rtpulint-expect: RT012
        return hit
    return server.dispatch(name, cmd, ctx)


def fused_run_path(server, batch, ctxs):
    out = []
    for cmd, ctx in zip(batch, ctxs):
        # Fused runs are dispatch paths too: serving under the flag
        # without consuming it re-opens the leak for the run's tail.
        if ctx.trace_next is not None:  # rtpulint-expect: RT012
            out.append(server.traced_dispatch(cmd, ctx))
        else:
            out.append(server.dispatch(cmd, ctx))
    return out
