# rtpulint: role=serve
"""RT012 known-good corpus: every license read is paired with a burn
(falsy store or the shared burner), and granting sites are exempt."""


def consume_one_shot_licenses(ctx, name):
    # The shared burner itself: reads paired with falsy stores.
    if getattr(ctx, "asking", False):
        ctx.asking = False
    if getattr(ctx, "trace_next", None) is not None:
        ctx.trace_next = None


def route(door, name, cmd, ctx):
    # Read + burn in the same dispatch path (the door's shape).
    asking = getattr(ctx, "asking", False)
    ctx.asking = False  # one-shot: consumed by this keyed command
    if asking and door.is_importing(cmd):
        return door.serve(name, cmd)
    return door.redirect(name, cmd)


def safe_dispatch(server, cmd, ctx, name):
    # Reads gate the traced path; the shared burner closes the loop.
    if ctx.trace_next is not None:
        reply = server.traced_dispatch(cmd, ctx)
    else:
        reply = server.dispatch(cmd, ctx)
    consume_one_shot_licenses(ctx, name)
    return reply


def cmd_asking(ctx):
    # The granting site: a truthy store is the license's birth, not a
    # leak.
    ctx.asking = True
    return b"+OK\r\n"
