# rtpulint: role=serve
"""RT013 known-bad corpus: pooled sockets kept after an except-OSError
arm (the PR 12 review class: a timed-out request leaves unread reply
bytes in flight — a reused socket returns them as a LATER command's
replies)."""

from redisson_tpu.serve.wireutil import exchange


class PooledConn:
    def __init__(self, sock):
        self._sock = sock

    def request_swallowing(self, cmds):
        try:
            return exchange(self._sock, cmds)
        except OSError:  # rtpulint-expect: RT013
            return None  # socket silently back in the pool, desynced


class ClientPool:
    def __init__(self):
        self._conns = {}

    def roundtrip(self, addr, payload):
        conn = self._conns[addr]
        try:
            conn.sendall(payload)
            return conn.recv(4096)
        except (OSError, TimeoutError):  # rtpulint-expect: RT013
            return b""  # timeout swallowed, connection still pooled
