# rtpulint: role=serve
"""RT013 known-good corpus: every except-OSError arm around wire I/O
drops the socket (close/pop/*drop* helper), re-raises, or flags the
connection doomed for the teardown path; EAGAIN/EINTR retry arms and
non-wire cleanup arms are out of scope."""

from redisson_tpu.serve.wireutil import exchange


class PooledConn:
    def __init__(self, sock):
        self._sock = sock

    def request(self, cmds):
        # Re-raise: the caller's drop discipline applies (the shipped
        # _NodeConn/_request shape).
        try:
            return exchange(self._sock, cmds)
        except OSError:
            self._sock.close()
            raise

    def close(self):
        # Non-wire cleanup arm: close() carries no reply stream.
        try:
            self._sock.close()
        except OSError:
            pass


class ClientPool:
    def __init__(self):
        self._conns = {}

    def _drop_conn(self, addr):
        conn = self._conns.pop(addr, None)
        if conn is not None:
            conn.close()

    def roundtrip(self, addr, payload):
        conn = self._conns[addr]
        try:
            conn.sendall(payload)
            return conn.recv(4096)
        except OSError:
            self._drop_conn(addr)  # desynced: out of the pool
            raise


def read_ready(rconn):
    # The reactor idiom: EAGAIN/EINTR retry arms are clean, and a real
    # OSError sets the doom flag the teardown path drives.
    eof = False
    try:
        data = rconn.sock.recv(1 << 16)
        if not data:
            eof = True
    except (BlockingIOError, InterruptedError):
        pass
    except OSError:
        eof = True
    return eof
