# rtpulint: role=host
"""RT014 known-bad corpus: tmp-file persistence writes that rename
before fsync, or let the final path escape before the rename."""

import os


def publish_without_fsync(directory, payload):
    path = os.path.join(directory, "blob.bin")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)  # rtpulint-expect: RT014
    return path


class BlobIndex:
    def __init__(self):
        self.by_name = {}

    def publish_escaping_early(self, directory, name, payload):
        final = os.path.join(directory, name)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        # The reference escapes BEFORE the rename: a reader chasing the
        # index finds a name that does not durably exist yet.
        self.by_name[name] = final  # rtpulint-expect: RT014
        notify_watchers(final)  # rtpulint-expect: RT014
        os.replace(tmp, final)


def notify_watchers(path):
    pass
