# rtpulint: role=host
"""RT014 known-good corpus: fsync-then-rename, final path escapes only
AFTER the durable publish (the residency blob / snapshot discipline)."""

import os


def publish(directory, payload):
    path = os.path.join(directory, "blob.bin")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path  # escape AFTER the rename: the name is durable


class BlobIndex:
    def __init__(self):
        self.by_name = {}

    def publish_then_index(self, directory, name, payload):
        final = os.path.join(directory, name)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        self.by_name[name] = final  # indexed only once durable


def composed_destination(directory, seq, payload):
    # The residency _write_blob shape: the final path is composed
    # inline at the rename — it never existed as a variable to escape.
    fname = f"obj-{seq}.rts"
    tmp = os.path.join(directory, fname + ".tmp")
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, fname))
    return fname
