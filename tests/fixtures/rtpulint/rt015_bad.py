"""RT015 known-bad corpus: flight-recorder emits whose kind is
dynamic, missing, or not registered in the obs/events.py KINDS
catalog."""


class Agent:
    def __init__(self, obs):
        self.obs = obs

    def _events(self):
        return getattr(self.obs, "events", None)

    def tick(self, kind, peer):
        events = self._events()
        if events is None:
            return
        # Dynamic kind built from a variable: invisible to the catalog
        # audit, unbounded rtpu_events_emitted cardinality.
        events.emit("failover." + kind, peer=peer)  # rtpulint-expect: RT015
        # f-string kind: same failure, fancier syntax.
        events.emit(f"failover.{kind}", peer=peer)  # rtpulint-expect: RT015
        # Literal, but never registered in KINDS: raises ValueError at
        # runtime — on a path that only runs during an outage.
        events.emit("failover.exploded", peer=peer)  # rtpulint-expect: RT015

    def audit(self):
        # Accessor-call receiver form; kind passed as a keyword but
        # still dynamic (str() call).
        self._events().emit(kind=str("x"), a=1)  # rtpulint-expect: RT015

    def note(self, obs):
        # Attribute-chain receiver with no kind argument at all.
        obs.events.emit(severity="warn")  # rtpulint-expect: RT015
