"""RT015 known-good corpus: every flight-recorder emit passes a plain
string literal registered in the obs/events.py KINDS catalog — one
literal per branch, the residency.py discipline."""


class Agent:
    def __init__(self, obs):
        self.obs = obs

    def _events(self):
        return getattr(self.obs, "events", None)

    def tick(self, peer, timeout_s):
        events = self._events()
        if events is None:
            return
        events.emit("failover.detected", severity="warn",
                    peer=peer, timeout_s=timeout_s)

    def transition(self, kind, name):
        # A dynamic category resolves to one literal per branch
        # instead of string-building the kind.
        events = getattr(self.obs, "events", None)
        if events is None:
            return
        if kind == "promote":
            events.emit("residency.promote", object=name)
        elif kind == "demote":
            events.emit("residency.demote", object=name)

    def audit(self):
        self._events().emit("doctor.finding", severity="error",
                            kind="dead-primary", subject="n2")

    def relay(self, bus, payload):
        # Not a flight-recorder receiver: an unrelated emit() API
        # (message bus) must not trip the rule.
        bus.emit(payload["topic"], payload)
