"""Worker process for the two-process multi-host test (docs/MULTIHOST.md).

Each worker is one *controller* in a standard JAX multi-controller SPMD
deployment: it joins the distributed runtime through the engine's own
``coordinator_address`` config path (objects/engines.py), then drives the
IDENTICAL op stream as its peer — the lockstep discipline every
multi-controller JAX program follows.  The device mesh spans both
processes (4 virtual CPU devices each → 8 global shards), so every
dispatch here exercises the real cross-process path: sharded pool state,
partition-by-owner dispatch, and replicate-on-fetch results
(executor/tpu_executor.py ``ensure_addressable``) whose gathers XLA
lowers to inter-process (DCN-role) collectives.

Run: ``python tests/multihost_worker.py <process_id> <port>``.
Prints one ``MH-OK <checksum-fields>`` line on success; the parent test
asserts both workers exit 0 with identical checksums.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Force exactly 4 local devices, replacing any inherited count (the pytest
# parent pins 8 for the single-process mesh suite).
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=4")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    pid = int(sys.argv[1])
    port = int(sys.argv[2])

    import redisson_tpu
    from redisson_tpu import Config
    from redisson_tpu.codecs import LongCodec

    cfg = (
        Config()
        .set_codec(LongCodec())
        .use_tpu_sketch(
            num_shards=8,
            coalesce=False,  # lockstep SPMD: dispatch order must be the
            # program order on every controller; the timing-driven
            # coalescer is a single-controller feature
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=2,
            process_id=pid,
        )
    )
    client = redisson_tpu.create(cfg)
    assert len(jax.devices()) == 8, jax.devices()
    assert jax.process_count() == 2

    # Bloom: cross-process sharded rows, device-side hashing.
    bf = client.get_bloom_filter("mh-bf")
    bf.try_init(50_000, 0.01)
    added = bf.add_all(np.arange(1000, dtype=np.uint64))
    got = bf.contains_each(np.arange(2000, dtype=np.uint64))
    assert bool(np.all(got[:1000])), "loaded keys must hit"
    fpp = float(np.mean(got[1000:]))
    assert fpp < 0.05, fpp
    count_est = bf.count()

    # HLL: scatter-max registers + Ertl estimate across shards.
    h = client.get_hyper_log_log("mh-hll")
    h.add_all(np.arange(20_000, dtype=np.uint64))
    est = h.count()
    assert abs(est - 20_000) / 20_000 < 0.05, est

    # BitSet: single-bit ops + cardinality reduce over the mesh.
    bs = client.get_bit_set("mh-bs")
    bs.set_many(np.arange(0, 4096, 7, dtype=np.uint32))
    card = bs.cardinality()
    assert card == len(range(0, 4096, 7)), card

    client.shutdown()
    print(f"MH-OK {added} {count_est} {est} {card}", flush=True)


if __name__ == "__main__":
    main()
