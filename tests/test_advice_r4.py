"""Regression tests for round-3 advisor findings, fixed in round 4:
snapshot lock-order deadlock, same-topology restore BUSYKEY, data-only
dump format, sweeper singleton, Redis-style zset score formatting."""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config


def _tpu_client():
    cfg = Config()
    cfg.use_tpu_sketch(min_bucket=64)
    return redisson_tpu.create(cfg)


def test_snapshot_vs_create_no_deadlock(tmp_path):
    """ADVICE r3 high: snapshot() took dispatch→registry while try_create
    takes registry→dispatch — a periodic snapshot racing object creation
    deadlocked both.  Hammer the two paths concurrently."""
    c = _tpu_client()
    try:
        c.get_bloom_filter("dl-seed").try_init(100, 0.01)
        stop = threading.Event()
        errors = []

        def snap_side():
            i = 0
            while not stop.is_set() and i < 60:
                try:
                    c._engine.snapshot(str(tmp_path / "snap"))
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return
                i += 1

        def create_side():
            i = 0
            while not stop.is_set() and i < 300:
                try:
                    c.get_bloom_filter(f"dl-bf-{i}").try_init(100, 0.01)
                except Exception as e:  # pragma: no cover
                    errors.append(e)
                    return
                i += 1

        t1 = threading.Thread(target=snap_side, daemon=True)
        t2 = threading.Thread(target=create_side, daemon=True)
        t1.start(); t2.start()
        t1.join(timeout=30); t2.join(timeout=30)
        alive = t1.is_alive() or t2.is_alive()
        stop.set()
        assert not errors, errors
        assert not alive, "snapshot vs create deadlocked"
    finally:
        c.shutdown()


def test_same_topology_restore_refuses_live_keyspace(tmp_path):
    """ADVICE r3 medium: the verbatim (same-topology) restore path reset
    pool free-lists under live tenants — silent row aliasing.  It must
    refuse with BUSYKEY, atomically, like the reshard path does."""
    c = _tpu_client()
    try:
        bf = c.get_bloom_filter("snap-a")
        bf.try_init(1000, 0.01)
        bf.add("x")
        c._engine.snapshot(str(tmp_path))
    finally:
        c.shutdown()

    c2 = _tpu_client()
    try:
        c2.get_bloom_filter("live-b").try_init(1000, 0.01)
        with pytest.raises(ValueError, match="BUSYKEY"):
            c2._engine.restore_snapshot(str(tmp_path))
        # Atomic refusal: the live object must be untouched.
        assert c2._engine._live_lookup("live-b") is not None
    finally:
        c2.shutdown()

    # Empty keyspace: restore works and state round-trips.
    c3 = _tpu_client()
    try:
        assert c3._engine.restore_snapshot(str(tmp_path)) is True
        assert c3.get_bloom_filter("snap-a").contains("x")
    finally:
        c3.shutdown()


def test_dump_format_is_data_only():
    """ADVICE r3 low: dump blobs must not be pickle (arbitrary code
    execution across trust boundaries)."""
    import pickle

    c = _tpu_client()
    try:
        bf = c.get_bloom_filter("fmt")
        bf.try_init(500, 0.01)
        bf.add("payload")
        blob = bf.dump()
        assert blob.startswith(b"RTPU")
        with pytest.raises(Exception):
            pickle.loads(blob)  # not a pickle stream
        with pytest.raises(ValueError, match="magic"):
            c._engine.restore("fmt2", b"\x80\x04garbage")
        c._engine.restore("fmt-copy", blob)
        assert c.get_bloom_filter("fmt-copy").contains("payload")
    finally:
        c.shutdown()


def test_sweeper_started_exactly_once():
    """ADVICE r3 low: concurrent first-TTL setters must not each start a
    sweeper thread (the orphan would outlive _stop_sweeper)."""
    c = _tpu_client()
    try:
        for i in range(8):
            c.get_bloom_filter(f"ttl-{i}").try_init(100, 0.01)
        before = sum(
            1 for t in threading.enumerate() if t.name == "rtpu-sketch-sweeper"
        )
        barrier = threading.Barrier(8)

        def arm(i):
            barrier.wait()
            c._engine.expire(f"ttl-{i}", 30.0)

        ts = [threading.Thread(target=arm, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        after = sum(
            1 for t in threading.enumerate() if t.name == "rtpu-sketch-sweeper"
        )
        assert after - before == 1
        c._engine._stop_sweeper()
        time.sleep(0.4)
        remaining = sum(
            1
            for t in threading.enumerate()
            if t.name == "rtpu-sketch-sweeper" and t.is_alive()
        )
        assert remaining == before
    finally:
        c.shutdown()


def test_zset_score_formatting_redis_style():
    """ADVICE r3 low: integral scores must encode as '1', not '1.0'."""
    from redisson_tpu.serve.resp import _fmt_score

    assert _fmt_score(1.0) == "1"
    assert _fmt_score(-3.0) == "-3"
    assert _fmt_score(0.0) == "0"
    assert _fmt_score(1.5) == "1.5"
    assert _fmt_score(2.25) == "2.25"
    # %.17g round-trips exactly
    assert float(_fmt_score(0.1)) == 0.1
    # Non-finite scores are valid in Redis (ZADD z inf a).
    assert _fmt_score(float("inf")) == "inf"
    assert _fmt_score(float("-inf")) == "-inf"
    assert _fmt_score(float("nan")) == "nan"
