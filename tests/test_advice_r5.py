"""Regression tests for the round-4 advisor findings (ADVICE.md):

1. Python RESP parser must reject negative multibulk/bulk lengths
   (they silently desynced the connection).
2. TransferQueue must not alias two concurrent transfers of the SAME
   bytes object under one identity.
3. RESP INCR on a Python-API AtomicLong/AtomicDouble must preserve the
   counter kind (it rewrote them as 'bucket', breaking the live handle).
4. LongCodec decode must be symmetric with its uint64 encode branch.
5. An empty multibulk frame ('*0\\r\\n') must be skipped with NO reply.
"""

import socket
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


@pytest.fixture
def stack():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    server = RespServer(client)
    yield client, server
    server.close()
    client.shutdown()


class TestNegativeLengths:
    def _raw(self, server, payload: bytes) -> bytes:
        s = socket.create_connection((server.host, server.port), timeout=5)
        try:
            s.sendall(payload)
            out = b""
            while True:
                try:
                    data = s.recv(65536)
                except socket.timeout:
                    break
                if not data:
                    break
                out += data
            return out
        finally:
            s.close()

    def test_negative_bulk_len_closes_with_protocol_error(self, stack):
        _, server = stack
        out = self._raw(server, b"*1\r\n$-1\r\n")
        assert b"Protocol error" in out

    def test_negative_multibulk_len_closes_with_protocol_error(self, stack):
        _, server = stack
        out = self._raw(server, b"*-3\r\nPING\r\n")
        assert b"Protocol error" in out

    def test_server_still_healthy_after_bad_frames(self, stack):
        _, server = stack
        self._raw(server, b"*1\r\n$-5\r\n")
        conn = RespClient(server.host, server.port)
        try:
            assert conn.cmd("PING") == "PONG"
        finally:
            conn.close()


class TestEmptyMultibulk:
    def test_empty_frame_skipped_without_reply(self, stack):
        """'*0\\r\\n' between two pipelined commands must produce exactly
        two replies — a third would desync the client's reply counting."""
        _, server = stack
        s = socket.create_connection((server.host, server.port), timeout=5)
        try:
            s.sendall(
                b"*1\r\n$4\r\nPING\r\n"
                b"*0\r\n"
                b"*2\r\n$4\r\nECHO\r\n$2\r\nhi\r\n"
            )
            s.settimeout(2)
            expected = b"+PONG\r\n$2\r\nhi\r\n"
            out = b""
            deadline = time.monotonic() + 5
            while len(out) < len(expected) and time.monotonic() < deadline:
                try:
                    data = s.recv(65536)
                except socket.timeout:
                    break
                if not data:
                    break
                out += data
            assert out == expected
        finally:
            s.close()


class TestTransferQueueAliasing:
    def test_same_bytes_object_two_transfers(self, stack):
        """Two concurrent transfer() calls with the SAME bytes object:
        the first consumer take must release exactly one transferer (it
        used to release neither until both copies drained)."""
        client, _ = stack
        q = client.get_transfer_queue("advice5-tq")
        payload = b"shared-payload"
        done = []

        def xfer():
            ok = q.transfer(payload, timeout_seconds=20)
            done.append(ok)

        t1 = threading.Thread(target=xfer)
        t2 = threading.Thread(target=xfer)
        t1.start()
        t2.start()
        deadline = time.monotonic() + 5
        while q.size() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert q.size() == 2

        assert q.poll() == payload
        deadline = time.monotonic() + 10
        while len(done) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(done) == 1 and done[0] is True

        assert q.poll() == payload
        t1.join(10)
        t2.join(10)
        assert done == [True, True]


    def test_interned_one_byte_value_two_transfers(self, stack):
        """CPython interns empty/1-byte bytes: a plain copy of b'a' IS
        b'a', so without a fresh-identity wrapper two transfers of the
        same tiny value alias one identity and neither releases until
        both copies drain."""
        client, _ = stack
        q = client.get_transfer_queue("advice5-tq-tiny")
        done = []

        def xfer():
            done.append(q.transfer(b"a", timeout_seconds=20))

        t1 = threading.Thread(target=xfer)
        t2 = threading.Thread(target=xfer)
        t1.start()
        t2.start()
        deadline = time.monotonic() + 5
        while q.size() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert q.size() == 2

        assert q.poll() == b"a"
        deadline = time.monotonic() + 10
        while len(done) < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(done) == 1 and done[0] is True

        assert q.poll() == b"a"
        t1.join(10)
        t2.join(10)
        assert done == [True, True]


class TestIncrKindPreservation:
    def test_incr_preserves_atomiclong(self, stack):
        client, server = stack
        al = client.get_atomic_long("advice5-counter")
        al.set(41)
        conn = RespClient(server.host, server.port)
        try:
            assert conn.cmd("INCR", "advice5-counter") == 42
        finally:
            conn.close()
        # The live Python handle must still work — the old behavior
        # rewrote the kind to 'bucket' and every later call raised.
        assert al.get() == 42
        assert al.increment_and_get() == 43

    def test_incrbyfloat_preserves_atomicdouble(self, stack):
        client, server = stack
        ad = client.get_atomic_double("advice5-double")
        ad.set(1.5)
        conn = RespClient(server.host, server.port)
        try:
            raw = conn.cmd("INCRBYFLOAT", "advice5-double", "2.25")
            assert float(raw) == 3.75
        finally:
            conn.close()
        assert ad.get() == 3.75

    def test_incrbyfloat_fractional_keeps_long_handle_alive(self, stack):
        """A fractional INCRBYFLOAT flips the entry to the sibling
        counter kind — the live AtomicLong handle must NOT raise
        WRONGTYPE: fractional reads raise ValueError (the Java
        NumberFormatException analog) and integral reads keep working."""
        client, server = stack
        al = client.get_atomic_long("advice5-frac")
        al.set(1)
        conn = RespClient(server.host, server.port)
        try:
            conn.cmd("INCRBYFLOAT", "advice5-frac", "0.5")
            with pytest.raises(ValueError):
                al.get()  # fractional: value error, never WRONGTYPE
            assert client.get_atomic_double("advice5-frac").get() == 1.5
            conn.cmd("INCRBYFLOAT", "advice5-frac", "0.5")
        finally:
            conn.close()
        assert al.get() == 2
        assert al.increment_and_get() == 3

    def test_string_reads_serve_counter_kinds(self, stack):
        """GET/MGET/STRLEN/GETRANGE on a preserved counter kind must
        serve the string view (TYPE says 'string'), not WRONGTYPE."""
        client, server = stack
        al = client.get_atomic_long("advice5-read")
        al.set(41)
        conn = RespClient(server.host, server.port)
        try:
            assert conn.cmd("INCR", "advice5-read") == 42
            assert conn.cmd("GET", "advice5-read") == b"42"
            assert conn.cmd("STRLEN", "advice5-read") == 2
            assert conn.cmd("GETRANGE", "advice5-read", 0, 0) == b"4"
            assert conn.cmd("MGET", "advice5-read") == [b"42"]
            assert conn.cmd("TYPE", "advice5-read") == "string"
        finally:
            conn.close()
        assert al.get() == 42

    def test_huge_int_counter_no_float_roundtrip(self, stack):
        """_as_int must not route ints through float(): 10**400
        overflows float64."""
        client, _ = stack
        al = client.get_atomic_long("advice5-big")
        al.set(10**400)
        assert al.get() == 10**400
        assert al.increment_and_get() == 10**400 + 1

    def test_plain_string_counters_still_bucket(self, stack):
        """SET+INCR (no Python counter involved) keeps Redis semantics:
        the key stays a string, TYPE says 'string'."""
        client, server = stack
        conn = RespClient(server.host, server.port)
        try:
            assert conn.cmd("SET", "advice5-str", "7") == "OK"
            assert conn.cmd("INCR", "advice5-str") == 8
            assert conn.cmd("TYPE", "advice5-str") == "string"
            assert conn.cmd("GET", "advice5-str") == b"8"
        finally:
            conn.close()


class TestLongCodecSymmetry:
    def test_signed_roundtrip(self):
        c = LongCodec()
        for v in (0, 1, -1, 2**63 - 1, -(2**63)):
            assert c.decode(c.encode(v)) == v

    def test_unsigned_roundtrip(self):
        c = LongCodec(unsigned=True)
        for v in (0, 7, 2**63, 2**63 + 7, 2**64 - 1):
            assert c.decode(c.encode(v)) == v

    def test_default_documents_signed_view(self):
        # The ambiguous half: a uint64 >= 2**63 stored through the
        # DEFAULT codec decodes as its signed reinterpretation (the two
        # ranges share byte patterns; unsigned=True selects the other).
        c = LongCodec()
        assert c.decode(c.encode(2**64 - 1)) == -1
