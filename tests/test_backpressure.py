"""Engine-side backpressure + adaptive in-flight (round 4, VERDICT #2):
submit() blocks at the queue bound (the pooled-acquire role) so an
unpaced producer cannot build an unbounded queue; the dispatch window
shrinks when launch retirement degrades (the >12-launch transport cliff)
and grows back when it recovers."""

import threading
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.executor.coalescer import BatchCoalescer


class _FakeLazy:
    def __init__(self, value, delay_s=0.0):
        self._v = value
        self._delay = delay_s

    def result(self):
        if self._delay:
            time.sleep(self._delay)
        return self._v


def _mk(**kw):
    kw.setdefault("batch_window_us", 500)
    kw.setdefault("max_batch", 1024)
    return BatchCoalescer(**kw)


def test_submit_blocks_at_queue_bound():
    """A producer outrunning a slow dispatch path must block in submit()
    (engine backpressure), keeping the queue at or under the bound."""
    gate = threading.Event()
    max_seen = [0]

    def dispatch(cols):
        gate.wait(5.0)  # first launch stalls; queue builds behind it
        return _FakeLazy(np.concatenate(cols))

    c = _mk(max_queued_ops=2048, max_inflight=1)
    try:
        futs = []
        t0 = time.monotonic()

        def producer():
            for i in range(40):
                futs.append(
                    c.submit(
                        ("k",), dispatch, (np.arange(256, dtype=np.int64),), 256
                    )
                )
                max_seen[0] = max(max_seen[0], c._queued_ops)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.5)
        # Producer must be blocked well before 40 submits (40*256 ≫ 2048).
        assert t.is_alive(), "producer was never backpressured"
        assert c._queued_ops <= 2048
        gate.set()
        t.join(timeout=20)
        assert not t.is_alive()
        assert max_seen[0] <= 2048
        for f in futs:
            assert f.result(timeout=20) is not None
    finally:
        gate.set()
        c.shutdown()


def test_oversize_single_submit_admitted_when_empty():
    """One submit larger than the bound must pass (no self-deadlock)."""
    c = _mk(max_queued_ops=100)
    try:
        f = c.submit(
            ("big",),
            lambda cols: _FakeLazy(np.concatenate(cols)),
            (np.arange(5000, dtype=np.int64),),
            5000,
        )
        assert len(f.result(timeout=10)) == 5000
    finally:
        c.shutdown()


def test_adaptive_window_shrinks_on_slow_retirement_and_regrows():
    c = _mk(max_inflight=8, min_inflight=2, adaptive_inflight=True)
    c.slow_launch_s = 0.05
    c.fast_launch_s = 0.02
    try:
        slow = lambda cols: _FakeLazy(np.concatenate(cols), delay_s=0.12)  # noqa: E731
        fast = lambda cols: _FakeLazy(np.concatenate(cols), delay_s=0.0)  # noqa: E731
        assert c._inflight_limit == 8
        for i in range(6):
            c.submit((f"s{i}",), slow, (np.arange(8, dtype=np.int64),), 8).result(
                timeout=10
            )
        assert c._inflight_limit == 2, c._inflight_limit
        # Recovery: a streak of fast retirements grows the window back.
        for i in range(80):
            c.submit((f"f{i}",), fast, (np.arange(8, dtype=np.int64),), 8).result(
                timeout=10
            )
        assert c._inflight_limit >= 6, c._inflight_limit
    finally:
        c.shutdown()


def test_unpaced_producer_bounded_latency_end_to_end():
    """VERDICT #2 done-criterion: an unpaced producer WITHOUT any
    client-side future window sees bounded batch-wait p99 — the engine's
    own admission control is the bound."""
    cfg = Config().use_tpu_sketch(
        min_bucket=64, batch_window_us=200, max_batch=4096,
        max_queued_ops=16384,
    )
    cl = redisson_tpu.create(cfg)
    try:
        bf = cl.get_bloom_filter("bp")
        bf.try_init(50_000, 0.01)
        # Warm every pow-2 bucket the run can hit (merge-at-pop forms
        # segments up to max_batch) so no compile lands in the window.
        b = 256
        while b <= 4096:
            bf.add_all_async(np.arange(b, dtype=np.uint64)).result(timeout=120)
            b *= 2
        cl._engine.metrics.reset()
        futs = []
        rng = np.random.default_rng(0)
        for i in range(400):  # no pacing, no result() while submitting
            futs.append(
                bf.add_all_async(rng.integers(0, 1 << 20, 256).astype(np.uint64))
            )
        for f in futs:
            f.result(timeout=60)
        m = cl.get_metrics()
        # Queue bound 16k ops @ >100k ops/s device floor ⇒ sub-second wait
        # even on a loaded CPU test host; without backpressure this shape
        # queued 100k+ ops and p99 grew with producer speed (round 2).
        assert m["p99_wait_ms"] < 2000, m
        assert m["ops_total"] == 400 * 256
    finally:
        cl.shutdown()


def test_bulk_submit_not_starved_by_small_stream():
    """FIFO admission: a submit larger than max_queued_ops must admit
    even while other threads stream small ops (the old empty-queue-only
    rule livelocked it)."""
    import threading
    import time

    import numpy as np

    from redisson_tpu.executor.coalescer import BatchCoalescer

    done = []

    def dispatch(cols):
        class _L:
            def result(self):
                return np.zeros(sum(len(c) for c in cols[:1]), bool)

        time.sleep(0.002)
        return _L()

    c = BatchCoalescer(batch_window_us=100, max_batch=256,
                       max_queued_ops=512)
    stop = threading.Event()

    def small_stream():
        while not stop.is_set():
            c.submit("k", dispatch, (np.zeros(64, np.uint32),), 64)
            time.sleep(0.0005)

    streamers = [threading.Thread(target=small_stream) for _ in range(3)]
    for t in streamers:
        t.start()
    time.sleep(0.1)  # queue saturated by the small stream

    def bulk():
        fut = c.submit("k", dispatch, (np.zeros(2048, np.uint32),), 2048)
        fut.result(timeout=30)
        done.append(True)

    b = threading.Thread(target=bulk)
    b.start()
    b.join(timeout=20)
    stop.set()
    for t in streamers:
        t.join(timeout=5)
    alive = b.is_alive()
    c.shutdown()
    assert not alive and done, "bulk submit starved behind small stream"
