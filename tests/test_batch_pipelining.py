"""Batch pipelines sync-named sketch calls (VERDICT r2 Weak #7 / Next #9)
and grid objects expose the RFuture *_async idiom."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


class TestBatchPipelinesSketchOps:
    def test_sync_named_calls_coalesce_into_few_dispatches(self, client):
        bf = client.get_bloom_filter("pb")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(64, dtype=np.uint64))  # warm shapes
        client._engine.metrics.reset()

        batch = client.create_batch()
        b_bf = batch.get_bloom_filter("pb")
        futs = []
        for i in range(16):  # natural SYNC calls, queued
            futs.append(b_bf.add(np.uint64(1000 + i)))
            futs.append(b_bf.contains(np.uint64(1000 + i)))
        res = batch.execute()
        # Sync contracts preserved:
        adds = res.get_responses()[0::2]
        conts = res.get_responses()[1::2]
        assert all(isinstance(a, bool) for a in adds)
        assert all(c is True for c in conts)  # same-batch read-your-write
        # N sketch ops coalesced into <= 2 device dispatches (the done-bar
        # from the verdict): the metrics count flushed batches.
        snap = client.get_metrics()
        assert snap.get("batches_total", 99) <= 2, snap

    def test_mixed_object_batch(self, client):
        batch = client.create_batch()
        h = batch.get_hyper_log_log("ph")
        c = batch.get_count_min_sketch("pc")
        client.get_count_min_sketch("pc").try_init(4, 1 << 10)
        f1 = h.add_all([1, 2, 3])
        f2 = c.add("hot", 5)
        f3 = c.estimate("hot")
        res = batch.execute()
        assert res[0] is True
        assert f2.result() == 5
        assert f3.result() == 5
        assert res.get_responses() == [True, 5, 5]


class TestGridAsyncFacades:
    def test_bucket_map_queue_async(self, client):
        b = client.get_bucket("ab")
        assert b.set_async("v").result() is None
        assert b.get_async().result() == "v"
        m = client.get_map("am")
        m.put_async("k", 1).result()
        assert m.get_async("k").result() == 1
        q = client.get_queue("aq")
        assert q.offer_async("x").result() is True
        assert q.poll_async().result() == "x"

    def test_camel_case_async(self, client):
        m = client.get_map("am2")
        m.fastPutAsync("k", 2).result()
        assert m.getAsync("k").result() == 2

    def test_async_future_resolves_off_thread(self, client):
        """Grid *_async runs off the caller thread (real futures), and
        blocking async ops can't starve one another (r4: VERDICT #5)."""
        import threading

        from redisson_tpu.grid.base import _spawn_future

        caller = threading.current_thread().name
        threads = []

        def probe():
            threads.append(threading.current_thread().name)
            return "ok"

        fut = _spawn_future(probe, (), {})
        assert fut.result(timeout=10) == "ok"
        assert threads and threads[0] != caller
        b = client.get_bucket("ab2")
        f2 = b.set_async("v")
        assert f2.result(timeout=10) is None
        assert f2.done()
        assert b.get() == "v"
        # Blocking async ops + the op that unblocks them, concurrently:
        # the per-call-thread design cannot deadlock on pool exhaustion.
        q = client.get_blocking_queue("abq")
        takes = [q.poll_async(5.0) for _ in range(4)]
        for i in range(4):
            q.offer_async(i).result(timeout=10)
        got = sorted(t.result(timeout=10) for t in takes)
        assert got == [0, 1, 2, 3]


class TestMixedBatchPipelining:
    """VERDICT r3 #5 done-criterion: a batch interleaving map (grid) and
    bloom (sketch) ops coalesces the sketch ops into <=2 device
    dispatches while grid ops run off the caller thread, in order."""

    def test_interleaved_map_bloom_batch(self):
        import threading

        import numpy as np

        cfg = Config().use_tpu_sketch(min_bucket=64, batch_window_us=5000)
        client = redisson_tpu.create(cfg)
        try:
            bf = client.get_bloom_filter("mixb")
            bf.try_init(10_000, 0.01)
            bf.add("warm")  # compile outside the measured window
            client._engine.metrics.reset()
            caller = threading.current_thread().name
            grid_threads = []
            m = client.get_map("mixm")
            from redisson_tpu.grid.maps import Map

            orig_put = Map.put

            def traced_put(self, k, v):
                grid_threads.append(threading.current_thread().name)
                return orig_put(self, k, v)

            Map.put = traced_put

            batch = client.create_batch()
            bbf = batch.get_bloom_filter("mixb")
            bm = batch.get_map("mixm")
            futs = []
            for i in range(10):
                futs.append(bbf.add(f"k{i}"))
                futs.append(bm.put(f"mk{i}", i))
                futs.append(bbf.contains(f"k{i}"))
            res = batch.execute()
            assert len(res) == 30
            # sketch results honored the sync contracts
            adds = res.get_responses()[0::3]
            gets = res.get_responses()[2::3]
            assert all(isinstance(a, bool) for a in adds)
            assert all(g is True for g in gets)
            # grid ops landed, in order, off the caller thread
            assert m.size() == 10
            assert len(grid_threads) == 10
            assert all(t != caller for t in grid_threads)
            assert all(t.startswith("rtpu-batch") for t in grid_threads)
            # sketch ops coalesced into <=2 device dispatches
            mm = client.get_metrics()
            assert mm["batches_total"] <= 2, mm
        finally:
            from redisson_tpu.grid.maps import Map

            Map.put = orig_put
            client.shutdown()
