"""Batch pipelines sync-named sketch calls (VERDICT r2 Weak #7 / Next #9)
and grid objects expose the RFuture *_async idiom."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


class TestBatchPipelinesSketchOps:
    def test_sync_named_calls_coalesce_into_few_dispatches(self, client):
        bf = client.get_bloom_filter("pb")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(64, dtype=np.uint64))  # warm shapes
        client._engine.metrics.reset()

        batch = client.create_batch()
        b_bf = batch.get_bloom_filter("pb")
        futs = []
        for i in range(16):  # natural SYNC calls, queued
            futs.append(b_bf.add(np.uint64(1000 + i)))
            futs.append(b_bf.contains(np.uint64(1000 + i)))
        res = batch.execute()
        # Sync contracts preserved:
        adds = res.get_responses()[0::2]
        conts = res.get_responses()[1::2]
        assert all(isinstance(a, bool) for a in adds)
        assert all(c is True for c in conts)  # same-batch read-your-write
        # N sketch ops coalesced into <= 2 device dispatches (the done-bar
        # from the verdict): the metrics count flushed batches.
        snap = client.get_metrics()
        assert snap.get("batches_total", 99) <= 2, snap

    def test_mixed_object_batch(self, client):
        batch = client.create_batch()
        h = batch.get_hyper_log_log("ph")
        c = batch.get_count_min_sketch("pc")
        client.get_count_min_sketch("pc").try_init(4, 1 << 10)
        f1 = h.add_all([1, 2, 3])
        f2 = c.add("hot", 5)
        f3 = c.estimate("hot")
        res = batch.execute()
        assert res[0] is True
        assert f2.result() == 5
        assert f3.result() == 5
        assert res.get_responses() == [True, 5, 5]


class TestGridAsyncFacades:
    def test_bucket_map_queue_async(self, client):
        b = client.get_bucket("ab")
        assert b.set_async("v").result() is None
        assert b.get_async().result() == "v"
        m = client.get_map("am")
        m.put_async("k", 1).result()
        assert m.get_async("k").result() == 1
        q = client.get_queue("aq")
        assert q.offer_async("x").result() is True
        assert q.poll_async().result() == "x"

    def test_camel_case_async(self, client):
        m = client.get_map("am2")
        m.fastPutAsync("k", 2).result()
        assert m.getAsync("k").result() == 2

    def test_async_future_is_done(self, client):
        b = client.get_bucket("ab2")
        fut = b.set_async("v")
        assert fut.done()
