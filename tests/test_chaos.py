"""Chaos engine + self-healing dispatch (ISSUE 3).

Deterministic fault injection (seeded schedules, named points), circuit
breakers (CLOSED -> OPEN -> HALF_OPEN), graceful degradation to the host
golden mirror with reconcile-on-close, the DEBUG INJECT admin surface,
and the satellites (script watchdog, XAUTOCLAIM deleted ids).

The disabled-overhead guard and the randomized soak live here too (the
soak is slow+chaos marked; tier-1 runs everything else).
"""

import threading
import time

import numpy as np
import pytest

from redisson_tpu import chaos
from redisson_tpu.chaos import ChaosSchedule, FaultInjected
from redisson_tpu.config import Config
from redisson_tpu.executor.health import (
    BreakerBoard,
    CLOSED,
    DispatchHealth,
    HALF_OPEN,
    OPEN,
    kind_of_op,
)


@pytest.fixture(autouse=True)
def _chaos_off():
    """Every test starts and ends with chaos disarmed."""
    chaos.clear()
    chaos.reset_counts()
    yield
    chaos.clear()
    chaos.reset_counts()


def make_client(**tpu_kw):
    from redisson_tpu.client import RedissonTpuClient

    tpu_kw.setdefault("batch_window_us", 100)
    cfg = Config().use_tpu_sketch(**tpu_kw)
    cfg.retry_attempts = 2
    cfg.retry_interval_ms = 5
    return RedissonTpuClient(cfg)


# -- schedule determinism ----------------------------------------------------


class TestSchedule:
    def test_same_seed_same_fire_pattern(self):
        def pattern(seed):
            (rule,) = ChaosSchedule(
                seed=seed, rate=0.3, points=("dispatch",)
            ).rules()
            return [rule.roll() for _ in range(200)]

        assert pattern(7) == pattern(7)
        assert pattern(7) != pattern(8)

    def test_rate_zero_never_fires_rate_one_always(self):
        (never,) = ChaosSchedule(seed=1, rate=0.0, points=("p",)).rules()
        (always,) = ChaosSchedule(seed=1, rate=1.0, points=("p",)).rules()
        assert not any(never.roll() for _ in range(100))
        assert all(always.roll() for _ in range(100))

    def test_points_roll_independently(self):
        sched = ChaosSchedule(seed=3, rate=0.5, points=("a", "b"))
        ra, rb = sched.rules()
        assert [ra.roll() for _ in range(64)] != [rb.roll() for _ in range(64)]

    def test_install_clear_toggle_guard(self):
        assert not chaos.ENABLED
        chaos.install(ChaosSchedule(seed=0, rate=1.0, points=("x",)))
        assert chaos.ENABLED
        chaos.clear()
        assert not chaos.ENABLED

    def test_fire_kinds(self):
        chaos.inject("err", kind="error", rate=1.0)
        with pytest.raises(FaultInjected):
            chaos.fire("err")
        chaos.inject("corr", kind="corrupt", rate=1.0)
        with pytest.raises(chaos.CorruptionDetected):
            chaos.fire("corr", data=np.arange(8, dtype=np.uint32))
        chaos.inject("lat", kind="latency", rate=1.0, latency_s=0.01)
        t0 = time.monotonic()
        chaos.fire("lat")  # must NOT raise
        assert time.monotonic() - t0 >= 0.009
        assert chaos.counts()[("err", "error")] == 1

    def test_prefix_match_for_dispatch_points(self):
        chaos.inject("dispatch", kind="error", rate=1.0)
        with pytest.raises(FaultInjected):
            chaos.fire("dispatch.bloom_mixed")
        chaos.clear()
        chaos.inject("dispatch.read_row", kind="error", rate=1.0)
        chaos.fire("dispatch.bloom_mixed")  # no rule for this method
        with pytest.raises(FaultInjected):
            chaos.fire("dispatch.read_row")


# -- disabled-overhead guard -------------------------------------------------


def test_disabled_guard_never_consults_fire(monkeypatch):
    """With chaos disabled, ``fire`` must be unreachable from the hot
    paths — the module-level guard is the ONLY cost."""
    calls = []
    monkeypatch.setattr(chaos, "fire", lambda *a, **k: calls.append(a))
    c = make_client()
    bf = c.get_bloom_filter("guard-bf")
    bf.try_init(1000, 0.01)
    assert bf.add("k") is True
    assert bf.contains("k") is True
    c._engine.shutdown()
    assert calls == []


def test_disabled_injection_overhead():
    """The guard (`if chaos.ENABLED: fire(...)`) must add no measurable
    submit overhead when chaos is off — same min-of-paired-ratios
    discipline as test_observability's ≤10% harness, on the coalescer
    submit path the guard fronts."""
    import gc

    from redisson_tpu.executor.coalescer import BatchCoalescer

    class _Lazy:
        def __init__(self, v):
            self._v = v

        def result(self):
            return self._v

    def plain_dispatch(cols):
        return _Lazy(np.concatenate(cols))

    def guarded_dispatch(cols):
        if chaos.ENABLED:  # the exact call-site shape
            chaos.fire("dispatch.bench")
        return _Lazy(np.concatenate(cols))

    arr = np.arange(64, dtype=np.int64)
    N = 500

    def make():
        return BatchCoalescer(
            batch_window_us=30_000_000, max_batch=1 << 22,
            max_queued_ops=1 << 24,
        )

    def round_time(c, dispatch):
        t0 = time.perf_counter()
        for _ in range(N):
            c.submit(("op",), dispatch, (arr,), 64)
        return time.perf_counter() - t0

    history = []
    for _ in range(6):
        plain, guarded = [], []
        cs = []
        gc.disable()
        try:
            for r in range(6):
                ca, cb = make(), make()
                cs += [ca, cb]
                round_time(ca, plain_dispatch)
                round_time(cb, guarded_dispatch)
                if r % 2 == 0:
                    plain.append(round_time(ca, plain_dispatch))
                    guarded.append(round_time(cb, guarded_dispatch))
                else:
                    guarded.append(round_time(cb, guarded_dispatch))
                    plain.append(round_time(ca, plain_dispatch))
        finally:
            gc.enable()
            for c in cs:
                c.shutdown()
        ratio = min(q / p for p, q in zip(plain, guarded))
        ratio = min(ratio, min(guarded) / min(plain))
        history.append(ratio)
        if ratio <= 1.10:
            return
    raise AssertionError(f"chaos guard >10% submit overhead: {history}")


# -- circuit breaker unit ----------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestBreaker:
    def test_closed_open_halfopen_close(self):
        clk = _Clock()
        b = BreakerBoard(failure_threshold=3, open_s=1.0, clock=clk)
        for _ in range(2):
            b.record_failure(0, "bloom_mix", RuntimeError("x"))
        assert b.states()[(0, "bloom_mix")] == CLOSED
        assert b.allow(0, "bloom_mix")
        b.record_failure(0, "bloom_mix", RuntimeError("x"))
        assert b.states()[(0, "bloom_mix")] == OPEN
        assert not b.allow(0, "bloom_mix")  # open: fail fast
        clk.t = 1.5
        assert b.allow(0, "bloom_mix")  # the probe
        assert b.states()[(0, "bloom_mix")] == HALF_OPEN
        assert not b.allow(0, "bloom_mix")  # one probe at a time
        b.record_success(0, "bloom_mix")
        assert b.states()[(0, "bloom_mix")] == CLOSED
        assert b.allow(0, "bloom_mix")

    def test_probe_failure_reopens(self):
        clk = _Clock()
        b = BreakerBoard(failure_threshold=1, open_s=1.0, clock=clk)
        b.record_failure(0, "cms_mix", RuntimeError("x"))
        assert b.states()[(0, "cms_mix")] == OPEN
        clk.t = 1.1
        assert b.allow(0, "cms_mix")
        b.record_failure(0, "cms_mix", RuntimeError("probe died"))
        assert b.states()[(0, "cms_mix")] == OPEN
        assert not b.allow(0, "cms_mix")
        clk.t = 2.5  # a second window elapses
        assert b.allow(0, "cms_mix")

    def test_success_resets_failure_streak(self):
        b = BreakerBoard(failure_threshold=3, open_s=1.0)
        b.record_failure(0, "hll_add", RuntimeError("x"))
        b.record_failure(0, "hll_add", RuntimeError("x"))
        b.record_success(0, "hll_add")
        b.record_failure(0, "hll_add", RuntimeError("x"))
        assert b.states()[(0, "hll_add")] == CLOSED  # streak broke

    def test_transition_callbacks(self):
        events = []
        b = BreakerBoard(failure_threshold=1, open_s=0.0)
        b.on_open = lambda s, o: events.append(("open", s, o))
        b.on_close = lambda s, o: events.append(("close", s, o))
        b.record_failure(1, "bs_mix", RuntimeError("x"))
        assert b.allow(1, "bs_mix")  # open_s=0: immediate half-open probe
        b.record_success(1, "bs_mix")
        assert events == [("open", 1, "bs_mix"), ("close", 1, "bs_mix")]

    def test_kind_of_op(self):
        assert kind_of_op("bloom_mixkr") == "bloom"
        assert kind_of_op("bs_mix") == "bitset"
        assert kind_of_op("bitset_get") == "bitset"
        assert kind_of_op("hll_add") == "hll"
        assert kind_of_op("cms_updest") == "cms"
        assert kind_of_op("write_row") is None


# -- engine-level: degrade, serve from mirror, reconcile ---------------------


BLOOM_POINTS = (
    "dispatch.bloom_mixed", "dispatch.bloom_mixed_keys",
    "dispatch.bloom_mixed_keys_runs",
)


def _await(cond, timeout=8.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.02)
    return True


def _flap(fn, attempts=8):
    """Run a degraded-phase op, riding out breaker FLAPS: with an
    opcode-targeted fault the monitor's read_row probe legitimately
    succeeds, briefly closing the breaker — the next real dispatch then
    fails typed and re-opens it (correct behavior for a fault that only
    one kernel hits).  Ops landing in that window fail typed; retrying
    resumes from the mirror.  State stays consistent across flaps: the
    reconcile wrote the mirror to the device, and the re-seed reads it
    back."""
    for _ in range(attempts - 1):
        try:
            return fn()
        except Exception:
            time.sleep(0.05)
    return fn()


class TestDegradedServe:
    def test_degrade_serve_reconcile_bloom(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=1500)
        eng = c._engine
        try:
            bf = c.get_bloom_filter("deg-bf")
            bf.try_init(50_000, 0.01)
            pre = [f"pre{i}" for i in range(50)]
            bf.add_all(pre)
            assert all(bf.contains(k) for k in pre)
            chaos.install(ChaosSchedule(seed=2, rate=1.0, points=BLOOM_POINTS))
            # Drive the breaker open: failures surface typed, never hang.
            for i in range(8):
                try:
                    bf.add(f"open{i}")
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            # Degraded serve: writes AND reads keep working, pre-fault
            # state is visible (mirror seeded from the device row).
            assert _flap(lambda: bf.add("while-down")) is True
            assert _flap(lambda: bf.contains("while-down")) is True
            assert _flap(lambda: bf.add("while-down")) is False  # present
            assert all(_flap(lambda k=k: bf.contains(k)) for k in pre)
            assert not _flap(lambda: bf.contains("never-added"))
            assert _await(lambda: "deg-bf" in eng._mirrors)
            mirror_bits = eng._mirrors["deg-bf"].model.bits.copy()
            # Heal the device: monitor probe closes the breaker and the
            # mirror reconciles back to the device row.
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            assert not eng._mirrors
            # Golden parity: the device row equals the mirror state.
            entry = eng.registry.lookup("deg-bf")
            row = eng.executor.read_row(entry.pool, entry.row)
            from redisson_tpu.objects.degraded import _bits_from_words

            device_bits = _bits_from_words(row, entry.params["size"])
            assert np.array_equal(device_bits, mirror_bits)
            # Device-served reads confirm the reconciled state.
            assert bf.contains("while-down")
            assert all(bf.contains(k) for k in pre)
            assert eng.health.summary()["recoveries"] >= 1
        finally:
            eng.shutdown()

    def test_snapshot_while_degraded_keeps_mirror_writes(self, tmp_path):
        """snapshot() taken mid-degradation must not crash on the
        read-only D2H arrays and must persist mirror-acked writes (the
        degraded overlay), so a crash during the window doesn't lose
        them."""
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=60_000)
        eng = c._engine
        try:
            bf = c.get_bloom_filter("snap-bf")
            bf.try_init(50_000, 0.01)
            bf.add("pre-fault")
            chaos.install(ChaosSchedule(seed=4, rate=1.0, points=BLOOM_POINTS))
            for i in range(8):
                try:
                    bf.add(f"open{i}")
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            assert _flap(lambda: bf.add("mirror-only")) is True
            assert _await(lambda: "snap-bf" in eng._mirrors)
            eng.snapshot(str(tmp_path))  # crashed before the overlay copy
        finally:
            chaos.clear()
            eng.shutdown()
        c2 = make_client()
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            bf2 = c2.get_bloom_filter("snap-bf")
            assert bf2.contains("pre-fault")
            assert bf2.contains("mirror-only")  # the mirror-acked write
            assert not bf2.contains("never-added")
        finally:
            c2.shutdown()

    def test_no_lost_futures_while_breaker_opens(self):
        """Every future submitted across the failure window resolves —
        with a value or a typed error — none hang."""
        from redisson_tpu.executor.failures import RedissonTpuError

        c = make_client(breaker_failure_threshold=2, breaker_open_ms=1500)
        eng = c._engine
        try:
            bf = c.get_bloom_filter("nl-bf")
            bf.try_init(10_000, 0.01)
            bf.add("seed")
            chaos.install(ChaosSchedule(seed=5, rate=1.0, points=BLOOM_POINTS))
            outcomes = []
            for i in range(12):
                try:
                    outcomes.append(("ok", bf.add(f"k{i}")))
                except RedissonTpuError as e:
                    outcomes.append(("err", type(e).__name__))
                except Exception as e:  # chaos surfaces raw on direct paths
                    outcomes.append(("err", type(e).__name__))
            assert len(outcomes) == 12  # nothing hung
            # Once degraded, ops succeed from the mirror.
            assert _await(lambda: eng.health.any_degraded)
            assert _flap(lambda: bf.add("mirror-op")) is True
        finally:
            chaos.clear()
            eng.shutdown()

    def test_degraded_flag_in_info_and_debug_inject(self):
        import socket

        from redisson_tpu.serve.resp import RespServer

        c = make_client(breaker_failure_threshold=1, breaker_open_ms=60_000)
        eng = c._engine
        server = RespServer(c, host="127.0.0.1", port=0)
        try:
            sock = socket.create_connection((server.host, server.port))
            f = sock.makefile("rwb")

            def cmd(*parts):
                out = b"*" + str(len(parts)).encode() + b"\r\n"
                for p in parts:
                    p = p if isinstance(p, bytes) else str(p).encode()
                    out += b"$" + str(len(p)).encode() + b"\r\n" + p + b"\r\n"
                f.write(out)
                f.flush()
                line = f.readline()
                if line[:1] == b"$":
                    n = int(line[1:])
                    return f.read(n + 2)[:-2]
                return line.strip()

            # DEBUG INJECT arms a rule; LIST shows it; OFF clears.
            assert cmd("DEBUG", "INJECT", "dispatch.bloom_mixed", "error",
                       "1.0", "7") == b"+OK"
            assert chaos.active() == {
                "dispatch.bloom_mixed": ("error", 1.0, 7)
            }
            info = cmd("INFO", "stats").decode()
            assert "degraded:0" in info
            assert "breakers_open:0" in info
            assert cmd("DEBUG", "INJECT", "OFF") == b"+OK"
            assert chaos.active() == {}
            # Degrade for real and read the flag back through INFO.
            chaos.install(
                ChaosSchedule(seed=2, rate=1.0, points=BLOOM_POINTS)
            )
            bf = c.get_bloom_filter("info-bf")
            bf.try_init(1000, 0.01)
            for i in range(4):
                try:
                    bf.add(f"x{i}")
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            assert bf.add("seed-mirror") is True  # lazily seeds the mirror
            info = cmd("INFO", "stats").decode()
            assert "degraded:1" in info
            assert "degraded_objects:1" in info
            sock.close()
        finally:
            chaos.clear()
            server.close()
            eng.shutdown()

    def test_debug_inject_gated_like_scripting(self, monkeypatch):
        from redisson_tpu.serve.resp import RespError, RespServer

        c = make_client()
        try:
            server = RespServer(c, host="127.0.0.1", port=0)
            try:
                # Simulate a non-loopback unauthenticated bind.
                server._inject_allowed = False
                with pytest.raises(RespError, match="requirepass"):
                    server._cmd_DEBUG([b"INJECT", b"dispatch", b"error", b"1"])
                # Loopback (the real bind here) allows it.
                server._inject_allowed = True
                server._cmd_DEBUG([b"INJECT", b"OFF"])
            finally:
                server.close()
        finally:
            c._engine.shutdown()


class TestNearCacheChaos:
    """Near cache × chaos (ISSUE 4 satellite): under breaker-open
    degradation every MIRROR write must bump the write epoch — a cached
    pre-degradation read can never serve stale — and reconcile-on-close
    must leave cache and device bit-identical."""

    def test_mirror_writes_bump_epochs_no_stale_negative(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=60_000)
        eng = c._engine
        try:
            bf = c.get_bloom_filter("ncc-bf")
            bf.try_init(20_000, 0.01)
            bf.add("pre")
            # Cache a negative AND a positive before the fault lands.
            assert bf.contains("late-add") is False
            assert bf.contains("pre") is True
            assert eng.nearcache.store.entries() >= 2
            chaos.install(ChaosSchedule(seed=4, rate=1.0, points=BLOOM_POINTS))
            for i in range(8):
                try:
                    bf.add(f"open{i}")
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            # The mirror write bumps the epoch at submit: the cached
            # negative must NOT answer this read.
            assert _flap(lambda: bf.add("late-add")) is True
            assert _flap(lambda: bf.contains("late-add")) is True
            # The monotone positive is still warm and still true.
            assert _flap(lambda: bf.contains("pre")) is True
        finally:
            chaos.clear()
            eng.shutdown()

    def test_reconcile_leaves_cache_and_device_bit_identical(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=600)
        eng = c._engine
        nc = eng.nearcache
        try:
            bf = c.get_bloom_filter("ncc-rec")
            bf.try_init(20_000, 0.01)
            pre = [f"pre{i}" for i in range(20)]
            bf.add_all(pre)
            chaos.install(ChaosSchedule(seed=6, rate=1.0, points=BLOOM_POINTS))
            for i in range(8):
                try:
                    bf.add(f"open{i}")
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            during = [f"during{i}" for i in range(20)]
            for k in during:
                assert _flap(lambda k=k: bf.add(k)) is True
            # Cache some degraded-window reads (mirror-served).
            assert all(_flap(lambda k=k: bf.contains(k)) for k in during)
            # Heal: breaker closes, mirror reconciles to the device row.
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            assert not eng._mirrors
            probe = pre + during + [f"ghost{i}" for i in range(20)]
            cached = [bf.contains(k) for k in probe]  # may serve from cache
            nc.store.clear()  # force the next pass to the device
            device = [bf.contains(k) for k in probe]
            assert cached == device  # bit-identical, entry for entry
        finally:
            chaos.clear()
            eng.shutdown()


class TestDegradedKinds:
    """Mirror parity for the other sketch kinds (hll/bitset/cms)."""

    def _degrade(self, eng, op, points, seed=3):
        chaos.install(ChaosSchedule(seed=seed, rate=1.0, points=points))
        for _ in range(6):
            try:
                op()
            except Exception:
                pass
            if eng.health.any_degraded:
                break
        assert _await(lambda: eng.health.any_degraded)

    def test_bloom_fast_paths_degrade_too(self):
        """exact_add_semantics=False routes adds through the fast
        single-tenant device path — once the kind degrades it must fail
        over to the mirror like every other bloom op."""
        c = make_client(
            breaker_failure_threshold=2, breaker_open_ms=1500,
            exact_add_semantics=False,
        )
        eng = c._engine
        try:
            bf = c.get_bloom_filter("fast-bf")
            bf.try_init(10_000, 0.01)
            bf.add("pre")
            # Open the breaker via the coalesced contains path.
            self._degrade(
                eng, lambda: bf.contains("x"), BLOOM_POINTS, seed=11,
            )
            assert _flap(lambda: bf.add("down-add")) is True  # mirror
            assert _flap(lambda: bf.contains("down-add")) is True
            assert _flap(lambda: bf.contains("pre")) is True
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            assert bf.contains("down-add") and bf.contains("pre")
        finally:
            chaos.clear()
            eng.shutdown()

    def test_bitset_mirror_and_reconcile(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=1500)
        eng = c._engine
        try:
            bs = c.get_bit_set("deg-bs")
            bs.set(3, True)
            bs.set(77, True)
            assert bs.get(3) and bs.get(77)
            self._degrade(
                eng, lambda: bs.set(5, True),
                ("dispatch.bitset_mixed", "dispatch.bitset_mixed_runs"),
            )
            # Degraded: mirror serves reads and writes with history.
            assert _flap(lambda: bs.get(3))
            assert not _flap(lambda: bs.set(100, True))  # prev bit
            assert _flap(lambda: bs.get(100))
            assert _flap(lambda: bs.cardinality()) >= 3
            # A degraded-window GROW (bitset_ensure migrates the entry to
            # a larger size class — not breaker-gated): the mirror must
            # grow with it and reconcile at the NEW row size.
            assert _await(lambda: "deg-bs" in eng._mirrors)
            seed_bits = eng._mirrors["deg-bs"].row_units * 32
            big = seed_bits + 513
            assert not _flap(lambda: bs.set(big, True))
            assert _flap(lambda: bs.get(big))
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            assert bs.get(100)  # reconciled to device
            assert bs.get(3) and bs.get(77)
            assert bs.get(big)  # grown row reconciled at the new size
            assert not bs.get(big - 1)
        finally:
            chaos.clear()
            eng.shutdown()

    def test_hll_mirror_and_reconcile(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=1500)
        eng = c._engine
        try:
            hll = c.get_hyper_log_log("deg-hll")
            hll.add_all([f"pre{i}" for i in range(500)])
            pre_count = hll.count()
            assert pre_count > 400
            self._degrade(
                eng, lambda: hll.add("x"),
                ("dispatch.hll_add_changed", "dispatch.hll_add_single",
                 "dispatch.hll_add", "dispatch.hll_add_keys_single"),
            )
            _flap(lambda: hll.add_all([f"down{i}" for i in range(500)]))
            degraded_count = _flap(lambda: hll.count())
            assert degraded_count > pre_count  # mirror kept counting
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            # Post-reconcile device count equals the mirror's last answer.
            assert hll.count() == degraded_count
        finally:
            chaos.clear()
            eng.shutdown()

    def test_cms_mirror_and_reconcile(self):
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=1500)
        eng = c._engine
        try:
            cms = c.get_count_min_sketch("deg-cms")
            cms.try_init(4, 256)
            for _ in range(5):
                cms.add("hot")
            assert cms.estimate("hot") >= 5
            self._degrade(
                eng, lambda: cms.add("x"),
                ("dispatch.cms_update_estimate",
                 "dispatch.cms_update_estimate_seq",
                 "dispatch.cms_update", "dispatch.cms_estimate"),
            )
            for _ in range(7):
                _flap(lambda: cms.add("hot"))
            assert _flap(lambda: cms.estimate("hot")) >= 12  # pre + degraded
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            assert cms.estimate("hot") >= 12  # reconciled to device
        finally:
            chaos.clear()
            eng.shutdown()


# -- randomized soak ---------------------------------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_soak_10k_ops_5pct():
    """10k mixed ops at a 5% seeded fault rate over every dispatch
    boundary: every future resolves (value or typed error), and after
    chaos lifts + breakers close, device state matches a golden oracle
    of the acknowledged-successful ops (monotone workloads, so 'applied
    but reported failed' can only ADD state, never lose it)."""
    rng = np.random.default_rng(42)
    c = make_client(breaker_failure_threshold=4, breaker_open_ms=100)
    eng = c._engine
    try:
        bf = c.get_bloom_filter("soak-bf")
        bf.try_init(200_000, 0.01)
        bs = c.get_bit_set("soak-bs")
        bs.set(0, True)
        hll = c.get_hyper_log_log("soak-hll")
        hll.add("seed")
        cms = c.get_count_min_sketch("soak-cms")
        cms.try_init(4, 1024)
        chaos.install(ChaosSchedule(
            seed=42, rate=0.05,
            points=("dispatch", "fetch", "h2d.staging"),
        ))
        ok_bloom, ok_bits, ok_hll, cms_ok = set(), set(), set(), 0
        resolved = 0
        for i in range(10_000):
            kind = i % 4
            try:
                if kind == 0:
                    k = f"b{rng.integers(0, 4000)}"
                    bf.add(k)
                    ok_bloom.add(k)
                elif kind == 1:
                    bit = int(rng.integers(0, 5000))
                    bs.set(bit, True)
                    ok_bits.add(bit)
                elif kind == 2:
                    k = f"h{rng.integers(0, 4000)}"
                    hll.add(k)
                    ok_hll.add(k)
                else:
                    cms.add("heavy")
                    cms_ok += 1
            except Exception:
                pass  # typed failure: resolved, not lost
            resolved += 1
        assert resolved == 10_000
        chaos.clear()
        # Let breakers close and mirrors reconcile, then verify against
        # the oracle of ACKNOWLEDGED ops (monotone: no acked write lost).
        deadline = time.monotonic() + 20
        while eng.health.board.open_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert eng.health.board.open_count() == 0
        assert not eng._mirrors
        missing = [k for k in ok_bloom if not bf.contains(k)]
        assert not missing, f"lost acked bloom adds: {missing[:5]}"
        lost_bits = [b for b in ok_bits if not bs.get(b)]
        assert not lost_bits, f"lost acked bitset sets: {lost_bits[:5]}"
        n = hll.count()
        assert n >= 0.8 * len(ok_hll)
        assert cms.estimate("heavy") >= cms_ok
    finally:
        chaos.clear()
        eng.shutdown()


# -- satellites: script watchdog + XAUTOCLAIM deleted ids --------------------


class TestScriptWatchdog:
    def _server(self, timeout_ms=150):
        from redisson_tpu.client import RedissonTpuClient
        from redisson_tpu.serve.resp import RespServer

        cfg = Config()
        cfg.enable_python_scripts = True
        cfg.script_timeout_ms = timeout_ms
        client = RedissonTpuClient(cfg)
        return client, RespServer(client, host="127.0.0.1", port=0)

    def test_busy_reply_while_script_runs_and_kill(self):
        client, server = self._server(timeout_ms=100)
        try:
            results = {}

            def run_loop():
                try:
                    results["script"] = server._cmd_EVAL(
                        [b"import time\nwhile True: time.sleep(0.005)", b"0"]
                    )
                except Exception as e:
                    results["script"] = e

            t = threading.Thread(target=run_loop, daemon=True)
            t.start()
            deadline = time.monotonic() + 5
            while not server._script_busy() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server._script_busy()
            # Another connection now gets BUSY...
            from redisson_tpu.serve.resp import _ConnCtx, RespError

            class _Ctx:
                authed = True
                in_multi = False
                in_exec = False
                subs = {}

            with pytest.raises(RespError, match="BUSY"):
                server._dispatch([b"PING"], _Ctx())
            # ...but SCRIPT KILL goes through and stops the loop.
            reply = server._dispatch([b"SCRIPT", b"KILL"], _Ctx())
            assert reply == b"+OK\r\n"
            t.join(timeout=5)
            assert not t.is_alive()
            assert isinstance(results["script"], RespError)
            assert "killed" in str(results["script"]).lower()
            assert server._script_run is None
            # Server serves normally again.
            assert server._dispatch([b"PING"], _Ctx()) == b"+PONG\r\n"
        finally:
            server.close()

    def test_nested_script_kill_uncatchable(self):
        """A script looping `try: redis.call(EVAL ...) except Exception`
        must still die to ONE SCRIPT KILL: the kill stays a BaseException
        through nested frames and only the outermost converts it."""
        client, server = self._server(timeout_ms=100)
        try:
            results = {}
            body = (
                "while True:\n"
                "    try:\n"
                "        redis.call('EVAL', '1 + 1', '0')\n"
                "    except Exception:\n"
                "        pass"
            )

            def run_loop():
                try:
                    results["script"] = server._cmd_EVAL([body.encode(), b"0"])
                except Exception as e:
                    results["script"] = e

            t = threading.Thread(target=run_loop, daemon=True)
            t.start()
            deadline = time.monotonic() + 5
            while not server._script_busy() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server._script_busy()
            from redisson_tpu.serve.resp import RespError

            class _Ctx:
                authed = True
                in_multi = False
                in_exec = False
                subs = {}

            assert server._dispatch([b"SCRIPT", b"KILL"], _Ctx()) == b"+OK\r\n"
            t.join(timeout=5)
            assert not t.is_alive(), "nested runaway survived SCRIPT KILL"
            assert isinstance(results["script"], RespError)
            assert server._script_run is None
        finally:
            server.close()

    def test_kill_without_script_is_notbusy(self):
        from redisson_tpu.serve.resp import RespError

        client, server = self._server()
        try:
            with pytest.raises(RespError, match="NOTBUSY"):
                server._cmd_SCRIPT([b"KILL"])
        finally:
            server.close()

    def test_compile_error_before_grid_lock(self):
        """A syntactically broken script fails at compile — without ever
        taking (or leaking) the grid lock."""
        client, server = self._server()
        try:
            with pytest.raises(SyntaxError):
                server._run_script("def broken(:", [], [])
            assert client._grid.lock.acquire(timeout=1)
            client._grid.lock.release()
            assert server._script_run is None
        finally:
            server.close()


class TestXAutoClaimDeleted:
    def test_deleted_ids_reported(self):
        from redisson_tpu.client import RedissonTpuClient

        client = RedissonTpuClient(Config())
        s = client.get_stream("xac")
        s.create_group("g", from_id="0-0")
        ids = [s.add({"v": str(i)}) for i in range(3)]
        s.read_group("g", "c1")
        # Remove one pending entry from the stream: the PEL still holds
        # it until a sweep notices.
        s.remove(ids[1])
        cursor, claimed, deleted = s.auto_claim(
            "g", "c2", 0, count=10, with_cursor=True
        )
        assert deleted == [ids[1]]
        assert [eid for eid, _ in claimed] == [ids[0], ids[2]]
        assert cursor == "0-0"

    def test_justid_leaves_delivery_count_untouched(self):
        """JUSTID is an inspection sweep: it claims ownership but must
        not inflate the PEL delivery counter (Redis contract — dead-
        letter logic keyed on the count would discard entries that were
        never actually redelivered)."""
        from redisson_tpu.client import RedissonTpuClient

        client = RedissonTpuClient(Config())
        s = client.get_stream("xacj")
        s.create_group("g", from_id="0-0")
        eid = s.add({"v": "1"})
        s.read_group("g", "c1")  # delivery count 1

        def count():
            with s._store.lock:
                return s._group("g")["pending"][
                    next(iter(s._group("g")["pending"]))
                ]["count"]

        base = count()
        _, claimed, _ = s.auto_claim(
            "g", "c2", 0, count=10, with_cursor=True, justid=True
        )
        assert [e for e, _ in claimed] == [eid]
        assert count() == base  # JUSTID: untouched
        s.auto_claim("g", "c3", 0, count=10, with_cursor=True)
        assert count() == base + 1  # full claim still increments

    def test_resp_reply_third_element(self):
        from redisson_tpu.client import RedissonTpuClient
        from redisson_tpu.serve.resp import RespServer

        client = RedissonTpuClient(Config())
        server = RespServer(client, host="127.0.0.1", port=0)
        try:
            s = client.get_stream("xac2")
            s.create_group("g", from_id="0-0")
            ids = [s.add({"v": str(i)}) for i in range(2)]
            s.read_group("g", "c1")
            s.remove(ids[0])
            reply = server._cmd_XAUTOCLAIM(
                [b"xac2", b"g", b"c2", b"0", b"0-0"]
            )
            # *3 header and a non-empty third (deleted-ids) array.
            assert reply.startswith(b"*3\r\n")
            assert ids[0].encode() in reply
            assert not reply.endswith(b"*0\r\n")  # deleted list is real
        finally:
            server.close()
