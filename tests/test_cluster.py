"""Cluster mode (ISSUE 12): slot math, the door's redirect protocol,
the slot-aware client's redirect handling (MOVED retries once after a
table refresh; ASK sends ASKING and does NOT touch the table; cross-slot
multi-key ops refuse client-side), pipelined scatter/gather, live slot
migration under concurrent writes (zero acked-write loss), and the
subprocess supervisor (slow-marked; the CI cluster-smoke job runs it).
"""

import socket
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.cluster.slotmap import SlotMap
from redisson_tpu.cluster.slots import (
    NSLOTS,
    command_keys,
    crc16,
    hash_tag,
    key_slot,
)
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.serve.wireutil import (
    ReplyError,
    decode_reply,
    wire_command,
)


# -- pure slot math -----------------------------------------------------------


def test_crc16_reference_vector():
    # The CRC16/XMODEM check value every redis-cluster implementation
    # agrees on — slot numbers printed here match redis-cli.
    assert crc16(b"123456789") == 0x31C3
    assert crc16(b"") == 0


def test_key_slot_and_hash_tags():
    assert key_slot(b"123456789") == 0x31C3 % NSLOTS
    assert 0 <= key_slot(b"foo") < NSLOTS
    # Hash tags: only the first non-empty {..} section hashes.
    assert hash_tag(b"{user:1}.cart") == b"user:1"
    assert key_slot(b"{user:1}.cart") == key_slot(b"{user:1}.profile")
    # Empty / unterminated braces hash the whole key.
    assert hash_tag(b"{}.x") == b"{}.x"
    assert hash_tag(b"a{b") == b"a{b"
    assert hash_tag(b"a{x}b{y}c") == b"x"
    # str and bytes agree.
    assert key_slot("k1") == key_slot(b"k1")


def test_command_keys_table():
    assert command_keys([b"GET", b"k"]) == [b"k"]
    assert command_keys([b"SET", b"k", b"v"]) == [b"k"]
    assert command_keys([b"MGET", b"a", b"b"]) == [b"a", b"b"]
    assert command_keys([b"MSET", b"a", b"1", b"b", b"2"]) == [b"a", b"b"]
    assert command_keys([b"RENAME", b"a", b"b"]) == [b"a", b"b"]
    assert command_keys(
        [b"ZUNIONSTORE", b"d", b"2", b"a", b"b", b"WEIGHTS", b"1", b"2"]
    ) == [b"d", b"a", b"b"]
    assert command_keys([b"EVAL", b"x", b"2", b"k1", b"k2", b"arg"]) == [
        b"k1", b"k2",
    ]
    assert command_keys([b"BLPOP", b"q1", b"q2", b"5"]) == [b"q1", b"q2"]
    assert command_keys(
        [b"XREAD", b"COUNT", b"2", b"STREAMS", b"s1", b"s2", b"0", b"0"]
    ) == [b"s1", b"s2"]
    # Keyless / admin / unknown commands route nowhere (served locally).
    for cmd in ([b"PING"], [b"CLUSTER", b"INFO"], [b"CONFIG", b"GET"],
                [b"WHATEVER", b"x"]):
        assert command_keys(cmd) == []
    # Malformed numeric fields degrade to keyless (the handler errors).
    assert command_keys([b"EVAL", b"x", b"notanint", b"k"]) == []


def test_slotmap_ranges_and_states():
    m = SlotMap.from_dict({"nodes": [
        {"id": "a", "host": "h", "port": 1, "slots": [[0, 9], [20, 29]]},
        {"id": "b", "host": "h", "port": 2, "slots": [[10, 19]]},
    ]})
    assert m.owner(5) == "a" and m.owner(15) == "b" and m.owner(25) == "a"
    assert m.owner(30) is None
    assert m.ranges("a") == [[0, 9], [20, 29]]
    assert m.assigned_count() == 30
    d = m.lookup(15)
    assert d.owner == "b" and d.owner_addr == ("h", 2)
    m.set_migrating(15, "a")
    m.set_importing(15, "b")  # (as seen on the other node)
    assert m.migration_counts() == (1, 1)
    closed = m.set_owner(15, "a")
    assert closed["was_migrating"] == "a"
    assert m.migration_counts() == (0, 0)
    assert m.owner(15) == "a"
    with pytest.raises(KeyError):
        m.set_owner(3, "nope")
    # Round-trips through the topology-file format.
    assert SlotMap.from_dict(m.to_dict()).ranges("b") == m.ranges("b")


# -- in-process two-node cluster ---------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Cluster2:
    """Two cluster-mode RespServers in this process splitting the slot
    space at 8192 (host engine: the door logic under test is
    engine-agnostic and this keeps the fixture cheap)."""

    def __init__(self):
        pa, pb = _free_port(), _free_port()
        topo = {"nodes": [
            {"id": "A", "host": "127.0.0.1", "port": pa,
             "slots": [[0, 8191]]},
            {"id": "B", "host": "127.0.0.1", "port": pb,
             "slots": [[8192, NSLOTS - 1]]},
        ]}
        self.nodes = {}
        for nid, port in (("A", pa), ("B", pb)):
            cfg = Config()
            cfg.cluster_enabled = True
            cfg.cluster_topology = topo
            cfg.cluster_node_id = nid
            client = redisson_tpu.create(cfg)
            self.nodes[nid] = (client, RespServer(client, port=port))
        self.addr = {"A": ("127.0.0.1", pa), "B": ("127.0.0.1", pb)}

    def owner_of(self, key) -> str:
        return "A" if key_slot(key) < 8192 else "B"

    def key_for(self, nid: str, prefix: str = "k") -> str:
        i = 0
        while True:
            k = f"{prefix}{i}"
            if self.owner_of(k) == nid:
                return k
            i += 1

    def close(self):
        for client, server in self.nodes.values():
            server.close()
            client.shutdown()


@pytest.fixture(scope="module")
def cluster2():
    c = _Cluster2()
    yield c
    c.close()


def _raw(addr, cmds, timeout=10.0):
    """Scripted wire exchange: send all, decode len(cmds) replies."""
    sock = socket.create_connection(addr, timeout=timeout)
    try:
        sock.sendall(b"".join(wire_command(c) for c in cmds))
        buf, out, pos = b"", [], 0
        while len(out) < len(cmds):
            chunk = sock.recv(1 << 16)
            assert chunk, "server closed early"
            buf += chunk
            while len(out) < len(cmds):
                try:
                    val, pos = decode_reply(buf, pos)
                except (IndexError, ValueError):
                    break
                out.append(val)
        return out
    finally:
        sock.close()


def test_door_moved_redirect_and_local_serve(cluster2):
    ka = cluster2.key_for("A", "dm")
    kb = cluster2.key_for("B", "dm")
    # Right node serves; wrong node redirects with slot + owner addr.
    assert _raw(cluster2.addr["A"], [["SET", ka, "v"]])[0] == b"OK"
    (moved,) = _raw(cluster2.addr["A"], [["GET", kb]])
    assert isinstance(moved, ReplyError) and moved.code == "MOVED"
    _, slot, addr = str(moved).split(" ")
    assert int(slot) == key_slot(kb)
    host, _, port = addr.rpartition(":")
    assert (host, int(port)) == cluster2.addr["B"]
    # Keyless commands serve on any node.
    assert _raw(cluster2.addr["B"], [["PING"]])[0] == b"PONG"


def test_door_crossslot_and_hash_tag_colocation(cluster2):
    a = cluster2.key_for("A", "csa")
    b = cluster2.key_for("B", "csb")
    (err,) = _raw(cluster2.addr["A"], [["MSET", a, "1", b, "2"]])
    assert isinstance(err, ReplyError) and err.code == "CROSSSLOT"
    # Hash tags co-locate: the same multi-key op with a shared tag runs.
    node = cluster2.owner_of("{cs}x")
    (ok,) = _raw(cluster2.addr[node],
                 [["MSET", "{cs}x", "1", "{cs}y", "2"]])
    assert ok == b"OK"


def test_door_cluster_command_surface(cluster2):
    addr = cluster2.addr["A"]
    myid, info, slots, keyslot = _raw(addr, [
        ["CLUSTER", "MYID"], ["CLUSTER", "INFO"], ["CLUSTER", "SLOTS"],
        ["CLUSTER", "KEYSLOT", "{user:1}.x"],
    ])
    assert myid == b"A"
    assert b"cluster_enabled:1" in info
    assert b"cluster_known_nodes:2" in info
    assert keyslot == key_slot("{user:1}.x")
    ranges = {(e[0], e[1]): (e[2][2], e[2][1]) for e in slots}
    assert ranges[(0, 8191)] == (b"A", cluster2.addr["A"][1])
    assert ranges[(8192, NSLOTS - 1)] == (b"B", cluster2.addr["B"][1])
    (shards,) = _raw(addr, [["CLUSTER", "SHARDS"]])
    assert len(shards) == 2 and shards[0][0] == b"slots"
    (nodes,) = _raw(addr, [["CLUSTER", "NODES"]])
    assert b"myself" in nodes and b"master" in nodes
    # INFO's cluster section carries the same facts.
    (full,) = _raw(addr, [["INFO", "cluster"]])
    assert b"cluster_enabled:1" in full and b"cluster_my_slots:8192" in full


def test_door_asking_is_one_shot(cluster2):
    """An IMPORTING slot serves only ASKING-prefixed commands; the flag
    does not persist past one keyed command."""
    tag = "{ask1}"
    slot = key_slot(tag)
    src = cluster2.owner_of(tag)
    dst = "B" if src == "A" else "A"
    dst_addr = cluster2.addr[dst]
    _raw(dst_addr, [["CLUSTER", "SETSLOT", str(slot), "IMPORTING", src]])
    try:
        key = tag + "k"
        r = _raw(dst_addr, [["ASKING"], ["SET", key, "v"],
                            ["GET", key]])
        assert r[0] == b"OK" and r[1] == b"OK"
        # Third command ran WITHOUT asking: redirected home.
        assert isinstance(r[2], ReplyError) and r[2].code == "MOVED"
        # ANY intervening command consumes the license, keyed or not
        # (Redis clears the flag after the next command, full stop):
        # ASKING, PING, GET must NOT serve the importing slot.
        r = _raw(dst_addr, [["ASKING"], ["PING"], ["GET", key]])
        assert r[1] == b"PONG"
        assert isinstance(r[2], ReplyError) and r[2].code == "MOVED"
    finally:
        _raw(dst_addr, [["CLUSTER", "SETSLOT", str(slot), "STABLE"]])
        _raw(dst_addr, [["ASKING"], ["DEL", tag + "k"]])


def test_door_pipelined_runs_do_not_skip_redirects(cluster2):
    """A pipelined same-key run that WOULD fuse must still redirect
    per-command when the key's slot lives elsewhere (the vectorizer
    barrier for non-plainly-served slots)."""
    kb = cluster2.key_for("B", "fuse")
    cmds = [["BF.ADD", kb, "x%d" % i] for i in range(8)]
    replies = _raw(cluster2.addr["A"], cmds)
    assert all(
        isinstance(r, ReplyError) and r.code == "MOVED" for r in replies
    )
    # ...and the same run on the OWNER fuses/serves normally.
    replies = _raw(cluster2.addr["B"],
                   [["BF.RESERVE", kb, "0.01", "1000"]] + cmds)
    assert replies[0] == b"OK"
    assert all(r in (0, 1) for r in replies[1:])


def test_multi_rejects_wrong_slot_member_at_queue_time(cluster2):
    """A MULTI member whose slot lives elsewhere surfaces its -MOVED at
    queue time and poisons the transaction — EXEC can never half-apply
    a cross-node transaction."""
    ka = cluster2.key_for("A", "txa")
    kb = cluster2.key_for("B", "txb")
    r = _raw(cluster2.addr["A"], [
        ["MULTI"], ["SET", ka, "1"], ["SET", kb, "2"], ["EXEC"],
        ["EXISTS", ka],
    ])
    assert r[0] == b"OK" and r[1] == b"QUEUED"
    assert isinstance(r[2], ReplyError) and r[2].code == "MOVED"
    assert isinstance(r[3], ReplyError) and r[3].code == "EXECABORT"
    assert r[4] == 0  # nothing partial ran


def test_migration_refuses_container_slots_cleanly(cluster2):
    """A slot holding an unmigratable container kind refuses BEFORE any
    migration state exists (CLUSTER MIGRATABLE pre-flight) and stays
    fully serveable."""
    from redisson_tpu.cluster.supervisor import migrate_slot

    tag = "{migrlist}"
    slot = key_slot(tag)
    src_id = cluster2.owner_of(tag)
    dst_id = "B" if src_id == "A" else "A"
    src, dst = cluster2.addr[src_id], cluster2.addr[dst_id]
    _raw(src, [["RPUSH", tag + "l", "a", "b"]])
    try:
        with pytest.raises(RuntimeError, match="refuses to migrate"):
            migrate_slot(slot, src, dst, notify=cluster2.addr.values())
        # No limbo: neither node carries importing/migrating state.
        for addr in (src, dst):
            (info,) = _raw(addr, [["CLUSTER", "INFO"]])
            assert b"cluster_slots_importing:0" in info
            assert b"cluster_slots_migrating:0" in info
        # ...and the container still serves on the source.
        (n,) = _raw(src, [["LLEN", tag + "l"]])
        assert n == 2
    finally:
        _raw(src, [["DEL", tag + "l"]])


# -- slot-aware client --------------------------------------------------------


def test_client_routes_and_scatter_gathers(cluster2):
    from redisson_tpu.cluster.client import ClusterClient

    cc = ClusterClient([cluster2.addr["A"]])
    try:
        keys = ["sg%d" % i for i in range(64)]
        assert {cluster2.owner_of(k) for k in keys} == {"A", "B"}
        acks = cc.execute_many([("SET", k, "v" + k) for k in keys])
        assert all(a == b"OK" for a in acks)
        got = cc.execute_many([("GET", k) for k in keys])
        assert got == [("v" + k).encode() for k in keys]
        # The batch fanned out to both nodes as pipelined legs.
        assert cc.stats["scatter_batches"] == 2
        assert cc.stats["scatter_legs"] == 4
        # Mixed keyless + keyed batches demux in order too.
        r = cc.execute_many([("PING",), ("GET", keys[0]), ("PING",)])
        assert r == [b"PONG", ("v" + keys[0]).encode(), b"PONG"]
    finally:
        cc.close()


def test_client_crossslot_raises_before_sending(cluster2):
    from redisson_tpu.cluster.client import ClusterClient, CrossSlotError

    cc = ClusterClient([cluster2.addr["A"]])
    try:
        a = cluster2.key_for("A", "ccs")
        b = cluster2.key_for("B", "ccs")
        with pytest.raises(CrossSlotError):
            cc.execute("MSET", a, "1", b, "2")
        # Hash-tagged keys co-locate and pass.
        assert cc.execute("MSET", "{ct}a", "1", "{ct}b", "2") == b"OK"
    finally:
        cc.close()


class _FakeNode(threading.Thread):
    """Scripted node: answers CLUSTER SLOTS claiming every slot, and
    every OTHER command via the ``script`` callable (argv -> bytes
    frame).  Counts commands by name."""

    def __init__(self, script):
        super().__init__(daemon=True)
        self._script = script
        self.counts: dict = {}
        self.log: list = []
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.addr = self._sock.getsockname()
        self._stop = False
        self.start()

    def run(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn):
        buf, pos = b"", 0
        try:
            while True:
                chunk = conn.recv(1 << 16)
                if not chunk:
                    return
                buf += chunk
                while True:
                    try:
                        argv, pos = decode_reply(buf, pos)
                    except (IndexError, ValueError):
                        break
                    name = argv[0].decode().upper()
                    self.counts[name] = self.counts.get(name, 0) + 1
                    self.log.append(argv)
                    if name == "CLUSTER" and argv[1].upper() == b"SLOTS":
                        host, port = self.addr
                        conn.sendall(
                            b"*1\r\n*3\r\n:0\r\n:16383\r\n*3\r\n"
                            + b"$%d\r\n%s\r\n" % (len(host), host.encode())
                            + b":%d\r\n$4\r\nfake\r\n" % port
                        )
                    else:
                        conn.sendall(self._script(argv))
        except OSError:
            pass
        finally:
            conn.close()

    def close(self):
        self._stop = True
        self._sock.close()


def test_client_moved_refreshes_table_and_retries_exactly_once():
    """-MOVED → one table refresh + ONE retry; a second MOVED surfaces
    as the error instead of looping."""
    from redisson_tpu.cluster.client import ClusterClient

    fake = _FakeNode(lambda argv: b"+OK\r\n")
    # Always bounce GETs back at ourselves: an unrecoverable MOVED loop.
    fake._script = lambda argv: (
        b"-MOVED %d %s:%d\r\n" % (
            key_slot(argv[1]), fake.addr[0].encode(), fake.addr[1]
        )
        if argv[0].upper() == b"GET" else b"+OK\r\n"
    )
    cc = ClusterClient([fake.addr])
    try:
        refreshes_before = cc.stats["table_refreshes"]
        with pytest.raises(ReplyError) as ei:
            cc.execute("GET", "k")
        assert ei.value.code == "MOVED"
        # Initial send + exactly one retry — never a third.
        assert fake.counts["GET"] == 2
        # The MOVED triggered a table refresh (one more CLUSTER SLOTS).
        assert cc.stats["table_refreshes"] == refreshes_before + 1
        assert cc.stats["moved"] == 1
    finally:
        cc.close()
        fake.close()


def test_client_ask_sends_asking_and_keeps_table():
    """-ASK → ASKING + the command at the named node, and the slot
    table is NOT updated (the source still owns the slot)."""
    from redisson_tpu.cluster.client import ClusterClient

    target = _FakeNode(
        lambda argv: b"+OK\r\n" if argv[0].upper() == b"ASKING"
        else b"$3\r\nval\r\n"
    )
    source = _FakeNode(lambda argv: b"+OK\r\n")
    source._script = lambda argv: (
        b"-ASK %d %s:%d\r\n" % (
            key_slot(argv[1]), target.addr[0].encode(), target.addr[1]
        )
        if argv[0].upper() == b"GET" else b"+OK\r\n"
    )
    cc = ClusterClient([source.addr])
    try:
        slot = key_slot("k")
        assert cc.slot_addr(slot) == source.addr
        assert cc.execute("GET", "k") == b"val"
        # The target saw the handshake immediately before the command.
        names = [a[0].decode().upper() for a in target.log]
        assert names == ["ASKING", "GET"]
        # Table untouched: the slot still routes to the source...
        assert cc.slot_addr(slot) == source.addr
        assert cc.stats["ask"] == 1 and cc.stats["moved"] == 0
        # ...so the NEXT execute asks the source again.
        assert cc.execute("GET", "k") == b"val"
        assert source.counts["GET"] == 2
    finally:
        cc.close()
        source.close()
        target.close()


# -- live slot migration ------------------------------------------------------


def test_live_migration_under_traffic_loses_no_acked_write(cluster2):
    """The acceptance differential: a writer keeps SETting hash-tagged
    keys in one slot while that slot live-migrates between the nodes;
    afterwards EVERY acked write must read back through the refreshed
    routing table."""
    from redisson_tpu.cluster.client import ClusterClient
    from redisson_tpu.cluster.supervisor import migrate_slot

    tag = "{mig}"
    slot = key_slot(tag)
    src_id = cluster2.owner_of(tag)
    dst_id = "B" if src_id == "A" else "A"
    src, dst = cluster2.addr[src_id], cluster2.addr[dst_id]
    acked: dict = {}
    failures: list = []
    stop = threading.Event()

    def writer():
        w = ClusterClient([cluster2.addr["A"]])
        i = 0
        try:
            while not stop.is_set():
                k = f"{tag}w{i}"
                if w.execute("SET", k, f"v{i}") == b"OK":
                    acked[k] = f"v{i}"
                i += 1
        except Exception as e:  # surfaced below: a writer must never die
            failures.append(e)
        finally:
            w.close()

    t = threading.Thread(target=writer)
    t.start()
    time.sleep(0.25)  # let writes land on the source first
    moved = migrate_slot(slot, src, dst, notify=cluster2.addr.values())
    time.sleep(0.15)  # post-finalize traffic exercises MOVED-chasing
    stop.set()
    t.join()
    assert not failures, failures
    assert moved > 0
    assert len(acked) > moved  # writes continued during + after
    cc = ClusterClient([cluster2.addr["A"]])
    try:
        assert cc.slot_addr(slot) == dst
        # Differential: every acked write reads back identical.
        got = cc.execute_many([("GET", k) for k in acked])
        lost = [
            k for k, g in zip(acked, got) if g != acked[k].encode()
        ]
        assert lost == [], f"{len(lost)} acked writes lost: {lost[:5]}"
        # The source kept nothing behind in the slot.
        (count,) = _raw(src, [["CLUSTER", "COUNTKEYSINSLOT", str(slot)]])
        assert count == 0
    finally:
        cc.close()


def test_migration_preserves_sketch_objects(cluster2):
    """Sketch keys ride the same DUMP/RESTORE machinery: a bloom filter
    migrates with its bits intact."""
    from redisson_tpu.cluster.client import ClusterClient
    from redisson_tpu.cluster.supervisor import migrate_slot

    tag = "{migbf}"
    slot = key_slot(tag)
    src_id = cluster2.owner_of(tag)
    dst_id = "B" if src_id == "A" else "A"
    cc = ClusterClient([cluster2.addr["A"]])
    try:
        key = tag + "bf"
        cc.execute("BF.RESERVE", key, "0.01", "1000")
        for i in range(32):
            cc.execute("BF.ADD", key, "item%d" % i)
        migrate_slot(slot, cluster2.addr[src_id], cluster2.addr[dst_id],
                     notify=cluster2.addr.values())
        cc.refresh_slots()
        assert all(
            cc.execute("BF.EXISTS", key, "item%d" % i) == 1
            for i in range(32)
        )
        assert cc.execute("BF.EXISTS", key, "never-added") in (0, 1)
        # And it genuinely moved: the old owner redirects now.
        (r,) = _raw(cluster2.addr[src_id], [["BF.EXISTS", key, "item0"]])
        assert isinstance(r, ReplyError) and r.code == "MOVED"
    finally:
        cc.close()


# -- subprocess supervisor (the CI cluster-smoke shape) -----------------------


@pytest.mark.slow
def test_supervisor_three_nodes_end_to_end():
    """Spawn 3 real server processes, route traffic across them,
    live-migrate a slot, and assert a clean shutdown with no orphans."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(n_nodes=3, platform="cpu")
    clean = None
    try:
        sup.start()
        assert sup.alive() == [0, 1, 2]
        cc = sup.client()
        try:
            keys = ["sv%d" % i for i in range(96)]
            acks = cc.execute_many(
                [("SET", k, "v" + k) for k in keys]
            )
            assert all(a == b"OK" for a in acks)
            # The population genuinely spans all three nodes.
            assert cc.stats["scatter_legs"] >= 3
            got = cc.execute_many([("GET", k) for k in keys])
            assert got == [("v" + k).encode() for k in keys]
            # Live migration across processes.
            slot = key_slot("{sup}")
            per = NSLOTS // 3
            dst_index = (min(slot // per, 2) + 1) % 3
            cc.execute("SET", "{sup}k", "before")
            moved = sup.migrate_slot(slot, dst_index)
            assert moved >= 1
            cc.refresh_slots()
            assert cc.execute("GET", "{sup}k") == b"before"
            assert cc.slot_addr(slot) == sup.addrs[dst_index]
        finally:
            cc.close()
    finally:
        clean = sup.shutdown()
        assert sup.alive() == []
    assert clean, "nodes needed SIGKILL: unclean supervisor shutdown"
