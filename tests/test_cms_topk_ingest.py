"""Engine-shared CMS top-K + the Topic→CMS ingest bridge (config 5).

Round-2 review flagged the per-client-instance top-K dict (two handles to
one sketch disagreed); the table now lives on the engine, name-addressed,
and ``top_k()`` re-estimates candidates on device.
"""

import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve import TopicCmsBridge


@pytest.fixture(params=["tpu", "host"])
def client(request):
    cfg = Config()
    if request.param == "tpu":
        cfg = cfg.use_tpu_sketch(min_bucket=64)
    c = redisson_tpu.create(cfg)
    yield c
    c.shutdown()


def zipf_stream(rng, n, n_keys=1000, a=1.3):
    keys = rng.zipf(a, size=n) % n_keys
    return keys.astype(np.uint64)


class TestSharedTopK:
    def test_two_handles_share_one_table(self, client):
        h1 = client.get_count_min_sketch("shared-cms")
        h1.try_init(4, 1 << 12, track_top_k=5)
        h2 = client.get_count_min_sketch("shared-cms")  # second handle
        h1.add_all(["a"] * 50 + ["b"] * 30 + ["c"] * 10)
        h2.add_all(["d"] * 80 + ["a"] * 25)
        # Both handles see the union of both handles' adds.
        for h in (h1, h2):
            top = h.top_k(2)
            assert [k for k, _ in top] == ["d", "a"]
            assert top[0][1] >= 80
            assert top[1][1] >= 75

    def test_topk_reestimates_current_counts(self, client):
        cms = client.get_count_min_sketch("re-est")
        cms.try_init(4, 1 << 12, track_top_k=3)
        cms.add_all(["x"] * 10 + ["y"] * 5)
        cms.add_all(["y"] * 20)  # y overtakes x
        top = cms.top_k(2)
        assert [k for k, _ in top] == ["y", "x"]

    def test_heavy_hitters_found_in_zipf_stream(self, client):
        cms = client.get_count_min_sketch("zipf")
        cms.try_init(5, 1 << 14, track_top_k=10)
        rng = np.random.default_rng(0)
        stream = zipf_stream(rng, 200_000)
        for i in range(0, len(stream), 8192):
            cms.add_all(stream[i : i + 8192])
        true_counts = np.bincount(stream.astype(np.int64))
        true_top = set(np.argsort(-true_counts)[:10].tolist())
        got = {int(k) for k, _ in cms.top_k(10)}
        # CMS overestimates slightly; demand >= 8/10 recall.
        assert len(got & true_top) >= 8, (got, true_top)

    def test_delete_drops_table(self, client):
        cms = client.get_count_min_sketch("drop-cms")
        cms.try_init(4, 1 << 10, track_top_k=3)
        cms.add_all(["k"] * 5)
        assert cms.top_k(1)
        cms.delete()
        assert client._engine.topk.candidates("drop-cms") == []


class TestTopicCmsBridge:
    def test_stream_topk_end_to_end(self, client):
        cms = client.get_count_min_sketch("stream-cms")
        cms.try_init(5, 1 << 14, track_top_k=10)
        bridge = TopicCmsBridge(
            client, "events", "stream-cms", batch_size=4096,
            flush_interval_s=0.01,
        )
        topic = client.get_topic("events")
        rng = np.random.default_rng(1)
        stream = zipf_stream(rng, 100_000)
        for key in stream[:2000]:  # publish one-by-one (listener path)
            topic.publish(int(key))
        # Bulk-feed the rest through the same listener callback (the
        # pub/sub delivery pool is the bottleneck for per-message publish
        # in-process; config-5's bench uses the same shortcut).
        for i in range(2000, len(stream), 4096):
            for key in stream[i : i + 4096]:
                bridge._on_message("events", int(key))
        client._topic_bus.drain()
        bridge.close()
        assert bridge.events_ingested == len(stream)
        true_counts = np.bincount(stream.astype(np.int64))
        true_top = set(np.argsort(-true_counts)[:10].tolist())
        got = {int(k) for k, _ in cms.top_k(10)}
        assert len(got & true_top) >= 8, (got, true_top)
        # Estimates are within CMS error of the true counts.
        heaviest = int(np.argmax(true_counts))
        est = cms.estimate(heaviest)
        assert est >= true_counts[heaviest]
        assert est <= true_counts[heaviest] + len(stream) // (1 << 12)

    def test_deadline_flush(self, client):
        cms = client.get_count_min_sketch("deadline-cms")
        cms.try_init(4, 1 << 10, track_top_k=3)
        bridge = TopicCmsBridge(
            client, "slow-events", "deadline-cms", batch_size=1 << 20,
            flush_interval_s=0.02,
        )
        topic = client.get_topic("slow-events")
        topic.publish("only-one")
        deadline = time.time() + 3.0
        while time.time() < deadline and cms.estimate("only-one") < 1:
            time.sleep(0.02)
        assert cms.estimate("only-one") == 1  # flushed by deadline, not size
        bridge.close()


def test_ttl_expiry_drops_topk_table():
    """r3 review: a sketch's shared top-K table dies with its TTL — a
    successor under the same name must not inherit ghost candidates."""
    import redisson_tpu
    from redisson_tpu import Config

    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    try:
        cms = c.get_count_min_sketch("ttl-topk")
        cms.try_init(4, 1 << 10, track_top_k=3)
        cms.add_all(["ghost"] * 9)
        assert cms.top_k(1)[0][0] == "ghost"
        cms.expire(0.05)
        time.sleep(0.1)
        assert not cms.is_exists()
        assert c._engine.topk.candidates("ttl-topk") == []
    finally:
        c.shutdown()


class TestDrainExactness:
    """TopicBus.drain must be EXACT: the old pool-rendezvous barrier broke
    silently at its timeout, and teardown then dropped queued deliveries —
    caught in the full-geometry bench as a NEGATIVE signed CMS estimate
    error (a lossless pipe can never undercount)."""

    def test_no_event_loss_through_bridge_teardown(self, client):
        from redisson_tpu.serve import TopicCmsBridge

        cms = client.get_count_min_sketch("drain-cms")
        cms.try_init(4, 1 << 12, track_top_k=5)
        bridge = TopicCmsBridge(
            client, "drain-ev", "drain-cms",
            batch_size=1 << 12, flush_interval_s=0.05,
        )
        topic = client.get_topic("drain-ev")
        rng = np.random.default_rng(4)
        n, chunk = 120_000, 1 << 12
        stream = (rng.zipf(1.2, size=n) % 500).astype(np.uint64)
        for i in range(0, n, chunk):
            topic.publish(stream[i : i + chunk])
        assert client._topic_bus.drain() is True
        bridge.close()
        true = np.bincount(stream.astype(np.int64), minlength=500)
        for key in np.argsort(-true)[:5]:
            est = cms.estimate(np.uint64(key))
            assert est >= true[key], (key, est, true[key])

    def test_drain_timeout_reports_pending(self, client):
        import threading
        import time

        release = threading.Event()
        topic = client.get_topic("drain-slow")
        topic.add_listener(lambda ch, m: release.wait(5.0))
        topic.publish(b"x")
        t0 = time.monotonic()
        assert client._topic_bus.drain(timeout=0.3) is False
        assert time.monotonic() - t0 < 2.0
        release.set()
        assert client._topic_bus.drain(timeout=10.0) is True

    def test_close_without_prior_drain_loses_nothing(self, client):
        # The teardown race the old close() had: deliveries queued on the
        # bus (targets snapshotted at publish) start AFTER flush() but
        # BEFORE _closed — close() now waits out its channel first.
        from redisson_tpu.serve import TopicCmsBridge

        cms = client.get_count_min_sketch("close-cms")
        cms.try_init(4, 1 << 12)
        bridge = TopicCmsBridge(
            client, "close-ev", "close-cms",
            batch_size=1 << 14, flush_interval_s=5.0,  # no deadline help
        )
        topic = client.get_topic("close-ev")
        n, chunk = 64_000, 1 << 11
        stream = np.arange(n, dtype=np.uint64) % 97
        for i in range(0, n, chunk):
            topic.publish(stream[i : i + chunk])
        bridge.close()  # deliberately NO bus drain first
        for key in (0, 1, 96):
            assert cms.estimate(np.uint64(key)) >= int(
                np.sum(stream == key)
            ), key


class TestArrayCoalescing:
    """Array-message launch coalescing (max_launch_events): weights stay
    aligned, dtype boundaries split launches, sub-threshold buffers
    flush on their own deadline clock."""

    def test_weights_align_across_mixed_messages(self, client):
        from redisson_tpu.serve import TopicCmsBridge

        cms = client.get_count_min_sketch("co-cms")
        cms.try_init(4, 1 << 12)
        # weight_fn: None for the first array (default-1), per-event for
        # the second, scalar for the third.
        state = {"n": 0}

        def wf(arr):
            state["n"] += 1
            if state["n"] == 1:
                return None
            if state["n"] == 2:
                return np.full(len(arr), 3, np.int64)
            return np.int64(5)

        bridge = TopicCmsBridge(
            client, "co-ev", "co-cms", weight_fn=wf,
            flush_interval_s=0.05, max_launch_events=1 << 20,
        )
        topic = client.get_topic("co-ev")
        topic.publish(np.array([1, 1], dtype=np.uint64))       # w=1 each
        topic.publish(np.array([2, 2], dtype=np.uint64))       # w=3 each
        topic.publish(np.array([3], dtype=np.uint64))          # w=5
        client._topic_bus.drain()
        bridge.close()
        assert cms.estimate(np.uint64(1)) == 2
        assert cms.estimate(np.uint64(2)) == 6
        assert cms.estimate(np.uint64(3)) == 5

    def test_subthreshold_arrays_flush_on_deadline(self, client):
        import time as _time

        from redisson_tpu.serve import TopicCmsBridge

        cms = client.get_count_min_sketch("dl-cms")
        cms.try_init(4, 1 << 12)
        bridge = TopicCmsBridge(
            client, "dl-ev", "dl-cms",
            flush_interval_s=0.05, max_launch_events=1 << 20,
        )
        topic = client.get_topic("dl-ev")
        topic.publish(np.array([9, 9, 9], dtype=np.uint64))
        client._topic_bus.drain()
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            if cms.estimate(np.uint64(9)) == 3:
                break
            _time.sleep(0.05)
        assert cms.estimate(np.uint64(9)) == 3, "deadline flush starved"
        bridge.close()

    def test_dtype_boundary_splits_launches(self, client):
        from redisson_tpu.serve import TopicCmsBridge

        cms = client.get_count_min_sketch("dt-cms")
        cms.try_init(4, 1 << 12)
        bridge = TopicCmsBridge(
            client, "dt-ev", "dt-cms",
            flush_interval_s=0.05, max_launch_events=1 << 20,
        )
        topic = client.get_topic("dt-ev")
        # uint64 then uint32: concatenating would upcast the uint32 keys
        # and change their codec encoding — they must launch separately.
        topic.publish(np.array([7, 7], dtype=np.uint64))
        topic.publish(np.array([7, 7, 7], dtype=np.uint32))
        client._topic_bus.drain()
        bridge.close()
        # Each dtype keeps ITS OWN codec encoding (np.uint32(7) and
        # np.uint64(7) are distinct keys under the default codec) — an
        # upcasting concat would have merged them into one phantom key.
        assert cms.estimate(np.uint64(7)) == 2
        assert cms.estimate(np.uint32(7)) == 3
