"""Coalescer: correctness under concurrency, merging, ordering, metrics.

The reference's substitute for race detection is hammering the API from
thread pools (SURVEY.md §4 BaseConcurrentTest#testMultiInstanceConcurrency);
we do the same against the coalesced TPU engine and check results against
golden models.
"""

import threading

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


def _client(**kw):
    return redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64, **kw))


def test_coalesced_ops_merge_into_batches():
    cl = _client(batch_window_us=5000, max_batch=4096)
    bf = cl.get_bloom_filter("c1")
    bf.try_init(10_000, 0.01)
    futs = [bf.add_async(f"k{i}") for i in range(50)]
    results = [f.result() for f in futs]
    assert all(results)
    m = cl.get_metrics()
    # 50 single-op submits must have merged into far fewer device batches.
    assert m["batches_total"] <= 10, m
    assert m["ops_total"] == 50
    assert m["mean_batch_occupancy"] >= 5
    cl.shutdown()


def test_read_your_writes_ordering():
    cl = _client(batch_window_us=2000)
    bf = cl.get_bloom_filter("c2")
    bf.try_init(1000, 0.01)
    for i in range(20):
        f = bf.add_async(f"x{i}")
        assert bf.contains(f"x{i}"), i  # contains segment flushes after add
        assert f.result()
    cl.shutdown()


def test_concurrent_multi_tenant_hammer():
    cl = _client(batch_window_us=500)
    n_threads, n_keys = 8, 300
    bfs = []
    for t in range(n_threads):
        bf = cl.get_bloom_filter(f"tenant{t}")
        bf.try_init(5000, 0.01)
        bfs.append(bf)
    errors = []

    def worker(t):
        try:
            bf = bfs[t]
            keys = [f"t{t}:k{i}" for i in range(n_keys)]
            futs = [bf.add_async(k) for k in keys]
            for f in futs:
                f.result()
            assert bf.contains_all(keys) == n_keys
            # other tenants' keys: near-zero hits (p=0.01 target)
            other = bf.contains_all([f"t{(t+1) % n_threads}:k{i}" for i in range(n_keys)])
            assert other < n_keys * 0.05
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors
    cl.shutdown()


def test_concurrent_hll_and_cms():
    cl = _client(batch_window_us=500)
    h = cl.get_hyper_log_log("ch")
    c = cl.get_count_min_sketch("cc")
    c.try_init(4, 1 << 12)
    errors = []

    def hll_worker(t):
        try:
            h.add_all([f"u{t}:{i}" for i in range(2000)])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def cms_worker(t):
        try:
            for _ in range(5):
                c.add_all(["hot"] * 20)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=hll_worker, args=(t,)) for t in range(4)]
    threads += [threading.Thread(target=cms_worker, args=(t,)) for t in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors, errors
    est = h.count()
    assert abs(est - 8000) / 8000 < 0.05
    assert c.estimate("hot") == 400
    cl.shutdown()


def test_hll_add_changed_flags_coalesced():
    cl = _client(batch_window_us=3000)
    h = cl.get_hyper_log_log("flags")
    f1 = h.add_async("a")
    f2 = h.add_async("a")  # same key, same batch: second must be False
    f3 = h.add_async("b")
    assert f1.result() is True
    assert f2.result() is False
    assert f3.result() is True
    cl.shutdown()


def test_bitset_grow_with_queued_ops():
    cl = _client(batch_window_us=5000)
    bs = cl.get_bit_set("grow")
    futs = [bs._engine.bitset_set("grow", [i], True) for i in range(10)]
    bs.set(100_000)  # forces class migration; must drain queued sets first
    for f in futs:
        f.result()
    assert bs.cardinality() == 11
    assert bs.get_many(np.arange(10)).all()
    cl.shutdown()


def test_shutdown_rejects_new_ops():
    cl = _client()
    bf = cl.get_bloom_filter("sd")
    bf.try_init(100, 0.01)
    bf.add("x")
    cl.shutdown()
    with pytest.raises(RuntimeError):
        bf.add_async("y")


def test_phase_aware_merge_cap_unit():
    """ISSUE 6 satellite: merge-at-pop may exceed the static max_batch up
    to max_batch_slow_phase ONLY while the put-RT EWMA says the link is
    in its per-transfer-RT phase; the fast phase keeps the static cap."""
    import time

    from redisson_tpu.executor.coalescer import BatchCoalescer

    class _Lazy:
        def __init__(self, n):
            self._n = n

        def result(self, timeout=None):
            return np.zeros(self._n)

    for slow, want_max in ((True, 32), (False, 8)):
        gate = threading.Event()
        launches = []

        def block_dispatch(cols):
            gate.wait(timeout=10)
            return _Lazy(len(cols[0]))

        def rec_dispatch(cols):
            launches.append(len(cols[0]))
            return _Lazy(len(cols[0]))

        c = BatchCoalescer(
            batch_window_us=100, max_batch=8, max_inflight=4,
            adaptive_window=False, adaptive_inflight=False,
            max_batch_slow_phase=32,
        )
        assert c.merge_cap() == 8
        # Stall the flush thread inside a first launch so a backlog of
        # same-key segments builds behind it deterministically.
        stall = c.submit("a", block_dispatch, (np.zeros(1),), 1)
        for _ in range(200):
            with c._lock:
                if c._inflight or not c._order:
                    break
            time.sleep(0.005)
        futs = [
            c.submit("b", rec_dispatch, (np.zeros(1),), 1)
            for _ in range(32)
        ]
        if slow:
            c._put_rt_ewma = 1.0  # simulated per-transfer-RT phase
            assert c.merge_cap() == 32
        gate.set()
        stall.result(10)
        for f in futs:
            np.asarray(
                f.result(10) if hasattr(f, "result") else f
            )
        assert max(launches) <= want_max
        if slow:
            # The whole backlog collapsed into one over-max_batch launch.
            assert max(launches) > 8
            assert len(launches) < 4
        else:
            assert len(launches) == 4
        c.shutdown()
