"""Regressions for the high-effort coordination-grid review: scheduler
cancellation, transaction atomicity, None elements, TransferQueue
interop/lifecycle, delayed-queue destinations, remote re-registration,
lock keyspace hygiene, reliable-topic pump lifetime."""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.grid import TransactionException


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


def test_cancelled_periodic_never_resurrects(client):
    ex = client.get_executor_service("cxl")
    ex.register_workers(1)
    runs = []
    fut = ex.schedule_at_fixed_rate(lambda: runs.append(1), 0.0, 0.05)
    deadline = time.monotonic() + 3.0
    while not runs and time.monotonic() < deadline:
        time.sleep(0.01)
    assert runs, "task never fired"
    fut.cancel()
    time.sleep(0.2)  # let any queued instance drain + purge
    count = len(runs)
    time.sleep(0.4)  # several periods: a resurrected task would refire
    assert len(runs) == count, "cancelled periodic task kept running"


def test_transaction_wrongtype_write_applies_nothing(client):
    client.get_bucket("txw-b").set(b"string!")  # 'txw-b' is a bucket
    tx = client.create_transaction()
    tx.get_map("txw-a").put("k", "v")
    tx.get_map("txw-b").put("k", "v")  # WRONGTYPE target
    with pytest.raises(TransactionException, match="WRONGTYPE"):
        tx.commit()
    # Atomicity: the valid write must NOT have been applied either.
    assert client.get_map("txw-a").get("k") is None
    assert client.get_bucket("txw-b").get() == b"string!"


def test_blocking_queue_none_element(client):
    q = client.get_blocking_queue("noneq")
    q.put(None)
    got = []
    t = threading.Thread(target=lambda: got.append(q.take()))
    t.start()
    t.join(timeout=5)
    assert not t.is_alive(), "take() hung on a stored None element"
    assert got == [None]
    # poll(timeout) path too
    q.put(None)
    assert q.poll(2.0) is None and q.size() == 0


def test_transfer_queue_shares_list_namespace(client):
    q = client.get_queue("tqns")
    q.offer(b"x")
    tq = client.get_transfer_queue("tqns")  # same key, same kind
    assert tq.poll() == b"x"


def test_transfer_completes_via_any_consumer_path(client):
    tq = client.get_transfer_queue("tqmove")
    done = []

    def xfer():
        done.append(tq.transfer(b"item", timeout_seconds=10))

    t = threading.Thread(target=xfer)
    t.start()
    time.sleep(0.15)
    # Consume via a PLAIN queue handle (RPOPLPUSH-style move).
    moved = client.get_queue("tqmove").poll_last_and_offer_first_to("tqdest")
    assert moved == b"item"
    t.join(timeout=5)
    assert not t.is_alive() and done == [True]
    assert client.get_queue("tqdest").poll() == b"item"


def test_transfer_not_stranded_by_clear(client):
    tq = client.get_transfer_queue("tqclear")
    done = []

    def xfer():
        done.append(tq.transfer(b"item", timeout_seconds=10))

    t = threading.Thread(target=xfer)
    t.start()
    time.sleep(0.15)
    tq.clear()  # deletes the backing entry while the transfer waits
    t.join(timeout=5)
    assert not t.is_alive(), "transfer stranded after clear()"


def test_delayed_queue_rejects_non_list_destination(client):
    rb = client.get_ring_buffer("dlq-rb")
    with pytest.raises(TypeError, match="list-backed"):
        client.get_delayed_queue(rb)


def test_remote_reregister_shuts_down_previous_workers(client):
    svc = client.get_remote_service("rsvc")

    class A:
        def ping(self):
            return "a"

    class B:
        def ping(self):
            return "b"

    svc.register("Svc", A())
    prev_ex = svc._impls["Svc"][1]
    svc.register("Svc", B())
    assert prev_ex.is_shutdown(), "replaced registration leaked workers"
    assert svc.get("Svc").ping() == "b"


def test_lock_keyspace_hygiene(client):
    keys = client.get_keys()
    holder = client.get_lock("lk-h")
    holder.lock()
    # A failed probe from another 'thread' must not materialize a key...
    # (the holder's key exists while held)
    assert keys.count_exists("lk-h") == 1
    holder.unlock()
    # ...and full release deletes the key (Redis unlock semantics).
    assert keys.count_exists("lk-h") == 0
    probe = client.get_lock("lk-p")
    assert probe.try_lock(0.0) is True
    probe.unlock()
    assert keys.count_exists("lk-p") == 0


def test_fencing_tokens_survive_release(client):
    fl = client.get_fenced_lock("fl")
    t1 = fl.lock_and_get_token()
    fl.unlock()
    t2 = fl.lock_and_get_token()
    fl.unlock()
    assert t2 > t1, "fencing token must stay monotonic across releases"


def test_reliable_topic_pump_exits_with_last_listener(client):
    t = client.get_reliable_topic("rt-pump")
    lid = t.add_listener(lambda ch, m: None)
    assert t._pump is not None
    t.remove_listener(lid)
    deadline = time.monotonic() + 5.0
    while t._pump is not None and time.monotonic() < deadline:
        time.sleep(0.05)
    assert t._pump is None, "pump thread survived the last listener"
    # Re-arm works.
    got = []
    t.add_listener(lambda ch, m: got.append(m))
    t.publish(b"x")
    deadline = time.monotonic() + 5.0
    while not got and time.monotonic() < deadline:
        time.sleep(0.05)
    assert got == [b"x"]


def test_ltrim_keep_all_negative_end(client):
    lst = client.get_list("lt")
    for v in (b"a", b"b", b"c"):
        lst.add(v)
    lst.trim(0, -1)  # Redis 'keep everything'
    assert lst.read_all() == [b"a", b"b", b"c"]
    lst.trim(1, -1)
    assert lst.read_all() == [b"b", b"c"]
    lst.trim(1, 0)  # from > to: empties
    assert lst.read_all() == []


def test_set_move_to_sketch_held_name_loses_nothing(client):
    bf = client.get_bloom_filter("smv-dest")
    bf.try_init(1000, 0.01)  # sketch backend holds the destination name
    s = client.get_set("smv-src")
    s.add(b"x")
    with pytest.raises(TypeError):
        s.move("smv-dest", b"x")
    assert s.contains(b"x"), "element lost in failed cross-backend move"


def test_local_cached_map_conditional_remove_none(client):
    m = client.get_local_cached_map("lcm-rm")
    m.put("k", "x")
    # Conditional remove expecting None must NOT delete 'x'.
    assert m.remove("k", None) is False
    assert m.get("k") == "x"


def test_local_cached_map_replace_invalidates_peers(client):
    a = client.get_local_cached_map("lcm-rep")
    b = client.get_local_cached_map("lcm-rep")
    a.put("k", 1)
    assert b.get("k") == 1  # b caches 1
    b_replaced = a.replace("k", 2)
    assert b_replaced == 1
    deadline = time.monotonic() + 5.0
    while b.get("k") != 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert b.get("k") == 2, "peer cache served stale value after replace"


def test_local_cached_map_preload(client):
    m = client.get_local_cached_map("lcm-pre")
    m.put("a", 1)
    m.pre_load_cache()  # must not raise (used a nonexistent API before)
    assert m.get("a") == 1


def test_mapcache_add_and_get_preserves_ttl(client):
    mc = client.get_map_cache("mc-ttl")
    mc.put("cnt", 5, ttl_seconds=300.0)
    assert mc.add_and_get("cnt", 1) == 6
    ttl = mc.remain_time_to_live_entry("cnt")
    assert 0 < ttl <= 300_000, "add_and_get wiped the entry TTL"


def test_grid_rename_onto_sketch_name_rejected(client):
    bf = client.get_bloom_filter("rn-sk")
    bf.try_init(1000, 0.01)
    client.get_bucket("rn-src").set(b"v")
    with pytest.raises(TypeError):
        client.get_keys().rename("rn-src", "rn-sk")
    assert client.get_bucket("rn-src").get() == b"v"


def test_timeseries_zero_count_is_empty(client):
    ts = client.get_time_series("ts0")
    for i in range(5):
        ts.add(i, f"v{i}")
    assert ts.last(0) == []
    assert ts.poll_last(0) == []  # used to DESTROY the whole series
    assert ts.size() == 5


def test_batch_camel_async_resolves_value(client):
    b = client.create_batch()
    b.getAtomicLong("bc").incrementAndGetAsync()
    b.get_atomic_long("bc").increment_and_get_async()
    out = b.execute()
    assert list(out) == [1, 2], "camelCase Async batch call must resolve"


def test_batch_mixed_async_sync_ordered(client):
    b = client.create_batch()
    m = b.get_map("bord")
    m.fast_put_async("k", b"1")
    m.get("k")
    out = b.execute()
    assert out[1] == b"1", "get must observe the earlier queued put"


class _QuacksLikeFuture:
    """Picklable user value with result()/done() methods."""

    def __init__(self, inner):
        self.inner = inner

    def result(self):
        return self.inner

    def done(self):
        return True

    def __eq__(self, other):
        return isinstance(other, _QuacksLikeFuture) and other.inner == self.inner


def test_reactive_returns_plain_future_objects():
    import asyncio

    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    try:
        rc = c.reactive()
        q = c.get_queue("rfq")
        q.offer(_QuacksLikeFuture("payload"))

        async def main():
            return await rc.get_queue("rfq").poll()

        got = asyncio.run(main())
        # The USER object must come back intact — duck-typed unwrapping
        # returned got == "payload" before.
        assert isinstance(got, _QuacksLikeFuture) and got.inner == "payload"
    finally:
        c.shutdown()


def test_reliable_publish_counts_cross_handle(client):
    a = client.get_reliable_topic("rtc")
    b = client.get_reliable_topic("rtc")
    a.add_listener(lambda ch, m: None)
    assert b.publish(b"x") == 1, "publish must count other handles' listeners"


def test_idgen_rejects_zero_allocation(client):
    gen = client.get_id_generator("idz")
    with pytest.raises(ValueError, match="allocation_size"):
        gen.try_init(0, 0)


def test_cas_on_absent_key_does_not_materialize(client):
    al = client.get_atomic_long("casx")
    assert al.compare_and_set(5, 6) is False
    assert client.get_keys().count_exists("casx") == 0
    assert al.compare_and_set(0, 1) is True  # absent reads as 0, like Redis
    assert al.get() == 1


def test_geo_add_entries_atomic(client):
    g = client.get_geo("gatomic")
    with pytest.raises(ValueError):
        g.add_entries((13.36, 38.11, "a"), (200.0, 0.0, "b"))
    assert g.pos("a") == {}, "partial GEOADD mutation"


def test_jcache_get_cache_none_when_absent(client):
    mgr = client.get_cache_manager() if hasattr(client, "get_cache_manager") else None
    if mgr is None:
        from redisson_tpu.grid.jcache import CacheManager

        mgr = CacheManager(client)
    cache = mgr.create_cache("jc1", default_ttl_seconds=30)
    assert mgr.get_cache("jc1") is cache
    mgr.destroy_cache("jc1")
    assert mgr.get_cache("jc1") is None
    assert mgr.get_or_create_cache("jc1") is not None


def test_topk_ranking_with_zero_count_candidates(client):
    cms = client.get_count_min_sketch("tkz")
    cms.try_init(4, 1 << 10, track_top_k=3)
    for _ in range(5):
        cms.add(1)
    client._engine.cms_reset("tkz") if hasattr(
        client._engine, "cms_reset"
    ) else None
    cms.add(2)  # count 1 vs key 1's post-reset 0 (or 5 if no reset API)
    top = cms.top_k(2)
    # Heaviest first; a zero-count stale candidate must never outrank a
    # live one (the uint32 negation wrap put zeros FIRST).
    counts = [c for _, c in top]
    assert counts == sorted(counts, reverse=True), top


def test_cms_generator_input_feeds_topk(client):
    cms = client.get_count_min_sketch("tkg")
    cms.try_init(4, 1 << 10, track_top_k=3)
    cms.add_all(x for x in [7, 7, 7, 8])  # generator input
    top = dict(cms.top_k(2))
    assert top.get(7) == 3, f"generator keys never reached the table: {top}"


def test_sketch_rename_missing_source_keeps_handle(client):
    bf = client.get_bloom_filter("rn-absent")
    with pytest.raises(RuntimeError):
        bf.rename("rn-elsewhere")
    assert bf.get_name() == "rn-absent" if hasattr(bf, "get_name") else True


def test_bloom_singular_tuple_key(client):
    # Default codec: a tuple is ONE key; add/contains must agree with
    # add_all([key]).
    c2 = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    try:
        bf = c2.get_bloom_filter("tup")
        bf.try_init(1000, 0.01)
        bf.add((1, "page"))
        assert bf.contains((1, "page"))
        assert c2.get_bloom_filter("tup2").try_init(1000, 0.01)
        bf2 = c2.get_bloom_filter("tup2")
        assert bf2.add_all([(1, "page")]) == 1
        assert bf2.contains((1, "page"))
    finally:
        c2.shutdown()


def test_bitset_array_set_returns_prev_values(client):
    import numpy as np

    bs = client.get_bit_set("prevs")
    bs.set(5)
    prev = bs.set(np.array([5, 6], dtype=np.uint32))
    assert list(prev) == [True, False]


def test_longcodec_full_uint64_range():
    import numpy as np

    from redisson_tpu import Config
    from redisson_tpu.codecs import LongCodec

    c = redisson_tpu.create(Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64))
    try:
        cms = c.get_count_min_sketch("u64")
        cms.try_init(4, 1 << 10, track_top_k=2)
        big = np.uint64((1 << 63) + 5)
        cms.add_all(np.array([big, big], dtype=np.uint64))
        assert cms.estimate(big) == 2  # per-element path must not crash
        assert dict(cms.top_k(1)).get(big) == 2
    finally:
        c.shutdown()


def test_cached_functions_do_not_collide():
    from redisson_tpu import Config
    from redisson_tpu.integrations import cached

    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    try:
        @cached(c, "shared")
        def f(x):
            return ("f", x)

        @cached(c, "shared")
        def g(x):
            return ("g", x)

        assert f(1) == ("f", 1)
        assert g(1) == ("g", 1), "g returned f's cached value"
    finally:
        c.shutdown()


def test_cms_tryinit_existing_does_not_arm_tracking(client):
    a = client.get_count_min_sketch("nta")
    assert a.try_init(4, 1 << 10) is True  # no tracking
    b = client.get_count_min_sketch("nta")
    assert b.try_init(4, 1 << 10, track_top_k=5) is False
    assert client._engine.topk.track("nta") == 0, (
        "failed tryInit armed tracking"
    )
