"""Kill-−9 crash-fault soak (ISSUE 10 acceptance): a child engine takes
acked writes under load, dies at a random point, and recovery must
restore exactly an acked-and-accepted prefix of the deterministic op
stream — bit-identical device rows vs a golden engine fed the same
prefix.

- ``appendfsync always``: every ACKED write survives (recovered state
  matches golden(R) for some R > the highest acked index).
- ``appendfsync everysec``: loss is bounded by the policy window —
  every write acked more than LOSS_WINDOW_S before the kill survives.

Slow-marked: each run boots three engines (child subprocess, recovered,
golden).  The CI ``crash-soak`` step runs this file with
RTPU_LOCK_WITNESS=1 (tier1.yml).
"""

import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu.chaos import crashchild

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# everysec: the writer fsyncs at most ~1 s apart; generous slack for a
# loaded CI box (the assertion is about the POLICY bound, not disk perf).
LOSS_WINDOW_S = 2.5
OPS = 300


class _Matched(Exception):
    def __init__(self, r):
        self.r = r


def _run_child(tmp, fsync, seed, kill_after_s):
    """Spawn the soak child, collect ACK lines, SIGKILL it mid-stream.
    Returns (acked: {index: unix_ts}, kill_time, finished_cleanly)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)  # single CPU device is enough for the child
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "redisson_tpu.chaos.crashchild",
            "--dir", str(tmp), "--fsync", fsync,
            "--seed", str(seed), "--ops", str(OPS),
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=_REPO, env=env, text=True,
    )
    acked = {}
    kill_time = None
    finished = False
    first_ack_at = None
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK "):
                _tag, idx, ts = line.split()
                acked[int(idx)] = float(ts)
                if first_ack_at is None:
                    first_ack_at = time.monotonic()
                if time.monotonic() - first_ack_at >= kill_after_s:
                    kill_time = time.time()
                    os.kill(proc.pid, signal.SIGKILL)
                    break
            elif line == "DONE":
                finished = True
                kill_time = time.time()
                os.kill(proc.pid, signal.SIGKILL)
                break
        # Drain whatever complete lines made it out before the kill.
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK ") and len(line.split()) == 3:
                _tag, idx, ts = line.split()
                acked[int(idx)] = float(ts)
            elif line == "DONE":
                finished = True
    finally:
        proc.stdout.close()
        proc.wait(timeout=30)
    return acked, kill_time, finished


def _recovered_rows(tmp, fsync):
    """Boot a fresh engine over the crashed directory (recovery runs at
    init) and capture every tenant's device row by name."""
    client = crashchild.build_client(str(tmp), fsync)
    eng = client._engine
    eng._drain()
    rows = {}
    for e in eng.registry.entries():
        rows[e.name] = np.asarray(
            eng.executor.read_row(e.pool, e.row)
        ).copy()
    replayed = eng.obs.journal_replayed.get(())
    # Tear down without snapshotting over the evidence.
    eng.config.snapshot_dir = None
    client.config.snapshot_dir = None
    j = eng.journal
    if j is not None:
        eng.journal = None
        j.close()
    client.shutdown()
    return rows, replayed


def _match_prefix(tmp_path, seed, target_rows, start_r):
    """Find R in [start_r, OPS] with golden(R ops) == target_rows by
    driving a journal-less golden engine through the same deterministic
    stream and comparing after each op.  Returns R or None."""
    import redisson_tpu as _rt
    from redisson_tpu import Config
    from redisson_tpu.codecs import LongCodec

    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64)
    golden = _rt.create(cfg)
    eng = golden.engine if hasattr(golden, "engine") else golden._engine

    def rows_now():
        eng._drain()
        out = {}
        for e in eng.registry.entries():
            out[e.name] = np.asarray(
                eng.executor.read_row(e.pool, e.row)
            )
        return out

    def same():
        got = rows_now()
        if set(got) != set(target_rows):
            return False
        return all(
            np.array_equal(got[n], target_rows[n]) for n in got
        )

    matched = None

    def ack(i):
        nonlocal matched
        r = i + 1
        if r >= start_r and matched is None and same():
            raise _Matched(r)

    try:
        crashchild.apply_ops(golden, seed, OPS, ack=ack)
        if matched is None and same():
            matched = OPS
    except _Matched as m:
        matched = m.r
    finally:
        golden.shutdown()
    return matched


@pytest.mark.parametrize("fsync", ["always", "everysec"])
def test_kill9_soak_recovers_acked_prefix(tmp_path, fsync):
    seed = random.randrange(1 << 30)
    kill_after_s = random.uniform(0.2, 1.0)
    acked, kill_time, finished = _run_child(
        tmp_path, fsync, seed, kill_after_s
    )
    assert acked, "child never acked a write (startup failure?)"
    max_acked = max(acked)
    rows, replayed = _recovered_rows(tmp_path, fsync)
    assert rows, "recovery produced an empty keyspace"
    if fsync == "always":
        # THE durability contract: every acked write survives, so the
        # recovered state is golden(R) for some R covering all acks
        # (accepted-but-unacked suffix ops may ride along).
        lower = max_acked + 1
    else:
        # everysec: loss bounded by the policy window — every write
        # acked LOSS_WINDOW_S before the kill must survive.
        covered = [
            i for i, ts in acked.items()
            if ts <= kill_time - LOSS_WINDOW_S
        ]
        lower = (max(covered) + 1) if covered else 0
    r = _match_prefix(tmp_path, seed, rows, lower)
    assert r is not None, (
        f"recovered state matches NO prefix >= {lower} of the op "
        f"stream (max_acked={max_acked}, replayed={replayed}, "
        f"finished={finished})"
    )
    assert lower <= r <= OPS


def test_kill9_with_midstream_snapshot(tmp_path):
    """Snapshot-coordinated truncation under load: the child snapshots
    every 50 ops (retiring covered segments), dies, and recovery =
    last snapshot + remaining tail still restores every acked write."""
    seed = random.randrange(1 << 30)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "redisson_tpu.chaos.crashchild",
            "--dir", str(tmp_path), "--fsync", "always",
            "--seed", str(seed), "--ops", str(OPS),
            "--snapshot-every", "50",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=_REPO, env=env, text=True,
    )
    acked = {}
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK "):
                _t, idx, ts = line.split()
                acked[int(idx)] = float(ts)
                if int(idx) >= 120:  # past at least two snapshot cuts
                    os.kill(proc.pid, signal.SIGKILL)
                    break
            elif line == "DONE":
                break
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK ") and len(line.split()) == 3:
                _t, idx, ts = line.split()
                acked[int(idx)] = float(ts)
    finally:
        proc.stdout.close()
        proc.wait(timeout=30)
    assert acked and max(acked) >= 120
    rows, _replayed = _recovered_rows(tmp_path, "always")
    r = _match_prefix(tmp_path, seed, rows, max(acked) + 1)
    assert r is not None, "acked write lost across snapshot truncation"
