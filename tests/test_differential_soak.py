"""Randomized differential property test: the TPU-sketch engine (virtual
mesh) and the host golden engine run the SAME op stream and must agree
exactly — the integration-level analog of the per-kernel golden-twin
tests (SURVEY.md §4).  A longer standalone version lives in
scratch/soak.py."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec


@pytest.mark.parametrize("shards,coalesce", [(1, True), (8, False)])
def test_differential_soak(shards, coalesce):
    rng = np.random.default_rng(shards * 10 + coalesce)
    tpu = redisson_tpu.create(
        Config().set_codec(LongCodec()).use_tpu_sketch(
            min_bucket=64, num_shards=shards, coalesce=coalesce,
            exact_add_semantics=True, batch_window_us=100, max_batch=4096,
        )
    )
    host = redisson_tpu.create(Config().set_codec(LongCodec()))
    try:
        # Fixed op count, not wall clock: the covered op stream must be
        # identical on every machine (and a failure at step N replays).
        for _step in range(120):
            kind = rng.integers(4)
            oid = int(rng.integers(4))
            keys = rng.integers(
                0, 3000, int(rng.integers(1, 300))
            ).astype(np.uint64)
            if kind == 0:
                a = tpu.get_bloom_filter(f"bf{oid}")
                b = host.get_bloom_filter(f"bf{oid}")
                for f in (a, b):
                    f.try_init(20_000, 0.01)
                if rng.integers(2):
                    assert a.add_all(keys) == b.add_all(keys)
                else:
                    assert np.array_equal(
                        a.contains_each(keys), b.contains_each(keys)
                    )
            elif kind == 1:
                a = tpu.get_hyper_log_log(f"h{oid}")
                b = host.get_hyper_log_log(f"h{oid}")
                a.add_all(keys)
                b.add_all(keys)
                assert a.count() == b.count()
            elif kind == 2:
                a = tpu.get_bit_set(f"bs{oid}")
                b = host.get_bit_set(f"bs{oid}")
                idx = keys.astype(np.uint32)
                a.set_many(idx)
                b.set_many(idx)
                assert a.cardinality() == b.cardinality()
            else:
                a = tpu.get_count_min_sketch(f"c{oid}")
                b = host.get_count_min_sketch(f"c{oid}")
                for c in (a, b):
                    c.try_init(4, 1 << 11, track_top_k=4)
                w = rng.integers(1, 5, len(keys)).astype(np.int64)
                a.add_all(keys, w)
                b.add_all(keys, w)
                assert np.array_equal(
                    a.estimate_all(keys[:8]), b.estimate_all(keys[:8])
                )
            if rng.integers(30) == 0:
                # Mailbox group collect mid-stream — DIFFERENTIALLY
                # checked: collected results must equal the host
                # engine's answers for the same queries.
                queries, futs = [], []
                for _ in range(4):
                    fid = int(rng.integers(4))
                    q = rng.integers(0, 3000, 64).astype(np.uint64)
                    bf = tpu.get_bloom_filter(f"bf{fid}")
                    bf.try_init(20_000, 0.01)
                    host.get_bloom_filter(f"bf{fid}").try_init(20_000, 0.01)
                    queries.append((fid, q))
                    futs.append(bf.contains_all_async(q))
                got = tpu.collect(futs)
                for (fid, q), g in zip(queries, got):
                    want = host.get_bloom_filter(f"bf{fid}").contains_each(q)
                    assert np.array_equal(g, want)
    finally:
        tpu.shutdown()
        host.shutdown()
