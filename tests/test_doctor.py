"""Fleet doctor (ISSUE 20): invariant sweeps, the finding ledger's
raise/clear lifecycle, the black-box canary, the CLUSTER DOCTOR
surface — and the chaos acceptance: an injected fault is detected
within one sweep, a clean fleet produces zero false positives, and
the doctor's events join the causal fleet timeline."""

import json
import socket
import time

import pytest

import redisson_tpu
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from redisson_tpu.config import Config
from redisson_tpu.obs.doctor import FINDING_KINDS, FleetDoctor, canary_key
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.serve.wireutil import ReplyError, exchange


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _raw(addr, cmds, timeout=10.0):
    sock = socket.create_connection(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        return exchange(sock, cmds)
    finally:
        sock.close()


class _Cluster2:
    """Two in-process cluster doors splitting the slot space, with an
    optional phantom third member (a node id whose address nothing
    listens on — the injectable dead primary)."""

    def __init__(self, phantom=False):
        pa, pb = _free_port(), _free_port()
        nodes = [
            {"id": "A", "host": "127.0.0.1", "port": pa,
             "slots": [[0, 8191]]},
            {"id": "B", "host": "127.0.0.1", "port": pb,
             "slots": [[8192, NSLOTS - 1]]},
        ]
        if phantom:
            # Carve C's range out of B: a primary that owns slots but
            # answers on a port nobody listens on.
            nodes[1]["slots"] = [[8192, 12287]]
            nodes.append({
                "id": "C", "host": "127.0.0.1", "port": _free_port(),
                "slots": [[12288, NSLOTS - 1]],
            })
        topo = {"nodes": nodes}
        self.nodes = {}
        for nid, port in (("A", pa), ("B", pb)):
            cfg = Config()
            cfg.cluster_enabled = True
            cfg.cluster_topology = topo
            cfg.cluster_node_id = nid
            client = redisson_tpu.create(cfg)
            self.nodes[nid] = (client, RespServer(client, port=port))
        self.addr = {"A": ("127.0.0.1", pa), "B": ("127.0.0.1", pb)}

    def server(self, nid):
        return self.nodes[nid][1]

    def close(self):
        for client, server in self.nodes.values():
            server.close()
            client.shutdown()


@pytest.fixture()
def cluster2():
    c = _Cluster2()
    yield c
    c.close()


def _doctor(server, **kw):
    kw.setdefault("interval_s", 3600.0)  # ticks only when forced
    kw.setdefault("canary", False)
    return FleetDoctor(server, **kw)


class TestSweepInvariants:
    def test_clean_fleet_zero_findings(self, cluster2):
        doc = _doctor(cluster2.server("A"), canary=True)
        assert doc.tick(force=True) == 0
        assert doc.active == {}
        assert doc.sweeps == 1
        st = doc.status()
        assert st["is_coordinator"] and st["coordinator"] == "A"
        assert st["active_findings"] == []
        # The canary ran against both primaries and failed nowhere.
        assert doc.canary_failures == 0

    def test_non_coordinator_observes_without_findings(self, cluster2):
        doc_b = _doctor(cluster2.server("B"))
        assert doc_b.status()["is_coordinator"] is False
        doc_b.tick()  # unforced: observer path
        assert doc_b.active == {}

    def test_dead_primary_detected_within_one_sweep(self):
        c = _Cluster2(phantom=True)
        try:
            doc = _doctor(c.server("A"))
            n = doc.tick(force=True)
            assert n >= 1
            keys = set(doc.active)
            assert "dead-primary:C" in keys, keys
            f = doc.active["dead-primary:C"]
            assert f["severity"] == "error"
            # The raise left a doctor.finding event on the ring.
            evs = c.server("A").obs.events.snapshot(
                kind="doctor.finding"
            )
            assert any(
                e["fields"]["kind"] == "dead-primary" for e in evs
            )
        finally:
            c.close()

    def test_finding_clears_when_invariant_restored(self, cluster2):
        doc = _doctor(cluster2.server("A"))
        # Inject: a slot stuck MIGRATING for longer than the (tiny)
        # threshold.
        doc.stuck_slot_s = 0.05
        slotmap = cluster2.server("A").cluster.slotmap
        slotmap.migrating[100] = "B"
        try:
            doc.tick(force=True)  # first sighting starts the clock
            time.sleep(0.1)
            doc.tick(force=True)
            assert any(
                k.startswith("stuck-migration:") for k in doc.active
            ), doc.active
        finally:
            slotmap.migrating.pop(100, None)
        doc.tick(force=True)
        assert doc.active == {}
        evs = cluster2.server("A").obs.events.snapshot(
            kind="doctor.clear"
        )
        assert any(
            e["fields"]["kind"] == "stuck-migration" for e in evs
        )

    def test_offset_and_epoch_regressions(self, cluster2):
        doc = _doctor(cluster2.server("A"))
        doc.tick(force=True)
        assert doc.active == {}
        # Poison the sweep memory to simulate a peer that previously
        # reported further ahead.
        doc._last_seen["B"]["offset"] += 1000
        doc._last_seen["B"]["epoch"] += 5
        doc.tick(force=True)
        assert "offset-regression:B" in doc.active
        assert "epoch-regression:B" in doc.active
        # Memory now reflects the regressed values: next sweep clears.
        doc.tick(force=True)
        assert doc.active == {}

    def test_findings_counter_and_total(self, cluster2):
        doc = _doctor(cluster2.server("A"))
        doc.stuck_slot_s = 0.0
        slotmap = cluster2.server("A").cluster.slotmap
        slotmap.migrating[7] = "B"
        try:
            doc.tick(force=True)
            time.sleep(0.02)
            doc.tick(force=True)
            assert doc.findings_total >= 1
        finally:
            slotmap.migrating.pop(7, None)

    def test_finding_kinds_are_a_bounded_catalog(self):
        assert len(FINDING_KINDS) == len(set(FINDING_KINDS))
        for k in FINDING_KINDS:
            assert k == k.lower() and " " not in k


class TestCanary:
    def test_canary_key_lands_on_the_node(self, cluster2):
        slotmap = cluster2.server("A").cluster.slotmap
        for nid in ("A", "B"):
            key = canary_key(nid, slotmap)
            assert key is not None
            assert slotmap.owner(key_slot(key.encode())) == nid
            assert key.startswith("{__rtpu-doctor-")

    def test_canary_probe_round_trips(self, cluster2):
        doc = _doctor(cluster2.server("A"), canary=True)
        assert doc._canary_probe("A") is None
        assert doc._canary_probe("B") is None
        assert doc.canary_failures == 0

    def test_canary_failure_raises_finding(self):
        c = _Cluster2(phantom=True)
        try:
            doc = _doctor(c.server("A"), canary=True)
            doc.tick(force=True)
            # C is unreachable: dead-primary, not a canary finding
            # (down nodes are skipped by the canary — the liveness
            # probe already told the truth).
            assert "dead-primary:C" in doc.active
            assert not any(
                k.startswith("canary:") for k in doc.active
            )
            # A reachable node whose door lies, though, is a canary
            # failure: point B's address at a closed port.
            dead = ("127.0.0.1", _free_port())
            with doc.slotmap._lock:
                doc.slotmap._nodes["B"] = dead
            err = doc._canary_probe("B")
            assert err is not None
        finally:
            c.close()


class TestClusterDoctorSurface:
    def test_status_unarmed(self, cluster2):
        (raw,) = _raw(cluster2.addr["A"], [("CLUSTER", "DOCTOR", "STATUS")])
        st = json.loads(raw)
        assert st == {"enabled": False, "node": "A"}

    def test_report_unarmed_is_friendly(self, cluster2):
        (raw,) = _raw(cluster2.addr["A"], [("CLUSTER", "DOCTOR")])
        assert b"--doctor" in raw

    def test_verbs_require_agent(self, cluster2):
        err = _raw(cluster2.addr["A"], [("CLUSTER", "DOCTOR", "NOW")])[0]
        assert isinstance(err, ReplyError)
        assert "--doctor" in str(err)

    def test_armed_status_now_pause_resume_report(self, cluster2):
        doc = _doctor(cluster2.server("A"))
        addr = cluster2.addr["A"]
        (n,) = _raw(addr, [("CLUSTER", "DOCTOR", "NOW")])
        assert n == 0
        (raw,) = _raw(addr, [("CLUSTER", "DOCTOR", "STATUS")])
        st = json.loads(raw)
        assert st["enabled"] and st["node"] == "A"
        assert st["sweeps"] >= 1 and st["active_findings"] == []
        assert st["is_coordinator"] is True
        assert _raw(addr, [("CLUSTER", "DOCTOR", "PAUSE")])[0] == b"OK" \
            or _raw(addr, [("CLUSTER", "DOCTOR", "STATUS")])
        assert doc.paused or json.loads(
            _raw(addr, [("CLUSTER", "DOCTOR", "STATUS")])[0]
        )["paused"]
        (raw,) = _raw(addr, [("CLUSTER", "DOCTOR", "RESUME")])
        assert doc.paused is False
        (report,) = _raw(addr, [("CLUSTER", "DOCTOR", "REPORT")])
        text = report.decode()
        assert "Fleet doctor on A" in text
        assert "No active findings" in text
        err = _raw(addr, [("CLUSTER", "DOCTOR", "BOGUS")])[0]
        assert isinstance(err, ReplyError)

    def test_report_lists_findings_and_events(self):
        c = _Cluster2(phantom=True)
        try:
            doc = _doctor(c.server("A"))
            doc.tick(force=True)
            text = doc.report()
            assert "dead-primary" in text
            assert "ACTIVE finding" in text
            assert "doctor.finding" in text  # the events tail
            assert "node C" in text and "DOWN" in text
        finally:
            c.close()

    def test_info_doctor_section(self, cluster2):
        addr = cluster2.addr["B"]
        (info,) = _raw(addr, [("INFO", "doctor")])
        assert b"doctor_enabled:0" in info
        _doctor(cluster2.server("B"))
        (info,) = _raw(addr, [("INFO", "doctor")])
        text = info.decode()
        assert "doctor_enabled:1" in text
        assert "doctor_is_coordinator:0" in text
        assert "doctor_active_findings:0" in text

    def test_cluster_migrations_verb(self, cluster2):
        slotmap = cluster2.server("A").cluster.slotmap
        slotmap.migrating[42] = "B"
        try:
            (raw,) = _raw(cluster2.addr["A"], [("CLUSTER", "MIGRATIONS")])
            doc = json.loads(raw)
            assert doc["node"] == "A"
            assert doc["migrating"] == {"42": "B"}
            assert doc["importing"] == {}
        finally:
            slotmap.migrating.pop(42, None)

    def test_doctor_metric_families_registered(self, cluster2):
        doc = _doctor(cluster2.server("A"))
        doc.tick(force=True)
        reg = cluster2.server("A").obs.registry
        # The sweep bumped the counter, so it renders; the findings and
        # canary families are registered but empty on a clean fleet
        # (a family with no series renders nothing — by design).
        assert "rtpu_doctor_sweeps_total" in reg.render_prometheus()
        assert reg.family("rtpu_doctor_findings_total") is not None
        assert reg.family("rtpu_doctor_canary_rtt_us") is not None

    def test_doctor_status_fleet_helper(self, cluster2):
        from redisson_tpu.cluster.client import ClusterClient

        _doctor(cluster2.server("A"))
        cc = ClusterClient(list(cluster2.addr.values()))
        try:
            st = cc.doctor_status()
        finally:
            cc.close()
        by_enabled = {
            n: row.get("enabled") for n, row in st.items()
        }
        assert sorted(by_enabled.values()) == [False, True]


# -- the doctor-armed chaos soak (ISSUE 20 acceptance) ------------------------


def _doctor_status_at(addr):
    from redisson_tpu.cluster.supervisor import _request

    (raw,) = _request(addr, [("CLUSTER", "DOCTOR", "STATUS")])
    return json.loads(raw)


@pytest.mark.slow
class TestDoctorChaosSoak:
    def test_kill9_soak_detects_election_then_clears(self):
        """The acceptance chain: kill -9 a primary under a doctor-armed
        fleet -> the coordinator raises dead-primary within its sweeps,
        the replica's election promotes it, the finding CLEARS, the
        fleet settles to zero active findings, and the merged
        fleet_events timeline shows election -> takeover ->
        doctor-clear in causal order."""
        from redisson_tpu.cluster.supervisor import (
            ClusterSupervisor,
            _request,
        )

        sup = ClusterSupervisor(
            n_nodes=3, replicas_per_shard=1, node_timeout_ms=3000,
            startup_timeout_s=180.0, node_args=("--doctor",),
        )
        try:
            sup.start()
            cc = sup.client()
            try:
                for i in range(24):
                    assert cc.execute("SET", f"dk{i}", "v") == b"OK"
                # The doctor audits on the lowest alive primary: node 0.
                addr0 = sup.addrs[0]
                deadline = time.monotonic() + 60.0
                st = {}
                while time.monotonic() < deadline:
                    st = _doctor_status_at(addr0)
                    if st.get("enabled") and st.get("is_coordinator") \
                            and st.get("sweeps", 0) >= 2:
                        break
                    time.sleep(0.25)
                assert st.get("is_coordinator"), st
                # Clean fleet, zero false positives before the fault.
                assert st["findings_total"] == 0, st

                sup.kill_node(1)

                # Detection within the sweep cadence: a dead-primary
                # finding event lands on the coordinator's ring.
                found = False
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline and not found:
                    (raw,) = _request(
                        addr0, [("EVENTS", "GET", "0", "doctor.finding")]
                    )
                    doc = json.loads(raw)
                    found = any(
                        ev["fields"].get("kind") == "dead-primary"
                        for ev in doc["events"]
                    )
                    if not found:
                        time.sleep(0.25)
                assert found, "doctor never raised dead-primary"

                # Settle: promotion happens, the finding clears, and the
                # fleet returns to zero ACTIVE findings.
                deadline = time.monotonic() + 60.0
                settled = False
                while time.monotonic() < deadline and not settled:
                    st = _doctor_status_at(addr0)
                    settled = st.get("active_findings") == []
                    if not settled:
                        time.sleep(0.5)
                assert settled, st

                # Dead-member degradation first: against the STALE slot
                # table (still naming the killed node) the merge
                # reports it down instead of raising.
                tl = cc.fleet_events()
                assert tl["down_nodes"], tl["down_nodes"]

                # Causal order on the merged fleet timeline: refresh so
                # the fan-out reaches the PROMOTED replica (it owns the
                # dead node's slots now), then assert
                # election won -> takeover applied -> doctor clear.
                cc.refresh_slots()
                tl = cc.fleet_events()
                kinds = [
                    (e["kind"], e["fields"].get("kind"))
                    for e in tl["events"]
                ]
                def first(kind, fkind=None):
                    for i, (k, fk) in enumerate(kinds):
                        if k == kind and (fkind is None or fk == fkind):
                            return i
                    return -1
                i_won = first("failover.election.won")
                i_take = first("failover.takeover.applied")
                i_clear = first("doctor.clear", "dead-primary")
                assert i_won >= 0, "no election.won event in the fleet"
                assert i_take > i_won, (i_won, i_take)
                assert i_clear > i_take, (i_take, i_clear)
            finally:
                cc.close()
        finally:
            sup.shutdown()

    def test_clean_soak_zero_false_positives(self):
        """A healthy doctor-armed fleet under steady traffic raises
        NOTHING: findings_total stays 0 and every canary round-trips."""
        from redisson_tpu.cluster.supervisor import ClusterSupervisor

        sup = ClusterSupervisor(
            n_nodes=2, replicas_per_shard=1, node_timeout_ms=1000,
            startup_timeout_s=180.0, node_args=("--doctor",),
        )
        try:
            sup.start()
            cc = sup.client()
            try:
                addr0 = sup.addrs[0]
                deadline = time.monotonic() + 60.0
                st = {}
                while time.monotonic() < deadline:
                    st = _doctor_status_at(addr0)
                    if st.get("sweeps", 0) >= 4:
                        break
                    for i in range(50):
                        cc.execute("SET", f"ck{i}", f"v{i}")
                    time.sleep(0.2)
                assert st.get("sweeps", 0) >= 4, st
                assert st["findings_total"] == 0, st
                assert st["canary_failures"] == 0, st
                assert st["active_findings"] == [], st
            finally:
                cc.close()
        finally:
            sup.shutdown()
