"""Sketch durability: TTL/expiry, dump/restore, snapshot round-trips.

Mirrors upstream RedissonExpirable/RedissonObject#dump semantics
(SURVEY.md §5 checkpoint row): a kill-and-restore must round-trip a loaded
bloom filter bit-exactly, and an expired sketch must vanish from the
keyspace.
"""

import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec


def make_client(tmp_path=None, host=False, **kw):
    cfg = Config().set_codec(LongCodec())
    if not host:
        cfg = cfg.use_tpu_sketch(min_bucket=64, **kw)
    if tmp_path is not None:
        cfg.snapshot_dir = str(tmp_path)
    return redisson_tpu.create(cfg)


@pytest.fixture(params=["tpu", "host"])
def client(request):
    c = make_client(host=(request.param == "host"))
    yield c
    c.shutdown()


class TestTTL:
    def test_expire_makes_sketch_vanish(self, client):
        bf = client.get_bloom_filter("ttl-bf")
        bf.try_init(1000, 0.01)
        bf.add(123)
        assert bf.is_exists()
        assert bf.remain_time_to_live() == -1
        assert bf.expire(0.15)
        assert 0 < bf.remain_time_to_live() <= 150
        time.sleep(0.2)
        assert not bf.is_exists()
        assert bf.remain_time_to_live() == -2
        # Re-init lands on a fresh, empty filter.
        assert bf.try_init(1000, 0.01)
        assert not bf.contains(123)

    def test_clear_expire(self, client):
        h = client.get_hyper_log_log("ttl-hll")
        h.add(1)
        assert h.expire(0.15)
        assert h.clear_expire()
        assert h.remain_time_to_live() == -1
        time.sleep(0.2)
        assert h.is_exists()

    def test_expire_absent_is_false(self, client):
        bf = client.get_bloom_filter("ttl-none")
        assert not bf.expire(1.0)
        assert not bf.clear_expire()

    def test_delete_expired_reports_false(self, client):
        bs = client.get_bit_set("ttl-bs")
        bs.set(5)
        assert bs.expire(0.05)
        time.sleep(0.1)
        assert not bs.delete()

    def test_sweeper_reclaims_without_touch(self):
        c = make_client()
        try:
            bf = c.get_bloom_filter("ttl-sweep")
            bf.try_init(1000, 0.01)
            bf.expire(0.1)
            engine = c._engine
            deadline = time.time() + 3.0
            while time.time() < deadline and engine.registry.lookup("ttl-sweep"):
                time.sleep(0.05)
            # The sweeper (not a user lookup) removed the registry entry.
            assert engine.registry.lookup("ttl-sweep") is None
        finally:
            c.shutdown()


class TestDumpRestore:
    def test_bloom_dump_restore_bit_exact(self, client):
        bf = client.get_bloom_filter("dump-bf")
        bf.try_init(10_000, 0.01)
        keys = np.arange(5000, dtype=np.uint64)
        bf.add_all(keys)
        blob = bf.dump()
        bf2 = client.get_bloom_filter("dump-bf2")
        bf2.restore(blob)
        assert all(bf2.contains_each(keys))
        probe = np.arange(100_000, 101_000, dtype=np.uint64)
        assert list(bf.contains_each(probe)) == list(bf2.contains_each(probe))

    def test_restore_busykey(self, client):
        h = client.get_hyper_log_log("dump-hll")
        h.add_all([1, 2, 3])
        blob = h.dump()
        with pytest.raises(ValueError, match="BUSYKEY"):
            h.restore(blob)
        h.restore(blob, replace=True)
        assert h.is_exists()

    def test_dump_absent_raises(self, client):
        bf = client.get_bloom_filter("dump-none")
        with pytest.raises(RuntimeError):
            bf.dump()

    def test_dump_wire_format_is_data_only(self, client):
        """ADVICE r3: dump blobs may cross trust boundaries — neither
        engine may emit (or accept) pickle."""
        import pickle

        c = client.get_count_min_sketch("dump-cms")
        c.try_init(4, 1 << 10)
        c.add(7)
        blob = c.dump()
        assert blob[:4] in (b"RTPU", b"RTPH")  # tpu / host magics
        with pytest.raises(Exception):
            pickle.loads(blob)  # not a pickle stream


class TestSnapshot:
    def test_kill_and_restore_round_trips(self, tmp_path):
        c1 = make_client(tmp_path)
        bf = c1.get_bloom_filter("snap-bf")
        bf.try_init(10_000, 0.001)
        keys = np.arange(7000, dtype=np.uint64)
        bf.add_all(keys)
        h = c1.get_hyper_log_log("snap-hll")
        h.add_all(np.arange(3000, dtype=np.uint64))
        hll_count = h.count()
        bs = c1.get_bit_set("snap-bs")
        bs.set_many(np.arange(0, 2048, 7, dtype=np.uint32))
        probe = np.arange(50_000, 52_000, dtype=np.uint64)
        fp_pattern = list(bf.contains_each(probe))
        c1.shutdown()  # writes the final snapshot

        c2 = make_client(tmp_path)  # restores on create
        try:
            bf2 = c2.get_bloom_filter("snap-bf")
            assert bf2.is_exists()
            assert bf2.count() > 6000
            assert all(bf2.contains_each(keys))
            # Bit-exact: identical false-positive pattern, not just hits.
            assert list(bf2.contains_each(probe)) == fp_pattern
            assert c2.get_hyper_log_log("snap-hll").count() == hll_count
            bs2 = c2.get_bit_set("snap-bs")
            assert bs2.cardinality() == len(range(0, 2048, 7))
            # Params survived: re-init reports already-initialized.
            assert not bf2.try_init(10_000, 0.001)
        finally:
            c2.shutdown()

    def test_snapshot_preserves_ttl(self, tmp_path):
        c1 = make_client(tmp_path)
        bf = c1.get_bloom_filter("snap-ttl")
        bf.try_init(1000, 0.01)
        bf.expire(30.0)
        c1.shutdown()
        c2 = make_client(tmp_path)
        try:
            bf2 = c2.get_bloom_filter("snap-ttl")
            ttl = bf2.remain_time_to_live()
            assert 0 < ttl <= 30_000
        finally:
            c2.shutdown()

    def test_periodic_snapshotter(self, tmp_path):
        c = make_client(tmp_path)
        c.config.snapshot_interval_s = 0.2
        c._engine._start_snapshotter(str(tmp_path), 0.2)
        bf = c.get_bloom_filter("snap-periodic")
        bf.try_init(1000, 0.01)
        bf.add_all(np.arange(100, dtype=np.uint64))
        deadline = time.time() + 3.0
        import os

        while time.time() < deadline and not os.path.exists(
            tmp_path / "sketch_meta.json"
        ):
            time.sleep(0.05)
        assert (tmp_path / "sketch_meta.json").exists()
        c.shutdown()

    def test_new_objects_after_restore_get_fresh_rows(self, tmp_path):
        """Restored free-lists must not hand out rows already owned by
        restored tenants."""
        c1 = make_client(tmp_path)
        for i in range(5):
            bf = c1.get_bloom_filter(f"fr-{i}")
            bf.try_init(1000, 0.01)
            bf.add(i)
        c1.shutdown()
        c2 = make_client(tmp_path)
        try:
            nbf = c2.get_bloom_filter("fr-new")
            nbf.try_init(1000, 0.01)
            nbf.add_all(np.arange(100, dtype=np.uint64))
            for i in range(5):
                old = c2.get_bloom_filter(f"fr-{i}")
                assert old.contains(i)
                assert old.count() <= 3  # new tenant's keys didn't leak in
        finally:
            c2.shutdown()


class TestResharding:
    """Snapshot→restore ACROSS shard counts: the explicit device-array
    remap standing in for cluster resharding (SURVEY §2.4)."""

    def _load(self, tmp_path, **kw):
        c = make_client(**kw)
        bf = c.get_bloom_filter("rs-bf")
        bf.try_init(10_000, 0.001)
        keys = np.arange(4000, dtype=np.uint64)
        bf.add_all(keys)
        h = c.get_hyper_log_log("rs-hll")
        h.add_all(np.arange(2000, dtype=np.uint64))
        hll_count = h.count()
        bs = c.get_bit_set("rs-bs")
        bs.set_many(np.arange(0, 2048, 5, dtype=np.uint32))
        probe = np.arange(30_000, 32_000, dtype=np.uint64)
        fp = list(bf.contains_each(probe))
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        return keys, hll_count, fp, probe

    def _check(self, tmp_path, keys, hll_count, fp, probe, **kw):
        c = make_client(**kw)
        try:
            assert c._engine.restore_snapshot(str(tmp_path))
            bf = c.get_bloom_filter("rs-bf")
            assert all(bf.contains_each(keys))
            assert list(bf.contains_each(probe)) == fp  # bit-exact remap
            assert c.get_hyper_log_log("rs-hll").count() == hll_count
            assert c.get_bit_set("rs-bs").cardinality() == len(range(0, 2048, 5))
            assert not bf.try_init(10_000, 0.001)  # params survived
        finally:
            c.shutdown()

    def test_single_to_mesh(self, tmp_path):
        state = self._load(tmp_path)
        self._check(tmp_path, *state, num_shards=8)

    def test_mesh_to_single(self, tmp_path):
        state = self._load(tmp_path, num_shards=8)
        self._check(tmp_path, *state)

    def test_mesh_to_smaller_mesh(self, tmp_path):
        state = self._load(tmp_path, num_shards=8)
        self._check(tmp_path, *state, num_shards=4)

    def test_msharded_bitset_reshards(self, tmp_path):
        c = make_client(num_shards=8, mbit_threshold_words=256)
        bs = c.get_bit_set("rs-mbit")
        idx = np.arange(0, 1 << 16, 37, dtype=np.uint32)
        bs.set_many(idx)
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        c2 = make_client(num_shards=4, mbit_threshold_words=256)
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            bs2 = c2.get_bit_set("rs-mbit")
            assert bs2.cardinality() == len(idx)
            assert all(bs2.get_many(idx))
        finally:
            c2.shutdown()

    def test_replicated_filter_survives_reshard_unreplicated(self, tmp_path):
        c = make_client(num_shards=8)
        bf = c.get_bloom_filter("rs-rep")
        bf.try_init(10_000, 0.01)
        keys = np.arange(1000, dtype=np.uint64)
        bf.add_all(keys)
        bf.set_replicated()
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        c2 = make_client(num_shards=4)
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            bf2 = c2.get_bloom_filter("rs-rep")
            assert not bf2.is_replicated()  # placement was per-old-shard
            assert all(bf2.contains_each(keys))
            assert bf2.set_replicated()  # re-replicable on the new mesh
            assert all(bf2.contains_each(keys))
        finally:
            c2.shutdown()

    def test_threshold_change_with_same_shards_remaps(self, tmp_path):
        """Same S but a different mbit threshold changes bitset word
        layout WITHOUT changing array shapes — must remap, not install
        verbatim (r3 review)."""
        c = make_client(num_shards=8, mbit_threshold_words=256)
        bs = c.get_bit_set("rs-thresh")
        idx = np.arange(0, 1 << 16, 41, dtype=np.uint32)
        bs.set_many(idx)
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        c2 = make_client(num_shards=8)  # default threshold: row-sharded now
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            bs2 = c2.get_bit_set("rs-thresh")
            assert bs2.cardinality() == len(idx)
            assert all(bs2.get_many(idx))
        finally:
            c2.shutdown()

    def test_legacy_snapshot_without_topology_stamp(self, tmp_path):
        """Snapshots from before the stamp infer topology from the array
        shape instead of misreading a sharded state as flat."""
        import json as _json

        c = make_client(num_shards=8)
        bf = c.get_bloom_filter("rs-legacy")
        bf.try_init(10_000, 0.01)
        keys = np.arange(800, dtype=np.uint64)
        bf.add_all(keys)
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        meta_path = tmp_path / "sketch_meta.json"
        meta = _json.loads(meta_path.read_text())
        del meta["num_shards"]
        del meta["mbit_threshold_words"]
        meta_path.write_text(_json.dumps(meta))
        c2 = make_client(num_shards=8)
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            assert all(c2.get_bloom_filter("rs-legacy").contains_each(keys))
        finally:
            c2.shutdown()

    def test_reshard_restore_refuses_live_keyspace(self, tmp_path):
        c = make_client(num_shards=8)
        c.get_bloom_filter("rs-busy").try_init(1000, 0.01)
        c._engine.snapshot(str(tmp_path))
        c.shutdown()
        c2 = make_client(num_shards=4)
        try:
            c2.get_bloom_filter("rs-busy").try_init(1000, 0.01)  # live tenant
            with pytest.raises(ValueError, match="BUSYKEY"):
                c2._engine.restore_snapshot(str(tmp_path))
        finally:
            c2.shutdown()


class TestForgedDumps:
    """Dump payloads cross trust boundaries (RESP RESTORE): forged
    headers must be rejected BEFORE allocation or object creation."""

    def test_forged_giant_npy_shape_rejected(self, client):
        import io
        import struct

        c = client.get_bloom_filter("forge-src")
        c.try_init(1000, 0.01)
        blob = bytearray(c.dump())
        # Both wire formats embed a .npy header; forge its shape field to
        # declare ~1TB and confirm the loader refuses to allocate.
        i = blob.find(b"'shape': (")
        assert i > 0
        j = blob.index(b")", i)
        forged = bytes(blob[:i]) + b"'shape': (1099511627776,)" + bytes(blob[j + 1:])
        with pytest.raises(ValueError, match="declares|descr|header"):
            client._engine.restore("forge-dst", forged)

    def test_host_restore_rejects_mismatched_fields(self):
        import io
        import json
        import struct

        c = make_client(host=True)
        try:
            hdr = json.dumps({
                "v": 2, "kind": "bloom", "params": {},
                "model_cls": "GoldenBloomFilter",
                "scalars": {"size": 100, "hash_iterations": 3},
                "arrays": ["bits"],
            }).encode()
            buf = io.BytesIO()
            np.save(buf, np.zeros(7, bool), allow_pickle=False)  # wrong len
            blob = b"RTPH" + struct.pack("<I", len(hdr)) + hdr + buf.getvalue()
            with pytest.raises(ValueError, match="shape"):
                c._engine.restore("mism", blob)
            # Unknown scalar fields rejected too.
            hdr2 = json.dumps({
                "v": 2, "kind": "bloom", "params": {},
                "model_cls": "GoldenBloomFilter",
                "scalars": {"size": 100, "hash_iterations": 3, "evil": 1},
                "arrays": ["bits"],
            }).encode()
            blob2 = b"RTPH" + struct.pack("<I", len(hdr2)) + hdr2 + buf.getvalue()
            with pytest.raises(ValueError, match="do not match"):
                c._engine.restore("mism2", blob2)
        finally:
            c.shutdown()


class TestTopKDurability:
    """The engine-shared heavy-hitter tables must survive durability
    boundaries: counters without candidates would return empty top_k()."""

    def test_dump_restore_keeps_topk(self, client):
        c = client.get_count_min_sketch("tk-src")
        c.try_init(4, 1 << 10, track_top_k=3)
        for key, n in ((1, 9), (2, 5), (3, 2)):
            for _ in range(n):
                c.add(key)
        blob = c.dump()
        c2 = client.get_count_min_sketch("tk-dst")
        c2.restore(blob)
        assert c2.top_k(2) == c.top_k(2) == [(1, 9), (2, 5)]

    def test_snapshot_restore_keeps_topk(self, tmp_path):
        d = str(tmp_path / "snap")
        c1 = make_client(tmp_path)
        cms = c1.get_count_min_sketch("tk-snap")
        cms.try_init(4, 1 << 10, track_top_k=3)
        for key, n in ((7, 11), (8, 4)):
            for _ in range(n):
                cms.add(key)
        c1._engine.snapshot(d)
        c1.shutdown()
        c2 = make_client()
        try:
            c2._engine.restore_snapshot(d)
            cms2 = c2.get_count_min_sketch("tk-snap")
            assert cms2.top_k(2) == [(7, 11), (8, 4)]
        finally:
            c2.shutdown()


    def test_topk_key_types_survive_round_trip(self):
        """Candidate keys keep their ORIGINAL scalar type across dump/
        restore: the codec encodes np.uint64(5) and 5 differently, so a
        type-collapsing export would re-estimate the wrong cells
        (count_min_sketch offer note).  Uses the default PickleCodec."""
        import redisson_tpu as _rt

        c = _rt.create(Config().use_tpu_sketch(min_bucket=64))
        try:
            cms = c.get_count_min_sketch("tk-np")
            cms.try_init(4, 1 << 10, track_top_k=3)
            keys = np.array([11, 11, 11, 22, 22, 33], dtype=np.uint64)
            cms.add_all(keys)
            before = cms.top_k(2)
            assert before == [(11, 3), (22, 2)]
            blob = cms.dump()
            cms2 = c.get_count_min_sketch("tk-np2")
            cms2.restore(blob)
            assert cms2.top_k(2) == before
            # The restored candidates must still be np.uint64.
            cands = c._engine.topk.candidates("tk-np2")
            assert all(type(k) is np.uint64 for k in cands), cands
        finally:
            c.shutdown()

    def test_topk_ghost_table_cleared_on_replace(self, client):
        """RESTORE with replace over a tracked CMS from an untracked dump
        must NOT leave the old object's heavy-hitter ghosts behind."""
        tracked = client.get_count_min_sketch("tk-ghost")
        tracked.try_init(4, 1 << 10, track_top_k=3)
        for _ in range(9):
            tracked.add(5)
        assert tracked.top_k(1) == [(5, 9)]
        plain = client.get_count_min_sketch("tk-plain")
        plain.try_init(4, 1 << 10)  # no tracking
        plain.add(7)
        tracked.restore(plain.dump(), replace=True)
        assert client._engine.topk.candidates("tk-ghost") == []

    def test_topk_forged_blob_rejected_before_install(self, client):
        """Malformed candidate tables must fail BEFORE the object is
        created — no half-restored state."""
        import json as _json

        src = client.get_count_min_sketch("tk-forge-src")
        src.try_init(4, 1 << 10, track_top_k=3)
        src.add(1)
        blob = bytearray(src.dump())
        for forged_topk in (
            '{"k": 1152921504606846976, "cands": []}',   # absurd k
            '{"k": 3, "cands": [["zz", 1, 2]]}',          # unknown tag
            '{"k": 3, "cands": [["b", "not-hex", 2]]}',   # bad hex
        ):
            raw = bytes(blob)
            # splice the forged table into the json header
            import struct as _struct

            (hlen,) = _struct.unpack("<I", raw[4:8])
            hdr = _json.loads(raw[8 : 8 + hlen].decode())
            hdr["topk"] = _json.loads(forged_topk)
            new_hdr = _json.dumps(hdr).encode()
            forged = (
                raw[:4]
                + _struct.pack("<I", len(new_hdr))
                + new_hdr
                + raw[8 + hlen :]
            )
            with pytest.raises(ValueError):
                client.get_count_min_sketch("tk-forge-dst").restore(forged)
            assert not client.get_count_min_sketch("tk-forge-dst").is_exists()
