"""Fleet flight recorder (ISSUE 20): the EventRing unit contract, the
EVENTS RESP surface + INFO section, the fleet_events() causal merge,
the uniform dead-member degradation of every fleet fanout, and the
three new LATENCY feeder event names."""

import json
import os
import shutil
import socket
import tempfile
import time

import pytest

import redisson_tpu
from redisson_tpu.cluster.slots import NSLOTS
from redisson_tpu.config import Config
from redisson_tpu.obs import trace
from redisson_tpu.obs.events import (
    KINDS,
    SEVERITIES,
    EventRing,
    merge_timelines,
)
from redisson_tpu.obs.latency import LatencyMonitor
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.serve.wireutil import ReplyError, exchange


# -- EventRing unit contract --------------------------------------------------


class TestEventRing:
    def test_emit_shape_and_seq_monotone(self):
        ring = EventRing()
        ring.node = "N1"
        a = ring.emit("failover.detected", severity="warn", peer="N2")
        b = ring.emit("config.set", key="appendonly", value="yes")
        assert a["seq"] == 1 and b["seq"] == 2
        assert a["node"] == "N1" and a["kind"] == "failover.detected"
        assert a["severity"] == "warn" and a["fields"] == {"peer": "N2"}
        assert a["wall"] <= b["wall"] and a["mono"] <= b["mono"]
        assert len(ring) == 2

    def test_unregistered_kind_and_severity_raise(self):
        ring = EventRing()
        with pytest.raises(ValueError):
            ring.emit("no.such.kind")
        with pytest.raises(ValueError):
            ring.emit("config.set", severity="fatal")
        assert len(ring) == 0

    def test_catalog_kinds_all_emittable(self):
        ring = EventRing(max_events=len(KINDS) + 1)
        for kind in KINDS:
            ring.emit(kind)
        assert len(ring) == len(KINDS)
        assert SEVERITIES == ("info", "warn", "error")

    def test_bounded_ring_evicts_and_seq_never_resets(self):
        ring = EventRing(max_events=4)
        for _ in range(10):
            ring.emit("config.set")
        assert len(ring) == 4
        assert ring.evicted == 6
        # Surviving events are the newest four, seq contiguous.
        assert [e["seq"] for e in ring.snapshot()] == [7, 8, 9, 10]
        st = ring.stats()
        assert st == {
            "events": 4, "seq": 10, "evicted": 6, "max_events": 4,
        }

    def test_reset_counts_as_eviction_and_seq_continues(self):
        ring = EventRing()
        for _ in range(3):
            ring.emit("config.set")
        assert ring.reset() == 3
        assert len(ring) == 0 and ring.evicted == 3
        # The next emit's seq proves the reset left a visible gap.
        assert ring.emit("config.set")["seq"] == 4

    def test_snapshot_count_and_kind_filters(self):
        ring = EventRing()
        ring.emit("doctor.finding", kind="dead-primary")
        ring.emit("doctor.clear", kind="dead-primary")
        ring.emit("failover.detected", peer="X")
        assert [e["kind"] for e in ring.snapshot(count=1)] == [
            "failover.detected"
        ]
        assert [e["kind"] for e in ring.snapshot(kind="doctor.clear")] \
            == ["doctor.clear"]
        # Trailing-dot prefix selects a whole plane.
        assert [e["kind"] for e in ring.snapshot(kind="doctor.")] == [
            "doctor.finding", "doctor.clear",
        ]

    def test_ambient_trace_scope_attaches_trace_id(self):
        ring = EventRing()
        ctx = trace.TraceContext(None, "t-abc", "s-1")
        with trace.scope(ctx):
            ev = ring.emit("config.set", key="k", value="v")
        assert ev["trace_id"] == "t-abc"
        assert "trace_id" not in ring.emit("config.set")

    def test_counters_bump(self):
        class Fam:
            def __init__(self):
                self.calls = []

            def inc(self, labels=(), n=1):
                self.calls.append((labels, n))

        emitted, evicted = Fam(), Fam()
        ring = EventRing(
            max_events=1, counter=emitted, evicted_counter=evicted
        )
        ring.emit("config.set")
        ring.emit("repl.link.down", severity="warn")
        assert emitted.calls == [
            (("config.set",), 1), (("repl.link.down",), 1),
        ]
        assert evicted.calls == [((), 1)]


class TestMergeTimelines:
    def test_orders_by_wall_then_node_then_seq(self):
        per_node = {
            "B": [
                {"node": "B", "wall": 2.0, "seq": 1, "kind": "config.set"},
                {"node": "B", "wall": 4.0, "seq": 2, "kind": "config.set"},
            ],
            "A": [
                {"node": "A", "wall": 1.0, "seq": 1, "kind": "config.set"},
                {"node": "A", "wall": 2.0, "seq": 2, "kind": "config.set"},
                {"node": "A", "wall": 3.0, "seq": 3, "kind": "config.set"},
            ],
        }
        merged, gaps = merge_timelines(per_node)
        assert [(e["node"], e["seq"]) for e in merged] == [
            ("A", 1), ("A", 2), ("B", 1), ("A", 3), ("B", 2),
        ]
        assert gaps == {}
        # Per-node seq stays monotone inside the merged stream.
        for node in ("A", "B"):
            seqs = [e["seq"] for e in merged if e["node"] == node]
            assert seqs == sorted(seqs)

    def test_seq_gaps_reported_as_evictions(self):
        merged, gaps = merge_timelines({
            "A": [
                {"node": "A", "wall": 1.0, "seq": 3},
                {"node": "A", "wall": 2.0, "seq": 7},
                {"node": "A", "wall": 3.0, "seq": 8},
            ],
            "B": [{"node": "B", "wall": 1.5, "seq": 1}],
        })
        assert gaps == {"A": 3}  # 4,5,6 evicted
        assert len(merged) == 4


# -- the new LATENCY feeder event names (ISSUE 20 satellite) ------------------


class TestNewLatencyFeeders:
    FEEDERS = ("election", "rebalance-wave", "full-resync")

    def test_injected_durations_surface_in_latest(self):
        mon = LatencyMonitor(threshold_ms=10)
        for i, ev in enumerate(self.FEEDERS):
            assert mon.record(ev, 25.0 + i)
        assert mon.record("election", 5.0) is False  # below threshold
        latest = dict(
            (name, (ms, mx)) for name, _ts, ms, mx in mon.latest()
        )
        assert set(latest) == set(self.FEEDERS)
        assert latest["election"] == (25, 25)
        assert latest["full-resync"] == (27, 27)

    def test_doctor_advice_covers_the_new_events(self):
        mon = LatencyMonitor(threshold_ms=1)
        for ev in self.FEEDERS:
            mon.record(ev, 100.0)
        advice = mon.doctor()
        for ev in self.FEEDERS:
            assert ev in advice


# -- RESP surface: EVENTS, INFO events, audit/fence emits ---------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Cluster2:
    """Two in-process cluster-mode doors splitting the slot space —
    the test_cluster.py fixture shape, rebuilt here so these tests can
    kill one member without disturbing a shared fixture."""

    def __init__(self):
        pa, pb = _free_port(), _free_port()
        topo = {"nodes": [
            {"id": "A", "host": "127.0.0.1", "port": pa,
             "slots": [[0, 8191]]},
            {"id": "B", "host": "127.0.0.1", "port": pb,
             "slots": [[8192, NSLOTS - 1]]},
        ]}
        self._jdir = tempfile.mkdtemp(prefix="rtpu-events-")
        self.nodes = {}
        for nid, port in (("A", pa), ("B", pb)):
            cfg = Config()
            cfg.cluster_enabled = True
            cfg.cluster_topology = topo
            cfg.cluster_node_id = nid
            if nid == "A":
                # A journal on A makes WAIT a real fence there (the
                # repl.wait.timeout emit path needs a hub).  Only the
                # TPU-sketch engine owns the op journal, so A runs it.
                cfg.use_tpu_sketch(min_bucket=64)
                cfg.journal_dir = os.path.join(self._jdir, "journal-a")
                cfg.journal_fsync = "no"
            client = redisson_tpu.create(cfg)
            self.nodes[nid] = (client, RespServer(client, port=port))
        self.addr = {"A": ("127.0.0.1", pa), "B": ("127.0.0.1", pb)}

    def server(self, nid):
        return self.nodes[nid][1]

    def key_for(self, nid, prefix="k"):
        from redisson_tpu.cluster.slots import key_slot

        i = 0
        while True:
            k = f"{prefix}{i}"
            owner = "A" if key_slot(k.encode()) < 8192 else "B"
            if owner == nid:
                return k
            i += 1

    def close(self):
        for client, server in self.nodes.values():
            server.close()
            client.shutdown()
        shutil.rmtree(self._jdir, ignore_errors=True)


def _raw(addr, cmds, timeout=10.0):
    sock = socket.create_connection(addr, timeout=timeout)
    try:
        sock.settimeout(timeout)
        return exchange(sock, cmds)
    finally:
        sock.close()


@pytest.fixture()
def cluster2():
    c = _Cluster2()
    yield c
    c.close()


class TestEventsRespSurface:
    def test_ring_is_node_stamped(self, cluster2):
        assert cluster2.server("A").obs.events.node == "A"
        assert cluster2.server("B").obs.events.node == "B"

    def test_events_get_len_reset_help(self, cluster2):
        addr = cluster2.addr["A"]
        (before,) = _raw(addr, [("EVENTS", "LEN")])
        # CONFIG SET leaves an audit-trail event.
        set_r, (doc_raw,) = _raw(
            addr, [("CONFIG", "SET", "slowlog-max-len", "64")]
        ), _raw(addr, [("EVENTS", "GET", "0", "config.set")])
        doc = json.loads(doc_raw)
        assert doc["node"] == "A"
        evs = doc["events"]
        assert evs and evs[-1]["kind"] == "config.set"
        assert evs[-1]["fields"] == {
            "key": "slowlog-max-len", "value": "64",
        }
        (after,) = _raw(addr, [("EVENTS", "LEN")])
        assert after == before + 1
        # Count cap returns the newest N.
        (one_raw,) = _raw(addr, [("EVENTS", "GET", "1")])
        assert len(json.loads(one_raw)["events"]) == 1
        # RESET drops the ring but seq keeps counting (gap honesty).
        (dropped,) = _raw(addr, [("EVENTS", "RESET")])
        assert dropped == after
        (st_raw,) = _raw(addr, [("EVENTS", "GET")])
        st = json.loads(st_raw)
        assert st["events"] == [] and st["seq"] == after \
            and st["evicted"] >= dropped
        (help_lines,) = _raw(addr, [("EVENTS", "HELP")])
        assert any(b"GET" in ln for ln in help_lines)
        err = _raw(addr, [("EVENTS", "BOGUS")])[0]
        assert isinstance(err, ReplyError)

    def test_info_events_section(self, cluster2):
        addr = cluster2.addr["B"]
        _raw(addr, [("CONFIG", "SET", "slowlog-max-len", "32")])
        (info,) = _raw(addr, [("INFO", "events")])
        text = info.decode()
        assert "events_enabled:1" in text
        assert "events_seq:" in text and "events_evicted:" in text

    def test_wait_fence_timeout_emits(self, cluster2):
        # No replicas exist, so WAIT 1 must come back short AND leave
        # a repl.wait.timeout event behind.
        addr = cluster2.addr["A"]
        (acked,) = _raw(addr, [("WAIT", "1", "50")])
        assert acked == 0
        (doc_raw,) = _raw(
            addr, [("EVENTS", "GET", "0", "repl.wait.timeout")]
        )
        evs = json.loads(doc_raw)["events"]
        assert evs and evs[-1]["fields"]["asked"] == 1
        assert evs[-1]["fields"]["acked"] == 0
        assert evs[-1]["severity"] == "warn"

    def test_events_metric_family_registered(self, cluster2):
        # A RESET counts as an eviction (the record is gone either
        # way), so it also materializes the evicted counter family.
        _raw(cluster2.addr["A"],
             [("CONFIG", "SET", "slowlog-max-len", "48"),
              ("EVENTS", "RESET")])
        text = cluster2.server("A").obs.registry.render_prometheus()
        assert "rtpu_events_emitted_total" in text
        assert 'kind="config.set"' in text
        assert "rtpu_events_evicted_total" in text


# -- fleet_events(): the causal fleet timeline --------------------------------


class TestFleetEvents:
    def _client(self, cluster2):
        from redisson_tpu.cluster.client import ClusterClient

        return ClusterClient(list(cluster2.addr.values()))

    def test_merged_timeline_is_causally_ordered(self, cluster2):
        # Interleave audited CONFIG SETs across both nodes so the
        # merged timeline has something to order.
        for i in range(3):
            _raw(cluster2.addr["A"],
                 [("CONFIG", "SET", "slowlog-max-len", str(100 + i))])
            _raw(cluster2.addr["B"],
                 [("CONFIG", "SET", "slowlog-max-len", str(200 + i))])
        cc = self._client(cluster2)
        try:
            fleet = cc.fleet_events(kind="config.set")
        finally:
            cc.close()
        assert fleet["down_nodes"] == []
        evs = fleet["events"]
        assert {e["node"] for e in evs} == {"A", "B"}
        # Global order is (wall, node, seq)…
        keys = [(e["wall"], e["node"], e["seq"]) for e in evs]
        assert keys == sorted(keys)
        # …and per-node seq stays monotone inside the merge.
        for node in ("A", "B"):
            seqs = [e["seq"] for e in evs if e["node"] == node]
            assert len(seqs) >= 3 and seqs == sorted(seqs)
        assert fleet["gaps"] == {}
        for row in fleet["nodes"].values():
            assert "seq" in row and "max_events" in row

    def test_dead_member_degrades_to_error_row(self, cluster2):
        _raw(cluster2.addr["A"],
             [("CONFIG", "SET", "slowlog-max-len", "77")])
        cc = self._client(cluster2)
        try:
            cc.execute("GET", "warmup")  # learn the slot table
            client_b, server_b = cluster2.nodes["B"]
            server_b.close()
            fleet = cc.fleet_events()
            label_b = "%s:%d" % cluster2.addr["B"]
            assert fleet["down_nodes"] == [label_b]
            assert "error" in fleet["nodes"][label_b]
            assert any(e["node"] == "A" for e in fleet["events"])
        finally:
            cc.close()


# -- uniform dead-member degradation across every fleet fanout ----------------


class TestFanoutDegradation:
    """ISSUE 20 satellite: fleet_info / fleet_slowlog / fleet_traces /
    fleet_latency degrade to partial results + per-node error rows
    when a member is down — the fleet_loadmap contract, now shared
    via _fanout_degraded."""

    @pytest.fixture()
    def half_dead(self, cluster2):
        from redisson_tpu.cluster.client import ClusterClient

        cc = ClusterClient(list(cluster2.addr.values()))
        # Arm slowlog + latency everywhere, generate one entry each,
        # THEN kill B.
        for addr in cluster2.addr.values():
            _raw(addr, [
                ("CONFIG", "SET", "slowlog-log-slower-than", "0"),
                ("CONFIG", "SET", "latency-monitor-threshold", "1"),
            ])
        cc.execute("SET", "degrade-key", "v")
        cluster2.server("A").obs.latency.record("command", 25.0)
        _client_b, server_b = cluster2.nodes["B"]
        cc.execute("GET", "warmup")  # slot table before the kill
        server_b.close()
        yield cc, "%s:%d" % cluster2.addr["B"]
        cc.close()

    def test_fleet_info_partial_plus_error_row(self, half_dead):
        cc, label_b = half_dead
        fi = cc.fleet_info("server")
        assert fi["down_nodes"] == [label_b]
        assert fi["nodes"][label_b].keys() == {"error"}
        live = [
            n for n, row in fi["nodes"].items() if "error" not in row
        ]
        assert live, "no partial results from the surviving node"

    def test_fleet_slowlog_trailing_error_row(self, half_dead):
        cc, label_b = half_dead
        merged = cc.fleet_slowlog(-1)
        err_rows = [e for e in merged if "error" in e]
        assert [e["node"] for e in err_rows] == [label_b]
        assert err_rows[-1] is merged[-1], "error rows must trail"
        assert any("error" not in e for e in merged)

    def test_fleet_latency_trailing_error_row(self, half_dead):
        cc, label_b = half_dead
        merged = cc.fleet_latency()
        err_rows = [e for e in merged if "error" in e]
        assert [e["node"] for e in err_rows] == [label_b]
        live = [e for e in merged if "error" not in e]
        assert any(e["event"] == "command" for e in live)

    def test_fleet_traces_down_nodes_key(self, half_dead):
        cc, label_b = half_dead
        out = cc.fleet_traces()
        assert label_b in out.get("down_nodes", {})
        assert "error" in out["down_nodes"][label_b]

    def test_fleet_loadmap_contract_unchanged(self, half_dead):
        cc, label_b = half_dead
        lm = cc.fleet_loadmap()
        assert lm["down_nodes"] == [label_b]
        assert "error" in lm["nodes"][label_b]


# -- emit points: breaker + residency planes (in-process spot checks) ---------


class TestControlPlaneEmits:
    def test_health_breaker_open_close_emits(self):
        from redisson_tpu.executor.health import DispatchHealth
        from redisson_tpu.obs import Observability

        obs = Observability()
        dh = DispatchHealth(failure_threshold=1, open_s=0.02)
        dh.obs = obs
        try:
            dh.record_failure("cms_update", RuntimeError("boom"))
            evs = obs.events.snapshot(kind="health.breaker.open")
            assert evs and evs[-1]["severity"] == "warn"
            assert evs[-1]["fields"]["opcode"] == "cms_update"
            assert evs[-1]["fields"]["kind"] == "cms"
            # Let the window lapse, win the half-open probe slot, and
            # report success: the close path must emit too.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if dh.allow_dispatch("cms_update"):
                    break
                time.sleep(0.005)
            dh.record_success("cms_update")
            while time.monotonic() < deadline:
                if obs.events.snapshot(kind="health.breaker.close"):
                    break
                time.sleep(0.005)
            evs = obs.events.snapshot(kind="health.breaker.close")
            assert evs and evs[-1]["fields"]["kind"] == "cms"
        finally:
            dh.shutdown()

    def test_staleness_gate_emit(self, cluster2):
        # Fake a replica link far behind its bound on node A, then a
        # read must refuse with -STALEREAD and leave repl.stale_read.
        server = cluster2.server("A")
        key = cluster2.key_for("A", "stale")

        class _Link:
            def lag_ops(self):
                return 999

        server._client.config.repl_max_staleness_ops = 10
        server.replica_link = _Link()
        try:
            err = _raw(cluster2.addr["A"], [("GET", key)])[0]
            assert isinstance(err, ReplyError)
            assert "STALEREAD" in str(err)
        finally:
            server.replica_link = None
            server._client.config.repl_max_staleness_ops = 0
        evs = server.obs.events.snapshot(kind="repl.stale_read")
        assert evs and evs[-1]["fields"]["lag"] == 999
