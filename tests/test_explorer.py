"""Deterministic schedule explorer (ISSUE 9): harness units, model
checks of the four core state machines, and the historical race bugs
re-encoded as schedule tests.

Every ``@schedule_test`` body runs under the cooperative scheduler in
``redisson_tpu/analysis/explorer.py``: interleavings are explored
bounded-exhaustively, any failing schedule prints a replay token, and
``RTPU_SCHEDULE_REPLAY=<token>`` re-runs exactly that schedule.

The historical tests are MUTATION-STYLE guards: each drives the REAL
shipped code (``RespServer._rc_install``, ``TpuSketchEngine._degraded``,
``TenantGovernor.set_limits``) through the interleaving that broke the
pre-fix version — reverting the fix makes a schedule fail
deterministically.
"""

import threading
import time
import types

import numpy as np
import pytest

from redisson_tpu.analysis.explorer import (
    DeadlockError,
    ScheduleFailure,
    checkpoint,
    explore,
    schedule_test,
)

pytestmark = pytest.mark.explorer


# -- harness units ------------------------------------------------------------


def _lost_update_body():
    state = {"x": 0}

    def worker():
        v = state["x"]
        checkpoint("between read and write")
        state["x"] = v + 1

    t1 = threading.Thread(target=worker)
    t2 = threading.Thread(target=worker)
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert state["x"] == 2, f"lost update: x={state['x']}"


def test_explorer_finds_lost_update_and_replays_it():
    with pytest.raises(ScheduleFailure) as ei:
        explore(_lost_update_body, max_schedules=500, preemption_bound=2)
    token = ei.value.token
    assert token.startswith("x:")
    # The printed token replays EXACTLY the failing schedule.
    with pytest.raises(ScheduleFailure) as ei2:
        explore(_lost_update_body, replay=token)
    assert ei2.value.token == token


def test_preemption_bound_zero_hides_the_race():
    # With no preemptions allowed, each worker runs its read->write
    # atomically — the schedule space collapses and the race is
    # unreachable (the knob trades coverage for tractability).
    r = explore(_lost_update_body, max_schedules=2000, preemption_bound=0)
    assert r.complete


def test_lock_closes_the_race_exhaustively():
    def body():
        state = {"x": 0}
        lock = threading.Lock()

        def worker():
            with lock:
                v = state["x"]
                checkpoint()
                state["x"] = v + 1

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert state["x"] == 2

    r = explore(body, max_schedules=5000, preemption_bound=2)
    assert r.complete  # the whole interleaving tree was proven


def test_exploration_is_deterministic():
    counts = []
    for _ in range(2):
        r = explore(_lost_update_body, max_schedules=2000,
                    preemption_bound=0)
        counts.append(r.schedules)
    assert counts[0] == counts[1]


def test_deadlock_detection_reports_ab_ba():
    def body():
        a, b = threading.Lock(), threading.Lock()

        def t1():
            with a:
                checkpoint()
                with b:
                    pass

        def t2():
            with b:
                checkpoint()
                with a:
                    pass

        x1 = threading.Thread(target=t1)
        x2 = threading.Thread(target=t2)
        x1.start()
        x2.start()
        x1.join()
        x2.join()

    with pytest.raises(ScheduleFailure) as ei:
        explore(body, max_schedules=2000, preemption_bound=2)
    assert isinstance(ei.value.__cause__, DeadlockError)
    assert "lock" in str(ei.value.__cause__)


def test_virtual_clock_orders_sleeps_instantly():
    def body():
        order = []

        def s(tag, secs):
            time.sleep(secs)
            order.append(tag)

        t1 = threading.Thread(target=s, args=("slow", 100.0))
        t2 = threading.Thread(target=s, args=("fast", 5.0))
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        assert order == ["fast", "slow"], order

    t0 = time.monotonic()
    r = explore(body, max_schedules=200)
    assert r.complete
    assert time.monotonic() - t0 < 5.0  # 100 virtual seconds cost ~nothing


def test_queue_and_future_primitives_are_cooperative():
    import queue
    from concurrent.futures import Future

    def body():
        q = queue.Queue(maxsize=1)
        f = Future()
        got = []

        def consumer():
            for _ in range(3):
                got.append(q.get())
            f.set_result(sum(got))

        def producer():
            for i in range(3):
                q.put(i)

        c = threading.Thread(target=consumer)
        p = threading.Thread(target=producer)
        c.start()
        p.start()
        assert f.result(timeout=30) == 3
        c.join()
        p.join()
        assert got == [0, 1, 2], got

    r = explore(body, max_schedules=400, preemption_bound=1)
    assert r.schedules >= 1


# -- model check 1: coalescer flush / park / merge ----------------------------


class _FakeLazy:
    def __init__(self, v):
        self._v = v

    def result(self):
        return self._v

    def get(self):
        return self._v


@schedule_test(max_schedules=60, random_schedules=24, preemption_bound=1,
               max_steps=200000)
def test_model_coalescer_flush_park_merge():
    """Two producer threads × flaky-once dispatch: across every explored
    schedule, (a) every future resolves with its own op's value, (b) no
    op is lost or double-dispatched through the park/backoff/merge
    machinery, (c) shutdown drains cleanly."""
    from redisson_tpu.executor.coalescer import BatchCoalescer

    calls = []
    flaky = {"armed": True}

    def dispatch(cols):
        if flaky["armed"]:
            flaky["armed"] = False
            raise RuntimeError("transient dispatch failure")
        arr = np.asarray(cols[0])
        calls.append(arr.copy())
        return _FakeLazy(arr * 2)

    c = BatchCoalescer(batch_window_us=200, max_batch=4, max_inflight=2,
                       retry_attempts=3, retry_interval_s=0.01,
                       adaptive_window=False, adaptive_inflight=False)
    futs = []

    def producer(base):
        for i in range(2):
            futs.append((base + i,
                         c.submit(("op", 1), dispatch,
                                  (np.asarray([base + i]),), 1,
                                  pool_key="p")))

    t = threading.Thread(target=producer, args=(100,))
    t.start()
    producer(200)
    t.join()
    for val, f in futs:
        got = f.result(timeout=60)
        assert list(got) == [val * 2], (val, got)
    c.drain(timeout=60)
    total = sum(len(a) for a in calls)
    assert total == 4, f"ops dispatched {total} != 4 submitted"
    c.shutdown()


@schedule_test(max_schedules=40, random_schedules=16, preemption_bound=1,
               max_steps=200000)
def test_model_coalescer_deadline_shed_vs_healthy_traffic():
    """An expired-at-flush segment is shed without dispatch while a
    healthy segment behind it still completes — in every schedule."""
    from redisson_tpu.executor.coalescer import BatchCoalescer
    from redisson_tpu.executor.failures import DeadlineExceededError

    dispatched = []

    def dispatch(cols):
        arr = np.asarray(cols[0])
        dispatched.append(arr.copy())
        return _FakeLazy(arr)

    # A huge flush window parks young segments in the queue, so the
    # doomed op is still QUEUED when its deadline lapses (virtually).
    c = BatchCoalescer(batch_window_us=10_000_000, max_batch=4,
                       adaptive_window=False, adaptive_inflight=False)
    # Deadline generous enough to pass the submit-time check, expired
    # by the time the flush loop sweeps (virtual sleep below).
    dead = c.submit(("doomed", 1), dispatch, (np.asarray([1]),), 1,
                    pool_key="d", deadline=time.monotonic() + 0.001)
    time.sleep(0.05)  # virtual: expires the deadline while queued
    live = c.submit(("live", 1), dispatch, (np.asarray([2]),), 1,
                    pool_key="l")
    assert list(live.result(timeout=60)) == [2]
    with pytest.raises(DeadlineExceededError):
        dead.result(timeout=60)
    assert all(1 not in a for a in dispatched), \
        "expired op reached the device"
    c.shutdown()


# -- model check 2: breaker CLOSED -> OPEN -> HALF_OPEN -----------------------


@schedule_test(max_schedules=400, random_schedules=64, preemption_bound=2)
def test_model_breaker_single_probe_half_open():
    """Across every schedule: the open window refuses dispatch, exactly
    ONE of two racing callers is admitted as the half-open probe, and
    the probe's success closes the circuit."""
    from redisson_tpu.executor.health import (
        BreakerBoard, CLOSED, OPEN,
    )

    board = BreakerBoard(failure_threshold=2, open_s=1.0,
                         clock=time.monotonic)
    board.record_failure(0, "op")
    board.record_failure(0, "op")
    assert board.states()[(0, "op")] == OPEN
    assert not board.allow(0, "op"), "open circuit admitted a dispatch"

    time.sleep(1.5)  # virtual: the open window elapses
    admitted = []

    def prober(tag):
        checkpoint(f"probe {tag}")
        if board.allow(0, "op"):
            admitted.append(tag)

    t1 = threading.Thread(target=prober, args=("a",))
    t2 = threading.Thread(target=prober, args=("b",))
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert len(admitted) == 1, \
        f"half-open admitted {admitted} — one probe at a time"
    board.record_success(0, "op")
    assert board.states()[(0, "op")] == CLOSED
    assert board.allow(0, "op")


@schedule_test(max_schedules=300, random_schedules=64, preemption_bound=2)
def test_model_breaker_failure_success_race_never_wedges():
    """record_failure / record_success racing from two threads: the
    breaker always lands in a legal state and a later success from
    half-open always closes (no schedule wedges it open forever)."""
    from redisson_tpu.executor.health import BreakerBoard, CLOSED

    board = BreakerBoard(failure_threshold=1, open_s=0.5,
                         clock=time.monotonic)

    def failer():
        board.record_failure(0, "op")

    def succeeder():
        board.record_success(0, "op")

    t1 = threading.Thread(target=failer)
    t2 = threading.Thread(target=succeeder)
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert board.states()[(0, "op")] in ("closed", "open")
    # Recovery is always reachable: wait out the window, probe, succeed.
    time.sleep(1.0)
    assert board.allow(0, "op")
    board.record_success(0, "op")
    assert board.states()[(0, "op")] == CLOSED


# -- model check 3: near-cache epoch protocol ---------------------------------


@schedule_test(max_schedules=600, random_schedules=64, preemption_bound=2)
def test_model_nearcache_never_serves_stale_after_write():
    """The whole epoch correctness argument, model-checked: a reader
    that captured its epoch pair before submitting can NEVER install a
    pre-write value that a post-write probe then serves.  Removing the
    exit bump (or the capture-before-submit guard) makes a schedule
    fail."""
    from redisson_tpu.cache.lru import MISS, ShardedLRUStore
    from redisson_tpu.cache.nearcache import SketchNearCache

    store = ShardedLRUStore(max_bytes=1 << 20, nshards=2)
    nc = SketchNearCache(store, max_batch=16)
    name, key = "obj", (1, 2)
    truth = {"v": 0}

    def writer():
        nc.note_write(name)       # entry bump: write is in flight
        checkpoint("device applies the write")
        truth["v"] = 1
        checkpoint("between apply and exit bump")
        nc.note_write(name)       # exit bump: retires in-window installs

    def reader():
        captured = nc.epochs(name)  # capture BEFORE submitting the miss
        checkpoint("miss dispatched")
        seen = truth["v"]           # the device-side read, ordered freely
        checkpoint("result resolves")
        nc.install(name, key, seen, captured=captured, monotone=False)

    w = threading.Thread(target=writer)
    r = threading.Thread(target=reader)
    w.start()
    r.start()
    w.join()
    r.join()
    v = nc.probe(name, key)
    assert v is MISS or v == truth["v"], \
        f"stale value {v!r} served after the write (truth={truth['v']})"


# -- model check 4 + historical race 3: tenant governor -----------------------


@schedule_test(max_schedules=500, random_schedules=64, preemption_bound=2)
def test_model_governor_charge_release_balance():
    """Concurrent admit/release across two tenants: in-flight charges
    never go negative, never leak, and capacity freed by release is
    admittable again in every schedule."""
    from redisson_tpu.executor.failures import TenantThrottledError
    from redisson_tpu.tenancy.registry import TenantGovernor

    gov = TenantGovernor(max_inflight=4, clock=time.monotonic)

    def tenant_load(tenant):
        gov.admit(tenant, 3)
        checkpoint(f"{tenant} ops in flight")
        gov.release(tenant, 3)
        gov.admit(tenant, 2)
        gov.release(tenant, 2)

    t1 = threading.Thread(target=tenant_load, args=("a",))
    t2 = threading.Thread(target=tenant_load, args=("b",))
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    assert gov._inflight == {}, f"leaked charges: {gov._inflight}"
    gov.admit("a", 4)  # full quota must be free again
    with pytest.raises(TenantThrottledError):
        gov.admit("a", 1)
    gov.release("a", 4)


@schedule_test(max_schedules=400, random_schedules=64, preemption_bound=2)
def test_history_governor_stranded_inflight_charges():
    """PR 7 review bug, re-encoded (CHANGES.md PR 7 'Review hardening'):
    release() is skipped while max_inflight is 0, so charges taken
    before a disable were stranded forever once re-enabled — the fix
    makes set_limits clear in-flight charges too.  Reverting that
    clear makes every schedule here fail."""
    from redisson_tpu.tenancy.registry import TenantGovernor

    gov = TenantGovernor(max_inflight=4, clock=time.monotonic)

    def tenant():
        gov.admit("t", 3)
        checkpoint("ops in flight across the disable")
        gov.release("t", 3)  # no-op while the quota is disabled

    def operator():
        checkpoint("operator reconfigures")
        gov.set_limits(max_inflight=0)   # disable
        checkpoint("quota disabled")
        gov.set_limits(max_inflight=4)   # re-enable

    t1 = threading.Thread(target=tenant)
    t2 = threading.Thread(target=operator)
    t1.start()
    t2.start()
    t1.join()
    t2.join()
    # Whatever the interleaving, the tenant must not be throttled
    # forever by charges stranded across the disable/re-enable cycle.
    if gov.max_inflight > 0:
        gov.admit("t", 2)
        gov.release("t", 2)


# -- historical race 1: the _rc_install epoch race (PR 5 review) --------------


def _resp_server_stub():
    from redisson_tpu.serve.resp import RespServer

    stub = types.SimpleNamespace(
        _write_epoch=0,
        _epoch_lock=threading.Lock(),
        obs=None,
        response_cache_size=128,
    )
    return RespServer, stub


@schedule_test(max_schedules=600, random_schedules=64, preemption_bound=2)
def test_history_rc_install_drops_cross_epoch_frame():
    """PR 5 review bug, re-encoded (CHANGES.md PR 5 'Review hardening'):
    _rc_install used to RE-HOME a frame computed before a concurrent
    write under the new epoch — a pre-write reply outlived the write.
    The fix drops the frame when the epoch moved between probe and
    install.  This drives the REAL RespServer methods; reverting the
    drop (falling through to install after the epoch check) fails."""
    RespServer, srv = _resp_server_stub()
    truth = {"v": b"+0\r\n"}
    acked = {"done": False}
    rc: dict = {}
    rc_state = [srv._write_epoch]
    name, cmd = "GET", (b"GET", b"k")

    def writer():
        # The real server's ordering (_safe_dispatch): the command
        # APPLIES, then the epoch bumps, then the reply is sent — the
        # write is ACKED only after the bump (resp.py ~830).  Until
        # the ack, a concurrent reader may legally see pre-write state
        # (same as two independent Redis clients).
        checkpoint("write arrives")
        truth["v"] = b"+1\r\n"
        RespServer._bump_write_epoch(srv)
        acked["done"] = True

    def connection():
        hit = RespServer._rc_probe(srv, rc, rc_state, name, cmd)
        if hit is None:
            checkpoint("reply computed")
            frame = truth["v"]  # may predate the concurrent write
            checkpoint("install")
            RespServer._rc_install(srv, rc, rc_state, name, cmd, frame)
        # Second identical command in the same pipeline window: once
        # the write is ACKED, a cached hit must never predate it.
        hit2 = RespServer._rc_probe(srv, rc, rc_state, name, cmd)
        if hit2 is not None and acked["done"]:
            assert hit2 == truth["v"], \
                f"stale cached reply {hit2!r} served after the acked " \
                f"write (truth {truth['v']!r})"

    w = threading.Thread(target=writer)
    c = threading.Thread(target=connection)
    w.start()
    c.start()
    w.join()
    c.join()


# -- historical race 2: mirror seeding vs reconcile (PR 3 round 2) ------------


class _HealthStub:
    def __init__(self):
        self.degraded = {"bloom"}

    @property
    def any_degraded(self):
        return bool(self.degraded)

    def degraded_kind(self, kind):
        return kind in self.degraded


@schedule_test(max_schedules=800, random_schedules=64, preemption_bound=2,
               max_steps=100000)
def test_history_mirror_seed_epoch_guard():
    """PR 3 second-round bug, re-encoded (CHANGES.md PR 3): mirror
    seeding runs OUTSIDE the mirror lock.  The lost-acked-writes
    schedule: a SLOW seeder snapshots the device row ("v0"), then a
    faster op seeds+writes the mirror ("v1" = v0 + an acked write),
    reconcile writes "v1" back to the device and drops the mirror
    (bumping _mirror_epoch under the lock), the breaker re-opens —
    and the slow seeder finally re-locks holding its ancient "v0"
    snapshot.  The epoch guard in the REAL TpuSketchEngine._degraded
    (`if self._mirror_epoch != epoch: continue`) discards the stale
    row and re-seeds; reverting it installs "v0" as the mirror,
    resurrecting pre-reconcile state — the acked write dies on the
    next write-back."""
    from redisson_tpu.objects.engines import TpuSketchEngine

    device = {"row": "v0"}
    health = _HealthStub()
    # row=0: a DEVICE-resident entry (ISSUE 14 gave _degraded a
    # row-less fast path that would short-circuit the seeding under
    # test here).
    entry = types.SimpleNamespace(name="t", kind="bloom", row=0)
    stub = types.SimpleNamespace(
        _mirrors={},
        _mirror_lock=threading.RLock(),
        _mirror_epoch=0,
        health=health,
    )

    def seed_row(_entry):
        checkpoint("seed read dispatched")
        row = device["row"]
        checkpoint("seed read resolves")
        return row

    def install_mirror(_entry, row):
        stub._mirrors[_entry.name] = row

    stub._seed_row = seed_row
    stub._install_mirror = install_mirror

    def slow_seeder():
        TpuSketchEngine._degraded(stub, entry)

    def mirror_write_reconcile_flap():
        # A faster op's mirror takes an acked write...
        checkpoint("fast op seeds the mirror")
        with stub._mirror_lock:
            stub._mirrors["t"] = "v1"  # v0 + an acked degraded write
        checkpoint("reconcile starts")
        # ...reconcile writes it back and drops it (the real
        # _reconcile_kind's discipline: write-back, drop, epoch bump,
        # clear — all under the mirror lock)...
        with stub._mirror_lock:
            for n in list(stub._mirrors):
                device["row"] = stub._mirrors.pop(n)
            stub._mirror_epoch += 1
            health.degraded = set()
        checkpoint("breaker re-opens")
        # ...and the kind flaps back to degraded.
        health.degraded = {"bloom"}

    s = threading.Thread(target=slow_seeder)
    r = threading.Thread(target=mirror_write_reconcile_flap)
    s.start()
    r.start()
    s.join()
    r.join()
    mirror = stub._mirrors.get("t")
    assert mirror is None or mirror == device["row"], (
        f"stale row {mirror!r} installed as mirror while the device "
        f"holds {device['row']!r} — the acked write would be lost on "
        f"the next write-back"
    )


# -- model check 5 (ISSUE 10): journal group-commit state machine -------------


def _journal_commit_body(journal_cls, segment_bytes=512,
                         check_rotation=True):
    """Two producers × a rotating group-commit writer.  Invariants, in
    EVERY schedule: (a) under appendfsync=always, a wait_durable return
    implies an fsync barrier actually ran (the ack-durability commit
    barrier); (b) across writer park/flush/rotate no record is lost or
    duplicated — the on-disk seqs are exactly 1..N, once each.

    ``check_rotation=False`` runs the same machine with a large segment
    (no size-rotation fsyncs inside a batch) — the configuration the
    commit-barrier mutation guard needs, since a rotation's own fsync
    would mask a reverted ack barrier."""
    import os as _os
    import tempfile

    from redisson_tpu.durability.journal import _scan_segment

    tmp = tempfile.mkdtemp()
    # Tiny segment bound + fat records: the 6 records force rotations.
    j = journal_cls(
        tmp, fsync_policy="always", max_segment_bytes=segment_bytes
    )
    pad = np.arange(64, dtype=np.uint64)  # ~512B/record on the wire

    def producer(base):
        for i in range(3):
            seq = j.append(
                {"op": "x", "name": "p", "i": base + i, "pad": pad}
            )
            checkpoint(f"appended {base + i}")
            assert j.wait_durable(seq, timeout=60.0)
            # The commit barrier: an acked (durable-reported) record
            # must be covered by a real fsync, never just a write.
            assert j.stats()["fsyncs"] >= 1, (
                "wait_durable returned before any fsync ran "
                "(commit barrier reverted?)"
            )
            assert j.durable_seq() >= seq

    t = threading.Thread(target=producer, args=(100,))
    t.start()
    producer(200)
    t.join()
    j.close()
    names = sorted(
        fn for fn in _os.listdir(tmp) if fn.endswith(".rtj")
    )
    seqs = []
    payload_is = []
    for fn in names:
        first_seq, frames, _end, clean = _scan_segment(
            _os.path.join(tmp, fn)
        )
        assert clean, f"segment {fn} torn after a clean close"
        seqs.extend(range(first_seq, first_seq + len(frames)))
        payload_is.append(len(frames))
    assert sorted(seqs) == list(range(1, 7)), (
        f"records lost/duplicated across park/flush/rotate: {seqs}"
    )
    if check_rotation:
        assert len(names) >= 2, "tiny segments must have rotated"


@schedule_test(max_schedules=40, random_schedules=16, preemption_bound=1,
               max_steps=400000)
def test_model_journal_group_commit_always():
    from redisson_tpu.durability.journal import OpJournal

    _journal_commit_body(OpJournal)


def test_model_journal_commit_barrier_mutation_guard():
    """Reverting the commit barrier — durability reported at WRITE time
    instead of fsync time — must be CAUGHT by the model: some schedule
    sees wait_durable return with zero fsyncs run."""
    from redisson_tpu.durability.journal import OpJournal

    class _BarrierReverted(OpJournal):
        def _write_batch(self, batch):
            super()._write_batch(batch)
            with self._lock:
                # The reverted commit barrier: durable == written.
                self._durable_seq = self._written_seq
                self._durable_cv.notify_all()

        def _do_fsync(self):
            # The fsync still happens eventually — the bug is ORDER
            # (ack before barrier), which only a schedule can see.
            import time as _t

            _t.sleep(0.01)  # virtual: lets an ack overtake the fsync
            super()._do_fsync()

    with pytest.raises(ScheduleFailure):
        explore(
            lambda: _journal_commit_body(
                _BarrierReverted, segment_bytes=1 << 20,
                check_rotation=False,
            ),
            max_schedules=200, preemption_bound=1, max_steps=400000,
        )


# -- reactor front door model (ISSUE 11 satellite) ----------------------------
#
# The RESP vectorizer's run fences + the reactor's tick machinery: the
# merged pass collects each connection's commands in arrival order,
# partial consumption (the reply-buffer bound) requeues the tail at the
# FRONT, a detached worker freezes its connection, and cross-thread
# reply enqueues ride the outbuf lock.  The model drives the REAL
# _Reactor._run_pass/_flush/enqueue code under explored schedules and
# asserts: no schedule reorders one connection's replies or loses an op
# across a tick boundary.


def _reactor_pass_body():
    from collections import deque

    from redisson_tpu.serve import reactor as rx

    class _FakeSock:
        def __init__(self, fd):
            self._fd = fd
            self.sent = bytearray()

        def fileno(self):
            return self._fd

        def getpeername(self):
            raise OSError("not connected")

        def send(self, view):
            checkpoint("wire send")
            self.sent += bytes(view)
            return len(view)

        def close(self):
            pass

        def shutdown(self, how):
            pass

    class _StubServer:
        _requirepass = None
        idle_timeout_s = 0.0
        output_buffer_limit = 0
        output_buffer_soft_seconds = 0.0
        obs = None

        def _dispatch_merged(self, cmds, ctxs):
            # Consume ONE command per pass: every tick with more than
            # one command exercises the requeue-at-front path, and a
            # cut can land on a connection that still has uncollected
            # commands behind a detach barrier (where front-vs-back
            # requeue order is actually observable).
            checkpoint("merged dispatch")
            return [b"+" + cmds[0][0] + b"\r\n"], 1

        def _safe_dispatch(self, cmd, ctx):
            checkpoint("detached dispatch")
            return b"+" + cmd[0] + b"\r\n"

    class _NoopWake:
        def send(self, data):
            return len(data)

    server = _StubServer()
    r = object.__new__(rx._Reactor)
    r.server = server
    r.conns = {}
    r._new = deque()
    r._stopping = False
    r.tid = 0
    r._attention = set()
    r.want_flush = set()
    r._wake_w = _NoopWake()

    conn_a = rx._RConn(_FakeSock(1001), server, r)
    conn_b = rx._RConn(_FakeSock(1002), server, r)
    # BLPOP rides a detached worker: conn A freezes mid-stream, PING3
    # must still follow the worker's reply.
    # Pending entries are (family, argv) pairs (ISSUE 17 native tick):
    # family 0 = non-fusable, which is all this model needs.
    conn_a.pending.extend(
        [(0, [b"PING1"]), (0, [b"PING2"]),
         (0, [b"BLPOP", b"q", b"1"]), (0, [b"PING3"])]
    )
    conn_b.pending.extend([(0, [b"PING4"]), (0, [b"PING5"])])
    conn_a.registered = conn_b.registered = False
    r.conns = {1001: conn_a, 1002: conn_b}
    # _read_ready would have flagged both as having framed commands.
    r._attention = {conn_a, conn_b}

    def done():
        return all(
            not c.pending and not c.busy and not c.outbuf
            for c in (conn_a, conn_b)
        )

    for _ in range(60):
        r._run_pass(0.0)
        checkpoint("tick boundary")
        if done():
            break
        # Virtual-clock sleep: blocks this thread so the scheduler can
        # run a pending detached worker (costs µs — the clock only
        # advances when every thread blocks).
        time.sleep(0.001)
    assert done(), (
        f"ops lost across tick boundary: a={list(conn_a.pending)} "
        f"busy={conn_a.busy} b={list(conn_b.pending)}"
    )
    # Per-connection reply streams: exact command order, nothing lost,
    # nothing duplicated — whatever the tick/worker interleaving.
    assert bytes(conn_a.sock.sent) == (
        b"+PING1\r\n+PING2\r\n+BLPOP\r\n+PING3\r\n"
    ), f"conn A replies reordered: {bytes(conn_a.sock.sent)!r}"
    assert bytes(conn_b.sock.sent) == b"+PING4\r\n+PING5\r\n", (
        f"conn B replies reordered: {bytes(conn_b.sock.sent)!r}"
    )


@schedule_test(max_schedules=150, random_schedules=32, preemption_bound=2,
               max_steps=400000)
def test_model_reactor_tick_ordering():
    _reactor_pass_body()


def test_model_reactor_requeue_mutation_guard():
    """Reverting the requeue-at-FRONT discipline (appending the
    unconsumed tail at the BACK, after newly-framed commands) must be
    caught: the model's partial consumption makes some schedule emit
    conn replies out of command order."""
    from redisson_tpu.serve import reactor as rx

    orig = rx._Reactor._run_pass

    def run_pass_reverted(self, now):
        # Monkeypatched deque whose appendleft APPENDS — exactly the
        # bug class the model exists to catch.
        for c in self.conns.values():
            if not isinstance(c.pending, _TailAppendDeque):
                c.pending = _TailAppendDeque(c.pending)
        return orig(self, now)

    from collections import deque as _deque

    class _TailAppendDeque(_deque):
        def appendleft(self, item):
            self.append(item)

    rx._Reactor._run_pass = run_pass_reverted
    try:
        with pytest.raises(ScheduleFailure):
            explore(
                _reactor_pass_body,
                max_schedules=150, preemption_bound=2, max_steps=400000,
            )
    finally:
        rx._Reactor._run_pass = orig


# -- in-node handoff model (ISSUE 17 satellite) -------------------------------
#
# The per-core front door's handoff leg rides the reactor's detach path:
# a sibling-owned command freezes its connection (busy) until the unix
# leg's relayed frame is enqueued, so NO schedule may lose or reorder
# one connection's replies across a worker handoff — local commands
# queued behind the handoff wait for its reply, whatever the worker
# thread's timing.


def _handoff_pass_body(conn_cls=None, small=False):
    from collections import deque

    from redisson_tpu.serve import reactor as rx

    class _FakeSock:
        def __init__(self, fd):
            self._fd = fd
            self.sent = bytearray()

        def fileno(self):
            return self._fd

        def getpeername(self):
            raise OSError("not connected")

        def send(self, view):
            checkpoint("wire send")
            self.sent += bytes(view)
            return len(view)

        def close(self):
            pass

        def shutdown(self, how):
            pass

    class _StubMulticore:
        # Stand-in for MulticoreRouter.needs_handoff: HOP* commands are
        # owned by a sibling worker, everything else is worker-local.
        def needs_handoff(self, cmd):
            return cmd[0].startswith(b"HOP")

    class _StubServer:
        _requirepass = None
        idle_timeout_s = 0.0
        output_buffer_limit = 0
        output_buffer_soft_seconds = 0.0
        obs = None
        multicore = _StubMulticore()

        def _dispatch_merged(self, cmds, ctxs):
            checkpoint("merged dispatch")
            return [b"+" + cmds[0][0] + b"\r\n"], 1

        def _safe_dispatch(self, cmd, ctx):
            # The handoff leg: ship to the sibling, block on its reply,
            # relay the frame verbatim.  The checkpoint is the leg's
            # round-trip window — the schedule explorer interleaves the
            # event loop against it.
            checkpoint("handoff leg rtt")
            return b"+" + cmd[0] + b"\r\n"

    class _NoopWake:
        def send(self, data):
            return len(data)

    server = _StubServer()
    r = object.__new__(rx._Reactor)
    r.server = server
    r.conns = {}
    r._new = deque()
    r._stopping = False
    r.tid = 0
    r._attention = set()
    r.want_flush = set()
    r._wake_w = _NoopWake()

    cls = conn_cls or rx._RConn
    conn_a = cls(_FakeSock(1001), server, r)
    if small:
        # Minimal shape for the mutation guard's exploration: one
        # handoff with one local command queued behind it.
        conn_a.pending.extend([(0, [b"HOP2"]), (0, [b"PING3"])])
        conns = (conn_a,)
        want_a = b"+HOP2\r\n+PING3\r\n"
    else:
        conn_b = cls(_FakeSock(1002), server, r)
        conn_a.pending.extend(
            [(0, [b"PING1"]), (0, [b"HOP2"]), (0, [b"PING3"]),
             (0, [b"HOP4"]), (0, [b"PING5"])]
        )
        conn_b.pending.extend([(0, [b"HOP6"]), (0, [b"PING7"])])
        conn_b.registered = False
        conns = (conn_a, conn_b)
        want_a = b"+PING1\r\n+HOP2\r\n+PING3\r\n+HOP4\r\n+PING5\r\n"
    conn_a.registered = False
    r.conns = {c.fd: c for c in conns}
    r._attention = set(conns)

    def done():
        return all(
            not c.pending and not c.busy and not c.outbuf
            for c in conns
        )

    def _state():
        return tuple(
            (len(c.pending), len(c.outbuf), len(c.sock.sent))
            for c in conns
        )

    prev = _state()
    for _ in range(80):
        r._run_pass(0.0)
        checkpoint("tick boundary")
        if done():
            break
        # Stay RUNNABLE while the loop is making progress (so schedules
        # where the event loop races an in-flight handoff leg are
        # explorable); only block on the virtual clock when a pass was
        # a no-op (waiting on the worker thread).
        cur = _state()
        if cur == prev:
            time.sleep(0.001)
        prev = cur
    assert done(), (
        f"ops lost across handoff: a={list(conn_a.pending)} "
        f"busy={conn_a.busy}"
    )
    assert bytes(conn_a.sock.sent) == want_a, (
        f"conn A replies reordered across handoff: {bytes(conn_a.sock.sent)!r}"
    )
    if not small:
        assert bytes(conn_b.sock.sent) == b"+HOP6\r\n+PING7\r\n", (
            f"conn B replies reordered: {bytes(conn_b.sock.sent)!r}"
        )


@schedule_test(max_schedules=150, random_schedules=32, preemption_bound=2,
               max_steps=400000)
def test_model_handoff_no_lost_or_reordered_replies():
    _handoff_pass_body()


def test_model_handoff_busy_freeze_mutation_guard():
    """Reverting the handoff busy-freeze — the event loop keeps
    dispatching a connection's local commands while its handoff leg is
    still in flight on the worker thread — must be caught: some schedule
    emits PING3's reply before HOP2's, and the failure carries a replay
    token."""
    from redisson_tpu.serve import reactor as rx

    class _NoFreezeConn(rx._RConn):
        # The reverted fix: the busy flag never sticks, so the loop
        # races the in-flight leg.
        @property
        def busy(self):
            return False

        @busy.setter
        def busy(self, v):
            pass

    with pytest.raises(ScheduleFailure) as ei:
        explore(
            lambda: _handoff_pass_body(conn_cls=_NoFreezeConn, small=True),
            max_schedules=600, preemption_bound=3, max_steps=400000,
        )
    assert ei.value.token, "failing schedule must carry a replay token"


# -- vectorizer run fences (ISSUE 11 satellite: the PR 9 leftover) ------------
#
# Property checks against the REAL collectors: a run may never cross a
# key change, a malformed member, or a connection that is mid-MULTI /
# unauthenticated (the fences that keep fused execution bit-identical
# to sequential dispatch).


def _fctx(**kw):
    ns = types.SimpleNamespace(
        authed=True, in_multi=False, op_deadline_ms=None
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_run_fence_key_change_barriers():
    from redisson_tpu.serve.resp import RespServer

    a = _fctx()
    batch = [
        [b"BF.ADD", b"k", b"x"], [b"BF.EXISTS", b"k", b"y"],
        [b"BF.EXISTS", b"k2", b"z"],
    ]
    run = RespServer._collect_bf_run(batch, 0, [a, a, a])
    assert run is not None and run[1] == 2  # k2 ends the run

    cms = [
        [b"CMS.QUERY", b"c", b"x"], [b"CMS.QUERY", b"c", b"y"],
        [b"CMS.QUERY", b"c2", b"z"],
    ]
    run = RespServer._collect_cms_run(cms, 0, [a, a, a])
    assert run is not None and run[1] == 2


def test_run_fence_deadline_mismatch_barrier():
    # A CLIENT DEADLINE connection's command must never fuse into a run
    # headed by a different-deadline connection: the run executes under
    # ONE deadline scope (the head's).
    from redisson_tpu.serve.resp import RespServer

    a, d = _fctx(), _fctx(op_deadline_ms=50)
    batch = [[b"BF.EXISTS", b"k", b"x"], [b"BF.EXISTS", b"k", b"y"]]
    assert RespServer._collect_bf_run(batch, 0, [a, d]) is None
    assert RespServer._collect_bf_run(batch, 0, [d, d]) is not None


def test_run_fence_multi_and_unauth_barrier():
    from redisson_tpu.serve.resp import RespServer

    a, m, u = _fctx(), _fctx(in_multi=True), _fctx(authed=False)
    batch = [[b"BF.EXISTS", b"k", b"x"], [b"BF.EXISTS", b"k", b"y"]]
    # A mid-MULTI (or unauthenticated) connection's command must QUEUE
    # (or NOAUTH), never execute inside a fused run.
    assert RespServer._collect_bf_run(batch, 0, [a, m]) is None
    assert RespServer._collect_bf_run(batch, 0, [a, u]) is None
    assert RespServer._collect_get_run(
        [[b"GET", b"k"], [b"GET", b"k"]], 0, [a, m]
    ) is None
    assert RespServer._collect_cms_run(
        [[b"CMS.QUERY", b"c", b"x"], [b"CMS.QUERY", b"c", b"y"]],
        0, [a, u],
    ) is None


def test_run_fence_malformed_member_barriers():
    from redisson_tpu.serve.resp import RespServer

    a = _fctx()
    # Non-integer SETBIT offset: sequential dispatch would error — it
    # must barrier the run, not poison the fused launch.
    batch = [
        [b"SETBIT", b"k", b"1", b"1"], [b"SETBIT", b"k", b"oops", b"1"],
        [b"GETBIT", b"k", b"1"],
    ]
    run = RespServer._collect_bit_run(batch, 0, [a, a, a])
    assert run is None  # fence at index 1 leaves a 1-command non-run
    short = [[b"CMS.QUERY", b"c", b"x"], [b"CMS.QUERY", b"c"]]
    assert RespServer._collect_cms_run(short, 0, [a, a]) is None


# -- model check 8 (ISSUE 14): residency-ladder state machine -----------------


def _residency_ladder_body(promote_repoints_before_drop=True,
                           full_cast=True):
    """Faithful miniature of storage/residency.py's transition protocol
    on ONE object: a writer (the engine's gate-held check->submit
    discipline), a mover cycling demote -> promote, a breaker flap
    (open -> epoch-guarded seed -> reconcile write-back), and a
    snapshot reader using the capture-row-BEFORE-check + _tier_row
    read discipline.  The object's value is a monotone counter, so
    every invariant is a one-liner:

    - a read must resolve to a REAL location (mirror or row >= 0) and
      must see every write acked before the read began (no stale
      reads, single-register linearizability);
    - after quiescence the truth equals the acked count (no schedule
      loses an acked write).

    ``promote_repoints_before_drop=False`` mutates promotion into the
    drop-mirror-THEN-repoint ordering the shipped code forbids
    (residency.py repoints ``entry.row`` while still holding the
    mirror lock, before ``del _mirrors[name]``) — under that mutation
    a reader can catch the object with no mirror AND no row, the
    exact window the real ordering closes.  ``full_cast=False`` spawns
    only the mover + reader — the focused cast the mutation hunt
    needs (4 threads push the failing interleaving past the bounded
    search's horizon; 2 keep it a few hundred schedules deep)."""
    gate = threading.RLock()    # the engine's journal gate
    mlock = threading.RLock()   # the engine's mirror lock
    name = "t"
    st = {
        "row": 0,               # entry.row (-1 = no device row)
        "rows": {0: 0},         # device storage; quarantined rows keep
        "next_row": 1,          # their pre-demotion contents (reclaim
        "quarantine": [],       # is a later, post-drain cycle)
        "mirrors": {},          # name -> {"v": int, "res": bool}
        "epoch": 0,             # _mirror_epoch
        "acked": 0,
        "degraded": False,      # the kind's breaker
    }

    def writer():
        # The engine's mutating-op discipline: the WHOLE
        # check-residency -> submit window runs under the gate, so no
        # write is in flight while a transition holds it.
        for _ in range(2):
            with gate:
                with mlock:
                    mir = st["mirrors"].get(name)
                    if mir is None and st["degraded"] and st["row"] >= 0:
                        # Degraded write: seed the breaker mirror from
                        # the (gate-stable) row, then apply to it.
                        mir = {"v": st["rows"][st["row"]], "res": False}
                        st["mirrors"][name] = mir
                    if mir is not None:
                        mir["v"] += 1
                        st["acked"] += 1
                        continue_to_next = True
                    else:
                        continue_to_next = False
                if not continue_to_next:
                    # Apply is modeled atomic with the gate-held
                    # submit: every row reader that could observe the
                    # gap (demote's capture, the breaker seed, the
                    # snapshot's _host_row) DRAINS the coalescer before
                    # reading, so a gate-submitted op has landed by the
                    # time any of them sees the row.
                    st["rows"][st["row"]] += 1
                    st["acked"] += 1
            checkpoint("between writes")

    def mover():
        # demote (residency.py demote(), line for line) ...
        with gate:
            if st["row"] >= 0 and not st["degraded"] \
                    and name not in st["mirrors"]:
                checkpoint("demote: row captured after drain")
                val = st["rows"][st["row"]]
                checkpoint("demote: mirror built, pre-install")
                with mlock:
                    if name not in st["mirrors"] and not st["degraded"]:
                        st["mirrors"][name] = {"v": val, "res": True}
                        st["epoch"] += 1
                        st["quarantine"].append(st["row"])
                        st["row"] = -1
        checkpoint("between demote and promote")
        # ... then promote (residency.py promote())
        with gate:
            if st["row"] < 0 and not st["degraded"]:
                with mlock:
                    mir = st["mirrors"].get(name)
                    if mir is not None and mir["res"]:
                        row = st["next_row"]
                        st["next_row"] += 1
                        st["rows"][row] = mir["v"]
                        if promote_repoints_before_drop:
                            # Shipped ordering: row live BEFORE the
                            # mirror drops (still under mlock).
                            st["row"] = row
                            del st["mirrors"][name]
                            st["epoch"] += 1
                            drop_late = False
                        else:
                            del st["mirrors"][name]
                            st["epoch"] += 1
                            drop_late = True
                if not promote_repoints_before_drop and drop_late:
                    # MUTATION: the repoint happens in a second lock
                    # section — readers can interleave into the gap.
                    checkpoint("BUG window: no mirror, no row")
                    with mlock:
                        st["row"] = row

    def breaker_flap():
        st["degraded"] = True
        checkpoint("breaker opens")
        # The epoch-guarded seeding loop (_degraded's discipline).
        for _ in range(2):
            with mlock:
                if name in st["mirrors"]:
                    break
                epoch = st["epoch"]
            row0 = st["row"]
            if row0 < 0:
                break  # row retired mid-seed: demoted mirror serves
            checkpoint("seed read dispatched")
            val = st["rows"][row0]
            checkpoint("seed read resolves")
            with mlock:
                if st["epoch"] != epoch:
                    continue  # stale row snapshot: discard, re-seed
                if name not in st["mirrors"]:
                    st["mirrors"][name] = {"v": val, "res": False}
                break
        checkpoint("breaker closes, reconcile runs")
        # Reconcile writes back BREAKER mirrors only — a demoted-tier
        # mirror has no device row and stays the truth (engines.py
        # _reconcile_kind_inner's residency guard).
        with gate:
            with mlock:
                mir = st["mirrors"].get(name)
                if mir is not None and not mir["res"]:
                    st["rows"][st["row"]] = mir["v"]
                    del st["mirrors"][name]
                    st["epoch"] += 1
            st["degraded"] = False

    def snapshot_reader():
        # The read discipline every engine read site follows: capture
        # entry.row BEFORE the residency check, resolve via _tier_row.
        for _ in range(2):
            lo = st["acked"]    # acked before the read began
            row0 = st["row"]    # capture BEFORE the mirror check
            checkpoint("snapshot: row captured")
            with mlock:
                mir = st["mirrors"].get(name)
                v = None if mir is None else mir["v"]
            if v is None:
                r = st["row"] if row0 < 0 else row0  # _tier_row
                assert r >= 0, (
                    "read dispatched with no mirror and no device row "
                    "(row -1) — the promote repoint-before-drop "
                    "ordering was violated"
                )
                checkpoint("snapshot: device read in flight")
                v = st["rows"][r]
            assert v >= lo, (
                f"stale read: saw {v} but {lo} writes were acked "
                f"before the read began"
            )
            checkpoint("between snapshot reads")

    cast = (
        (writer, mover, breaker_flap, snapshot_reader)
        if full_cast else (mover, snapshot_reader)
    )
    threads = [threading.Thread(target=f) for f in cast]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with mlock:
        mir = st["mirrors"].get(name)
        truth = mir["v"] if mir is not None else st["rows"][st["row"]]
    assert truth == st["acked"], (
        f"acked-write loss: truth={truth}, acked={st['acked']} "
        f"(tier={'mirror' if mir is not None else 'device'})"
    )


@schedule_test(max_schedules=1200, random_schedules=128,
               preemption_bound=2, max_steps=200000)
def test_model_residency_ladder_no_lost_write_no_stale_read():
    _residency_ladder_body()


# -- model check 9 (ISSUE 15 satellite): the prewarm ladder --------------------
#
# The REAL BucketPrewarmer (executor/prewarm.py) driven under explored
# schedules: a compile thread popping ladder tasks, racing registration,
# pool growth, and a dispatcher taking the pool's dispatch lock for
# launches.  Invariants, in EVERY schedule: (a) no launch ever waits on
# a compile holding the dispatch lock (warm calls go through the
# UNWRAPPED methods against scratch state — the module's founding
# rule); (b) racing registrations of one signature enqueue ONE ladder
# (no bucket compiled twice at a given shape); (c) after a pool growth,
# every bucket is compiled at the NEW capacity (the capacity-tag fix in
# _warm_pool_for: a growth landing between the scratch-state snapshot
# and the cache tag must not pin the stale layout).


_COMPILE_S = 10.0  # virtual seconds one "XLA compile" takes in the model


def _prewarm_ladder_body(warm_takes_dispatch_lock=False,
                         warmer_cls=None, grow_during_build=False):
    from redisson_tpu.executor import prewarm as pw

    warmer_cls = warmer_cls or pw.BucketPrewarmer
    compiles: list = []  # (scratch capacity, bucket) per warm call
    build_hook: list = [None]

    class _Pool:
        capacity = 4
        row_units = 8
        spec = types.SimpleNamespace(dtype="uint32", kind="bloom")
        on_grow = None

    pool = _Pool()
    pool._dispatch_lock = threading.Lock()

    class _Exec:
        _retired = False

        @staticmethod
        def _bucket(n):
            return 1 << max(0, (n - 1).bit_length())

        @staticmethod
        def make_pool_state(cap, row_units, dtype, kind=None):
            # The H2D allocation pause: the real scratch-state build
            # crosses the device boundary, so a growth may land here.
            checkpoint("scratch state allocating")
            if build_hook[0] is not None:
                build_hook[0]()
            return ("state", cap)

    def warm(ex, wpool, bucket):
        compiles.append((wpool.capacity, bucket))
        if warm_takes_dispatch_lock:
            # MUTATION: warming through the WRAPPED method — the
            # compile runs inside the dispatch lock.
            with pool._dispatch_lock:
                time.sleep(_COMPILE_S)
        else:
            time.sleep(_COMPILE_S)  # virtual: the compile itself

    warmer = warmer_cls(_Exec(), max_batch=4)
    ladder = warmer.ladder()
    grown = [False]

    def grow():
        pool.capacity = 8
        warmer.on_pool_grow(pool)

    if grow_during_build:
        # Deterministic placement of the race window: the growth lands
        # INSIDE the first scratch-state build (between the capacity
        # snapshot and the cache tag) — the 1-in-~20 interleaving from
        # CHANGES.md PR 2, pinned so every schedule walks it.
        def _grow_once():
            if not grown[0]:
                grown[0] = True
                grow()

        build_hook[0] = _grow_once
    try:
        def registrar():
            warmer.register(pool, "sig", warm)

        def grower():
            if grow_during_build:
                return
            checkpoint("growth lands")
            grow()

        def dispatcher():
            for _ in range(2):
                t0 = time.monotonic()
                with pool._dispatch_lock:
                    checkpoint("launch")
                dt = time.monotonic() - t0
                assert dt < _COMPILE_S, (
                    f"a launch waited {dt:.1f}s on a compile holding "
                    f"the dispatch lock"
                )

        warmer.register(pool, "sig", warm)
        threads = [threading.Thread(target=f)
                   for f in (registrar, grower, dispatcher)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert warmer.wait_idle(timeout=600.0), "ladder never drained"
        # (b) one ladder per signature at the initial shape: the racing
        # re-register enqueued NOTHING.
        at4 = [b for cap, b in compiles if cap == 4]
        assert sorted(set(at4)) == sorted(at4), (
            f"bucket compiled twice at one shape: {sorted(at4)}"
        )
        # (c) the growth re-warm covers every bucket at the NEW shape.
        at8 = {b for cap, b in compiles if cap == 8}
        assert at8 == set(ladder), (
            f"buckets missing at the grown capacity: "
            f"{sorted(set(ladder) - at8)} (compiled {sorted(compiles)})"
        )
    finally:
        warmer.shutdown(timeout=60.0)


@schedule_test(max_schedules=200, random_schedules=64, preemption_bound=2,
               max_steps=400000)
def test_model_prewarm_ladder_growth_and_lock_discipline():
    import redisson_tpu.executor.prewarm as pw

    orig = pw._ensure_listener
    pw._ensure_listener = lambda: None  # no jax inside the explored body
    try:
        _prewarm_ladder_body()
    finally:
        pw._ensure_listener = orig


@schedule_test(max_schedules=60, random_schedules=32, preemption_bound=2,
               max_steps=400000)
def test_model_prewarm_growth_inside_scratch_build():
    """The focused window the capacity-tag fix closes: growth lands
    between the scratch state's capacity snapshot and its cache tag.
    The shipped tag (the capacity the state was BUILT at) rebuilds on
    the next task and every bucket still compiles at the new shape."""
    import redisson_tpu.executor.prewarm as pw

    orig = pw._ensure_listener
    pw._ensure_listener = lambda: None
    try:
        _prewarm_ladder_body(grow_during_build=True)
    finally:
        pw._ensure_listener = orig


def test_model_prewarm_compile_under_dispatch_lock_mutation_guard():
    """Warming through the WRAPPED executor methods (the design the
    module exists to forbid: the dispatch lock held across a 10-60s
    compile) must be caught — some schedule has a launch waiting out
    the whole compile."""
    import redisson_tpu.executor.prewarm as pw

    orig = pw._ensure_listener
    pw._ensure_listener = lambda: None
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(
                lambda: _prewarm_ladder_body(
                    warm_takes_dispatch_lock=True
                ),
                max_schedules=200, random_schedules=64,
                preemption_bound=2, max_steps=400000,
            )
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(
                lambda: _prewarm_ladder_body(
                    warm_takes_dispatch_lock=True
                ),
                replay=token, max_steps=400000,
            )
        assert ei2.value.token == token
    finally:
        pw._ensure_listener = orig


def test_model_prewarm_capacity_tag_mutation_guard():
    """Reverting the _warm_pool_for capacity-tag fix (tagging the
    scratch cache with a RE-READ of pool.capacity instead of the
    capacity the state was built at) must be caught: a growth landing
    between the snapshot and the tag pins the stale layout and the
    new-capacity buckets never compile (the measured 1-in-~20
    interleaving from CHANGES.md PR 2)."""
    import redisson_tpu.executor.prewarm as pw

    class _TagRereadsCapacity(pw.BucketPrewarmer):
        def _warm_pool_for(self, pool):
            cached = self._warm_pools.get(id(pool))
            if cached is not None and cached[0] == pool.capacity:
                return cached[1]
            wp = pw._WarmPool(pool, self._executor)
            # The reverted bug: re-read AFTER the build.
            self._warm_pools[id(pool)] = (pool.capacity, wp)
            return wp

    orig = pw._ensure_listener
    pw._ensure_listener = lambda: None
    body = lambda: _prewarm_ladder_body(  # noqa: E731
        warmer_cls=_TagRereadsCapacity, grow_during_build=True
    )
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(body, max_schedules=200, random_schedules=64,
                    preemption_bound=2, max_steps=400000)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(body, replay=token, max_steps=400000)
        assert ei2.value.token == token
    finally:
        pw._ensure_listener = orig


def test_model_residency_promote_drop_order_found_and_replayed():
    """The replay-token test the ISSUE 14 satellite asks for: mutate
    promotion into drop-mirror-before-repoint and the explorer FINDS a
    schedule where a reader resolves to row -1 (or a write lands in a
    dropped mirror), prints a token, and the token replays exactly
    that schedule."""
    def buggy():
        _residency_ladder_body(promote_repoints_before_drop=False,
                               full_cast=False)

    with pytest.raises(ScheduleFailure) as ei:
        explore(buggy, max_schedules=3000, random_schedules=256,
                preemption_bound=2, max_steps=200000)
    token = ei.value.token
    with pytest.raises(ScheduleFailure) as ei2:
        explore(buggy, replay=token)
    assert ei2.value.token == token
