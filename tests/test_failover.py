"""Automatic failover (ISSUE 18): failure-detection + election state
units, the epoch-gated takeover slot math, and the slow kill -9
supervisor soak — a real 3-primary × 1-replica cluster loses a primary
to SIGKILL under load and must promote, reconverge every client, and
lose ZERO replica-acked writes.

The election protocol's interleavings are modeled exhaustively in
tests/test_netsim_failover.py; the single-link stream mechanics live
in tests/test_repl_stream.py."""

import time

import pytest

from redisson_tpu.cluster.failover import FailoverState
from redisson_tpu.cluster.slotmap import SlotMap
from redisson_tpu.cluster.slots import NSLOTS


def _map(n_primaries=3, replicas=(("R1", "A"), ("R2", "A"))):
    nodes = []
    for i in range(n_primaries):
        nid = chr(ord("A") + i)
        nodes.append({
            "id": nid, "host": f"h{i}", "port": 7000 + i,
            "slots": [[0, NSLOTS - 1]] if i == 0 else [],
        })
    for j, (rid, parent) in enumerate(replicas):
        nodes.append({
            "id": rid, "host": f"r{j}", "port": 7100 + j, "slots": [],
            "role": "replica", "replica_of": parent,
        })
    return SlotMap.from_dict({"nodes": nodes})


class TestFailureDetection:
    def test_timeout_marks_failed_and_pong_revives(self):
        sm = _map()
        st = FailoverState("B", sm, node_timeout=1.0)
        assert st.check_timeouts(now=10.0) == []  # first sight = grace
        newly = st.check_timeouts(now=11.5)
        assert set(newly) == {"A", "C", "R1", "R2"}
        assert st.is_failed("A")
        # A PONG un-fails and restarts the clock.
        st.note_pong("A", now=12.0)
        assert not st.is_failed("A")
        assert st.check_timeouts(now=12.5) == []
        assert st.check_timeouts(now=13.5) == ["A"]

    def test_never_marks_self(self):
        sm = _map()
        st = FailoverState("B", sm, node_timeout=1.0)
        st.check_timeouts(now=0.0)
        st.check_timeouts(now=100.0)
        assert not st.is_failed("B")

    def test_note_ping_learns_cluster_epoch(self):
        sm = _map()
        st = FailoverState("B", sm, node_timeout=1.0)
        assert st.note_ping("C", 7, now=1.0) == 7
        assert st.current_epoch == 7
        assert st.note_ping("C", 3, now=2.0) == 7  # monotonic max
        assert not st.is_failed("C")


class TestElectionRules:
    def test_majority_is_over_all_primaries(self):
        assert FailoverState("B", _map(3)).majority() == 2
        assert FailoverState("B", _map(5)).majority() == 3
        # 2 primaries: majority 2, but a dead primary leaves ONE live
        # voter — automatic failover is impossible by design (the
        # docs/clustering.md "needs >= 3 primaries" rule).
        assert FailoverState("B", _map(2)).majority() == 2

    def test_one_vote_per_epoch(self):
        sm = _map()
        st = FailoverState("B", sm, node_timeout=1.0)
        st.mark_failed("A")
        assert st.grant_vote("R1", 1, "A")
        assert not st.grant_vote("R2", 1, "A"), "second grant in epoch 1"
        assert not st.grant_vote("R2", 1, "A")
        assert st.grant_vote("R2", 2, "A"), "a NEWER epoch votes again"
        assert not st.grant_vote("R1", 2, "A")

    def test_no_vote_while_primary_looks_alive(self):
        st = FailoverState("B", _map(), node_timeout=1.0)
        assert not st.grant_vote("R1", 1, "A"), "we still see A alive"
        st.mark_failed("A")
        assert st.grant_vote("R1", 2, "A")

    def test_only_own_replicas_may_succeed(self):
        sm = _map(replicas=(("R1", "A"), ("RB", "B")))
        st = FailoverState("C", sm, node_timeout=1.0)
        st.mark_failed("A")
        assert not st.grant_vote("RB", 1, "A"), "RB replicates B, not A"
        assert not st.grant_vote("B", 2, "A"), "a primary is no successor"
        assert st.grant_vote("R1", 3, "A")

    def test_start_election_bumps_epoch(self):
        st = FailoverState("R1", _map(), node_timeout=1.0)
        st.current_epoch = 4
        assert st.start_election() == 5
        assert st.start_election() == 6

    def test_note_takeover_learns_epoch_and_revives_winner(self):
        st = FailoverState("B", _map(), node_timeout=1.0)
        st.mark_failed("R1")
        st.note_takeover("R1", "A", 9)
        assert st.current_epoch == 9
        assert not st.is_failed("R1")


class TestApplyTakeover:
    def test_claimant_moves_slots_and_flips_roles(self):
        sm = _map()
        moved = sm.apply_takeover("A", "R1", 1)
        assert moved == NSLOTS
        assert sm.owner(0) == "R1" and sm.owner(NSLOTS - 1) == "R1"
        assert sm.role("R1") == "master"
        assert sm.role("A") == "replica"
        assert sm.replica_of("A") == "R1"
        assert sm.slot_epoch(0) == 1

    def test_stale_broadcast_is_a_noop(self):
        sm = _map()
        assert sm.apply_takeover("A", "R1", 2) == NSLOTS
        # A lost election's late broadcast (lower epoch) changes nothing
        # — whether it names the old owner or carries explicit ranges.
        assert sm.apply_takeover("A", "R2", 1) == 0
        assert sm.apply_takeover(
            "A", "R2", 1, slots=[[0, NSLOTS - 1]]
        ) == 0
        assert sm.owner(0) == "R1"

    def test_explicit_claim_converges_regardless_of_order(self):
        """The delivery-order contract (netsim's double-takeover
        model): two successive-epoch claims over the same slots settle
        on the HIGHER epoch whichever arrives last."""
        claim = [[0, NSLOTS - 1]]
        sm1 = _map()  # epoch 1 first, then epoch 2
        sm1.apply_takeover("A", "R2", 1, slots=claim)
        assert sm1.apply_takeover("A", "R1", 2, slots=claim) == NSLOTS
        sm2 = _map()  # reversed delivery
        sm2.apply_takeover("A", "R1", 2, slots=claim)
        assert sm2.apply_takeover("A", "R2", 1, slots=claim) == 0
        for sm in (sm1, sm2):
            assert sm.owner(0) == "R1"
            assert sm.slot_epoch(0) == 2

    def test_partial_explicit_claim(self):
        sm = _map()
        assert sm.apply_takeover("A", "R1", 1, slots=[[0, 9]]) == 10
        assert sm.owner(5) == "R1"
        assert sm.owner(10) == "A"

    def test_unknown_winner_is_refused(self):
        sm = _map()
        with pytest.raises(KeyError):
            sm.apply_takeover("A", "nobody", 1)


# -- the kill -9 soak (the CI failover-soak job's core) ----------------------


@pytest.mark.slow
def test_supervisor_kill9_primary_promotes_replica_no_acked_loss():
    """3 primaries × 1 replica each.  Writes are fenced through WAIT 1
    (replica-acked) on every primary, then primary 0 dies by SIGKILL.
    Its replica must win the election and take over, every fenced
    write must read back (zero acked-write loss), clients must
    reconverge through the transition, and shutdown must leave no
    orphan processes."""
    from redisson_tpu.cluster.supervisor import (
        ClusterSupervisor,
        _request,
    )

    sup = ClusterSupervisor(
        n_nodes=3, replicas_per_shard=1, node_timeout_ms=1000,
        startup_timeout_s=180.0,
    )
    procs = None
    try:
        sup.start()
        cc = sup.client()
        try:
            keys = {f"fo{i}": f"v{i}" for i in range(40)}
            for k, v in keys.items():
                assert cc.execute("SET", k, v) == b"OK"
            # Fence: every primary has its replica's ack for the above.
            for addr in sup.addrs:
                (acked,) = _request(addr, [("WAIT", "1", "8000")])
                assert acked == 1, f"{addr} never got a replica ack"

            sup.kill_node(0)

            # The replica must take over within a few node timeouts.
            raddr = sup.replica_addrs[0]
            deadline = time.monotonic() + 30.0
            promoted = False
            while time.monotonic() < deadline and not promoted:
                try:
                    (info,) = _request(raddr, [("INFO", "replication")])
                    promoted = b"role:master" in info
                except OSError:
                    pass
                if not promoted:
                    time.sleep(0.25)
            assert promoted, "replica never promoted after kill -9"

            # Zero acked-write loss: every fenced key reads back its
            # fenced value through the redirect-chasing client.
            lost = [
                k for k, v in keys.items()
                if cc.execute("GET", k) != v.encode()
            ]
            assert not lost, f"acked writes lost across failover: {lost}"

            # The cluster accepts NEW writes on the taken-over slots.
            for i in range(12):
                assert cc.execute("SET", f"post{i}", "new") == b"OK"
                assert cc.execute("GET", f"post{i}") == b"new"
            assert 0 not in sup.alive()
        finally:
            cc.close()
    finally:
        with sup._lock:
            procs = list(sup._procs)
        sup.shutdown()
    # No orphans: every spawned process (primaries, replicas, any
    # front-door workers they supervise) is genuinely gone.
    for p in procs:
        assert p.poll() is not None, f"orphan process pid={p.pid}"
