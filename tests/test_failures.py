"""Executor-boundary failure semantics (SURVEY.md §5 failure row /
VERDICT r2 Next #7): injected kernel failures must retry (dispatch-time),
fail with per-op attribution (completion-time), and time out with a typed
error.
"""

import time

import numpy as np
import pytest

from redisson_tpu.executor.coalescer import BatchCoalescer, HintedFuture
from redisson_tpu.executor.failures import (
    DispatchTimeoutError,
    KernelExecutionError,
    RetryExhaustedError,
)


class _Lazy:
    def __init__(self, value=None, error=None):
        self._value = value
        self._error = error

    def result(self):
        if self._error is not None:
            raise self._error
        return self._value


def make_coalescer(**kw):
    kw.setdefault("batch_window_us", 100)
    kw.setdefault("max_batch", 1 << 10)
    kw.setdefault("retry_interval_s", 0.01)
    return BatchCoalescer(**kw)


class TestDispatchRetry:
    def test_transient_dispatch_failure_retries(self):
        c = make_coalescer(retry_attempts=3)
        calls = []

        def flaky(cols):
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient trace error")
            return _Lazy(np.arange(len(cols[0])))

        fut = c.submit("k", flaky, (np.arange(4),), 4)
        out = HintedFuture(fut, c).result(5.0)
        assert list(out) == [0, 1, 2, 3]
        assert len(calls) == 3  # two failures + one success
        c.shutdown()

    def test_retry_budget_exhaustion(self):
        c = make_coalescer(retry_attempts=2)

        def always_fails(cols):
            raise RuntimeError("permanent")

        fut = c.submit("k", always_fails, (np.arange(4),), 4)
        with pytest.raises(RetryExhaustedError) as ei:
            HintedFuture(fut, c).result(5.0)
        assert ei.value.attempts == 2
        assert isinstance(ei.value.__cause__, RuntimeError)
        c.shutdown()


class TestCompletionFailure:
    def test_per_op_attribution(self):
        """A segment holding two submissions fails at completion: each
        caller's error names ITS op range within the launch.  A wide
        window: both submits MUST share one segment even if the host is
        loaded (100 us windows flush between adjacent lines under
        contention — observed flake with a concurrent bench process)."""
        c = make_coalescer(batch_window_us=50_000)

        def dispatch(cols):
            return _Lazy(error=RuntimeError("device died"))

        f1 = c.submit("k", dispatch, (np.arange(3),), 3)
        f2 = c.submit("k", dispatch, (np.arange(5),), 5)
        with pytest.raises(KernelExecutionError) as e1:
            HintedFuture(f1, c).result(5.0)
        with pytest.raises(KernelExecutionError) as e2:
            HintedFuture(f2, c).result(5.0)
        ranges = sorted(
            [(e1.value.op_start, e1.value.op_count),
             (e2.value.op_start, e2.value.op_count)]
        )
        assert ranges == [(0, 3), (3, 5)]
        assert e1.value.segment_ops == 8
        assert isinstance(e1.value.__cause__, RuntimeError)
        c.shutdown()

    def test_completion_failure_not_retried(self):
        c = make_coalescer(retry_attempts=3)
        calls = []

        def dispatch(cols):
            calls.append(1)
            return _Lazy(error=RuntimeError("async device error"))

        fut = c.submit("k", dispatch, (np.arange(2),), 2)
        with pytest.raises(KernelExecutionError):
            HintedFuture(fut, c).result(5.0)
        assert len(calls) == 1  # donated state: no blind re-dispatch
        c.shutdown()

    def test_later_segments_survive_failure(self):
        c = make_coalescer()
        state = {"fail": True}

        def dispatch(cols):
            if state["fail"]:
                state["fail"] = False
                return _Lazy(error=RuntimeError("one bad launch"))
            return _Lazy(np.zeros(len(cols[0]), bool))

        f1 = c.submit("a", dispatch, (np.arange(2),), 2)
        with pytest.raises(KernelExecutionError):
            HintedFuture(f1, c).result(5.0)
        f2 = c.submit("b", dispatch, (np.arange(2),), 2)
        assert list(HintedFuture(f2, c).result(5.0)) == [False, False]
        c.shutdown()


class TestBackoffNonBlocking:
    def test_healthy_pool_flushes_during_backoff(self):
        """ISSUE 3 satellite: a failing segment PARKS with a backoff
        deadline instead of sleeping the flush thread — a healthy pool's
        segment submitted behind it must resolve while the failing one
        is still backing off."""
        c = make_coalescer(retry_attempts=5, retry_interval_s=0.25)
        block = {"on": True}

        def failing(cols):
            if block["on"]:
                raise RuntimeError("device busy")
            return _Lazy(np.zeros(len(cols[0]), bool))

        def healthy(cols):
            return _Lazy(np.zeros(len(cols[0]), bool))

        f_bad = c.submit("bad", failing, (np.arange(2),), 2, pool_key="A")
        t0 = time.monotonic()
        f_ok = c.submit("ok", healthy, (np.arange(2),), 2, pool_key="B")
        out = HintedFuture(f_ok, c).result(5.0)
        waited = time.monotonic() - t0
        assert list(out) == [False, False]
        # The healthy segment resolved well inside the failing one's
        # first 250 ms backoff window (the old in-place sleep serialized
        # them: >= one full retry interval).
        assert waited < 0.2, f"healthy pool stalled {waited:.3f}s"
        assert not f_bad.done()  # still parked, not failed
        block["on"] = False
        assert list(HintedFuture(f_bad, c).result(10.0)) == [False, False]
        c.shutdown()

    def test_backoff_is_exponential_and_capped(self):
        c = make_coalescer(
            retry_attempts=8, retry_interval_s=0.01,
        )
        c.retry_jitter = 0.0
        assert c._backoff_s(1) == pytest.approx(0.01)
        assert c._backoff_s(2) == pytest.approx(0.02)
        assert c._backoff_s(3) == pytest.approx(0.04)
        assert c._backoff_s(100) == pytest.approx(c.retry_max_backoff_s)
        c.retry_jitter = 0.5
        vals = {round(c._backoff_s(1), 6) for _ in range(32)}
        assert len(vals) > 1  # jitter decorrelates
        assert all(0.005 <= v <= 0.015 for v in vals)
        c.shutdown()

    def test_same_pool_order_preserved_across_backoff(self):
        """A later same-pool segment must NOT overtake a parked earlier
        one (read-your-writes at flush granularity)."""
        order = []
        c = make_coalescer(retry_attempts=4, retry_interval_s=0.05)
        state = {"fail_first": True}

        def d1(cols):
            if state["fail_first"]:
                state["fail_first"] = False
                raise RuntimeError("transient")
            order.append("first")
            return _Lazy(np.zeros(len(cols[0]), bool))

        def d2(cols):
            order.append("second")
            return _Lazy(np.zeros(len(cols[0]), bool))

        f1 = c.submit("k1", d1, (np.arange(1),), 1, pool_key="P")
        f2 = c.submit("k2", d2, (np.arange(1),), 1, pool_key="P")
        HintedFuture(f2, c).result(10.0)
        HintedFuture(f1, c).result(10.0)
        assert order == ["first", "second"]
        c.shutdown()


class TestCoalescerBreaker:
    def _health(self, **kw):
        from redisson_tpu.executor.health import DispatchHealth

        kw.setdefault("failure_threshold", 2)
        kw.setdefault("open_s", 0.15)
        return DispatchHealth(**kw)

    def test_breaker_opens_then_fails_fast(self):
        from redisson_tpu.executor.health import CircuitOpenError

        h = self._health()
        c = make_coalescer(retry_attempts=1, health=h)

        def always_fails(cols):
            raise RuntimeError("dead device")

        for _ in range(2):
            fut = c.submit(("bloom_mix",), always_fails, (np.arange(1),), 1)
            with pytest.raises(RetryExhaustedError):
                HintedFuture(fut, c).result(5.0)
        assert h.board.states()[(0, "bloom_mix")] == "open"
        # Next segment is refused WITHOUT calling dispatch.
        calls = []

        def counting(cols):
            calls.append(1)
            raise RuntimeError("unreachable")

        fut = c.submit(("bloom_mix",), counting, (np.arange(1),), 1)
        with pytest.raises(RetryExhaustedError) as ei:
            HintedFuture(fut, c).result(5.0)
        assert isinstance(ei.value.__cause__, CircuitOpenError)
        assert calls == []
        c.shutdown()
        h.shutdown()

    def test_completion_failures_open_breaker(self):
        """A device whose dispatch ENQUEUE succeeds but every result
        fetch fails must still open the circuit — recording success at
        enqueue time would reset the failure streak each launch."""
        h = self._health(failure_threshold=2, open_s=60.0)
        c = make_coalescer(retry_attempts=1, health=h)

        def dispatch(cols):
            return _Lazy(error=RuntimeError("fetch died"))

        for _ in range(2):
            fut = c.submit(("bloom_mix",), dispatch, (np.arange(1),), 1)
            with pytest.raises(KernelExecutionError):
                HintedFuture(fut, c).result(5.0)
        assert h.board.states()[(0, "bloom_mix")] == "open"
        c.shutdown()
        h.shutdown()

    def test_half_open_probe_closes_breaker(self):
        h = self._health(failure_threshold=2, open_s=0.1)
        c = make_coalescer(retry_attempts=1, health=h)
        state = {"fail": True}

        def flaky(cols):
            if state["fail"]:
                raise RuntimeError("down")
            return _Lazy(np.zeros(len(cols[0]), bool))

        for _ in range(2):
            fut = c.submit(("bloom_mix",), flaky, (np.arange(1),), 1)
            with pytest.raises(RetryExhaustedError):
                HintedFuture(fut, c).result(5.0)
        assert h.board.states()[(0, "bloom_mix")] == "open"
        state["fail"] = False
        time.sleep(0.15)  # open window elapses -> next dispatch probes
        fut = c.submit(("bloom_mix",), flaky, (np.arange(2),), 2)
        assert list(HintedFuture(fut, c).result(5.0)) == [False, False]
        assert h.board.states()[(0, "bloom_mix")] == "closed"
        c.shutdown()
        h.shutdown()

    def test_probe_failure_reopens(self):
        h = self._health(failure_threshold=1, open_s=0.05)
        c = make_coalescer(retry_attempts=1, health=h)

        def always_fails(cols):
            raise RuntimeError("still dead")

        fut = c.submit(("cms_mix",), always_fails, (np.arange(1),), 1)
        with pytest.raises(RetryExhaustedError):
            HintedFuture(fut, c).result(5.0)
        assert h.board.states()[(0, "cms_mix")] == "open"
        time.sleep(0.08)
        fut = c.submit(("cms_mix",), always_fails, (np.arange(1),), 1)
        with pytest.raises(RetryExhaustedError):
            HintedFuture(fut, c).result(5.0)
        assert h.board.states()[(0, "cms_mix")] == "open"
        c.shutdown()
        h.shutdown()


class TestTimeout:
    def test_result_timeout_is_typed(self):
        c = make_coalescer()
        release = {"go": False}

        def dispatch(cols):
            while not release["go"]:
                time.sleep(0.01)
            return _Lazy(np.zeros(1, bool))

        fut = c.submit("k", dispatch, (np.arange(1),), 1)
        with pytest.raises(DispatchTimeoutError):
            HintedFuture(fut, c).result(0.1)
        release["go"] = True
        c.shutdown()
