"""Executor-boundary failure semantics (SURVEY.md §5 failure row /
VERDICT r2 Next #7): injected kernel failures must retry (dispatch-time),
fail with per-op attribution (completion-time), and time out with a typed
error.
"""

import time

import numpy as np
import pytest

from redisson_tpu.executor.coalescer import BatchCoalescer, HintedFuture
from redisson_tpu.executor.failures import (
    DispatchTimeoutError,
    KernelExecutionError,
    RetryExhaustedError,
)


class _Lazy:
    def __init__(self, value=None, error=None):
        self._value = value
        self._error = error

    def result(self):
        if self._error is not None:
            raise self._error
        return self._value


def make_coalescer(**kw):
    kw.setdefault("batch_window_us", 100)
    kw.setdefault("max_batch", 1 << 10)
    kw.setdefault("retry_interval_s", 0.01)
    return BatchCoalescer(**kw)


class TestDispatchRetry:
    def test_transient_dispatch_failure_retries(self):
        c = make_coalescer(retry_attempts=3)
        calls = []

        def flaky(cols):
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient trace error")
            return _Lazy(np.arange(len(cols[0])))

        fut = c.submit("k", flaky, (np.arange(4),), 4)
        out = HintedFuture(fut, c).result(5.0)
        assert list(out) == [0, 1, 2, 3]
        assert len(calls) == 3  # two failures + one success
        c.shutdown()

    def test_retry_budget_exhaustion(self):
        c = make_coalescer(retry_attempts=2)

        def always_fails(cols):
            raise RuntimeError("permanent")

        fut = c.submit("k", always_fails, (np.arange(4),), 4)
        with pytest.raises(RetryExhaustedError) as ei:
            HintedFuture(fut, c).result(5.0)
        assert ei.value.attempts == 2
        assert isinstance(ei.value.__cause__, RuntimeError)
        c.shutdown()


class TestCompletionFailure:
    def test_per_op_attribution(self):
        """A segment holding two submissions fails at completion: each
        caller's error names ITS op range within the launch.  A wide
        window: both submits MUST share one segment even if the host is
        loaded (100 us windows flush between adjacent lines under
        contention — observed flake with a concurrent bench process)."""
        c = make_coalescer(batch_window_us=50_000)

        def dispatch(cols):
            return _Lazy(error=RuntimeError("device died"))

        f1 = c.submit("k", dispatch, (np.arange(3),), 3)
        f2 = c.submit("k", dispatch, (np.arange(5),), 5)
        with pytest.raises(KernelExecutionError) as e1:
            HintedFuture(f1, c).result(5.0)
        with pytest.raises(KernelExecutionError) as e2:
            HintedFuture(f2, c).result(5.0)
        ranges = sorted(
            [(e1.value.op_start, e1.value.op_count),
             (e2.value.op_start, e2.value.op_count)]
        )
        assert ranges == [(0, 3), (3, 5)]
        assert e1.value.segment_ops == 8
        assert isinstance(e1.value.__cause__, RuntimeError)
        c.shutdown()

    def test_completion_failure_not_retried(self):
        c = make_coalescer(retry_attempts=3)
        calls = []

        def dispatch(cols):
            calls.append(1)
            return _Lazy(error=RuntimeError("async device error"))

        fut = c.submit("k", dispatch, (np.arange(2),), 2)
        with pytest.raises(KernelExecutionError):
            HintedFuture(fut, c).result(5.0)
        assert len(calls) == 1  # donated state: no blind re-dispatch
        c.shutdown()

    def test_later_segments_survive_failure(self):
        c = make_coalescer()
        state = {"fail": True}

        def dispatch(cols):
            if state["fail"]:
                state["fail"] = False
                return _Lazy(error=RuntimeError("one bad launch"))
            return _Lazy(np.zeros(len(cols[0]), bool))

        f1 = c.submit("a", dispatch, (np.arange(2),), 2)
        with pytest.raises(KernelExecutionError):
            HintedFuture(f1, c).result(5.0)
        f2 = c.submit("b", dispatch, (np.arange(2),), 2)
        assert list(HintedFuture(f2, c).result(5.0)) == [False, False]
        c.shutdown()


class TestTimeout:
    def test_result_timeout_is_typed(self):
        c = make_coalescer()
        release = {"go": False}

        def dispatch(cols):
            while not release["go"]:
                time.sleep(0.01)
            return _Lazy(np.zeros(1, bool))

        fut = c.submit("k", dispatch, (np.arange(1),), 1)
        with pytest.raises(DispatchTimeoutError):
            HintedFuture(fut, c).result(0.1)
        release["go"] = True
        c.shutdown()
