"""Fast-path kernels: bit-exact state, documented snapshot newly semantics."""

import numpy as np
import jax.numpy as jnp

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.ops import bloom, fastpath, golden
from redisson_tpu.utils import hashing


def _hashes(n, seed, m):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    return hashing.km_reduce_mod(h1, h2, m)


def test_fast_add_bit_exact_vs_exact_kernel():
    M, K, W = 1 << 16, 7, (1 << 16) // 32
    pool_a = jnp.zeros((2 * W + 1,), jnp.uint32)
    pool_b = jnp.zeros((2 * W + 1,), jnp.uint32)
    h1m, h2m = _hashes(700, 3, M)
    rows = np.ones(700, np.int32)  # tenant row 1
    # exact kernel
    pool_a, newly_a = bloom.bloom_add(
        pool_a, jnp.asarray(rows), jnp.asarray(h1m), jnp.asarray(h2m),
        m=M, k=K, words_per_row=W,
    )
    # fast single-tenant kernel
    pool_b, newly_b = fastpath.bloom_add_fast_st(
        pool_b, np.int32(1), jnp.asarray(h1m), jnp.asarray(h2m), np.uint32(M),
        None, k=K, words_per_row=W,
    )
    np.testing.assert_array_equal(np.asarray(pool_a)[:-1], np.asarray(pool_b)[:-1])
    # unique random keys: newly flags agree too
    np.testing.assert_array_equal(np.asarray(newly_a), np.asarray(newly_b))
    # contains_st agrees with exact contains
    got = fastpath.bloom_contains_st(
        pool_b, np.int32(1), jnp.asarray(h1m), jnp.asarray(h2m), np.uint32(M),
        k=K, words_per_row=W,
    )
    assert np.asarray(got).all()


def test_fast_add_snapshot_duplicate_semantics():
    M, K, W = 1 << 16, 5, (1 << 16) // 32
    pool = jnp.zeros((W + 1,), jnp.uint32)
    h1m = jnp.asarray(np.array([9, 9], np.uint32))
    h2m = jnp.asarray(np.array([3, 3], np.uint32))
    pool, newly = fastpath.bloom_add_fast_st(
        pool, np.int32(0), h1m, h2m, np.uint32(M), None, k=K, words_per_row=W
    )
    # Snapshot semantics: both duplicates report newly=True.
    assert np.asarray(newly).tolist() == [True, True]
    # Second batch: nothing newly.
    pool, newly2 = fastpath.bloom_add_fast_st(
        pool, np.int32(0), h1m, h2m, np.uint32(M), None, k=K, words_per_row=W
    )
    assert np.asarray(newly2).tolist() == [False, False]


def test_fast_add_padding_mask():
    M, K, W = 1 << 16, 5, (1 << 16) // 32
    pool = jnp.zeros((W + 1,), jnp.uint32)
    h1m = jnp.asarray(np.array([0, 0], np.uint32))
    h2m = jnp.asarray(np.array([1, 0], np.uint32))
    valid = jnp.asarray(np.array([True, False]))
    pool, _ = fastpath.bloom_add_fast_st(
        pool, np.int32(0), h1m, h2m, np.uint32(M), valid, k=K, words_per_row=W
    )
    g = golden.GoldenBloomFilter(M, K)
    g.add_hashed(np.array([0], np.uint32), np.array([1], np.uint32))
    bits = np.unpackbits(np.asarray(pool)[:-1].view(np.uint8), bitorder="little")
    np.testing.assert_array_equal(bits.astype(bool), g.bits)


def test_fast_mode_e2e_parity_with_host():
    keys = [f"k{i}" for i in range(3000)]
    ghosts = [f"g{i}" for i in range(3000)]
    results = {}
    for mode in ("fast", "host"):
        cfg = Config()
        if mode == "fast":
            cfg.use_tpu_sketch(min_bucket=64, exact_add_semantics=False)
        cl = redisson_tpu.create(cfg)
        bf = cl.get_bloom_filter("fp")
        bf.try_init(3000, 0.01)
        added = bf.add_all(keys)
        if mode == "fast":
            # Snapshot semantics: unique keys vs empty pre-state all count.
            assert added == 3000
        else:
            # Sequential semantics may mark a few late keys as dups (all k
            # bits already set by earlier keys).
            assert 2900 <= added <= 3000
        results[mode] = (
            np.asarray(bf.contains_each(keys)),
            np.asarray(bf.contains_each(ghosts)),
        )
    np.testing.assert_array_equal(results["fast"][0], results["host"][0])
    np.testing.assert_array_equal(results["fast"][1], results["host"][1])


def test_device_hash_path_matches_host_engine():
    """The *_keys_st device-hash kernels (murmur + 64-bit mod in-kernel)
    must be bit-identical to the host hash pipeline: same membership
    answers, same newly flags, same HLL changed booleans."""
    import redisson_tpu
    from redisson_tpu import Config

    results = {}
    for mode, kwargs in (
        ("devhash", dict(exact_add_semantics=False, coalesce=False)),
        ("host", None),
    ):
        cfg = Config()
        if kwargs is not None:
            cfg.use_tpu_sketch(min_bucket=64, **kwargs)
        cl = redisson_tpu.create(cfg)
        bf = cl.get_bloom_filter("dh-bf")
        bf.try_init(5000, 0.01)
        keys = [f"key-{i}" for i in range(300)]
        n_added = bf.add_all(keys)
        hits = bf.contains_each(keys + [f"miss-{i}" for i in range(300)])
        h = cl.get_hyper_log_log("dh-hll")
        first = h.add("x")
        second = h.add("x")
        h.add_all([f"v{i}" for i in range(2000)])
        results[mode] = (n_added, hits.tolist(), first, second, h.count())
        cl.shutdown()
    assert results["devhash"] == results["host"]


def test_mod64_bits_exact():
    """Device bit-Horner mod == host uint64 mod for random 64-bit values."""
    import jax
    import jax.numpy as jnp

    from redisson_tpu.ops import fastpath

    rng = np.random.default_rng(5)
    hi = rng.integers(0, 1 << 32, 512).astype(np.uint32)
    lo = rng.integers(0, 1 << 32, 512).astype(np.uint32)
    for m in (3, 17, 9585059, (1 << 31) - 1, 1 << 31):
        got = np.asarray(
            jax.jit(lambda h, l: fastpath.mod64_bits(h, l, np.uint32(m)))(
                jnp.asarray(hi), jnp.asarray(lo)
            )
        )
        h64 = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
        np.testing.assert_array_equal(got, (h64 % np.uint64(m)).astype(np.uint32))
