"""Metrics federation (ISSUE 13 tentpole part 2): exposition merging
with node relabeling (unit), and the slow-marked 3-node supervisor test
— federated /metrics serving all nodes' rtpu_* series under distinct
node labels, fleet-aggregated INFO, and the cross-node SLOWLOG merge."""

import re
import time
import urllib.request

import pytest

from redisson_tpu.obs.federate import (
    FederatedMetrics,
    merge_expositions,
    start_federation_endpoint,
)

PAGE_A = """\
# HELP rtpu_x_total things
# TYPE rtpu_x_total counter
rtpu_x_total{cmd="GET"} 3
rtpu_x_total{cmd="SET"} 1
# TYPE rtpu_up gauge
rtpu_up 1
"""

PAGE_B = """\
# HELP rtpu_x_total things
# TYPE rtpu_x_total counter
rtpu_x_total{cmd="GET"} 7
# TYPE rtpu_up gauge
rtpu_up 1
"""


def test_merge_expositions_relabels_and_regroups():
    merged = merge_expositions([("n1:1", PAGE_A), ("n2:2", PAGE_B)])
    # Node label injected FIRST, existing labels preserved.
    assert 'rtpu_x_total{node="n1:1",cmd="GET"} 3' in merged
    assert 'rtpu_x_total{node="n2:2",cmd="GET"} 7' in merged
    # Label-less samples get a fresh label set.
    assert 'rtpu_up{node="n1:1"} 1' in merged
    assert 'rtpu_up{node="n2:2"} 1' in merged
    # ONE TYPE block per family (duplicate TYPE lines are a Prometheus
    # parse error), with all nodes' samples under it.
    assert merged.count("# TYPE rtpu_x_total counter") == 1
    assert merged.count("# TYPE rtpu_up gauge") == 1
    type_pos = merged.index("# TYPE rtpu_x_total counter")
    up_pos = merged.index("# TYPE rtpu_up gauge")
    for node in ("n1:1", "n2:2"):
        sample = merged.index(f'rtpu_x_total{{node="{node}"')
        assert type_pos < sample < up_pos


def test_unreachable_node_degrades_to_node_up_zero():
    # A port nothing listens on: the page still renders, with the
    # member marked down instead of a 500.
    fm = FederatedMetrics(["127.0.0.1:1"], timeout_s=0.5)
    page = fm.render()
    assert 'rtpu_federation_node_up{node="127.0.0.1:1"} 0' in page


def test_federation_requires_targets():
    with pytest.raises(ValueError):
        FederatedMetrics([])


def test_standalone_endpoint_over_fake_members():
    """--federate mode wiring, no engine involved: two stub member
    endpoints, one merged page."""
    from redisson_tpu.obs.promhttp import MetricsHTTPServer

    m1 = MetricsHTTPServer(lambda: PAGE_A)
    m2 = MetricsHTTPServer(lambda: PAGE_B)
    fed = start_federation_endpoint([
        f"{m1.host}:{m1.port}", f"{m2.host}:{m2.port}",
    ])
    try:
        with urllib.request.urlopen(
            f"http://{fed.host}:{fed.port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
        assert f'node="{m1.host}:{m1.port}"' in body
        assert f'node="{m2.host}:{m2.port}"' in body
        assert body.count("# TYPE rtpu_x_total counter") == 1
        assert 'rtpu_federation_node_up' in body
    finally:
        fed.close()
        m1.close()
        m2.close()


# -- 3-node supervisor federation (the CI cluster-smoke assertion) ----------


@pytest.mark.slow
def test_three_node_federated_metrics_and_fleet_merges():
    """ISSUE 13 acceptance: the supervisor's federated endpoint serves
    all three nodes' rtpu_* series under distinct node labels; the
    cluster client merges SLOWLOG and INFO across nodes."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(n_nodes=3, metrics=True).start()
    try:
        client = sup.client()
        try:
            # Traffic on every node (keyless commands fan out per node
            # via _fanout; keyed traffic rides the slot split).
            assert len(sup.metrics_addrs) == 3
            for addr, r in client._fanout(
                [b"CONFIG", b"SET", b"slowlog-log-slower-than", b"0"]
            ).items():
                assert not isinstance(r, Exception), (addr, r)
            for i in range(30):
                client.execute("SET", f"fed-key-{i}", f"v{i}")
            fed = sup.start_federation()
            assert sup.start_federation() is fed  # idempotent
            with urllib.request.urlopen(
                f"http://{fed.host}:{fed.port}/metrics", timeout=10
            ) as r:
                body = r.read().decode()
            node_labels = {
                "%s:%d" % a for a in sup.metrics_addrs
            }
            for label in node_labels:
                # Every node's command counters appear under its label.
                assert re.search(
                    r'rtpu_resp_commands_total\{node="%s"'
                    % re.escape(label), body
                ), f"no series for {label}"
                assert (
                    f'rtpu_federation_node_up{{node="{label}"}} 1'
                    in body
                )
            # Regrouped: one TYPE block for the command family.
            assert body.count(
                "# TYPE rtpu_resp_commands_total counter"
            ) == 1
            # Cross-node SLOWLOG merge: entries from all 3 nodes,
            # newest-first, node-tagged.
            merged = client.fleet_slowlog(-1)
            nodes_seen = {e["node"] for e in merged}
            assert len(nodes_seen) == 3, nodes_seen
            ts = [e["ts"] for e in merged]
            assert ts == sorted(ts, reverse=True)
            assert all(e["duration_us"] >= 0 for e in merged)
            # Bounded form returns the newest `count` across the fleet.
            assert len(client.fleet_slowlog(5)) == 5
            # Fleet INFO: per-node sections + summed ADDITIVE totals.
            fi = client.fleet_info("stats")
            assert len(fi["nodes"]) == 3
            total = fi["totals"]["total_commands_processed"]
            assert total >= 30
            assert total == sum(
                int(n["total_commands_processed"])
                for n in fi["nodes"].values()
            )
            # Non-additive numerics never enter totals (review
            # regression: summing an uptime/port across nodes is a lie).
            full = client.fleet_info()
            assert "uptime_in_seconds" in next(
                iter(full["nodes"].values())
            )
            assert "uptime_in_seconds" not in full["totals"]
            assert "maxclients" not in full["totals"]
            assert "trace_sample_rate" not in full["totals"]
        finally:
            client.close()
    finally:
        assert sup.shutdown()
    # Federation server is torn down with the supervisor.
    assert sup._federation is None


@pytest.mark.slow
def test_federate_cli_mode():
    """`python -m redisson_tpu --federate ... --metrics-port N` serves
    the merged page without booting an engine."""
    import socket
    import subprocess
    import sys

    from redisson_tpu.obs.promhttp import MetricsHTTPServer

    member = MetricsHTTPServer(lambda: PAGE_A)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, "-m", "redisson_tpu",
         "--federate", f"{member.host}:{member.port}",
         "--metrics-port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.monotonic() + 30
        body = None
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as r:
                    body = r.read().decode()
                break
            except OSError:
                time.sleep(0.2)
        assert body is not None, "federation endpoint never came up"
        assert f'node="{member.host}:{member.port}"' in body
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        member.close()
