"""Front-door command-stream vectorization (ISSUE 6).

The tentpole contract: fusing runs of adjacent pipelined commands into
single engine launches must be INVISIBLE on the wire — the reply stream
is byte-identical to sequential execution, whatever the parse-ahead batch
boundaries, including under chaos fault injection at the fused dispatch
points.  The randomized differential soak at the bottom enforces exactly
that against a ``resp_vectorize=False`` reference server.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer


# One shared wire-helper implementation (redisson_tpu/serve/wireutil.py)
# for bench + tests: a framing fix lands everywhere at once.
from redisson_tpu.serve.wireutil import (  # noqa: E402
    skip_reply_frame as _skip_frame,
    wire_command as _wire,
)


def _recv_replies(sock, n, timeout=60.0):
    """Read exactly ``n`` complete reply frames; returns (frames, rest)."""
    sock.settimeout(timeout)
    data = b""
    frames = []
    pos = 0
    deadline = time.monotonic() + timeout
    while len(frames) < n:
        try:
            while len(frames) < n:
                end = _skip_frame(data, pos)
                frames.append(data[pos:end])
                pos = end
        except (IndexError, ValueError):
            pass
        if len(frames) >= n:
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                f"timeout with {len(frames)}/{n} replies"
            )
        chunk = sock.recv(1 << 16)
        if not chunk:
            raise AssertionError(
                f"connection closed with {len(frames)}/{n} replies"
            )
        data += chunk
    return frames, data[pos:]


def _mk_server(vectorize: bool, retry_attempts=None, **tpu_kw):
    cfg = Config().use_tpu_sketch(min_bucket=64, **tpu_kw)
    cfg.resp_vectorize = vectorize
    if retry_attempts is not None:
        cfg.retry_attempts = retry_attempts
    client = redisson_tpu.create(cfg)
    server = RespServer(client)
    return client, server


def _roundtrip(server, cmds, chunks=None, sock=None):
    """Send ``cmds`` pipelined (optionally split at ``chunks`` byte
    offsets) and return the reply frames."""
    own = sock is None
    if own:
        sock = socket.create_connection((server.host, server.port))
    try:
        payload = b"".join(_wire(c) for c in cmds)
        if chunks:
            pos = 0
            for cut in chunks:
                sock.sendall(payload[pos:cut])
                pos = cut
                time.sleep(0.001)
            sock.sendall(payload[pos:])
        else:
            sock.sendall(payload)
        frames, rest = _recv_replies(sock, len(cmds))
        assert rest == b""
        return frames
    finally:
        if own:
            sock.close()


@pytest.fixture(scope="module")
def vec():
    client, server = _mk_server(True)
    yield client, server
    server.close()
    client.shutdown()


class TestFusedRuns:
    def test_bf_mixed_run_exact_semantics(self, vec):
        client, server = vec
        cmds = [[b"BF.RESERVE", b"fd-f1", b"0.01", b"5000"]]
        cmds += [[b"BF.ADD", b"fd-f1", b"a"]]
        cmds += [[b"BF.EXISTS", b"fd-f1", b"a"]]   # added one cmd ago
        cmds += [[b"BF.ADD", b"fd-f1", b"a"]]      # duplicate: 0
        cmds += [[b"BF.EXISTS", b"fd-f1", b"zzz-never"]]
        cmds += [[b"BF.MADD", b"fd-f1", b"b", b"a", b"c"]]
        cmds += [[b"BF.MEXISTS", b"fd-f1", b"a", b"b", b"c", b"nope2"]]
        frames = _roundtrip(server, cmds)
        assert frames[0] == b"+OK\r\n"
        assert frames[1] == b":1\r\n"
        assert frames[2] == b":1\r\n"   # intra-run read-your-writes
        assert frames[3] == b":0\r\n"   # duplicate add
        assert frames[4] == b":0\r\n"
        assert frames[5] == b"*3\r\n:1\r\n:0\r\n:1\r\n"  # a already in
        assert frames[6] == b"*4\r\n:1\r\n:1\r\n:1\r\n:0\r\n"
        # The whole mixed span fused into runs.
        st = server.obs.resp_fused_cmds
        assert sum(int(c.value) for _, c in st.items()) >= 6

    def test_bitset_run_prev_values(self, vec):
        client, server = vec
        cmds = [
            [b"SETBIT", b"fd-bs", b"5", b"1"],
            [b"GETBIT", b"fd-bs", b"5"],
            [b"SETBIT", b"fd-bs", b"5", b"0"],  # prev 1
            [b"GETBIT", b"fd-bs", b"5"],
            [b"SETBIT", b"fd-bs", b"9", b"1"],
            [b"GETBIT", b"fd-bs", b"9"],
            [b"GETBIT", b"fd-bs", b"1000"],     # out of range: 0
        ]
        frames = _roundtrip(server, cmds)
        assert frames == [
            b":0\r\n", b":1\r\n", b":1\r\n", b":0\r\n",
            b":0\r\n", b":1\r\n", b":0\r\n",
        ]

    def test_get_run_and_response_cache(self, vec):
        client, server = vec
        cmds = [[b"SET", b"fd-k", b"v1"]]
        cmds += [[b"GET", b"fd-k"]] * 5
        cmds += [[b"SET", b"fd-k", b"v2"]]     # epoch bump mid-batch
        cmds += [[b"GET", b"fd-k"]] * 3
        cmds += [[b"MGET", b"fd-k", b"fd-missing"]]
        frames = _roundtrip(server, cmds)
        assert frames[0] == b"+OK\r\n"
        assert all(f == b"$2\r\nv1\r\n" for f in frames[1:6])
        assert frames[6] == b"+OK\r\n"
        # The cached v1 reply must NOT survive the write.
        assert all(f == b"$2\r\nv2\r\n" for f in frames[7:10])
        assert frames[10] == b"*2\r\n$2\r\nv2\r\n$-1\r\n"

    def test_mixed_run_read_frames_never_cached_stale(self, vec):
        # Review regression: a mixed fused run computes its read frames
        # in run order, so a GETBIT that PRECEDED a same-key SETBIT must
        # not be installed into the response cache — a later identical
        # GETBIT in the same window would serve the pre-write bit.
        client, server = vec
        cmds = [
            [b"GETBIT", b"fd-stale", b"5"],      # 0 (pre-write)
            [b"SETBIT", b"fd-stale", b"5", b"1"],
            [b"PING"],                            # barrier, no epoch bump
            [b"GETBIT", b"fd-stale", b"5"],      # must be 1, never cached 0
        ]
        frames = _roundtrip(server, cmds)
        assert frames == [b":0\r\n", b":0\r\n", b"+PONG\r\n", b":1\r\n"]

    def test_get_run_respects_reply_buffer_bound(self, vec):
        # Review regression: a fused GET run must stop buffering at the
        # 1 MB reply bound (the tail re-queues) — and every reply still
        # arrives, in order.
        client, server = vec
        big = b"x" * (300 << 10)
        setup = [[b"SET", b"fd-big", big]]
        reads = [[b"GET", b"fd-big"]] * 8
        frames = _roundtrip(server, setup + reads)
        assert frames[0] == b"+OK\r\n"
        want = b"$%d\r\n%s\r\n" % (len(big), big)
        assert all(f == want for f in frames[1:])

    def test_uninitialized_filter_errors_per_command(self, vec):
        client, server = vec
        cmds = [
            [b"BF.EXISTS", b"fd-missing-f", b"x"],
            [b"BF.ADD", b"fd-missing-f", b"y"],
            [b"BF.EXISTS", b"fd-missing-f", b"z"],
        ]
        frames = _roundtrip(server, cmds)
        # One fused call raised once; every command still gets its own
        # error frame — same bytes the sequential path produces.
        assert all(f.startswith(b"-ERR") for f in frames)
        assert len(set(frames)) == 1

    def test_multi_exec_inside_pipeline(self, vec):
        client, server = vec
        cmds = [
            [b"SET", b"fd-m", b"1"],
            [b"GET", b"fd-m"],
            [b"MULTI"],
            [b"GET", b"fd-m"],
            [b"SET", b"fd-m", b"2"],
            [b"EXEC"],
            [b"GET", b"fd-m"],
        ]
        frames = _roundtrip(server, cmds)
        assert frames[2] == b"+OK\r\n"
        assert frames[3] == frames[4] == b"+QUEUED\r\n"
        assert frames[5] == b"*2\r\n$1\r\n1\r\n+OK\r\n"
        assert frames[6] == b"$1\r\n2\r\n"

    def test_vectorize_off_still_correct(self):
        client, server = _mk_server(False)
        try:
            cmds = [[b"BF.RESERVE", b"nf", b"0.01", b"100"]]
            cmds += [[b"BF.ADD", b"nf", b"x"], [b"BF.EXISTS", b"nf", b"x"]]
            frames = _roundtrip(server, cmds)
            assert frames == [b"+OK\r\n", b":1\r\n", b":1\r\n"]
            fused = sum(
                int(c.value) for _, c in server.obs.resp_fused_cmds.items()
            )
            assert fused == 0
        finally:
            server.close()
            client.shutdown()


# -- randomized differential soak --------------------------------------------


def _gen_stream(rng: random.Random, n_cmds: int):
    """Interleaved pipelined command stream: fusable reads/writes,
    structural barriers, repeated reads (cache hits) — everything
    deterministic (no TTLs, no randomized replies)."""
    filters = [b"soak-f0", b"soak-f1"]
    bitsets = [b"soak-b0", b"soak-b1"]
    strkeys = [b"soak-s%d" % i for i in range(4)]
    cmds = [[b"BF.RESERVE", f, b"0.01", b"4000"] for f in filters]
    item = lambda: b"it%d" % rng.randrange(60)  # noqa: E731

    def one():
        r = rng.random()
        if r < 0.30:
            f = rng.choice(filters)
            k = rng.random()
            if k < 0.35:
                return [b"BF.ADD", f, item()]
            if k < 0.75:
                return [b"BF.EXISTS", f, item()]
            if k < 0.88:
                return [b"BF.MADD", f] + [item() for _ in range(
                    rng.randrange(1, 5))]
            return [b"BF.MEXISTS", f] + [item() for _ in range(
                rng.randrange(1, 5))]
        if r < 0.55:
            b = rng.choice(bitsets)
            off = b"%d" % rng.randrange(256)
            if rng.random() < 0.5:
                return [b"SETBIT", b, off, b"1" if rng.random() < 0.8
                        else b"0"]
            return [b"GETBIT", b, off]
        if r < 0.80:
            s = rng.choice(strkeys)
            k = rng.random()
            if k < 0.3:
                return [b"SET", s, b"v%d" % rng.randrange(1000)]
            if k < 0.8:
                return [b"GET", s]
            if k < 0.9:
                return [b"MGET"] + rng.sample(strkeys, 2)
            return [b"STRLEN", s]
        if r < 0.86:  # structural barriers
            k = rng.random()
            if k < 0.4:
                return [b"DEL", rng.choice(strkeys)]
            if k < 0.7:
                return [b"DEL", rng.choice(filters)]
            return [b"BF.RESERVE", rng.choice(filters), b"0.01", b"4000"]
        if r < 0.93:
            return [b"PFADD", b"soak-h", item()]
        if r < 0.97:
            return [b"PFCOUNT", b"soak-h"]
        return [b"APPEND", rng.choice(strkeys), b"x"]

    cmds += [one() for _ in range(n_cmds)]
    return cmds


def _run_stream(server, cmds, rng: random.Random):
    """Send the stream in random chunk splits (varying the parse-ahead
    batch boundaries) and return the concatenated reply bytes."""
    payload = b"".join(_wire(c) for c in cmds)
    cuts = sorted(
        rng.sample(range(1, len(payload)), min(12, len(payload) - 1))
    )
    frames = _roundtrip(server, cmds, chunks=cuts)
    return b"".join(frames)


class TestDifferentialSoak:
    def _pair(self, **kw):
        vec_c, vec_s = _mk_server(True, **kw)
        ref_c, ref_s = _mk_server(False, **kw)
        return (vec_c, vec_s), (ref_c, ref_s)

    def test_soak_byte_identical(self):
        (vc, vs), (rc, rs) = self._pair()
        try:
            for seed in (11, 23):
                rng = random.Random(seed)
                cmds = _gen_stream(rng, 400)
                got = _run_stream(vs, cmds, random.Random(seed + 1))
                want = _run_stream(rs, cmds, random.Random(seed + 2))
                assert got == want, f"reply streams diverged (seed {seed})"
                # Cleanup both keyspaces between rounds (same commands
                # on both → still comparable).
                for s_, c_ in ((vs, vc), (rs, rc)):
                    c_.get_keys().flushall()
        finally:
            vs.close()
            vc.shutdown()
            rs.close()
            rc.shutdown()

    def test_soak_byte_identical_under_chaos(self):
        """Fault injection at the fused dispatch points: the coalescer's
        retry discipline absorbs injected dispatch errors, so the fused
        and sequential servers still answer byte-identically."""
        from redisson_tpu import chaos

        # Deep retry budget: the soak asserts EQUALITY, so an exhausted
        # retry (different call counts → different fire sequences per
        # server) must be statistically impossible, not just rare.
        (vc, vs), (rc, rs) = self._pair(retry_attempts=8)
        try:
            for point in (
                "dispatch.bloom_mixed_keys",
                "dispatch.bloom_mixed_keys_runs",
                "dispatch.bitset_mixed",
                "dispatch.bitset_mixed_runs",
            ):
                chaos.inject(point, kind="error", rate=0.04, seed=97)
            rng = random.Random(5)
            cmds = _gen_stream(rng, 300)
            got = _run_stream(vs, cmds, random.Random(6))
            want = _run_stream(rs, cmds, random.Random(7))
            assert got == want, "chaos soak diverged"
        finally:
            chaos.clear()
            vs.close()
            vc.shutdown()
            rs.close()
            rc.shutdown()
