"""FunctionService — the RFunction analog (org/redisson/api/RFunction.java,
upstream ≥3.17: FUNCTION LOAD/LIST/DELETE/FLUSH + FCALL/FCALL_RO)."""

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


def _counter_lib():
    def incr_twice(client, keys, args):
        a = client.get_atomic_long(keys[0])
        a.add_and_get(int(args[0]))
        return a.add_and_get(int(args[0]))

    def peek(client, keys, args):
        return client.get_atomic_long(keys[0]).get()

    return {"incr_twice": incr_twice, "peek": peek}


def test_load_and_fcall(client):
    f = client.get_function()
    f.load("counters", _counter_lib(), no_writes=("peek",))
    assert f.call("incr_twice", ["c"], [5]) == 10
    assert f.call("peek", ["c"]) == 10
    # Atomicity: runs under the grid lock like a script.
    assert client.get_atomic_long("c").get() == 10


def test_fcall_ro_contract(client):
    f = client.get_function()
    f.load("counters", _counter_lib(), no_writes=("peek",))
    assert f.call_ro("peek", ["c"]) == 0
    with pytest.raises(ValueError, match="fcall_ro"):
        f.call_ro("incr_twice", ["c"], [1])


def test_unknown_function(client):
    f = client.get_function()
    with pytest.raises(KeyError):
        f.call("nope")


def test_library_replace_and_global_names(client):
    f = client.get_function()
    f.load("libA", {"fn1": lambda c, k, a: 1})
    with pytest.raises(ValueError, match="already exists"):
        f.load("libA", {"fn1": lambda c, k, a: 2})
    # Global function-name namespace across libraries (the Redis rule).
    with pytest.raises(ValueError, match="already registered"):
        f.load("libB", {"fn1": lambda c, k, a: 3})
    f.load("libA", {"fn2": lambda c, k, a: 4}, replace=True)
    assert f.call("fn2") == 4
    with pytest.raises(KeyError):
        f.call("fn1")  # replaced out of the library


def test_list_delete_flush(client):
    f = client.get_function()
    f.load("alpha", {"a1": lambda c, k, a: 0}, no_writes=("a1",))
    f.load("beta", {"b1": lambda c, k, a: 0})
    libs = {d["library_name"]: d for d in f.list()}
    assert set(libs) == {"alpha", "beta"}
    assert libs["alpha"]["functions"][0]["flags"] == ["no-writes"]
    assert [d["library_name"] for d in f.list("al*")] == ["alpha"]
    f.delete("alpha")
    with pytest.raises(KeyError):
        f.call("a1")
    f.flush()
    assert f.list() == []
    # get_function returns the shared instance (FCALL sees prior loads).
    f.load("gamma", {"g": lambda c, k, a: 7})
    assert client.get_function().call("g") == 7


def test_camel_aliases(client):
    f = client.get_function()
    f.load("lib", {"x": lambda c, k, a: 42}, no_writes=("x",))
    assert f.callRo("x") == 42  # CamelCompatMixin surface
