"""Golden model self-consistency: analytic FPP / HLL error bounds.

This is the §4 strategy upgrade over the reference: the reference trusts a
live Redis server for sketch semantics; we pin semantics to analytic math.
"""

import numpy as np

from redisson_tpu.ops import golden
from redisson_tpu.utils import hashing


def _hashes(n, seed=1, m=None):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    if m is None:
        return hashing.murmur3_x86_128(blocks, lengths)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    return hashing.km_reduce_mod(h1, h2, m)


def test_bloom_formulas():
    m = golden.optimal_num_of_bits(1_000_000, 0.01)
    k = golden.optimal_num_of_hash_functions(1_000_000, m)
    assert m == 9_585_059  # ceil(-n ln p / ln^2 2) for n=1e6, p=0.01
    assert k == 7


def test_bloom_fpp_within_bounds():
    n, p = 100_000, 0.01
    m = golden.optimal_num_of_bits(n, p)
    k = golden.optimal_num_of_hash_functions(n, m)
    bf = golden.GoldenBloomFilter(m, k)
    h1m, h2m = _hashes(n, seed=2, m=m)
    idx = bf._indexes(h1m, h2m)
    bf.bits[idx.ravel()] = True  # bulk insert; newly-set tracking not needed
    # Inserted keys always hit.
    assert bf.contains_hashed(h1m, h2m).all()
    # Fresh keys: FPP within 2x analytic target (generous for n=100k).
    q1, q2 = _hashes(200_000, seed=3, m=m)
    fpp = float(bf.contains_hashed(q1, q2).mean())
    assert fpp < 2 * p, fpp
    assert fpp > p / 4, fpp  # sanity: filter is actually loaded
    # Cardinality estimate within 5%.
    est = bf.cardinality_estimate()
    assert abs(est - n) / n < 0.05


def test_bloom_add_newly_set_semantics():
    bf = golden.GoldenBloomFilter(1 << 16, 5)
    h1m, h2m = _hashes(10, seed=4, m=1 << 16)
    newly = bf.add_hashed(h1m, h2m)
    assert newly.all()
    again = bf.add_hashed(h1m, h2m)
    assert not again.any()


def test_hll_error_within_budget():
    for n in (1_000, 100_000, 1_000_000):
        h = golden.GoldenHyperLogLog()
        c0, c1, c2, _ = _hashes(n, seed=n)
        h.add_hashed(c0, c1, c2)
        err = abs(h.count() - n) / n
        # Standard error 1.04/sqrt(16384) ≈ 0.81%; allow 3 sigma.
        assert err < 3 * 1.04 / np.sqrt(golden.HLL_M), (n, h.count())


def test_hll_small_range_exact_ish():
    h = golden.GoldenHyperLogLog()
    c0, c1, c2, _ = _hashes(10, seed=7)
    h.add_hashed(c0, c1, c2)
    assert abs(h.count() - 10) <= 1


def test_hll_merge_equals_union():
    a, b, u = (golden.GoldenHyperLogLog() for _ in range(3))
    ca = _hashes(50_000, seed=11)
    cb = _hashes(60_000, seed=12)
    a.add_hashed(ca[0], ca[1], ca[2])
    b.add_hashed(cb[0], cb[1], cb[2])
    u.add_hashed(
        np.concatenate([ca[0], cb[0]]),
        np.concatenate([ca[1], cb[1]]),
        np.concatenate([ca[2], cb[2]]),
    )
    a.merge(b)
    assert (a.regs == u.regs).all()
    assert a.count() == u.count()


def test_hll_idempotent():
    h = golden.GoldenHyperLogLog()
    c0, c1, c2, _ = _hashes(10_000, seed=13)
    h.add_hashed(c0, c1, c2)
    n1 = h.count()
    h.add_hashed(c0, c1, c2)
    assert h.count() == n1


def test_bitset_semantics():
    bs = golden.GoldenBitSet()
    prev = bs.set(np.array([5, 100, 5]))
    assert list(prev) == [False, False, True]  # duplicate sees earlier write
    assert bs.get(np.array([5, 100, 101, 10_000])).tolist() == [True, True, False, False]
    assert bs.cardinality() == 2
    assert bs.length() == 101
    prev = bs.set(np.array([100]), value=False)
    assert prev.tolist() == [True]
    assert bs.cardinality() == 1
