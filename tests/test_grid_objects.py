"""Per-object tests for the host data grid (wave 1), mirroring the
reference's per-RObject test classes (SURVEY.md §4: RedissonBucketTest,
RedissonMapTest, RedissonQueueTest, RedissonTopicTest, …)."""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    cl = redisson_tpu.create(Config())
    yield cl
    cl.shutdown()


# -- bucket ----------------------------------------------------------------


class TestBucket:
    def test_set_get(self, client):
        b = client.get_bucket("b1")
        assert b.get() is None
        b.set({"a": 1})
        assert b.get() == {"a": 1}
        assert b.is_exists()

    def test_set_if_absent_and_exists(self, client):
        b = client.get_bucket("b2")
        assert b.set_if_absent("v1") is True
        assert b.set_if_absent("v2") is False
        assert b.get() == "v1"
        assert b.set_if_exists("v3") is True
        assert b.get() == "v3"
        assert client.get_bucket("missing").set_if_exists("x") is False

    def test_compare_and_set(self, client):
        b = client.get_bucket("b3")
        assert b.compare_and_set(None, "first") is True
        assert b.compare_and_set("wrong", "nope") is False
        assert b.compare_and_set("first", "second") is True
        assert b.get() == "second"

    def test_get_and_ops(self, client):
        b = client.get_bucket("b4")
        b.set(10)
        assert b.get_and_set(20) == 10
        assert b.get_and_delete() == 20
        assert b.get() is None

    def test_ttl(self, client):
        b = client.get_bucket("b5")
        b.set("ephemeral", ttl_seconds=0.15)
        assert b.get() == "ephemeral"
        assert 0 < b.remain_time_to_live() <= 150
        time.sleep(0.2)
        assert b.get() is None
        assert b.remain_time_to_live() == -2

    def test_buckets_multi(self, client):
        client.get_buckets().set({"x": 1, "y": 2})
        got = client.get_buckets().get("x", "y", "z")
        assert got == {"x": 1, "y": 2}
        assert client.get_buckets().try_set({"y": 9, "w": 3}) is False
        assert client.get_buckets().try_set({"w": 3}) is True

    def test_wrongtype_guard(self, client):
        client.get_bucket("typed").set(1)
        with pytest.raises(TypeError):
            client.get_map("typed").put("k", "v")

    def test_camelcase(self, client):
        b = client.get_bucket("camel")
        b.set("v")
        assert b.getAndSet("w") == "v"
        assert client.getBucket("camel").get() == "w"


class TestBinaryStream:
    def test_stream_io(self, client):
        bs = client.get_binary_stream("bin")
        out = bs.get_output_stream()
        out.write(b"hello ")
        out.close()
        out = bs.get_output_stream()
        out.write(b"world")
        out.close()
        assert bs.get_input_stream().read() == b"hello world"
        assert bs.size() == 11


# -- counters --------------------------------------------------------------


class TestCounters:
    def test_atomic_long(self, client):
        a = client.get_atomic_long("al")
        assert a.get() == 0
        assert a.increment_and_get() == 1
        assert a.add_and_get(10) == 11
        assert a.get_and_add(5) == 11
        assert a.get() == 16
        assert a.compare_and_set(16, 100) is True
        assert a.compare_and_set(16, 0) is False
        assert a.get_and_set(7) == 100
        assert a.decrement_and_get() == 6

    def test_atomic_long_concurrent(self, client):
        a = client.get_atomic_long("alc")
        threads = [
            threading.Thread(target=lambda: [a.increment_and_get() for _ in range(500)])
            for _ in range(4)
        ]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert a.get() == 2000

    def test_atomic_double(self, client):
        d = client.get_atomic_double("ad")
        assert d.add_and_get(1.5) == 1.5
        assert d.compare_and_set(1.5, 2.25) is True
        assert d.get() == 2.25

    def test_adders(self, client):
        la = client.get_long_adder("la")
        la.add(5)
        la.increment()
        la.decrement()
        assert la.sum() == 5
        la.reset()
        assert la.sum() == 0
        da = client.get_double_adder("da")
        da.add(0.5)
        da.add(0.25)
        assert da.sum() == 0.75

    def test_id_generator(self, client):
        g = client.get_id_generator("ids")
        assert g.try_init(100, 10) is True
        assert g.try_init(0, 5) is False
        ids = [g.next_id() for _ in range(25)]
        assert ids == list(range(100, 125))
        # A second handle allocates a fresh block — ids never collide.
        g2 = client.get_id_generator("ids")
        assert g2.next_id() not in ids


# -- map -------------------------------------------------------------------


class TestMap:
    def test_put_get_remove(self, client):
        m = client.get_map("m1")
        assert m.put("k", "v1") is None
        assert m.put("k", "v2") == "v1"
        assert m.get("k") == "v2"
        assert m.fast_put("k2", 42) is True
        assert m.fast_put("k2", 43) is False
        assert m.size() == 2
        assert m.remove("k") == "v2"
        assert m.remove("k") is None
        assert m.fast_remove("k2", "nope") == 1

    def test_conditional_ops(self, client):
        m = client.get_map("m2")
        assert m.put_if_absent("k", 1) is None
        assert m.put_if_absent("k", 2) == 1
        assert m.replace("k", 5) == 1
        assert m.replace("missing", 5) is None
        assert m.replace("k", 5, 6) is True
        assert m.replace("k", 5, 7) is False
        assert m.remove("k", 99) is False
        assert m.remove("k", 6) is True

    def test_views_and_bulk(self, client):
        m = client.get_map("m3")
        m.put_all({"a": 1, "b": 2, "c": 3})
        assert sorted(m.key_set()) == ["a", "b", "c"]
        assert sorted(m.values()) == [1, 2, 3]
        assert m.read_all_map() == {"a": 1, "b": 2, "c": 3}
        assert m.get_all(["a", "c", "z"]) == {"a": 1, "c": 3}
        assert m.key_set(pattern="[ab]") == ["a", "b"] or sorted(
            m.key_set(pattern="[ab]")
        ) == ["a", "b"]
        assert m.contains_key("a") and not m.contains_key("z")
        assert m.contains_value(2) and not m.contains_value(9)

    def test_add_and_get(self, client):
        m = client.get_map("m4")
        assert m.add_and_get("cnt", 5) == 5
        assert m.add_and_get("cnt", -2) == 3

    def test_dict_protocol(self, client):
        m = client.get_map("m5")
        m["x"] = 1
        assert m["x"] == 1
        assert "x" in m
        assert len(m) == 1

    def test_map_cache_entry_ttl(self, client):
        mc = client.get_map_cache("mc1")
        mc.put("t", "gone", ttl_seconds=0.15)
        mc.put("p", "stays")
        assert mc.get("t") == "gone"
        assert mc.remain_time_to_live_entry("t") > 0
        assert mc.remain_time_to_live_entry("p") == -1
        time.sleep(0.2)
        assert mc.get("t") is None
        assert mc.get("p") == "stays"
        assert mc.size() == 1

    def test_map_cache_max_idle(self, client):
        mc = client.get_map_cache("mc2")
        mc.put("i", "v", max_idle_seconds=0.2)
        time.sleep(0.1)
        assert mc.get("i") == "v"  # access refreshes idle clock
        time.sleep(0.15)
        assert mc.get("i") == "v"
        time.sleep(0.25)
        assert mc.get("i") is None


# -- set / list ------------------------------------------------------------


class TestSet:
    def test_basic(self, client):
        s = client.get_set("s1")
        assert s.add("a") is True
        assert s.add("a") is False
        s.add_all(["b", "c"])
        assert s.contains("b")
        assert s.size() == 3
        assert s.remove("b") is True
        assert s.remove("b") is False
        assert sorted(s.read_all()) == ["a", "c"]

    def test_algebra(self, client):
        a = client.get_set("sa")
        b = client.get_set("sb")
        a.add_all([1, 2, 3])
        b.add_all([2, 3, 4])
        assert sorted(a.read_union("sb")) == [1, 2, 3, 4]
        assert sorted(a.read_intersection("sb")) == [2, 3]
        c = client.get_set("sc")
        c.add_all([1, 2, 3])
        c.diff("sb")
        assert c.read_all() == [1]

    def test_move_and_random(self, client):
        a = client.get_set("sm1")
        b = client.get_set("sm2")
        a.add_all([1, 2])
        assert a.move("sm2", 1) is True
        assert a.move("sm2", 99) is False
        assert b.contains(1)
        got = b.remove_random(1)
        assert got and not b.contains(got[0])

    def test_set_cache_ttl(self, client):
        sc = client.get_set_cache("scache")
        sc.add("fleeting", ttl_seconds=0.15)
        sc.add("durable")
        assert sc.contains("fleeting")
        time.sleep(0.2)
        assert not sc.contains("fleeting")
        assert sc.read_all() == ["durable"]


class TestList:
    def test_basic(self, client):
        lst = client.get_list("l1")
        lst.add_all(["a", "b", "c"])
        assert lst.get(1) == "b"
        assert lst[0] == "a"
        lst.set(1, "B")
        assert lst.read_all() == ["a", "B", "c"]
        lst.insert(1, "x")
        assert lst.read_all() == ["a", "x", "B", "c"]
        assert lst.index_of("B") == 2
        assert lst.remove("x") is True
        assert lst.remove_at(0) == "a"
        assert len(lst) == 2

    def test_sublist_trim(self, client):
        lst = client.get_list("l2")
        lst.add_all(list(range(10)))
        assert lst.sub_list(2, 5) == [2, 3, 4]
        lst.trim(1, 3)
        assert lst.read_all() == [1, 2, 3]


class TestSortedSets:
    def test_sorted_set(self, client):
        ss = client.get_sorted_set("ss")
        for v in (5, 1, 3):
            ss.add(v)
        assert ss.add(3) is False
        assert ss.read_all() == [1, 3, 5]
        assert ss.first() == 1 and ss.last() == 5
        assert ss.remove(3) is True
        assert ss.read_all() == [1, 5]

    def test_scored_sorted_set(self, client):
        z = client.get_scored_sorted_set("z")
        z.add(3.0, "c")
        z.add(1.0, "a")
        z.add(2.0, "b")
        assert z.get_score("b") == 2.0
        assert z.rank("b") == 1
        assert z.value_range(0, -1) == ["a", "b", "c"]
        assert z.value_range_by_score(1.5, 3.0) == ["b", "c"]
        assert z.add_score("a", 5.0) == 6.0
        assert z.poll_first() == "b"
        assert z.poll_last() == "a"
        assert z.read_all() == ["c"]
        assert z.entry_range(0, -1) == [("c", 3.0)]

    def test_lex_sorted_set(self, client):
        lx = client.get_lex_sorted_set("lx")
        lx.add_all(["b", "a", "d", "c"])
        assert lx.range("a", False, "d", False) == ["b", "c"]
        assert lx.range("a", True, "c", True) == ["a", "b", "c"]
        assert lx.range_head("c") == ["a", "b"]
        assert lx.range_tail("b", inclusive=True) == ["b", "c", "d"]
        assert lx.count("a", True, "d", True) == 4


# -- queues ----------------------------------------------------------------


class TestQueues:
    def test_fifo(self, client):
        q = client.get_queue("q1")
        q.offer("a")
        q.offer("b")
        assert q.peek() == "a"
        assert q.poll() == "a"
        assert q.poll() == "b"
        assert q.poll() is None

    def test_rpoplpush(self, client):
        q = client.get_queue("q2")
        q.offer_all(["x", "y"])
        moved = q.poll_last_and_offer_first_to("q3")
        assert moved == "y"
        assert client.get_queue("q3").peek() == "y"

    def test_deque(self, client):
        d = client.get_deque("d1")
        d.add_last("m")
        d.add_first("f")
        d.add_last("l")
        assert d.peek_first() == "f"
        assert d.peek_last() == "l"
        assert d.poll_last() == "l"
        assert d.poll_first() == "f"

    def test_blocking_poll_timeout(self, client):
        bq = client.get_blocking_queue("bq1")
        t0 = time.monotonic()
        assert bq.poll(timeout_seconds=0.15) is None
        assert time.monotonic() - t0 >= 0.14

    def test_blocking_wakeup_across_threads(self, client):
        bq = client.get_blocking_queue("bq2")
        got = []

        def taker():
            got.append(bq.poll(timeout_seconds=3.0))

        t = threading.Thread(target=taker)
        t.start()
        time.sleep(0.05)
        bq.offer("wake")
        t.join(timeout=3.0)
        assert got == ["wake"]

    def test_poll_from_any(self, client):
        a = client.get_blocking_queue("any-a")
        b = client.get_blocking_queue("any-b")
        b.offer("from-b")
        assert a.poll_from_any(0.5, "any-b") == "from-b"

    def test_delayed_queue(self, client):
        dest = client.get_blocking_queue("dq-dest")
        dq = client.get_delayed_queue(dest)
        dq.offer("later", 0.2)
        dq.offer("sooner", 0.05)
        assert dest.poll() is None  # nothing due yet
        assert dest.poll(timeout_seconds=2.0) == "sooner"
        assert dest.poll(timeout_seconds=2.0) == "later"
        assert dq.size() == 0

    def test_priority_queue(self, client):
        pq = client.get_priority_queue("pq")
        for v in (5, 1, 3):
            pq.offer(v)
        assert pq.peek() == 1
        assert [pq.poll(), pq.poll(), pq.poll()] == [1, 3, 5]

    def test_ring_buffer(self, client):
        rb = client.get_ring_buffer("rb")
        assert rb.try_set_capacity(3) is True
        assert rb.try_set_capacity(5) is False
        for i in range(5):
            rb.add(i)
        assert rb.read_all() == [2, 3, 4]  # oldest evicted
        assert rb.capacity() == 3
        assert rb.remaining_capacity() == 0
        assert rb.poll() == 2


# -- topics ----------------------------------------------------------------


class TestTopics:
    def test_publish_subscribe(self, client):
        topic = client.get_topic("news")
        got = []
        lid = topic.add_listener(lambda ch, msg: got.append((ch, msg)))
        n = topic.publish("hello")
        client._topic_bus.drain()
        assert n == 1
        assert got == [("news", "hello")]
        topic.remove_listener(lid)
        assert topic.publish("ignored") == 0

    def test_pattern_topic(self, client):
        pt = client.get_pattern_topic("news.*")
        got = []
        pt.add_listener(lambda pat, ch, msg: got.append((pat, ch, msg)))
        n = client.get_topic("news.sports").publish("goal")
        client._topic_bus.drain()
        assert n == 1
        assert got == [("news.*", "news.sports", "goal")]
        assert client.get_topic("weather").publish("rain") == 0

    def test_count_subscribers(self, client):
        t = client.get_topic("counted")
        t.add_listener(lambda ch, m: None)
        client.get_pattern_topic("count*").add_listener(lambda p, ch, m: None)
        assert t.count_subscribers() == 2

    def test_listener_error_does_not_break_delivery(self, client):
        t = client.get_topic("errs")
        got = []

        def bad(ch, m):
            raise RuntimeError("boom")

        t.add_listener(bad)
        t.add_listener(lambda ch, m: got.append(m))
        t.publish("m1")
        client._topic_bus.drain()
        assert got == ["m1"]


# -- object-level TTL + dump/restore ---------------------------------------


class TestObjectLifecycle:
    def test_expire_whole_object(self, client):
        m = client.get_map("ttl-map")
        m.put("k", "v")
        assert m.expire(0.15) is True
        assert m.remain_time_to_live() > 0
        time.sleep(0.2)
        assert m.get("k") is None
        assert not m.is_exists()

    def test_clear_expire(self, client):
        b = client.get_bucket("persist")
        b.set("v")
        b.expire(0.2)
        assert b.clear_expire() is True
        time.sleep(0.25)
        assert b.get() == "v"
        assert b.remain_time_to_live() == -1

    def test_sweeper_removes_expired(self, client):
        b = client.get_bucket("swept")
        b.set("v")
        b.expire(0.1)
        time.sleep(0.5)  # sweeper interval 0.25s
        with client._grid.lock:
            assert "swept" not in client._grid._data

    def test_rename(self, client):
        b = client.get_bucket("old")
        b.set("v")
        b.rename("new")
        assert client.get_bucket("new").get() == "v"
        assert not client.get_bucket("old").is_exists()

    def test_dump_restore(self, client):
        m = client.get_map("dumpme")
        m.put_all({"a": 1, "b": 2})
        blob = m.dump()
        m.delete()
        m.restore(blob)
        assert m.read_all_map() == {"a": 1, "b": 2}
        with pytest.raises(RuntimeError):
            m.restore(blob)  # already exists
        m.restore(blob, replace=True)
        with pytest.raises(TypeError):
            client.get_bucket("dumpme2").restore(blob)


# -- review-fix regressions -------------------------------------------------


class TestReviewFixes:
    def test_ring_buffer_inherited_methods(self, client):
        rb = client.get_ring_buffer("rb-r")
        rb.try_set_capacity(4)
        rb.offer_all([1, 2, 3])
        assert rb.contains(2) is True
        assert rb.remove(2) is True
        assert rb.contains(2) is False
        assert rb.read_all() == [1, 3]
        moved = rb.poll_last_and_offer_first_to("rb-dest")
        assert moved == 3
        assert client.get_queue("rb-dest").peek() == 3

    def test_max_idle_not_refreshed_by_size_or_sweeper(self, client):
        mc = client.get_map_cache("mc-idle")
        mc.put("i", "v", max_idle_seconds=0.25)
        # Trigger the grid sweeper (it calls prune_expired on every value).
        client.get_bucket("tick").set("x")
        client.get_bucket("tick").expire(10)
        for _ in range(6):
            time.sleep(0.1)
            mc.size()  # size() must not refresh the idle clock
        assert mc.get("i") is None

    def test_set_move_wrongtype_keeps_source(self, client):
        client.get_bucket("dst-b").set(1)
        s = client.get_set("src-s")
        s.add("x")
        with pytest.raises(TypeError):
            s.move("dst-b", "x")
        assert s.contains("x")  # element not lost

    def test_queue_transfer_wrongtype_keeps_source(self, client):
        client.get_bucket("dst-q").set(1)
        q = client.get_queue("src-q")
        q.offer("x")
        with pytest.raises(TypeError):
            q.poll_last_and_offer_first_to("dst-q")
        assert q.contains("x")

    def test_rename_missing_raises(self, client):
        with pytest.raises(RuntimeError):
            client.get_bucket("ghost").rename("ghost2")
        assert not client.get_bucket("ghost2").is_exists()
        b = client.get_bucket("same")
        b.set("v")
        b.rename("same")  # RENAME key key: fine when it exists
        assert b.get() == "v"

    def test_topic_camelcase_full(self, client):
        t = client.get_topic("cc")
        assert t.getName() == "cc"
        lid = t.addListener(lambda ch, m: None)
        assert t.countSubscribers() == 1
        t.removeAllListeners()
        assert t.countSubscribers() == 0
        assert client.get_pattern_topic("cc*").getPattern() == "cc*"
