"""Host-keyspace persistence (grid RDB analog, grid/store.py
snapshot_to/restore_from + client.snapshot): data-only wire format,
value-bearing kinds round-trip bit-exactly, runtime-state kinds are
skipped, TTLs survive."""

import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec


def make_client(tmp_path):
    cfg = Config().use_tpu_sketch(min_bucket=64)
    cfg.snapshot_dir = str(tmp_path / "snap")
    return redisson_tpu.create(cfg)


def test_grid_kinds_round_trip(tmp_path):
    c1 = make_client(tmp_path)
    c1.get_bucket("b").set(b"payload-\x00\xff")
    c1.get_binary_stream("bin").set(b"\x01\x02")
    s = c1.get_set("s")
    s.add(b"m1")
    s.add(b"m2")
    z = c1.get_scored_sorted_set("z")
    z.add(1.5, b"one")
    z.add(2.5, b"two")
    m = c1.get_map("m")
    m.put(b"k1", b"v1")
    mc = c1.get_map_cache("mc")
    mc.put(b"k", b"v", ttl_seconds=300.0)
    lst = c1.get_list("l")
    lst.add(b"a")
    lst.add(b"b")
    c1.get_atomic_long("al").set(42)
    c1.get_atomic_double("ad").set(2.5)
    lx = c1.get_lex_sorted_set("lx")
    lx.add("alpha")
    lx.add("beta")
    ttl_bucket = c1.get_bucket("ttlb")
    ttl_bucket.set(b"x", ttl_seconds=300.0)
    gen = c1.get_id_generator("gen")
    gen.try_init(100, 10)
    ids1 = [gen.next_id() for _ in range(15)]  # consumes blocks [100,120)
    c1.get_long_adder("la").add(7)
    rb = c1.get_ring_buffer("rb")
    rb.try_set_capacity(3)
    rb.offer_all([b"r1", b"r2", b"r3", b"r4"])
    # Runtime-state kind in the same keyspace: must be skipped cleanly.
    c1.get_queue("rtq")  # list kind, persists
    c1.get_lock("rtlock")  # lock kind: skipped
    c1.shutdown()  # writes grid_store.bin + sketch snapshot

    c2 = make_client(tmp_path)
    try:
        assert c2.get_bucket("b").get() == b"payload-\x00\xff"
        assert c2.get_binary_stream("bin").get() == b"\x01\x02"
        assert sorted(c2.get_set("s").read_all()) == [b"m1", b"m2"]
        assert c2.get_scored_sorted_set("z").get_score(b"two") == 2.5
        assert c2.get_map("m").get(b"k1") == b"v1"
        assert c2.get_map_cache("mc").get(b"k") == b"v"
        assert c2.get_list("l").read_all() == [b"a", b"b"]
        assert c2.get_atomic_long("al").get() == 42
        assert c2.get_atomic_double("ad").get() == 2.5
        assert c2.get_lex_sorted_set("lx").read_all() == ["alpha", "beta"]
        ttl = c2.get_bucket("ttlb").remain_time_to_live()
        assert 0 < ttl <= 300_000
        # idgenerator: restarted process must NOT re-issue handed-out ids.
        nxt = c2.get_id_generator("gen").next_id()
        assert nxt >= 120 and nxt not in ids1
        assert c2.get_long_adder("la").sum() == 7
        assert c2.get_ring_buffer("rb").read_all() == [b"r2", b"r3", b"r4"]
    finally:
        c2.shutdown()


def test_sketch_and_grid_one_dir(tmp_path):
    c1 = make_client(tmp_path)
    bf = c1.get_bloom_filter("bf")
    bf.try_init(1000, 0.01)
    bf.add_all(np.arange(100, dtype=np.uint64))
    c1.get_bucket("gb").set(b"gv")
    c1.snapshot()  # explicit full-keyspace snapshot
    c1.shutdown()
    c2 = make_client(tmp_path)
    try:
        assert bool(np.all(
            c2.get_bloom_filter("bf").contains_each(
                np.arange(100, dtype=np.uint64)
            )
        ))
        assert c2.get_bucket("gb").get() == b"gv"
    finally:
        c2.shutdown()


def test_expired_entries_dropped_on_restore(tmp_path):
    c1 = make_client(tmp_path)
    c1.get_bucket("gone").set(b"x", ttl_seconds=0.05)
    c1.get_bucket("stays").set(b"y")
    time.sleep(0.1)
    c1.shutdown()
    c2 = make_client(tmp_path)
    try:
        assert c2.get_bucket("gone").get() is None
        assert c2.get_bucket("stays").get() == b"y"
    finally:
        c2.shutdown()


def test_forged_grid_snapshot_rejected(tmp_path):
    import os

    d = tmp_path / "snap"
    os.makedirs(d, exist_ok=True)
    path = d / "grid_store.bin"
    path.write_bytes(b"RTPG\x08\x00\x00\x00notjson!")
    cfg = Config().use_tpu_sketch(min_bucket=64)
    cfg.snapshot_dir = str(d)
    with pytest.raises(Exception):
        redisson_tpu.create(cfg)


def test_periodic_snapshot_covers_grid(tmp_path):
    """Crash-safety: the engine's PERIODIC snapshotter persists the host
    keyspace too (snapshot_extra hook), so a SIGKILL loses at most one
    interval — not every grid write since boot."""
    import os
    import time as _time

    cfg = Config().use_tpu_sketch(min_bucket=64)
    cfg.snapshot_dir = str(tmp_path / "snap")
    cfg.snapshot_interval_s = 0.2
    c1 = redisson_tpu.create(cfg)
    c1.get_bucket("periodic").set(b"pv")
    path = os.path.join(cfg.snapshot_dir, "grid_store.bin")
    deadline = _time.monotonic() + 10.0
    while not os.path.exists(path) and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert os.path.exists(path), "periodic snapshot never wrote the grid"
    # Simulate a crash: stop the timer, abandon without clean shutdown.
    c1._engine._stop_snapshotter()
    cfg2 = Config().use_tpu_sketch(min_bucket=64)
    cfg2.snapshot_dir = cfg.snapshot_dir
    c2 = redisson_tpu.create(cfg2)
    try:
        assert c2.get_bucket("periodic").get() == b"pv"
    finally:
        c2.config.snapshot_dir = None  # don't re-snapshot on teardown
        c2.shutdown()
        c1.config.snapshot_dir = None
        c1.shutdown()


def test_host_engine_shutdown_persists_grid(tmp_path):
    """Host-engine clients (no sketch snapshotter) must still write the
    grid snapshot at shutdown — the snapshot_extra hook is only wired
    when an engine snapshotter exists to fire it."""
    import warnings

    cfg = Config()
    cfg.snapshot_dir = str(tmp_path / "snap")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c1 = redisson_tpu.create(cfg)
    c1.get_bucket("hk").set(b"hv")
    c1.shutdown()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c2 = redisson_tpu.create(cfg)
    try:
        assert c2.get_bucket("hk").get() == b"hv"
    finally:
        c2.shutdown()
