"""Hashing twins: NumPy vs JAX bit-identical; distribution sanity."""

import numpy as np
import pytest

from redisson_tpu.utils import hashing


def _random_bytes_batch(rng, n, maxlen=40):
    return [bytes(rng.integers(0, 256, size=rng.integers(0, maxlen), dtype=np.uint8)) for _ in range(n)]


def test_numpy_jax_twins_identical():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    blocks, lengths = hashing.encode_bytes_batch(_random_bytes_batch(rng, 257))
    out_np = hashing.murmur3_x86_128(blocks, lengths, xp=np)
    out_jx = hashing.murmur3_x86_128(jnp.asarray(blocks), jnp.asarray(lengths), xp=jnp)
    for a, b in zip(out_np, out_jx):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_uint64_fast_path_matches_bytes_path():
    keys = np.arange(1000, dtype=np.uint64) * np.uint64(2654435761)
    fast_blocks, fast_len = hashing.encode_uint64_batch(keys)
    slow_blocks, slow_len = hashing.encode_bytes_batch(
        [int(k).to_bytes(8, "little") for k in keys]
    )
    np.testing.assert_array_equal(fast_blocks, slow_blocks)
    np.testing.assert_array_equal(fast_len, slow_len)
    h_fast = hashing.hash128_np(fast_blocks, fast_len)
    h_slow = hashing.hash128_np(slow_blocks, slow_len)
    np.testing.assert_array_equal(h_fast[0], h_slow[0])
    np.testing.assert_array_equal(h_fast[1], h_slow[1])


def test_hash_determinism_and_sensitivity():
    b1, l1 = hashing.encode_bytes_batch([b"hello", b"hello", b"hellp"])
    c = hashing.murmur3_x86_128(b1, l1)
    assert all(int(x[0]) == int(x[1]) for x in c)
    assert any(int(x[0]) != int(x[2]) for x in c)
    # Length is mixed in: zero-padded prefix keys differ.
    b2, l2 = hashing.encode_bytes_batch([b"a", b"a\x00"])
    c2 = hashing.murmur3_x86_128(b2, l2)
    assert any(int(x[0]) != int(x[1]) for x in c2)


def test_uniformity_chi_squared():
    """Low 14 bits of each lane should be uniform over 2^14 buckets."""
    keys = np.arange(1 << 16, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    c0, c1, c2, c3 = hashing.murmur3_x86_128(blocks, lengths)
    nbuckets = 1 << 14
    for lane in (c0, c1, c2, c3):
        counts = np.bincount(lane & (nbuckets - 1), minlength=nbuckets)
        expected = len(keys) / nbuckets
        chi2 = float(((counts - expected) ** 2 / expected).sum())
        # dof = 16383; 5-sigma band ≈ dof ± 5*sqrt(2*dof) ≈ [15478, 17288]
        assert 14000 < chi2 < 19000, chi2


def test_km_reduce_mod_bounds():
    keys = np.arange(4096, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    for m in (17, 9_585_059, 1 << 31):
        h1m, h2m = hashing.km_reduce_mod(h1, h2, m)
        assert h1m.dtype == np.uint32 and h2m.dtype == np.uint32
        assert int(h1m.max()) < m and int(h2m.max()) < m
    with pytest.raises(ValueError):
        hashing.km_reduce_mod(h1, h2, (1 << 31) + 1)


def test_empty_batch():
    blocks, lengths = hashing.encode_bytes_batch([])
    assert blocks.shape == (0, 4)
    c = hashing.murmur3_x86_128(blocks, lengths)
    assert c[0].shape == (0,)


def test_hash_is_batch_shape_independent():
    """r3 fix: a key's hash must not depend on the batch it rides in —
    the unmasked block mix made mixed-length batches hash short keys
    against the batch-wide padding width."""
    from redisson_tpu.utils import hashing

    single, ls = hashing.encode_bytes_batch([b"x"])
    hs = hashing.murmur3_x86_128(single, ls)
    mixed, lm = hashing.encode_bytes_batch([b"x", b"a-much-longer-key-here!!!"])
    hm = hashing.murmur3_x86_128(mixed, lm)
    assert all(int(a[0]) == int(b[0]) for a, b in zip(hs, hm))
    # And through the public API: estimate finds keys added in other batches.
    import redisson_tpu
    from redisson_tpu import Config

    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    try:
        cms = c.get_count_min_sketch("mixlen")
        cms.try_init(4, 1 << 12)
        cms.add("x", count=12)
        assert list(cms.estimate_all(["x", "a-much-longer-key"])) == [12, 0]
        bf = c.get_bloom_filter("mixlen-bf")
        bf.try_init(1000, 0.01)
        bf.add("y")
        assert list(bf.contains_each(["y", "a-much-longer-key"])) == [True, False]
    finally:
        c.shutdown()
