"""L8 framework integrations: @Cacheable-style decorator, cache manager,
TTL'd web-session store."""

import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.integrations import CacheManagerAdapter, SessionStore, cached


@pytest.fixture
def client():
    c = redisson_tpu.create(Config())
    yield c
    c.shutdown()


class TestCachedDecorator:
    def test_memoizes_and_evicts(self, client):
        calls = []

        @cached(client, "fib-cache")
        def slow_square(x):
            calls.append(x)
            return x * x

        assert slow_square(4) == 16
        assert slow_square(4) == 16
        assert calls == [4]  # second call served from cache
        slow_square.cache_evict(4)
        assert slow_square(4) == 16
        assert calls == [4, 4]

    def test_ttl(self, client):
        calls = []

        @cached(client, "ttl-cache", ttl_seconds=0.1)
        def f(x):
            calls.append(x)
            return x + 1

        f(1)
        time.sleep(0.15)
        f(1)
        assert calls == [1, 1]  # expired between calls

    def test_custom_key_and_clear(self, client):
        @cached(client, "k-cache", key_fn=lambda user_id: f"u:{user_id}")
        def profile(user_id):
            return {"id": user_id}

        profile(7)
        assert profile.cache.contains_key("u:7")
        profile.cache_clear()
        assert not profile.cache.contains_key("u:7")


class TestCacheManagerAdapter:
    def test_named_configs(self, client):
        mgr = CacheManagerAdapter(
            client, {"short": {"ttl_seconds": 0.1}, "long": {}}
        )
        mgr.get_cache("short").put("k", 1)
        mgr.get_cache("long").put("k", 2)
        time.sleep(0.15)
        assert mgr.get_cache("short").get("k") is None
        assert mgr.get_cache("long").get("k") == 2
        assert "short" in mgr.get_cache_names()


class TestSessionStore:
    def test_create_load_save(self, client):
        store = SessionStore(client, max_inactive_seconds=30)
        s = store.create()
        s["user"] = "ada"
        s.save()
        again = store.load(s.session_id)
        assert again["user"] == "ada"
        again.invalidate()
        assert store.load(s.session_id) is None

    def test_inactivity_expiry_and_touch(self, client):
        store = SessionStore(client, max_inactive_seconds=0.2)
        s = store.create()
        time.sleep(0.12)
        assert store.load(s.session_id) is not None  # touch resets window
        time.sleep(0.12)
        assert store.load(s.session_id) is not None
        time.sleep(0.25)
        assert store.load(s.session_id) is None  # inactivity exceeded
