"""JSR-107 depth (round-5 VERDICT item 5): entry listeners incl.
expired, CacheLoader/CacheWriter read/write-through, per-cache
statistics, access/update ExpiryPolicy."""

import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.grid.jcache import CacheManager, ExpiryPolicy, JCache


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


@pytest.fixture
def manager(client):
    return CacheManager(client)


def _drain(client):
    client._topic_bus.drain()


class TestEntryListeners:
    def test_created_updated_removed(self, manager, client):
        cache = manager.create_cache("jl")
        events = []
        lid = cache.register_cache_entry_listener(
            lambda ev, k, v: events.append((ev, k, v))
        )
        cache.put("a", 1)
        cache.put("a", 2)
        cache.remove("a")
        _drain(client)
        assert events == [
            ("created", "a", 1), ("updated", "a", 2), ("removed", "a", 2),
        ]
        cache.deregister_cache_entry_listener(lid)
        cache.put("b", 1)
        _drain(client)
        assert len(events) == 3  # deregistered: no further events

    def test_event_filter(self, manager, client):
        cache = manager.create_cache("jl2")
        removed = []
        cache.register_cache_entry_listener(
            lambda ev, k, v: removed.append(k), event=JCache.EVENT_REMOVED
        )
        cache.put("x", 1)
        cache.remove("x")
        _drain(client)
        assert removed == ["x"]

    def test_expired_event_fires_on_lazy_reap(self, manager, client):
        cache = manager.create_cache(
            "jexp", expiry_policy=ExpiryPolicy(creation_ttl=0.1)
        )
        events = []
        cache.register_cache_entry_listener(
            lambda ev, k, v: events.append((ev, k, v)),
            event=JCache.EVENT_EXPIRED,
        )
        cache.put("gone", 41)
        time.sleep(0.25)
        assert cache.get("gone") is None  # lazy reap fires the event
        _drain(client)
        assert events == [("expired", "gone", 41)]


class TestReadWriteThrough:
    def test_read_through_loads_on_miss(self, manager):
        loads = []

        def loader(k):
            loads.append(k)
            return f"db:{k}"

        cache = manager.create_cache(
            "jrt", cache_loader=loader, read_through=True,
            statistics_enabled=True,
        )
        assert cache.get("k1") == "db:k1"
        assert cache.statistics.misses == 1  # a LOAD is a miss (JSR)
        assert loads == ["k1"]
        assert cache.get("k1") == "db:k1"  # now cached: no second load
        assert loads == ["k1"]

    def test_read_through_get_all(self, manager):
        cache = manager.create_cache(
            "jrt2", cache_loader=lambda k: k.upper(), read_through=True
        )
        cache.put("a", "cached")
        out = cache.get_all(["a", "b"])
        assert out == {"a": "cached", "b": "B"}

    def test_load_all(self, manager):
        cache = manager.create_cache("jla", cache_loader=lambda k: k * 2)
        cache.put("x", "keep")
        assert cache.load_all(["x", "y"]) == 1  # x kept, y loaded
        assert cache.get("x") == "keep"
        assert cache.get("y") == "yy"
        assert cache.load_all(["x"], replace_existing=True) == 1
        assert cache.get("x") == "xx"

    def test_write_through_mirrors_puts_and_removes(self, manager):
        backing = {}

        class Writer:
            def write(self, k, v):
                backing[k] = v

            def delete(self, k):
                backing.pop(k, None)

        cache = manager.create_cache(
            "jwt", cache_writer=Writer(), write_through=True
        )
        cache.put("a", 1)
        cache.get_and_put("b", 2)
        assert backing == {"a": 1, "b": 2}
        cache.remove("a")
        assert backing == {"b": 2}
        cache.remove_all(["b"])
        assert backing == {}

    def test_failing_writer_leaves_cache_unchanged(self, manager):
        class Writer:
            def write(self, k, v):
                raise IOError("db down")

            def delete(self, k):
                raise IOError("db down")

        cache = manager.create_cache(
            "jwf", cache_writer=Writer(), write_through=True
        )
        with pytest.raises(IOError):
            cache.put("a", 1)
        assert cache.get("a") is None  # JSR: writer runs FIRST


class TestStatistics:
    def test_hits_misses_puts_removals(self, manager):
        cache = manager.create_cache("jst", statistics_enabled=True)
        cache.put("a", 1)
        cache.put("b", 2)
        assert cache.get("a") == 1
        assert cache.get("ghost") is None
        cache.remove("a")
        s = cache.statistics
        assert (s.puts, s.hits, s.misses, s.removals) == (2, 1, 1, 1)
        assert s.gets == 2 and s.hit_percentage == 50.0
        s.reset()
        assert (s.puts, s.hits, s.misses, s.removals) == (0, 0, 0, 0)

    def test_statistics_disabled_by_default(self, manager):
        assert manager.create_cache("jsd").statistics is None


class TestExpiryPolicy:
    def test_creation_ttl(self, manager):
        cache = manager.create_cache(
            "jec", expiry_policy=ExpiryPolicy(creation_ttl=0.15)
        )
        cache.put("k", 1)
        assert cache.get("k") == 1
        time.sleep(0.25)
        assert cache.get("k") is None

    def test_access_ttl_refreshes_on_get(self, manager):
        cache = manager.create_cache(
            "jea", expiry_policy=ExpiryPolicy(access_ttl=0.3)
        )
        cache.put("k", 1)
        for _ in range(3):
            time.sleep(0.15)
            assert cache.get("k") == 1  # touches keep it alive
        time.sleep(0.45)
        assert cache.get("k") is None  # idle past the access TTL

    def test_update_ttl_on_replace(self, manager):
        cache = manager.create_cache(
            "jeu",
            expiry_policy=ExpiryPolicy(creation_ttl=10.0, update_ttl=0.15),
        )
        cache.put("k", 1)
        assert cache.replace("k", 2) is True
        time.sleep(0.3)
        assert cache.get("k") is None  # replace re-armed the short TTL

    def test_default_ttl_seconds_back_compat(self, manager):
        cache = manager.create_cache("jbc", default_ttl_seconds=0.15)
        cache.put("k", 1)
        time.sleep(0.3)
        assert cache.get("k") is None


class TestReviewFixes:
    def test_failed_conditional_remove_keeps_writer_row(self, manager):
        backing = {}

        class Writer:
            def write(self, k, v):
                backing[k] = v

            def delete(self, k):
                backing.pop(k, None)

        cache = manager.create_cache(
            "jcr", cache_writer=Writer(), write_through=True
        )
        cache.put("k", "v1")
        assert cache.remove("k", "wrong") is False
        assert backing == {"k": "v1"}  # failed compare: writer untouched
        assert cache.get("k") == "v1"
        assert cache.remove("k", "v1") is True
        assert backing == {}

    def test_update_ttl_applies_on_plain_put(self, manager):
        cache = manager.create_cache(
            "jup", expiry_policy=ExpiryPolicy(update_ttl=0.15)
        )
        cache.put("k", 1)   # creation: no TTL
        cache.put("k", 2)   # update: re-armed under update_ttl
        time.sleep(0.3)
        assert cache.get("k") is None
        cache.put("fresh", 1)  # creation path: still immortal
        time.sleep(0.2)
        assert cache.get("fresh") == 1
