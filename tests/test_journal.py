"""Crash-safe durability tier (ISSUE 10): op journal, group commit,
point-in-time recovery, snapshot coordination, and the RESP
persistence surface.

The crash harness proper (subprocess kill -9 soak) lives in
tests/test_crash_recovery.py (slow-marked); these are the
deterministic, tier-1-speed pieces.
"""

import os
import struct
import time
import zlib

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config, chaos
from redisson_tpu.codecs import LongCodec
from redisson_tpu.durability.journal import (
    JournalError,
    OpJournal,
    decode_record,
    encode_record,
)


def make_cfg(tmp_path, fsync="always", journal=True, snap=True, **kw):
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        min_bucket=64, **kw
    )
    if snap:
        cfg.snapshot_dir = str(tmp_path / "snap")
    if journal:
        cfg.journal_dir = str(tmp_path / "journal")
        cfg.journal_fsync = fsync
    return cfg


def make_client(tmp_path, **kw):
    return redisson_tpu.create(make_cfg(tmp_path, **kw))


def crash(client):
    """Tear a client down WITHOUT the clean-shutdown snapshot, so the
    journal tail is what recovery has to work with.  (The journal's
    own close flushes what a crashed OS would eventually have written;
    torn-tail cases are driven explicitly via chaos/truncation.)"""
    eng = client._engine
    j = eng.journal
    if j is not None:
        eng.journal = None
        j.close()
    eng.config.snapshot_dir = None
    client.config.snapshot_dir = None
    client.shutdown()


def engine_rows(eng):
    eng._drain()
    out = {}
    for e in eng.registry.entries():
        out[e.name] = np.asarray(
            eng.executor.read_row(e.pool, e.row)
        ).copy()
    return out


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.clear()
    yield
    chaos.clear()


# -- record codec -------------------------------------------------------------


class TestRecordCodec:
    def test_round_trip(self):
        rec = {
            "op": "bloom.add",
            "name": "x",
            "h1": np.arange(5, dtype=np.uint64),
            "h2": np.arange(5, dtype=np.uint32) * 7,
            "blocks": np.arange(12, dtype=np.uint32).reshape(3, 4),
            "flag": True,
            "n": 42,
            "f": 0.5,
            "names": ["a", "b"],
            "blob": b"\x00\x01\xff",
        }
        out = decode_record(encode_record(rec))
        assert out["op"] == "bloom.add" and out["name"] == "x"
        assert out["flag"] is True and out["n"] == 42 and out["f"] == 0.5
        assert out["names"] == ["a", "b"]
        np.testing.assert_array_equal(out["h1"], rec["h1"])
        assert out["h1"].dtype == np.uint64
        np.testing.assert_array_equal(out["blocks"], rec["blocks"])
        assert out["blocks"].shape == (3, 4)
        assert np.asarray(out["blob"], np.uint8).tobytes() == rec["blob"]

    def test_malformed_payload_rejected(self):
        good = encode_record({"op": "x", "name": "y"})
        with pytest.raises(ValueError):
            decode_record(good[:2])
        # Header length overruns the payload.
        bad = struct.pack("<I", 1 << 20) + good[4:]
        with pytest.raises(ValueError):
            decode_record(bad)

    def test_declared_array_overrun_rejected(self):
        rec = {"op": "x", "name": "y", "a": np.arange(8, dtype=np.uint32)}
        enc = encode_record(rec)
        with pytest.raises(ValueError):
            decode_record(enc[:-8])  # truncated array bytes


# -- journal core (no engine) -------------------------------------------------


class TestJournalCore:
    def test_always_ack_is_durable(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="always")
        seq = j.append({"op": "x", "name": "a", "v": 1})
        assert j.wait_durable(seq, timeout=10.0)
        assert j.is_durable(seq)
        assert j.stats()["fsyncs"] >= 1
        j.close()

    def test_everysec_durable_within_window(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="everysec")
        seq = j.append({"op": "x", "name": "a"})
        deadline = time.monotonic() + 5.0
        while not j.is_durable(seq) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert j.is_durable(seq), "everysec never fsynced"
        j.close()

    def test_no_policy_fence_forces_fsync(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="no")
        seq = j.append({"op": "x", "name": "a"})
        # The explicit fence is the one durability promise 'no' makes.
        assert j.wait_durable(seq, timeout=10.0)
        assert j.stats()["fsyncs"] >= 1
        j.close()

    def test_rotation_and_replay_order(self, tmp_path):
        j = OpJournal(
            str(tmp_path), fsync_policy="always",
            max_segment_bytes=1 << 12,
        )
        for i in range(200):
            j.append({"op": "x", "name": "a", "i": i})
        j.wait_durable(timeout=30.0)
        st = j.stats()
        assert st["segments"] > 1, "tiny segments must rotate"
        recs = list(j.records_after(0))
        assert len(recs) == 200
        assert [r["i"] for _s, r in recs] == list(range(200))
        assert [s for s, _r in recs] == list(range(1, 201))
        j.close()

    def test_reopen_continues_sequence(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="always")
        for i in range(5):
            j.append({"op": "x", "name": "a", "i": i})
        j.wait_durable(timeout=10.0)
        j.close()
        j2 = OpJournal(str(tmp_path), fsync_policy="always")
        assert j2.cut() == 5
        s = j2.append({"op": "x", "name": "a", "i": 5})
        assert s == 6
        j2.wait_durable(timeout=10.0)
        assert len(list(j2.records_after(0))) == 6
        j2.close()

    def test_torn_tail_truncates_not_corrupts(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="always")
        for i in range(10):
            j.append({"op": "x", "name": "a", "i": i})
        j.wait_durable(timeout=10.0)
        seg = j.stats()
        j.close()
        assert seg["segments"] == 1
        path = [
            os.path.join(str(tmp_path), fn)
            for fn in os.listdir(str(tmp_path)) if fn.endswith(".rtj")
        ][0]
        # Simulate a crash mid-write: half a frame of garbage.
        payload = encode_record({"op": "x", "name": "a", "i": 99})
        frame = struct.pack(
            "<II", len(payload), zlib.crc32(payload)
        ) + payload
        with open(path, "ab") as f:
            f.write(frame[: len(frame) // 2])
        pre = os.path.getsize(path)
        j2 = OpJournal(str(tmp_path), fsync_policy="always")
        recs = list(j2.records_after(0))
        assert len(recs) == 10, "torn tail must truncate to the prefix"
        assert [r["i"] for _s, r in recs] == list(range(10))
        assert os.path.getsize(path) < pre, "tail not truncated"
        j2.close()

    def test_corrupt_mid_segment_drops_later_segments(self, tmp_path):
        j = OpJournal(
            str(tmp_path), fsync_policy="always",
            max_segment_bytes=1 << 12,
        )
        for i in range(200):
            # Per-record durability keeps batches small, so the tiny
            # segment bound rotates many times.
            j.wait_durable(j.append({"op": "x", "name": "a", "i": i}),
                           timeout=10.0)
        j.close()
        segs = sorted(
            fn for fn in os.listdir(str(tmp_path)) if fn.endswith(".rtj")
        )
        assert len(segs) > 2
        # Flip a byte inside the FIRST segment's frame area: everything
        # from that record on — later segments included — is untrusted.
        victim = os.path.join(str(tmp_path), segs[0])
        with open(victim, "r+b") as f:
            f.seek(40)
            b = f.read(1)
            f.seek(40)
            f.write(bytes([b[0] ^ 0xFF]))
        j2 = OpJournal(str(tmp_path), fsync_policy="always")
        recs = list(j2.records_after(0))
        assert len(recs) < 200
        # The surviving prefix is contiguous from seq 1.
        assert [s for s, _ in recs] == list(range(1, len(recs) + 1))
        remaining = [
            fn for fn in os.listdir(str(tmp_path)) if fn.endswith(".rtj")
        ]
        assert len(remaining) <= 2  # truncated head + fresh tail segment
        j2.close()

    def test_mark_snapshot_retires_segments(self, tmp_path):
        j = OpJournal(
            str(tmp_path), fsync_policy="always",
            max_segment_bytes=1 << 12,
        )
        for i in range(200):
            j.append({"op": "x", "name": "a", "i": i})
        j.wait_durable(timeout=30.0)
        before = j.stats()["segments"]
        cut = j.cut()
        retired = j.mark_snapshot(cut)
        assert retired > 0 and before > j.stats()["segments"] - 1
        assert list(j.records_after(cut)) == []
        # Post-truncation appends still replay correctly.
        j.append({"op": "x", "name": "a", "i": 999})
        j.wait_durable(timeout=10.0)
        tail = list(j.records_after(cut))
        assert len(tail) == 1 and tail[0][1]["i"] == 999
        j.close()

    def test_torn_tail_chaos_point_breaks_then_recovers(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="always")
        j.append({"op": "x", "name": "a", "i": 0})
        j.wait_durable(timeout=10.0)
        chaos.inject("journal.torn_tail", kind="error", rate=1.0)
        seq = j.append({"op": "x", "name": "a", "i": 1})
        with pytest.raises(JournalError):
            j.wait_durable(seq, timeout=10.0)
        with pytest.raises(JournalError):
            j.append({"op": "x", "name": "a", "i": 2})
        chaos.clear()
        j.close()
        # Recovery: the half-written frame truncates; record 0 intact.
        j2 = OpJournal(str(tmp_path), fsync_policy="always")
        recs = list(j2.records_after(0))
        assert [r["i"] for _s, r in recs] == [0]
        j2.close()

    def test_fsync_error_breaks_journal(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="always")
        chaos.inject("journal.fsync", kind="error", rate=1.0)
        seq = j.append({"op": "x", "name": "a"})
        with pytest.raises(JournalError):
            j.wait_durable(seq, timeout=10.0)
        chaos.clear()
        j.close()

    def test_lag_estimate_only_under_always(self, tmp_path):
        j = OpJournal(str(tmp_path), fsync_policy="everysec")
        assert j.lag_s() == 0.0
        j.set_policy("always")
        assert j.policy == "always"
        j.close()


# -- engine-level recovery ----------------------------------------------------


class TestEngineRecovery:
    def _fill(self, client, n=40):
        bf = client.get_bloom_filter("bf")
        bf.try_init(10_000, 0.01)
        for i in range(n):
            bf.add(i)
        h = client.get_hyper_log_log("hll")
        h.add_all(list(range(100)))
        bs = client.get_bit_set("bs")
        bs.set(5)
        bs.set(77)
        bs.flip(5)
        cms = client.get_count_min_sketch("cms")
        cms.try_init(4, 256)
        for i in range(10):
            cms.add(i, 3)

    def test_full_replay_without_snapshot(self, tmp_path):
        c1 = make_client(tmp_path, snap=False)
        self._fill(c1)
        want = engine_rows(c1._engine)
        crash(c1)
        c2 = make_client(tmp_path, snap=False)
        got = engine_rows(c2._engine)
        assert set(got) == set(want)
        for name in want:
            np.testing.assert_array_equal(got[name], want[name])
        assert c2._engine.obs.journal_replayed.get(()) > 0
        bf = c2.get_bloom_filter("bf")
        assert bf.contains(7) and not bf.contains(987654)
        crash(c2)

    def test_snapshot_plus_tail_replay(self, tmp_path):
        c1 = make_client(tmp_path)
        self._fill(c1)
        pre_cut = c1._engine.journal.cut()
        c1._engine.snapshot(c1.config.snapshot_dir)
        # The snapshot retired the covered records.
        assert list(c1._engine.journal.records_after(0)) == []
        # Tail ops after the snapshot.
        bf = c1.get_bloom_filter("bf")
        for i in range(1000, 1020):
            bf.add(i)
        cms = c1.get_count_min_sketch("cms")
        cms.add(999, 7)
        want = engine_rows(c1._engine)
        tail = len(list(c1._engine.journal.records_after(0)))
        assert tail > 0
        crash(c1)
        c2 = make_client(tmp_path)
        assert c2._engine._restored_journal_seq >= pre_cut
        got = engine_rows(c2._engine)
        for name in want:
            np.testing.assert_array_equal(got[name], want[name], err_msg=name)
        bf2 = c2.get_bloom_filter("bf")
        assert bf2.contains(1010) and bf2.contains(3)
        assert c2.get_count_min_sketch("cms").estimate(999) >= 7
        crash(c2)

    def test_clean_shutdown_replays_nothing(self, tmp_path):
        c1 = make_client(tmp_path)
        self._fill(c1, n=10)
        c1.shutdown()  # final snapshot covers + retires the journal
        c2 = make_client(tmp_path)
        assert c2._engine.obs.journal_replayed.get(()) == 0
        assert c2.get_bloom_filter("bf").contains(3)
        crash(c2)

    def test_structural_ops_replay(self, tmp_path):
        c1 = make_client(tmp_path, snap=False)
        bf = c1.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add(1)
        dump = c1._engine.dump("bf")
        c1._engine.delete("bf")
        h = c1.get_hyper_log_log("h1")
        h.add(1)
        c1._engine.rename("h1", "h2")
        c1._engine.restore("bf-restored", dump)
        want = engine_rows(c1._engine)
        crash(c1)
        c2 = make_client(tmp_path, snap=False)
        got = engine_rows(c2._engine)
        assert set(got) == set(want) == {"h2", "bf-restored"}
        for name in want:
            np.testing.assert_array_equal(got[name], want[name])
        assert c2.get_bloom_filter("bf-restored").contains(1)
        crash(c2)

    def test_merge_ops_replay(self, tmp_path):
        c1 = make_client(tmp_path, snap=False)
        a = c1.get_hyper_log_log("a")
        a.add_all(list(range(50)))
        b = c1.get_hyper_log_log("b")
        b.add_all(list(range(40, 90)))
        a.merge_with("b")
        ca = c1.get_count_min_sketch("ca")
        ca.try_init(4, 256)
        cb = c1.get_count_min_sketch("cb")
        cb.try_init(4, 256)
        ca.add(1, 5)
        cb.add(1, 9)
        ca.merge("cb")
        bs1 = c1.get_bit_set("x")
        bs1.set_many([1, 5, 9])
        bs2 = c1.get_bit_set("y")
        bs2.set_many([5, 6])
        c1._engine.bitset_bitop("z", ["x", "y"], "and")
        want = engine_rows(c1._engine)
        crash(c1)
        c2 = make_client(tmp_path, snap=False)
        got = engine_rows(c2._engine)
        for name in want:
            np.testing.assert_array_equal(got[name], want[name], err_msg=name)
        assert c2.get_count_min_sketch("ca").estimate(1) >= 14
        assert list(np.nonzero(
            c2.get_bit_set("z").as_bit_array()
        )[0]) == [5]
        crash(c2)

    def test_always_future_done_tracks_durability(self, tmp_path):
        c1 = make_client(tmp_path, snap=False, fsync="always")
        eng = c1._engine
        bf = c1.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        res = eng.bloom_add("bf", np.array([1], np.uint64),
                            np.array([2], np.uint64))
        from redisson_tpu.objects.engines import _DurableResult

        assert isinstance(res, _DurableResult)
        res.result()
        assert eng.journal.is_durable(eng.journal.cut())
        crash(c1)

    def test_journal_lag_rides_admission_estimate(self, tmp_path):
        c1 = make_client(tmp_path, snap=False, fsync="always")
        eng = c1._engine
        assert eng.coalescer.journal_lag_s.__self__ is eng.journal
        # Pending records + a non-zero fsync EWMA → non-zero estimate.
        eng.journal._fsync_ewma_s = 0.5
        with eng.journal._lock:
            eng.journal._next_seq += 10  # simulate a 10-record backlog
        assert eng.coalescer.estimate_wait_s() > 0.0
        with eng.journal._lock:
            eng.journal._next_seq -= 10
        crash(c1)


# -- recovery edge cases (ISSUE 10 satellite) ---------------------------------


class TestRecoveryEdgeCases:
    def test_replay_onto_resharded_topology(self, tmp_path):
        c1 = make_client(tmp_path, fsync="always")
        bf = c1.get_bloom_filter("bf")
        bf.try_init(10_000, 0.01)
        for i in range(30):
            bf.add(i)
        c1._engine.snapshot(c1.config.snapshot_dir)  # S_old = 1
        for i in range(1000, 1030):
            bf.add(i)  # journal tail
        crash(c1)
        # Recover onto S_new = 2: restore_snapshot's reshard path +
        # topology-agnostic tail replay through the current executor.
        c2 = redisson_tpu.create(
            make_cfg(tmp_path, fsync="always", num_shards=2)
        )
        assert getattr(c2._engine.executor, "S", 1) == 2
        bf2 = c2.get_bloom_filter("bf")
        assert all(bf2.contains(i) for i in range(30))
        assert all(bf2.contains(i) for i in range(1000, 1030))
        assert not bf2.contains(777777)
        crash(c2)

    def test_replay_interleaved_with_ttl_expiry(self, tmp_path):
        c1 = make_client(tmp_path, snap=False)
        short = c1.get_hyper_log_log("short")
        short.add_all([1, 2, 3])
        c1._engine.expire_at("short", time.time() + 0.2)
        long = c1.get_hyper_log_log("long")
        long.add_all([1, 2, 3])
        c1._engine.expire_at("long", time.time() + 3600.0)
        crash(c1)
        time.sleep(0.3)  # the short TTL lapses across the "crash"
        c2 = make_client(tmp_path, snap=False)
        eng = c2._engine
        assert eng._live_lookup("short") is None, \
            "expired object must not resurrect through replay"
        entry = eng._live_lookup("long")
        assert entry is not None and entry.expire_at is not None
        assert c2.get_hyper_log_log("long").count() == 3
        crash(c2)

    def test_mid_degradation_snapshot_with_journaled_mirror_writes(
        self, tmp_path
    ):
        # Breaker open → writes land in the host golden mirror; both the
        # snapshot (mirror overlay) and the journal tail must carry them.
        c1 = make_client(
            tmp_path, fsync="always",
            breaker_failure_threshold=1, breaker_open_ms=3_600_000,
        )
        bf = c1.get_bloom_filter("bf")
        bf.try_init(10_000, 0.01)
        bf.add(1)
        chaos.inject("dispatch.bloom_mixed", kind="error", rate=1.0)
        chaos.inject("dispatch.bloom_mixed_keys", kind="error", rate=1.0)
        chaos.inject(
            "dispatch.bloom_mixed_keys_runs", kind="error", rate=1.0
        )
        # Drive the breaker open (the first add surfaces the typed
        # failure), then every retried add lands mirror-acked.
        for i in range(100, 110):
            for _attempt in range(10):
                try:
                    bf.add(i)
                    break
                except Exception:
                    continue
            else:
                pytest.fail(f"add({i}) never acked via the mirror")
        assert c1._engine._mirrors, "expected degraded mirror"
        c1._engine.snapshot(c1.config.snapshot_dir)  # mid-degradation
        for i in range(200, 210):
            bf.add(i)  # journaled mirror writes (the tail)
        chaos.clear()
        crash(c1)
        c2 = make_client(tmp_path)
        bf2 = c2.get_bloom_filter("bf")
        assert all(bf2.contains(i) for i in (1, *range(100, 110),
                                             *range(200, 210)))
        crash(c2)


# -- snapshot crash-safety (ISSUE 10 satellite) -------------------------------


class TestSnapshotCrashSafety:
    def test_crash_between_write_and_rename_keeps_old_snapshot(
        self, tmp_path
    ):
        c1 = make_client(tmp_path, journal=False)
        bf = c1.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add(1)
        c1._engine.snapshot(c1.config.snapshot_dir)  # good snapshot
        bf.add(2)
        chaos.inject("snapshot.rename", kind="error", rate=1.0)
        with pytest.raises(chaos.FaultInjected):
            c1._engine.snapshot(c1.config.snapshot_dir)
        chaos.clear()
        c1.config.snapshot_dir = None
        c1._engine.config.snapshot_dir = None
        c1.shutdown()
        # The interrupted attempt must leave the PREVIOUS snapshot
        # fully loadable (fsynced files, renamed-in atomically).
        c2 = make_client(tmp_path, journal=False)
        bf2 = c2.get_bloom_filter("bf")
        assert bf2.contains(1)
        c2.config.snapshot_dir = None
        c2._engine.config.snapshot_dir = None
        c2.shutdown()

    def test_torn_install_detected_by_crc(self, tmp_path):
        c1 = make_client(tmp_path, journal=False)
        bf = c1.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        c1._engine.snapshot(c1.config.snapshot_dir)
        c1.config.snapshot_dir = None
        c1._engine.config.snapshot_dir = None
        c1.shutdown()
        pools = os.path.join(str(tmp_path / "snap"), "sketch_pools.npz")
        with open(pools, "r+b") as f:
            f.seek(0, os.SEEK_END)
            f.write(b"garbage")  # new-blob-under-old-meta stand-in
        with pytest.raises(Exception, match="torn snapshot"):
            make_client(tmp_path, journal=False)


# -- RESP persistence surface -------------------------------------------------


class TestRespPersistence:
    @pytest.fixture
    def served(self, tmp_path):
        from tests.test_resp_server import RespClient
        from redisson_tpu.serve.resp import RespServer

        client = make_client(tmp_path, fsync="everysec")
        server = RespServer(client)
        conn = RespClient(server.host, server.port)
        yield conn, client
        conn.close()
        server.close()
        client.config.snapshot_dir = None
        client._engine.config.snapshot_dir = None
        client.shutdown()

    def test_config_appendonly_live(self, served):
        conn, client = served
        assert conn.cmd("CONFIG", "GET", "appendonly") == [
            b"appendonly", b"yes"
        ]
        assert conn.cmd("CONFIG", "GET", "appendfsync") == [
            b"appendfsync", b"everysec"
        ]
        assert conn.cmd(
            "CONFIG", "SET", "appendfsync", "always"
        ) == "OK"
        assert client._engine.journal.policy == "always"
        assert conn.cmd("CONFIG", "SET", "appendonly", "no") == "OK"
        assert client._engine.journal is None
        assert conn.cmd("CONFIG", "SET", "appendonly", "yes") == "OK"
        assert client._engine.journal is not None
        with pytest.raises(RuntimeError):
            conn.cmd("CONFIG", "SET", "appendfsync", "sometimes")

    def test_wait_is_a_journal_fence(self, served):
        conn, client = served
        conn.cmd("BF.RESERVE", "bf", "0.01", "1000")
        conn.cmd("BF.ADD", "bf", "123")
        assert conn.cmd("WAIT", "0", "5000") == 0
        j = client._engine.journal
        assert j.durable_seq() == j.cut(), \
            "WAIT must fence every appended record"

    def test_info_persistence_and_save_family(self, served):
        conn, client = served
        conn.cmd("BF.RESERVE", "bf", "0.01", "1000")
        conn.cmd("BF.ADD", "bf", "123")
        info = conn.cmd("INFO", "persistence").decode()
        assert "aof_enabled:1" in info
        assert "appendfsync:everysec" in info
        assert conn.cmd("LASTSAVE") == 0
        assert conn.cmd("SAVE") == "OK"
        assert conn.cmd("LASTSAVE") > 0
        assert conn.cmd("BGREWRITEAOF").startswith("Background")
        assert conn.cmd("BGSAVE").startswith("Background")
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if "rdb_last_save_time:0" not in conn.cmd(
                "INFO", "persistence"
            ).decode():
                break
            time.sleep(0.05)

    def test_tenant_aware_ingress_shed(self, served):
        conn, client = served
        gov = client._engine.governor
        gov.set_limits(rate_limit=5, burst=5, max_inflight=0)
        # Drain the hot tenant's bucket at the engine boundary.
        gov.admit("hot", 5)
        assert gov.peek_over_quota("hot")
        assert not gov.peek_over_quota("cold")
        with pytest.raises(RuntimeError, match="BUSY.*tenant"):
            conn.cmd("BF.EXISTS", "hot", "x")
        # A well-behaved tenant passes the door untouched...
        conn.cmd("BF.RESERVE", "cold", "0.01", "1000")
        # ...and the exempt surface stays usable during the incident.
        assert "redis_version" in conn.cmd("INFO", "server").decode()
        shed = client._engine.obs.resp_ingress_shed.get(("tenant",))
        assert shed >= 1
        gov.set_limits(rate_limit=0, burst=0, max_inflight=0)
