"""Round-5 VERDICT item 7: LiveObject @RId index/find machinery and
transactional List / ScoredSortedSet breadth."""

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.grid.services import TransactionException


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


class Person:
    def __init__(self, id, name, city):
        self.id = id
        self.name = name
        self.city = city


class TestLiveObjectFind:
    def test_find_by_indexed_field(self, client):
        svc = client.get_live_object_service()
        for i, city in enumerate(["rome", "oslo", "rome", "kyiv", "rome"]):
            svc.persist(Person(i, f"p{i}", city), index=("city",))
        hits = svc.find_by_field(Person, "city", "rome")
        assert sorted(p._rid for p in hits) == [0, 2, 4]
        assert all(p.city == "rome" for p in hits)
        assert svc.count(Person) == 5

    def test_index_maintained_through_proxy_writes(self, client):
        svc = client.get_live_object_service()
        p = svc.persist(Person(1, "ann", "rome"), index=("city",))
        p.city = "oslo"  # move between index sets
        assert svc.find_by_field(Person, "city", "rome") == []
        assert [q._rid for q in svc.find_by_field(Person, "city", "oslo")] == [1]

    def test_delete_removes_from_index_and_registry(self, client):
        svc = client.get_live_object_service()
        svc.persist(Person(1, "ann", "rome"), index=("city",))
        svc.persist(Person(2, "bob", "rome"), index=("city",))
        assert svc.delete(Person, 1) is True
        assert [q._rid for q in svc.find_by_field(Person, "city", "rome")] == [2]
        assert svc.count(Person) == 1
        assert sorted(svc.list_ids(Person)) == [2]

    def test_find_unindexed_field_scans(self, client):
        svc = client.get_live_object_service()
        svc.persist(Person(1, "ann", "rome"))
        svc.persist(Person(2, "bob", "oslo"))
        hits = svc.find_by_field(Person, "name", "bob")
        assert [p._rid for p in hits] == [2]


class TestTxList:
    def test_commit_and_rollback(self, client):
        lst = client.get_list("txl")
        lst.add_all(["a", "b"])
        tx = client.create_transaction()
        tl = tx.get_list("txl")
        assert tl.read_all() == ["a", "b"]
        tl.add("c")
        assert tl.size() == 3 and tl.get(2) == "c"
        assert lst.read_all() == ["a", "b"]  # not yet visible
        tx.commit()
        assert lst.read_all() == ["a", "b", "c"]

        tx2 = client.create_transaction()
        tl2 = tx2.get_list("txl")
        tl2.add("d")
        tx2.rollback()
        assert lst.read_all() == ["a", "b", "c"]

    def test_remove_and_contains(self, client):
        lst = client.get_list("txl2")
        lst.add_all(["x", "y"])
        tx = client.create_transaction()
        tl = tx.get_list("txl2")
        assert tl.contains("x") is True
        assert tl.remove("x") is True
        tx.commit()
        assert lst.read_all() == ["y"]

    def test_concurrent_write_invalidates_read(self, client):
        lst = client.get_list("txl3")
        lst.add("a")
        tx = client.create_transaction()
        tl = tx.get_list("txl3")
        assert tl.read_all() == ["a"]
        lst.add("intruder")  # concurrent writer
        tl.add("mine")
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()
        assert lst.read_all() == ["a", "intruder"]  # log NOT applied


class TestTxScoredSortedSet:
    def test_commit_scores(self, client):
        z = client.get_scored_sorted_set("txz")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz")
        assert tz.get_score("a") == 1.0
        assert tz.contains("ghost") is False
        tz.add(2.5, "b")
        assert tz.get_score("b") == 2.5  # read-your-writes
        assert z.get_score("b") is None  # not yet visible
        tx.commit()
        assert z.get_score("b") == 2.5

    def test_remove_and_rollback(self, client):
        z = client.get_scored_sorted_set("txz2")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz2")
        assert tz.remove("a") is True
        tx.rollback()
        assert z.get_score("a") == 1.0

    def test_score_read_invalidated_by_concurrent_change(self, client):
        z = client.get_scored_sorted_set("txz3")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz3")
        assert tz.get_score("a") == 1.0
        z.add(9.0, "a")  # concurrent score change
        tz.add(5.0, "b")
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()
        assert z.get_score("b") is None


class TestTxListReadYourRemoves:
    def test_remove_masks_later_reads(self, client):
        lst = client.get_list("txl4")
        lst.add("x")
        tx = client.create_transaction()
        tl = tx.get_list("txl4")
        assert tl.remove("x") is True
        assert tl.contains("x") is False
        assert tl.read_all() == [] and tl.size() == 0
        assert tl.remove("x") is False  # already removed in this tx
        tx.commit()
        assert lst.read_all() == []

    def test_add_then_remove_cancels(self, client):
        lst = client.get_list("txl5")
        lst.add("keep")
        tx = client.create_transaction()
        tl = tx.get_list("txl5")
        tl.add("temp")
        assert tl.remove("temp") is True
        tx.commit()
        assert lst.read_all() == ["keep"]


class TestGridSweepFixes:
    """Regressions for the round-5 grid-side high-effort sweep."""

    def test_txlist_on_absent_key_commits(self, client):
        tx = client.create_transaction()
        tl = tx.get_list("ghost-list")
        assert tl.read_all() == [] and tl.size() == 0
        tl.add("first")
        tx.commit()  # used to abort spuriously: () vs None snapshot
        assert client.get_list("ghost-list").read_all() == ["first"]

    def test_txlist_repeatable_reads(self, client):
        """The FIRST read is the validation snapshot — a concurrent
        write between two in-tx reads must still abort the commit."""
        from redisson_tpu.grid.services import TransactionException
        lst = client.get_list("rr-list")
        lst.add("a")
        tx = client.create_transaction()
        tl = tx.get_list("rr-list")
        assert tl.read_all() == ["a"]
        lst.add("intruder")
        assert tl.read_all() == ["a"]  # repeatable: first snapshot view
        tl.add("mine")
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()

    def test_txmap_repeatable_reads(self, client):
        from redisson_tpu.grid.services import TransactionException
        m = client.get_map("rr-map")
        m.put("k", 1)
        tx = client.create_transaction()
        tm = tx.get_map("rr-map")
        assert tm.get("k") == 1
        m.put("k", 99)  # concurrent write between the two in-tx reads
        assert tm.get("k") == 1  # repeatable
        tm.put("other", 2)
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()

    def test_persist_repersist_moves_index(self, client):
        svc = client.get_live_object_service()
        p = Person(1, "ann", "NY")
        svc.persist(p, index=("city",))
        p.city = "LA"
        svc.persist(p)  # re-persist with changed indexed field
        assert svc.find_by_field(Person, "city", "NY") == []
        assert [q._rid for q in svc.find_by_field(Person, "city", "LA")] == [1]

    def test_index_backfills_preexisting_objects(self, client):
        svc = client.get_live_object_service()
        svc.persist(Person(10, "a", "SF"))          # not yet indexed
        svc.persist(Person(11, "b", "SF"), index=("city",))  # now indexed
        hits = sorted(q._rid for q in svc.find_by_field(Person, "city", "SF"))
        assert hits == [10, 11]  # the pre-index object is found too


class TestJCacheSweepFixes:
    def test_get_and_put_never_loads(self, client):
        from redisson_tpu.grid.jcache import CacheManager
        loads = []
        cache = CacheManager(client).create_cache(
            "gp", cache_loader=lambda k: loads.append(k) or f"db:{k}",
            read_through=True,
        )
        assert cache.get_and_put("k", "v") is None  # absent -> None
        assert loads == []  # JSR: getAndPut must NOT load
        assert cache.get_and_put("k", "v2") == "v"

    def test_get_all_stats_counted_once(self, client):
        from redisson_tpu.grid.jcache import CacheManager
        cache = CacheManager(client).create_cache(
            "ga", cache_loader=lambda k: k.upper(), read_through=True,
            statistics_enabled=True,
        )
        cache.put("a", "cached")
        cache.statistics.reset()
        out = cache.get_all(["a", "b"])
        assert out == {"a": "cached", "b": "B"}
        s = cache.statistics
        assert (s.hits, s.misses) == (1, 1)  # once each; load = miss

    def test_three_arg_replace(self, client):
        from redisson_tpu.grid.jcache import CacheManager
        cache = CacheManager(client).create_cache("r3")
        cache.put("k", "v1")
        assert cache.replace("k", "wrong", "v2") is False
        assert cache.get("k") == "v1"
        assert cache.replace("k", "v1", "v2") is True
        assert cache.get("k") == "v2"
        assert cache.replace("k", "v3") is True  # 2-arg form still works

    def test_get_and_remove_event_carries_value(self, client):
        from redisson_tpu.grid.jcache import CacheManager
        cache = CacheManager(client).create_cache("gr")
        events = []
        cache.register_cache_entry_listener(
            lambda ev, k, v: events.append((ev, k, v)), event="removed"
        )
        cache.put("r1", "val1")
        assert cache.get_and_remove("r1") == "val1"
        client._topic_bus.drain()
        assert events == [("removed", "r1", "val1")]
