"""Round-5 VERDICT item 7: LiveObject @RId index/find machinery and
transactional List / ScoredSortedSet breadth."""

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.grid.services import TransactionException


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


class Person:
    def __init__(self, id, name, city):
        self.id = id
        self.name = name
        self.city = city


class TestLiveObjectFind:
    def test_find_by_indexed_field(self, client):
        svc = client.get_live_object_service()
        for i, city in enumerate(["rome", "oslo", "rome", "kyiv", "rome"]):
            svc.persist(Person(i, f"p{i}", city), index=("city",))
        hits = svc.find_by_field(Person, "city", "rome")
        assert sorted(p._rid for p in hits) == [0, 2, 4]
        assert all(p.city == "rome" for p in hits)
        assert svc.count(Person) == 5

    def test_index_maintained_through_proxy_writes(self, client):
        svc = client.get_live_object_service()
        p = svc.persist(Person(1, "ann", "rome"), index=("city",))
        p.city = "oslo"  # move between index sets
        assert svc.find_by_field(Person, "city", "rome") == []
        assert [q._rid for q in svc.find_by_field(Person, "city", "oslo")] == [1]

    def test_delete_removes_from_index_and_registry(self, client):
        svc = client.get_live_object_service()
        svc.persist(Person(1, "ann", "rome"), index=("city",))
        svc.persist(Person(2, "bob", "rome"), index=("city",))
        assert svc.delete(Person, 1) is True
        assert [q._rid for q in svc.find_by_field(Person, "city", "rome")] == [2]
        assert svc.count(Person) == 1
        assert sorted(svc.list_ids(Person)) == [2]

    def test_find_unindexed_field_scans(self, client):
        svc = client.get_live_object_service()
        svc.persist(Person(1, "ann", "rome"))
        svc.persist(Person(2, "bob", "oslo"))
        hits = svc.find_by_field(Person, "name", "bob")
        assert [p._rid for p in hits] == [2]


class TestTxList:
    def test_commit_and_rollback(self, client):
        lst = client.get_list("txl")
        lst.add_all(["a", "b"])
        tx = client.create_transaction()
        tl = tx.get_list("txl")
        assert tl.read_all() == ["a", "b"]
        tl.add("c")
        assert tl.size() == 3 and tl.get(2) == "c"
        assert lst.read_all() == ["a", "b"]  # not yet visible
        tx.commit()
        assert lst.read_all() == ["a", "b", "c"]

        tx2 = client.create_transaction()
        tl2 = tx2.get_list("txl")
        tl2.add("d")
        tx2.rollback()
        assert lst.read_all() == ["a", "b", "c"]

    def test_remove_and_contains(self, client):
        lst = client.get_list("txl2")
        lst.add_all(["x", "y"])
        tx = client.create_transaction()
        tl = tx.get_list("txl2")
        assert tl.contains("x") is True
        assert tl.remove("x") is True
        tx.commit()
        assert lst.read_all() == ["y"]

    def test_concurrent_write_invalidates_read(self, client):
        lst = client.get_list("txl3")
        lst.add("a")
        tx = client.create_transaction()
        tl = tx.get_list("txl3")
        assert tl.read_all() == ["a"]
        lst.add("intruder")  # concurrent writer
        tl.add("mine")
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()
        assert lst.read_all() == ["a", "intruder"]  # log NOT applied


class TestTxScoredSortedSet:
    def test_commit_scores(self, client):
        z = client.get_scored_sorted_set("txz")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz")
        assert tz.get_score("a") == 1.0
        assert tz.contains("ghost") is False
        tz.add(2.5, "b")
        assert tz.get_score("b") == 2.5  # read-your-writes
        assert z.get_score("b") is None  # not yet visible
        tx.commit()
        assert z.get_score("b") == 2.5

    def test_remove_and_rollback(self, client):
        z = client.get_scored_sorted_set("txz2")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz2")
        assert tz.remove("a") is True
        tx.rollback()
        assert z.get_score("a") == 1.0

    def test_score_read_invalidated_by_concurrent_change(self, client):
        z = client.get_scored_sorted_set("txz3")
        z.add(1.0, "a")
        tx = client.create_transaction()
        tz = tx.get_scored_sorted_set("txz3")
        assert tz.get_score("a") == 1.0
        z.add(9.0, "a")  # concurrent score change
        tz.add(5.0, "b")
        with pytest.raises(TransactionException, match="invalidated"):
            tx.commit()
        assert z.get_score("b") is None


class TestTxListReadYourRemoves:
    def test_remove_masks_later_reads(self, client):
        lst = client.get_list("txl4")
        lst.add("x")
        tx = client.create_transaction()
        tl = tx.get_list("txl4")
        assert tl.remove("x") is True
        assert tl.contains("x") is False
        assert tl.read_all() == [] and tl.size() == 0
        assert tl.remove("x") is False  # already removed in this tx
        tx.commit()
        assert lst.read_all() == []

    def test_add_then_remove_cancels(self, client):
        lst = client.get_list("txl5")
        lst.add("keep")
        tx = client.create_transaction()
        tl = tx.get_list("txl5")
        tl.add("temp")
        assert tl.remove("temp") is True
        tx.commit()
        assert lst.read_all() == ["keep"]
