"""Load-attribution plane (ISSUE 16): LoadMap unit coverage (dogfooded
decayed CMS + space-saving top-k, bounded tenant attribution, exact
per-slot key counters), the RESP surface (HOTKEYS, INFO loadstats,
CONFIG loadmap-*), the bounded-cardinality export guard, the 3-node
fleet merge (CLUSTER LOADMAP / fleet_loadmap / fleet_latency /
federated visibility), and the accounting-overhead A/B guard."""

import json
import re
import socket
import sys
import time
import urllib.request

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from redisson_tpu.obs import Observability
from redisson_tpu.obs.loadmap import (
    OTHER_TENANT,
    SLOT_FIELDS,
    DecayedCMS,
    LoadMap,
    SpaceSavingTopK,
)
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# -- sketch units -----------------------------------------------------------


def test_decayed_cms_estimates_and_halves_on_half_life():
    clk = _FakeClock()
    cms = DecayedCMS(width=256, depth=4, half_life_s=10.0, clock=clk)
    for _ in range(8):
        cms.add("hot")
    cms.add("cold")
    assert cms.estimate("hot") >= 8.0  # CMS only ever overestimates
    assert cms.estimate("cold") >= 1.0
    assert cms.estimate("never") == 0.0
    # One half-life later the pending decay halves every cell.
    clk.t += 10.0
    factor = cms.maybe_decay(clk.t)
    assert factor == pytest.approx(0.5)
    assert cms.estimate("hot") == pytest.approx(4.0, rel=0.26)
    # No double decay: immediately re-asking applies nothing.
    assert cms.maybe_decay(clk.t) == 1.0


def test_space_saving_topk_is_bounded_and_newcomer_inherits_floor():
    tk = SpaceSavingTopK(capacity=4)
    for i in range(4):
        tk.offer(f"k{i}", 10 - i)  # k3 is the minimum at 7
    assert len(tk) == 4
    tk.offer("newcomer", 1)
    assert len(tk) == 4  # bounded: the table never grows past capacity
    assert "k3" not in tk  # minimum evicted ...
    assert "newcomer" in tk  # ... and the newcomer inherits its floor
    top = dict(tk.top(4))
    assert top["newcomer"] == pytest.approx(8.0)  # floor 7 + offered 1
    tk.scale(0.5)
    assert dict(tk.top(1))["k0"] == pytest.approx(5.0)


def test_loadmap_hot_keys_decay_in_lockstep():
    clk = _FakeClock()
    lm = LoadMap(sample_rate=1.0, half_life_s=10.0, clock=clk)
    lm.sample_keys([b"a"] * 6 + [b"b"] * 2)
    hot = dict(lm.hot_keys(4))
    assert hot["a"] == pytest.approx(6.0)
    clk.t += 10.0
    hot = dict(lm.hot_keys(4))
    # CMS and top-k halve together, so estimates stay comparable.
    assert hot["a"] == pytest.approx(3.0)
    assert hot["b"] == pytest.approx(1.0)
    assert lm.sampled_keys() == 8
    assert lm.tracked_keys() == 2


# -- slot accounting + snapshot ---------------------------------------------


def test_loadmap_slot_accounting_and_snapshot_roundtrip():
    lm = LoadMap(cluster=True)
    s = key_slot("user:1")
    lm.note_command(s, True, 100, 5)
    lm.note_command(s, False, 40, 60, nops=3)
    lm.note_shed(s)
    lm.note_command(None, True, 9, 9)  # redirected: not served here
    lm.note_key("user:1", +1)
    t = lm.totals()
    assert t["ops"] == 4 and t["writes"] == 1 and t["reads"] == 3
    assert t["bytes_in"] == 140 and t["bytes_out"] == 65
    assert t["shed"] == 1 and t["keys"] == 1
    assert lm.top_slots(2) == [(s, 4)]
    snap = json.loads(json.dumps(lm.snapshot()))  # JSON-clean payload
    assert snap["fields"] == list(SLOT_FIELDS)
    row = dict(zip(snap["fields"], snap["slots"][str(s)]))
    assert row["ops"] == 4 and row["shed"] == 1 and row["keys"] == 1
    # Disabled: every plane freezes.
    lm.enabled = False
    lm.note_command(s, True, 1, 1)
    lm.note_shed(s)
    assert lm.sample_keys([b"x"]) == 0
    assert lm.totals()["ops"] == 4
    # reset() zeroes the load counters but PRESERVES the key-count
    # plane — live keys are a gauge of present state, not accumulated
    # load, and zeroing them would silently break COUNTKEYSINSLOT.
    lm.reset()
    assert lm.totals() == {
        "ops": 0, "reads": 0, "writes": 0, "bytes_in": 0,
        "bytes_out": 0, "shed": 0, "device_us": 0, "keys": 1,
    }


def test_loadmap_exact_key_counters_seed_and_clamp():
    lm = LoadMap(cluster=True)
    lm.seed_keys(["a", "b", "{tag}x", "{tag}y"])
    assert lm.keys_in_slot(key_slot("a")) == 1
    assert lm.keys_in_slot(key_slot("{tag}x")) == 2
    lm.note_key("{tag}x", -1)
    assert lm.keys_in_slot(key_slot("{tag}x")) == 1
    # A transient hook/seed race can dip below zero; reads clamp.
    lm.note_key("a", -1)
    lm.note_key("a", -1)
    assert lm.keys_in_slot(key_slot("a")) == 0
    assert lm.totals()["keys"] == 2
    # Standalone mode degrades every key to slot 0.
    lm2 = LoadMap(cluster=False)
    lm2.seed_keys(["a", "b"])
    lm2.note_key("c", +1)
    assert lm2.keys_in_slot(0) == 3


# -- bounded tenant attribution ---------------------------------------------


def test_tenant_attribution_folds_past_max_tenants():
    lm = LoadMap(max_tenants=8)
    for i in range(40):
        lm.attribute_launch("bloom_add", [(f"t{i}", 2)], 100.0)
    shares = lm.tenant_shares()
    assert len(shares) <= 8  # bounded: top-N plus the fold bucket
    assert OTHER_TENANT in shares
    # Conservation: folding moves time/ops, it never drops them.
    assert sum(d["device_us"] for d in shares.values()) == pytest.approx(
        40 * 100.0
    )
    assert sum(d["ops"] for d in shares.values()) == 80
    assert sum(d["share"] for d in shares.values()) == pytest.approx(
        1.0, abs=0.01
    )
    # The fold bucket itself is never evicted by later folds.
    for i in range(40, 60):
        lm.attribute_launch("bloom_add", [(f"t{i}", 1)], 50.0)
    assert OTHER_TENANT in lm.tenant_shares()


def test_attribute_launch_splits_by_op_share_and_slots():
    lm = LoadMap(cluster=True)
    lm.attribute_launch("cms_add", [("alpha", 3), ("beta", 1)], 400.0)
    shares = lm.tenant_shares()
    assert shares["alpha"]["device_us"] == pytest.approx(300.0)
    assert shares["beta"]["device_us"] == pytest.approx(100.0)
    # The tenant label IS the sketch name: device time lands on its slot.
    assert lm.device_us[key_slot("alpha")] == pytest.approx(300.0)
    assert lm.device_us[key_slot("beta")] == pytest.approx(100.0)


# -- bounded-cardinality export guard ---------------------------------------


def test_export_cardinality_is_bounded():
    """The guard the ISSUE names: no 16384-slot label explosion and no
    unbounded per-tenant series, no matter how wide the traffic."""
    obs = Observability()
    lm = obs.loadmap
    lm.cluster = True
    for s in range(0, NSLOTS, 16):  # 1024 busy slots
        lm.note_command(s, False, 10, 10)
    for i in range(500):  # 500 distinct tenants
        lm.attribute_launch("bloom_add", [(f"tenant-{i}", 1)], 10.0)
    body = obs.registry.render_prometheus()
    slot_series = re.findall(r"rtpu_loadmap_slot_ops\{[^}]*\}", body)
    assert 0 < len(slot_series) <= 8  # top-N view, never per-slot
    tenant_series = {
        m for m in re.findall(
            r'rtpu_tenant_device_us_total\{tenant="([^"]+)"', body
        )
    }
    assert len(tenant_series) <= lm.max_tenants + 1
    assert OTHER_TENANT in tenant_series  # the fold label absorbed the tail
    assert len(lm.tenant_shares()) <= lm.max_tenants


# -- RESP surface (standalone) ----------------------------------------------


@pytest.fixture
def resp_host():
    cl = redisson_tpu.create(Config())
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    yield conn, srv, cl
    srv.close()
    cl.shutdown()


def test_resp_hotkeys_info_and_config(resp_host):
    conn, srv, cl = resp_host
    assert conn.cmd("CONFIG", "GET", "loadmap-key-sample-rate") == [
        b"loadmap-key-sample-rate", b"0.01",
    ]
    # Bounds are validated before any table write (telemetry pattern).
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "loadmap-key-sample-rate", "1.5")
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "loadmap-key-sample-rate", "nope")
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "loadmap-enabled", "maybe")
    assert conn.cmd(
        "CONFIG", "SET", "loadmap-key-sample-rate", "1"
    ) == "OK"
    for _ in range(9):
        conn.cmd("SET", "hotkey", "v")
    conn.cmd("SET", "coldkey", "v")
    # HOTKEYS: flat [key, count, ...] pairs, hottest first.
    flat = conn.cmd("HOTKEYS", "2")
    assert flat[0] == b"hotkey" and flat[1] >= 9
    assert flat[2] == b"coldkey"
    with pytest.raises(RuntimeError):
        conn.cmd("HOTKEYS", "x")
    info = conn.cmd("INFO", "loadstats").decode()
    assert "# Loadstats" in info
    for needle in (
        "loadmap_enabled:1", "loadmap_key_sample_rate:1",
        "loadmap_ops:", "loadmap_shed_ops:", "loadmap_device_us:",
        "loadmap_top_slots:", "loadmap_hot_keys:hotkey=",
        "loadmap_keys_exact:",
    ):
        assert needle in info, needle
    # Default INFO includes the section; the master switch freezes it.
    assert "# Loadstats" in conn.cmd("INFO").decode()
    assert conn.cmd("CONFIG", "SET", "loadmap-enabled", "no") == "OK"
    ops_before = srv.loadmap.totals()["ops"]
    conn.cmd("SET", "hotkey", "v")
    assert srv.loadmap.totals()["ops"] == ops_before
    assert "loadmap_enabled:0" in conn.cmd("INFO", "loadstats").decode()


def test_resp_counts_reads_writes_and_sheds():
    cl = redisson_tpu.create(Config())
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    try:
        lm = srv.loadmap
        base = lm.totals()
        conn.cmd("SET", "k", "v")
        conn.cmd("GET", "k")
        t = lm.totals()
        assert t["writes"] == base["writes"] + 1
        assert t["reads"] >= base["reads"] + 1
        # Shed accounting: forced queue pressure over the watermark
        # refuses the write and bumps the SHED plane, not the ops plane.
        srv._pressure = lambda: 1.0
        srv.admission_watermark = 0.5
        ops_before = lm.totals()["ops"]
        with pytest.raises(RuntimeError):
            conn.cmd("SET", "k2", "v")
        del srv._pressure
        srv.admission_watermark = 1.0
        t = lm.totals()
        assert t["shed"] == base["shed"] + 1
        assert t["ops"] == ops_before  # refused != served
    finally:
        srv.close()
        cl.shutdown()


def test_resp_exact_key_counters_on_engine_path():
    """TPU-path engine (jax on CPU): BOTH keyspace backends hook the
    counters, so loadmap_keys is exact and DEBUG COUNTKEYSINSLOT's scan
    agrees with the O(1) plane."""
    cfg = Config().use_tpu_sketch(min_bucket=64)
    cl = redisson_tpu.create(cfg)
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    try:
        assert srv._loadmap_keys_exact
        conn.cmd("CMS.INITBYDIM", "sk0", "64", "2")
        conn.cmd("CMS.INCRBY", "sk0", "item", "1")
        conn.cmd("SET", "grid0", "v")
        info = conn.cmd("INFO", "loadstats").decode()
        assert "loadmap_keys_exact:1" in info
        assert "loadmap_keys:2" in info
        assert conn.cmd("DEBUG", "COUNTKEYSINSLOT", "0") == 2
        assert srv.loadmap.keys_in_slot(0) == 2
        conn.cmd("DEL", "grid0")
        assert srv.loadmap.keys_in_slot(0) == 1
        # Device attribution rode the engine commands (completer path).
        deadline = time.time() + 10
        while time.time() < deadline:
            if srv.loadmap.tenant_shares().get("sk0"):
                break
            time.sleep(0.05)
        shares = srv.loadmap.tenant_shares()
        assert shares["sk0"]["device_us"] > 0
        assert "loadmap_tenant_shares:sk0=" in conn.cmd(
            "INFO", "loadstats"
        ).decode()
    finally:
        srv.close()
        cl.shutdown()


# -- 3-node fleet (the CI cluster-smoke surface) ----------------------------


@pytest.mark.slow
def test_three_node_fleet_loadmap_latency_and_federation():
    """ISSUE 16 acceptance: CLUSTER LOADMAP per node, fleet_loadmap
    ranking the true hot slot first with the hot key found,
    COUNTKEYSINSLOT answered O(1) and agreeing with the DEBUG scan,
    fleet_latency node-tagged, and the new series visible through the
    federated endpoint under node labels."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(n_nodes=3, metrics=True).start()
    try:
        client = sup.client()
        try:
            for addr, r in client._fanout(
                [b"CONFIG", b"SET", b"loadmap-key-sample-rate", b"1",
                 b"latency-monitor-threshold", b"1"]
            ).items():
                assert r == b"OK", (addr, r)
            client.execute("CMS.INITBYDIM", "lmt0", "64", "2")
            for _ in range(30):
                client.execute("CMS.INCRBY", "lmt0", "item", "1")
            for i in range(10):
                client.execute("SET", f"lmcold{i}", "v")
            hot_slot = key_slot("lmt0")

            # Raw per-node snapshots: JSON bulk, node-stamped.
            seen_hot = 0
            for addr, raw in client._fanout(
                [b"CLUSTER", b"LOADMAP"]
            ).items():
                assert not isinstance(raw, Exception), (addr, raw)
                snap = json.loads(raw)
                assert snap["fields"] == list(SLOT_FIELDS)
                assert snap["node"]
                if str(hot_slot) in snap["slots"]:
                    seen_hot += 1
            assert seen_hot == 1  # exactly the owner accounted it

            fl = client.fleet_loadmap()
            assert fl["top_slots"][0] == hot_slot
            assert fl["slots"][hot_slot]["writes"] >= 30
            assert fl["slots"][hot_slot]["keys"] == 1
            assert fl["slots"][hot_slot]["device_us"] > 0
            assert fl["hot_keys"][0]["key"] == "lmt0"
            assert "lmt0" in fl["tenants"]
            assert len(fl["nodes"]) == 3

            # O(1) counters agree with the DEBUG cross-check scan.
            for cmdname in (b"CLUSTER", b"DEBUG"):
                counts = client._fanout(
                    [cmdname, b"COUNTKEYSINSLOT",
                     str(hot_slot).encode()]
                )
                assert sorted(
                    v for v in counts.values()
                    if not isinstance(v, Exception)
                ) == [0, 0, 1], (cmdname, counts)

            # Engine launches on a CPU backend clear 1 ms easily, so
            # the armed latency monitor saw events on the hot node.
            lat = client.fleet_latency()
            assert lat and all("node" in e and e["event"] for e in lat)

            fed = sup.start_federation()
            with urllib.request.urlopen(
                f"http://{fed.host}:{fed.port}/metrics", timeout=10
            ) as r:
                body = r.read().decode()
            assert re.search(
                r'rtpu_loadmap_slot_ops\{node="[^"]+",slot="%d"\}'
                % hot_slot, body
            )
            assert re.search(
                r'rtpu_tenant_device_us_total\{node="[^"]+",'
                r'tenant="lmt0"', body
            )
            assert re.search(
                r'rtpu_loadmap_sampled_keys\{node="[^"]+"\}', body
            )
        finally:
            client.close()
    finally:
        sup.shutdown()


# -- overhead guard ---------------------------------------------------------


@pytest.mark.slow
def test_loadmap_accounting_overhead_under_five_percent():
    """ISSUE 16 acceptance: per-slot accounting ON (production default:
    sampling at 0.01) must cost <=5% on the dispatch path vs the master
    switch OFF.  Same discipline as the metrics/trace overhead guards:
    interleaved rounds, GC paused, min of paired ratios (external load
    only ever inflates a sample), a few attempts for a quiet window."""
    import gc

    from redisson_tpu.serve.resp import _ConnCtx

    cl = redisson_tpu.create(Config())
    srv = RespServer(cl)
    try:
        ctx = _ConnCtx(socket.socket(), server=srv)
        lm = srv.loadmap
        lm.sample_rate = 0.01
        cmd = [b"SET", b"ovh-key", b"v"]
        N = 1500

        def round_time():
            t0 = time.perf_counter()
            for _ in range(N):
                srv._safe_dispatch(cmd, ctx)
            return time.perf_counter() - t0

        def measure():
            on, off = [], []
            gc.disable()
            try:
                for r in range(10):
                    lm.enabled = False
                    round_time()  # warm
                    if r % 2 == 0:
                        off.append(round_time())
                        lm.enabled = True
                        on.append(round_time())
                    else:
                        lm.enabled = True
                        on.append(round_time())
                        lm.enabled = False
                        off.append(round_time())
            finally:
                gc.enable()
            return off, on

        history = []
        for _ in range(4):
            off, on = measure()
            ratio = min(q / p for p, q in zip(off, on))
            ratio = min(ratio, min(on) / min(off))
            history.append(ratio)
            if ratio <= 1.05:
                return
        raise AssertionError(
            f"loadmap accounting >5% dispatch overhead: {history}"
        )
    finally:
        srv.close()
        cl.shutdown()
