"""Static lock-order graph (ISSUE 9): catalog extraction, the
whole-tree cycle gate, the artificial out-of-order fixture, runtime
merge, and RT010 suppression semantics."""

import json
import os
import subprocess
import sys
import textwrap

from redisson_tpu.analysis import lockgraph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "redisson_tpu")


# -- the CI gate on the shipped tree ------------------------------------------


def test_shipped_tree_catalog_covers_all_tiers():
    g = lockgraph.build_graph([PKG])
    names = set(g.catalog)
    # The original witness tier...
    for expected in ("coalescer.queue", "coalescer.inflight",
                     "engine.mirror", "resp.conn.send",
                     "tenancy.governor", "tenancy.registry",
                     "nearcache.epochs", "health.breakers"):
        assert expected in names, f"missing {expected}"
    # ...and the grid/serve tier this PR names (ROADMAP "witness
    # coverage for grid/ locks").
    for expected in ("grid.store", "grid.shared_pool",
                     "grid.localmap.hub", "grid.topics.bus",
                     "grid.services.executor", "serve.ingest",
                     "serve.metrics", "serve.nodes.sweep",
                     "serve.native_codec"):
        assert expected in names, f"missing {expected}"
    # ...and the cluster tier (ISSUE 12): the slot map, the per-key
    # move guard, the supervisor, and the client's table/conn locks.
    for expected in ("cluster.slotmap", "cluster.move",
                     "cluster.supervisor", "cluster.client.table",
                     "cluster.client.conn"):
        assert expected in names, f"missing {expected}"
    # ...and the residency ladder (ISSUE 14): the heat table and the
    # manager's tier-accounting lock.
    for expected in ("storage.heat", "storage.residency"):
        assert expected in names, f"missing {expected}"
    # ...and the per-core front door (ISSUE 17): the peer-socket pool.
    assert "serve.multicore.pool" in names, "missing serve.multicore.pool"


def test_shipped_tree_has_no_lock_order_cycles():
    """The acceptance criterion's clean half: the static gate passes on
    the shipped tree (same check CI runs)."""
    graph, violations = lockgraph.lint_tree([PKG])
    assert violations == [], "\n".join(v.format() for v in violations)
    assert len(graph.catalog) >= 30


def test_shipped_tree_finds_the_known_real_edges():
    """Interprocedural proof: the engine.mirror -> health.state edge
    only exists through a call chain (_reconcile_kind under the mirror
    lock calls health.clear_degraded, which takes health.state)."""
    g = lockgraph.build_graph([PKG])
    assert ("engine.mirror", "health.state") in g.edges
    site = g.edges[("engine.mirror", "health.state")][0]
    assert site.chain, "edge should carry its call chain"


# -- artificial out-of-order acquisition (the failing half) -------------------


_CYCLE_SRC = """
    import threading

    from redisson_tpu.analysis import witness as _witness

    LOCK_A = _witness.named(threading.Lock(), "fix.a")
    LOCK_B = _witness.named(threading.Lock(), "fix.b")


    def forward():
        with LOCK_A:
            with LOCK_B:
                pass


    def backward():
        with LOCK_B:
            with LOCK_A:
                pass
"""


def test_artificial_out_of_order_acquisition_fails_the_gate(tmp_path):
    """The acceptance criterion's failing half: an introduced
    out-of-order acquisition trips RT010 even though no test ever runs
    the bad schedule."""
    mod = tmp_path / "crossed.py"
    mod.write_text(textwrap.dedent(_CYCLE_SRC))
    graph, violations = lockgraph.lint_tree([str(mod)])
    assert ("fix.a", "fix.b") in graph.edges
    assert ("fix.b", "fix.a") in graph.edges
    assert len(violations) == 1
    v = violations[0]
    assert v.rule == "RT010"
    assert "fix.a" in v.message and "fix.b" in v.message
    assert "potential deadlock" in v.message


def test_cross_function_cycle_via_call_chain(tmp_path):
    """A cycle assembled across FUNCTIONS (neither function nests both
    locks lexically) is still found through call resolution."""
    mod = tmp_path / "chained.py"
    mod.write_text(textwrap.dedent("""
        import threading

        from redisson_tpu.analysis import witness as _witness


        class Left:
            def __init__(self):
                self._left_lock = _witness.named(
                    threading.Lock(), "chain.left"
                )

            def outer(self, right):
                with self._left_lock:
                    right.take_right()

            def take_left(self):
                with self._left_lock:
                    pass


        class Right:
            def __init__(self):
                self._right_lock = _witness.named(
                    threading.Lock(), "chain.right"
                )

            def outer(self, left):
                with self._right_lock:
                    left.take_left()

            def take_right(self):
                with self._right_lock:
                    pass
    """))
    graph, violations = lockgraph.lint_tree([str(mod)])
    assert len(violations) == 1
    assert "chain.left" in violations[0].message
    assert "chain.right" in violations[0].message


def test_rt010_suppression_documents_a_by_design_edge(tmp_path):
    mod = tmp_path / "allowed.py"
    mod.write_text(textwrap.dedent("""
        import threading

        from redisson_tpu.analysis import witness as _witness

        LOCK_A = _witness.named(threading.Lock(), "ok.a")
        LOCK_B = _witness.named(threading.Lock(), "ok.b")


        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass


        def backward():
            with LOCK_B:
                # rtpulint: disable=RT010 teardown-only path, forward() can never run concurrently
                with LOCK_A:
                    pass
    """))
    graph, violations = lockgraph.lint_tree([str(mod)])
    assert violations == []
    assert ("ok.b", "ok.a") in graph.suppressed


# -- runtime witness merge ----------------------------------------------------


def test_runtime_edges_close_a_static_half_cycle(tmp_path):
    """Static A->B + witness-OBSERVED B->A = reported cycle: schedules
    the static pass cannot see (dynamic dispatch, getattr) still gate
    CI when the witness recorded them."""
    mod = tmp_path / "half.py"
    mod.write_text(textwrap.dedent("""
        import threading

        from redisson_tpu.analysis import witness as _witness

        LOCK_A = _witness.named(threading.Lock(), "half.a")
        LOCK_B = _witness.named(threading.Lock(), "half.b")


        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass
    """))
    graph, violations = lockgraph.lint_tree([str(mod)])
    assert violations == []
    graph, violations = lockgraph.lint_tree(
        [str(mod)], runtime_edges=[("half.b", "half.a")]
    )
    assert len(violations) == 1
    assert lockgraph.RUNTIME_SITE in violations[0].message


def test_witness_export_edges_round_trip(tmp_path):
    """witness.export_edges / export_to produce exactly the shape
    load_runtime_edges reads."""
    from redisson_tpu.analysis import witness

    witness.force(True)
    try:
        import threading

        a = witness.named(threading.Lock(), "xport.a")
        b = witness.named(threading.Lock(), "xport.b")
        with a:
            with b:
                pass
        edges = witness.export_edges()
        assert ("xport.a", "xport.b") in edges
        path = tmp_path / "edges.json"
        witness.export_to(str(path))
        loaded = lockgraph.load_runtime_edges(str(path))
        assert ("xport.a", "xport.b") in loaded
    finally:
        witness.force(False)
        witness.reset()


# -- CLI ----------------------------------------------------------------------


def test_cli_lock_graph_gate_and_dump(tmp_path):
    """`python -m redisson_tpu.analysis <dir>` runs the RT010 pass on
    directories and exits 1 on a cycle; --dump-lock-graph emits the
    catalog + edges JSON."""
    pkgdir = tmp_path / "tree"
    pkgdir.mkdir()
    (pkgdir / "crossed.py").write_text(textwrap.dedent(_CYCLE_SRC))
    proc = subprocess.run(
        [sys.executable, "-m", "redisson_tpu.analysis", str(pkgdir)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "RT010" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "redisson_tpu.analysis",
         "--dump-lock-graph", str(pkgdir)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    dumped = json.loads(proc.stdout)
    assert "fix.a" in dumped["catalog"]
    assert "fix.a -> fix.b" in dumped["edges"]
