"""Tests for locks/synchronizers, RRateLimiter, RKeys, RBatch — mirroring
the reference's RedissonLockTest / RedissonFairLockTest /
RedissonSemaphoreTest / RedissonCountDownLatchTest / RedissonBatchTest /
RedissonKeysTest (SURVEY.md §4)."""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    cl = redisson_tpu.create(Config())
    yield cl
    cl.shutdown()


class TestLock:
    def test_reentrant(self, client):
        lk = client.get_lock("L")
        lk.lock()
        lk.lock()
        assert lk.is_held_by_current_thread()
        assert lk.get_hold_count() == 2
        lk.unlock()
        assert lk.is_locked()
        lk.unlock()
        assert not lk.is_locked()

    def test_unlock_foreign_raises(self, client):
        lk = client.get_lock("L2")
        lk.lock()
        err = []

        def other():
            try:
                lk.unlock()
            except RuntimeError as e:
                err.append(e)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert err
        lk.unlock()

    def test_contention_and_wakeup(self, client):
        lk = client.get_lock("L3")
        order = []

        def worker(n):
            lk.lock()
            order.append(n)
            time.sleep(0.02)
            lk.unlock()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert sorted(order) == [0, 1, 2, 3]
        assert not lk.is_locked()

    def test_try_lock_timeout(self, client):
        lk = client.get_lock("L4")
        lk.lock()
        got = []

        def other():
            got.append(lk.try_lock(wait_seconds=0.1))

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert got == [False]
        lk.unlock()

    def test_lease_expiry(self, client):
        lk = client.get_lock("L5")
        lk.lock(lease_seconds=0.15)
        assert 0 < lk.remain_lease_time() <= 150
        got = []

        def other():
            got.append(lk.try_lock(wait_seconds=1.0))

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert got == [True]  # lease expired, other thread took it

    def test_force_unlock_and_context_manager(self, client):
        lk = client.get_lock("L6")
        with lk:
            assert lk.is_locked()
        assert not lk.is_locked()
        lk.lock()
        assert lk.force_unlock() is True
        assert lk.force_unlock() is False

    def test_fenced_lock_token_increases(self, client):
        fl = client.get_fenced_lock("F")
        t1 = fl.lock_and_get_token()
        fl.unlock()
        t2 = fl.lock_and_get_token()
        fl.unlock()
        assert t2 > t1
        assert fl.get_token() is None

    def test_fair_lock_fifo(self, client):
        lk = client.get_fair_lock("FA")
        lk.lock()
        order = []
        threads = []
        for i in range(3):
            t = threading.Thread(
                target=lambda n=i: (lk.lock(), order.append(n), lk.unlock())
            )
            t.start()
            time.sleep(0.05)  # deterministic queue order
            threads.append(t)
        lk.unlock()
        [t.join() for t in threads]
        assert order == [0, 1, 2]

    def test_multi_lock(self, client):
        a, b = client.get_lock("MA"), client.get_lock("MB")
        ml = client.get_multi_lock(a, b)
        assert ml.try_lock() is True
        assert a.is_locked() and b.is_locked()
        ml.unlock()
        assert not a.is_locked() and not b.is_locked()
        # Partial failure releases what was taken.
        done = threading.Event()
        release = threading.Event()

        def holder():
            b.lock()
            done.set()
            release.wait(2)
            b.unlock()

        t = threading.Thread(target=holder)
        t.start()
        done.wait(2)
        assert ml.try_lock(wait_seconds=0.1) is False
        assert not a.is_locked()  # rolled back
        release.set()
        t.join()


class TestReadWriteLock:
    def test_many_readers(self, client):
        rw = client.get_read_write_lock("RW")
        r1, r2 = rw.read_lock(), rw.read_lock()
        assert r1.try_lock() and r2.try_lock()
        r1.unlock()
        r2.unlock()

    def test_writer_excludes_readers_from_other_threads(self, client):
        rw = client.get_read_write_lock("RW2")
        w = rw.write_lock()
        w.lock()
        got = []

        def reader():
            got.append(rw.read_lock().try_lock(wait_seconds=0.1))

        t = threading.Thread(target=reader)
        t.start()
        t.join()
        assert got == [False]
        # Writer may downgrade: its own read lock succeeds.
        assert rw.read_lock().try_lock() is True
        w.unlock()

    def test_reader_blocks_writer(self, client):
        rw = client.get_read_write_lock("RW3")
        r = rw.read_lock()
        r.lock()
        got = []

        def writer():
            got.append(rw.write_lock().try_lock(wait_seconds=0.1))

        t = threading.Thread(target=writer)
        t.start()
        t.join()
        assert got == [False]
        r.unlock()


class TestSemaphores:
    def test_semaphore(self, client):
        s = client.get_semaphore("S")
        assert s.try_set_permits(2) is True
        assert s.try_set_permits(5) is False
        assert s.try_acquire() is True
        assert s.try_acquire() is True
        assert s.try_acquire() is False
        s.release()
        assert s.available_permits() == 1
        assert s.drain_permits() == 1
        s.add_permits(3)
        assert s.available_permits() == 3

    def test_semaphore_blocking_release(self, client):
        s = client.get_semaphore("S2")
        s.try_set_permits(0)
        got = []

        def taker():
            got.append(s.try_acquire(wait_seconds=2.0))

        t = threading.Thread(target=taker)
        t.start()
        time.sleep(0.05)
        s.release()
        t.join()
        assert got == [True]

    def test_permit_expirable(self, client):
        s = client.get_permit_expirable_semaphore("PS")
        assert s.try_set_permits(1) is True
        pid = s.try_acquire()
        assert pid is not None
        assert s.try_acquire() is None
        assert s.try_release(pid) is True
        assert s.try_release(pid) is False
        with pytest.raises(RuntimeError):
            s.release("bogus")

    def test_permit_lease_expiry(self, client):
        s = client.get_permit_expirable_semaphore("PS2")
        s.try_set_permits(1)
        s.try_acquire(lease_seconds=0.1)
        assert s.available_permits() == 0
        time.sleep(0.15)
        assert s.available_permits() == 1  # reclaimed

    def test_count_down_latch(self, client):
        latch = client.get_count_down_latch("CDL")
        assert latch.try_set_count(2) is True
        assert latch.try_set_count(3) is False
        done = []

        def waiter():
            done.append(latch.wait_for(timeout_seconds=2.0))

        t = threading.Thread(target=waiter)
        t.start()
        latch.count_down()
        assert latch.get_count() == 1
        latch.count_down()
        t.join()
        assert done == [True]
        assert latch.wait_for(timeout_seconds=0.0) is True


class TestRateLimiter:
    def test_rate_enforced(self, client):
        rl = client.get_rate_limiter("RL")
        assert rl.try_set_rate(rl.OVERALL, 3, 0.2) is True
        assert rl.try_set_rate(rl.OVERALL, 9, 1.0) is False
        assert rl.try_acquire() and rl.try_acquire() and rl.try_acquire()
        assert rl.try_acquire() is False  # window exhausted
        assert rl.try_acquire(wait_seconds=0.5) is True  # next window

    def test_multi_permit_and_errors(self, client):
        rl = client.get_rate_limiter("RL2")
        rl.try_set_rate(rl.OVERALL, 5, 10.0)
        assert rl.try_acquire(permits=5) is True
        with pytest.raises(ValueError):
            rl.try_acquire(permits=6)
        un = client.get_rate_limiter("RL3")
        with pytest.raises(RuntimeError):
            un.try_acquire()


class TestKeys:
    def test_spans_grid_and_sketch(self, client):
        client.get_bucket("gk1").set(1)
        client.get_map("gk2").put("a", 1)
        bf = client.get_bloom_filter("sk1")
        bf.try_init(100, 0.01)
        keys = client.get_keys()
        assert sorted(keys.get_keys()) == ["gk1", "gk2", "sk1"]
        assert keys.count() == 3
        assert keys.count_exists("gk1", "sk1", "nope") == 2
        assert sorted(keys.get_keys("gk*")) == ["gk1", "gk2"]

    def test_delete_and_flush(self, client):
        client.get_bucket("d1").set(1)
        client.get_bucket("d2").set(2)
        client.get_bloom_filter("d3").try_init(100, 0.01)
        keys = client.get_keys()
        assert keys.delete("d1", "d3", "missing") == 2
        assert keys.count() == 1
        client.get_bucket("e1").set(1)
        assert keys.delete_by_pattern("d*") == 1
        keys.flushall()
        assert keys.count() == 0

    def test_random_and_rename(self, client):
        keys = client.get_keys()
        assert keys.random_key() is None
        client.get_bucket("rk").set("v")
        assert keys.random_key() == "rk"
        keys.rename("rk", "rk2")
        assert client.get_bucket("rk2").get() == "v"
        with pytest.raises(RuntimeError):
            keys.rename("nope", "x")

    def test_keys_ttl(self, client):
        client.get_bucket("tk").set("v")
        assert client.get_keys().expire("tk", 0.1) is True
        assert client.get_keys().remain_time_to_live("tk") > 0
        time.sleep(0.15)
        assert client.get_keys().remain_time_to_live("tk") == -2


class TestBatch:
    def test_mixed_batch(self, client):
        batch = client.create_batch()
        bf = batch.get_bloom_filter("bb")
        f0 = bf.try_init(1000, 0.01)
        f1 = bf.add("k1")
        f2 = bf.contains("k1")
        bucket = batch.get_bucket("bv")
        f3 = bucket.set("hello")
        f4 = bucket.get()
        counter = batch.get_atomic_long("bc")
        f5 = counter.increment_and_get()
        with pytest.raises(RuntimeError):
            f1.result()  # not executed yet
        res = batch.execute()
        assert len(res) == 6
        assert f0.result() is True
        assert f1.result() is True
        assert f2.result() is True
        assert f4.result() == "hello"
        assert f5.result() == 1
        assert res.get_responses()[5] == 1
        # effects are visible outside the batch
        assert client.get_bloom_filter("bb").contains("k1")
        assert client.get_bucket("bv").get() == "hello"

    def test_batch_single_shot(self, client):
        batch = client.create_batch()
        batch.get_bucket("x").set(1)
        batch.execute()
        with pytest.raises(RuntimeError):
            batch.execute()

    def test_batch_discard(self, client):
        batch = client.create_batch()
        batch.get_bucket("never").set(1)
        batch.discard()
        assert not client.get_bucket("never").is_exists()

    def test_batch_coalesces_sketch_ops(self, client2=None):
        cl = redisson_tpu.create(
            Config().use_tpu_sketch(min_bucket=64, batch_window_us=50_000)
        )
        try:
            bf = cl.get_bloom_filter("cb")
            bf.try_init(5000, 0.01)
            batch = cl.create_batch()
            proxy = batch.get_bloom_filter("cb")
            # *_async queued calls resolve at the end of execute(), so the
            # dispatches pipeline through the coalescer as one stream.
            futs = [proxy.add_all_async([f"k{i}"]) for i in range(20)]
            res = batch.execute()
            assert all(f.result()[0] for f in futs)
            assert len(res) == 20
            assert all(bf.contains_each([f"k{i}" for i in range(20)]))
        finally:
            cl.shutdown()

    def test_camelcase_through_batch(self, client):
        batch = client.create_batch()
        b = batch.getBucket("cc")
        b.set("v")
        f = b.getAndSet("w")
        batch.execute()
        assert f.result() == "v"
