"""Device-side result mailbox (PROFILE.md remaining-lever 2): a group of
launches' packed results concatenates on device and fetches in ONE D2H.
Parity discipline: mailbox-collected results must be bit-identical to
per-launch fetches through every path (direct, bulk API, coalescer)."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec


def make_client(**kw):
    # coalesce=False by default: these tests target the DIRECT dispatch
    # path where futures are LazyResults (or MappedFuture wrappers over
    # them) — the shapes collect_group actually mailboxes.  The hammer
    # test opts back into coalesce=True explicitly.
    kw.setdefault("coalesce", False)
    return redisson_tpu.create(
        Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64, **kw)
    )


def test_collect_group_parity():
    c = make_client()
    try:
        bf = c.get_bloom_filter("mb-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(3000, dtype=np.uint64))
        rng = np.random.default_rng(1)
        batches = [
            rng.integers(0, 6000, 256).astype(np.uint64) for _ in range(5)
        ]
        # Reference: per-launch fetches.
        want = [bf.contains_each(b) for b in batches]
        # Mailbox: group dispatch + one collect.
        lazies = [bf.contains_all_async(b) for b in batches]
        c._engine.executor.collect_group(lazies)
        got = [l.result() for l in lazies]
        for w, g in zip(want, got):
            assert np.array_equal(w, g)
    finally:
        c.shutdown()


def test_collect_group_mixed_dtypes_and_resolved():
    c = make_client()
    try:
        bf = c.get_bloom_filter("mb2-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(100, dtype=np.uint64))
        l1 = bf.contains_all_async(np.arange(50, dtype=np.uint64))
        l1.result()  # already resolved: collect_group must skip it
        l2 = bf.contains_all_async(np.arange(50, 100, dtype=np.uint64))
        l3 = bf.contains_all_async(np.arange(100, 150, dtype=np.uint64))
        c._engine.executor.collect_group([l1, None, l2, l3])
        assert np.all(l1.result()) and np.all(l2.result())
        assert not np.any(l3.result())
    finally:
        c.shutdown()


def test_contains_many_bulk_api():
    c = make_client()
    try:
        bf = c.get_bloom_filter("mb3-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(2000, dtype=np.uint64))
        batches = [
            np.arange(i * 500, (i + 1) * 500, dtype=np.uint64)
            for i in range(6)
        ]
        res = bf.contains_many(batches)
        assert len(res) == 6
        for i, r in enumerate(res):
            expect = (np.arange(i * 500, (i + 1) * 500) < 2000)
            # below 2000 all hit; above: FPP-rare
            assert np.array_equal(r[expect], np.ones(expect.sum(), bool))
    finally:
        c.shutdown()


def test_contains_many_host_engine():
    # Host engine returns ImmediateResults — the bulk API must degrade.
    c = redisson_tpu.create(Config().set_codec(LongCodec()))
    try:
        bf = c.get_bloom_filter("mb4-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(100, dtype=np.uint64))
        res = bf.contains_many([np.arange(50, dtype=np.uint64)] * 2)
        assert all(np.all(r) for r in res)
    finally:
        c.shutdown()


@pytest.mark.parametrize("mailbox", [True, False])
def test_coalesced_hammer_parity(mailbox):
    c = make_client(
        coalesce=True, batch_window_us=100, max_batch=4096,
        mailbox_collect=mailbox, exact_add_semantics=True,
    )
    try:
        filters = [c.get_bloom_filter(f"mbham{i}") for i in range(8)]
        for f in filters:
            f.try_init(5000, 0.01)
        rng = np.random.default_rng(3)
        futs = []
        added: dict = {i: [] for i in range(8)}
        for step in range(60):
            fi = int(rng.integers(8))
            f = filters[fi]
            keys = rng.integers(0, 5000, 64).astype(np.uint64)
            if step % 3 == 0:
                added[fi].append(keys)
                futs.append(f.add_all_async(keys))
            else:
                futs.append(f.contains_all_async(keys))
        for fut in futs:
            fut.result()  # no exceptions, all resolve
        # Ground truth after quiesce: every added key must be present —
        # a group-slice off-by-one in the mailbox path would scramble
        # results without raising.
        for fi, batches in added.items():
            if batches:
                all_keys = np.concatenate(batches)
                assert bool(np.all(filters[fi].contains_each(all_keys)))
    finally:
        c.shutdown()


def test_client_collect_mixed_kinds():
    """client.collect — the RBatch#execute reply-flush applied to
    already-dispatched async calls, across result dtypes/objects."""
    c = make_client()
    try:
        h = c.get_hyper_log_log("cc-h")
        bs = c.get_bit_set("cc-b")
        bf = c.get_bloom_filter("cc-f")
        bf.try_init(1000, 0.01)
        futs = [
            h.add_all_async(np.arange(200, dtype=np.uint64)),
            bf.add_all_async(np.arange(100, dtype=np.uint64)),
            bs.set_many_async(np.arange(64, dtype=np.uint32)),
            bf.contains_all_async(np.arange(100, dtype=np.uint64)),
            bs.get_many_async(np.arange(64, dtype=np.uint32)),
        ]
        out = c.collect(futs)
        assert int(np.sum(out[1])) == 100  # all newly added
        assert bool(np.all(out[3]))  # all present
        assert int(np.sum(out[4])) == 64  # all bits read back set
        # The GROUP path must actually have run (not the per-item
        # degrade): a mailbox concat program was compiled.
        assert any(
            isinstance(k, tuple) and k and k[0] == "mailbox"
            for k in c._engine.executor._jit_cache
        )
    finally:
        c.shutdown()
