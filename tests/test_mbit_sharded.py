"""m-sharded giant bitsets through the public API (config 3,
SURVEY.md §7-L4): rows at/above ``mbit_threshold_words`` split their words
contiguously across the 8-device virtual mesh; every BitSet operation must
agree with the host golden engine bit-for-bit.
"""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config

# Low threshold so modest test shapes exercise the m-sharded layout.
THRESH = 256
NBITS = 1 << 16  # -> 2048-word rows, WL=256 over 8 shards


@pytest.fixture
def clients():
    tpu = redisson_tpu.create(
        Config().use_tpu_sketch(
            num_shards=8, mbit_threshold_words=THRESH, min_bucket=64
        )
    )
    host = redisson_tpu.create(Config())
    yield tpu, host
    tpu.shutdown()
    host.shutdown()


def both(clients, name):
    return clients[0].get_bit_set(name), clients[1].get_bit_set(name)


class TestMbitSharded:
    def test_pool_is_msharded(self, clients):
        tpu, _ = clients
        bs = tpu.get_bit_set("layout")
        bs.set(NBITS - 1)
        entry = tpu._engine.registry.lookup("layout")
        assert tpu._engine.executor._is_mbit(entry.pool)
        # state [S, T*WL+1]
        assert entry.pool.state.shape[0] == 8

    def test_set_get_across_shards(self, clients):
        a, b = both(clients, "sg")
        rng = np.random.default_rng(3)
        idx = rng.integers(0, NBITS, 5000).astype(np.uint32)
        pa = a.set_many(idx)
        pb = b.set_many(idx)
        assert list(pa) == list(pb)  # exact sequential prev-bit semantics
        probe = rng.integers(0, NBITS, 8000).astype(np.uint32)
        assert list(a.get_many(probe)) == list(b.get_many(probe))
        assert a.cardinality() == b.cardinality()

    def test_mixed_ops_sequential_semantics(self, clients):
        a, b = both(clients, "mix")
        rng = np.random.default_rng(4)
        idx = rng.integers(0, NBITS, 3000).astype(np.uint32)
        a.set_many(idx)
        b.set_many(idx)
        flip_idx = rng.integers(0, NBITS, 512).astype(np.uint32)
        for i in flip_idx[:32]:
            assert a.flip(int(i)) == b.flip(int(i))
        clear_idx = idx[:500]
        assert list(a.set_many(clear_idx, value=False)) == list(
            b.set_many(clear_idx, value=False)
        )
        assert a.cardinality() == b.cardinality()

    def test_length_bitpos(self, clients):
        a, b = both(clients, "len")
        for i in (0, 1000, NBITS // 2 + 7, NBITS - 3):
            a.set(i)
            b.set(i)
        assert a.length() == b.length()
        assert a.first_set_bit() == b.first_set_bit()
        assert a.first_clear_bit() == b.first_clear_bit()

    def test_set_range_spanning_shards(self, clients):
        a, b = both(clients, "range")
        lo, hi = NBITS // 4 + 13, 3 * NBITS // 4 - 5  # spans several shards
        a.set(NBITS - 1)  # materialize full capacity first
        b.set(NBITS - 1)
        a.set_range(lo, hi)
        b.set_range(lo, hi)
        assert a.cardinality() == b.cardinality()
        probe = np.asarray(
            [lo - 1, lo, lo + 1, NBITS // 2, hi - 1, hi, hi + 1], np.uint32
        )
        assert list(a.get_many(probe)) == list(b.get_many(probe))
        a.clear_range(lo + 100, hi - 100)
        b.clear_range(lo + 100, hi - 100)
        assert a.cardinality() == b.cardinality()

    def test_bitop_and_not(self, clients):
        tpu, host = clients
        rng = np.random.default_rng(5)
        for c in (tpu, host):
            x = c.get_bit_set("bo-x")
            y = c.get_bit_set("bo-y")
            x.set(NBITS - 1)
            y.set(NBITS - 1)
            x.set_many(rng.integers(0, NBITS, 4000).astype(np.uint32))
            rng2 = np.random.default_rng(6)
            y.set_many(rng2.integers(0, NBITS, 4000).astype(np.uint32))
            rng = np.random.default_rng(5)  # same draws for both clients
        ax = tpu.get_bit_set("bo-x")
        bx = host.get_bit_set("bo-x")
        ax.and_op("bo-y")
        bx.and_op("bo-y")
        assert ax.cardinality() == bx.cardinality()
        assert ax.to_byte_array() == bx.to_byte_array()
        ax.not_op()
        bx.not_op()
        assert ax.to_byte_array() == bx.to_byte_array()

    def test_dump_restore_msharded(self, clients):
        tpu, _ = clients
        bs = tpu.get_bit_set("dump-m")
        idx = np.arange(0, NBITS, 37, dtype=np.uint32)
        bs.set_many(idx)
        blob = bs.dump()
        bs2 = tpu.get_bit_set("dump-m2")
        bs2.restore(blob)
        assert bs2.cardinality() == len(idx)
        assert list(bs2.get_many(idx)) == [True] * len(idx)
