"""Mixed-opcode kernels: combined bloom add+contains and the unified
bitset affine batch — the kernels that keep one coalescer segment per pool
under interleaved traffic (config 4's shape).

Gate: exact sequential (one-op-at-a-time Redis) semantics vs golden models,
including duplicate keys/bits inside one batch and padding.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.ops import bitops, bitset, bloom, golden
from redisson_tpu.utils import hashing


def _hashes(n, seed, m):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    return hashing.km_reduce_mod(h1, h2, m)


class TestBloomMixed:
    M = 1 << 14
    K = 5
    W = (1 << 14) // 32

    def _golden_run(self, g, rows, h1m, h2m, is_add):
        out = np.zeros(len(rows), bool)
        for i in range(len(rows)):
            t = rows[i]
            a = np.array([h1m[i]]), np.array([h2m[i]])
            if is_add[i]:
                out[i] = g[t].add_hashed(*a)[0]
            else:
                out[i] = g[t].contains_hashed(*a)[0]
        return out

    def test_vs_golden_sequential(self):
        T = 3
        pool = jnp.zeros((T * self.W + 1,), jnp.uint32)
        g = [golden.GoldenBloomFilter(self.M, self.K) for _ in range(T)]
        rng = np.random.default_rng(11)
        for step in range(4):
            n = 300
            # Small key space forces duplicates within and across batches,
            # so add/contains interleavings on the same key are exercised.
            keys = rng.integers(0, 150, size=n, dtype=np.uint64)
            blocks, lengths = hashing.encode_uint64_batch(keys)
            h1, h2 = hashing.hash128_np(blocks, lengths)
            h1m, h2m = hashing.km_reduce_mod(h1, h2, self.M)
            rows = rng.integers(0, T, size=n).astype(np.int32)
            is_add = rng.random(n) < 0.5
            pool, res = bloom.bloom_mixed(
                pool,
                jnp.asarray(rows),
                jnp.asarray(h1m),
                jnp.asarray(h2m),
                jnp.asarray(is_add),
                m=self.M,
                k=self.K,
                words_per_row=self.W,
            )
            expect = self._golden_run(g, rows, h1m, h2m, is_add)
            np.testing.assert_array_equal(np.asarray(res), expect)

    def test_padding_routes_to_scratch(self):
        T = 2
        pool = jnp.zeros((T * self.W + 1,), jnp.uint32)
        n, n_pad = 70, 128
        h1m, h2m = _hashes(n, 3, self.M)
        h1p = np.zeros(n_pad, h1m.dtype)
        h2p = np.zeros(n_pad, h2m.dtype)
        h1p[:n], h2p[:n] = h1m, h2m
        rows = np.zeros(n_pad, np.int32)
        is_add = np.zeros(n_pad, bool)
        is_add[:n] = True
        valid = np.zeros(n_pad, bool)
        valid[:n] = True
        m_arr = np.full(n_pad, self.M, np.uint32)
        m_arr[n:] = 1
        new_pool, res = bloom.bloom_mixed(
            pool,
            jnp.asarray(rows),
            jnp.asarray(h1p),
            jnp.asarray(h2p),
            jnp.asarray(is_add),
            m=jnp.asarray(m_arr),
            k=self.K,
            words_per_row=self.W,
            valid=jnp.asarray(valid),
        )
        # Row 1 untouched; row 0 identical to an unpadded add batch.
        ref_pool, newly = bloom.bloom_add(
            pool,
            jnp.zeros(n, jnp.int32),
            jnp.asarray(h1m),
            jnp.asarray(h2m),
            m=self.M,
            k=self.K,
            words_per_row=self.W,
        )
        np.testing.assert_array_equal(
            np.asarray(new_pool)[: self.W], np.asarray(ref_pool)[: self.W]
        )
        np.testing.assert_array_equal(
            np.asarray(new_pool)[self.W : 2 * self.W], 0
        )
        np.testing.assert_array_equal(
            np.asarray(res)[:n], np.asarray(newly)
        )


class TestBitsetMixed:
    W = 8  # 256-bit rows

    def _sim(self, state_bits, rows, idx, ops):
        out = np.zeros(len(idx), bool)
        for i in range(len(idx)):
            cur = state_bits[rows[i], idx[i]]
            out[i] = cur
            if ops[i] == bitset.OP_SET:
                state_bits[rows[i], idx[i]] = True
            elif ops[i] == bitset.OP_CLEAR:
                state_bits[rows[i], idx[i]] = False
            elif ops[i] == bitset.OP_FLIP:
                state_bits[rows[i], idx[i]] = not cur
        return out

    def test_vs_sequential_sim(self):
        T = 2
        nbits = self.W * 32
        pool = jnp.zeros((T * self.W + 1,), jnp.uint32)
        bits = np.zeros((T, nbits), bool)
        rng = np.random.default_rng(23)
        for step in range(4):
            n = 400
            # Tiny index space → long duplicate runs with mixed opcodes.
            idx = rng.integers(0, 48, size=n).astype(np.uint32)
            rows = rng.integers(0, T, size=n).astype(np.int32)
            ops = rng.integers(0, 4, size=n).astype(np.uint32)
            pool, obs = bitset.bitset_mixed(
                pool,
                jnp.asarray(rows),
                jnp.asarray(idx),
                jnp.asarray(ops),
                words_per_row=self.W,
            )
            expect = self._sim(bits, rows, idx, ops)
            np.testing.assert_array_equal(np.asarray(obs), expect)
            # Full state equality after each batch.
            words = np.asarray(pool)[:-1].reshape(T, self.W)
            got_bits = np.unpackbits(
                words.view(np.uint8), bitorder="little"
            ).reshape(T, nbits)
            np.testing.assert_array_equal(got_bits.astype(bool), bits)

    def test_get_only_batch_leaves_state(self):
        pool = jnp.asarray(
            np.r_[
                np.random.default_rng(1).integers(
                    0, 1 << 32, size=self.W, dtype=np.uint32
                ),
                np.zeros(1, np.uint32),
            ]
        )
        idx = np.arange(64, dtype=np.uint32)
        ops = np.full(64, bitset.OP_GET, np.uint32)
        new, obs = bitset.bitset_mixed(
            pool,
            jnp.zeros(64, jnp.int32),
            jnp.asarray(idx),
            jnp.asarray(ops),
            words_per_row=self.W,
        )
        np.testing.assert_array_equal(np.asarray(new)[:-1], np.asarray(pool)[:-1])
        words = np.asarray(pool)[: self.W]
        expect = (words[idx // 32] >> (idx % 32)) & 1
        np.testing.assert_array_equal(np.asarray(obs), expect.astype(bool))


class TestCoalescedMixedE2E:
    """Interleaved add/contains through the public coalesced API must both
    coalesce (few device batches) and honor arrival order."""

    def test_interleaved_ops_coalesce_and_order(self):
        cl = redisson_tpu.create(
            Config().use_tpu_sketch(
                min_bucket=64, batch_window_us=5000, max_batch=1 << 14
            )
        )
        try:
            bf = cl.get_bloom_filter("mx1")
            bf.try_init(10_000, 0.01)
            a = np.arange(0, 200, dtype=np.uint64)
            b = np.arange(1000, 1200, dtype=np.uint64)
            futs = [
                bf.add_all_async(a),
                bf.contains_all_async(a),   # must see the add before it
                bf.contains_all_async(b),   # not added yet
                bf.add_all_async(b),
                bf.contains_all_async(b),   # must see the 2nd add
            ]
            r = [f.result() for f in futs]
            assert np.all(r[0])            # all newly added
            assert np.all(r[1])            # arrival order: adds visible
            assert not np.any(r[2])        # b not yet added (no FP at 1%*)
            assert np.all(r[3])
            assert np.all(r[4])
            m = cl.get_metrics()
            # 5 interleaved submissions on one pool: a single mixed segment
            # (or two if a flush raced), not one per alternation.
            assert m["batches_total"] <= 2, m
        finally:
            cl.shutdown()

    def test_bitset_interleaved_opcodes(self):
        cl = redisson_tpu.create(
            Config().use_tpu_sketch(min_bucket=64, batch_window_us=5000)
        )
        try:
            eng = cl._engine
            idx = np.arange(100, dtype=np.uint32)
            futs = [
                eng.bitset_set("mxbs", idx, True),   # prev all 0
                eng.bitset_get("mxbs", idx),         # all 1
                eng.bitset_flip("mxbs", idx[:50]),   # prev 1
                eng.bitset_get("mxbs", idx),         # first 50 off
            ]
            r = [np.asarray(f.result()) for f in futs]
            assert not np.any(r[0])
            assert np.all(r[1])
            assert np.all(r[2])
            assert not np.any(r[3][:50]) and np.all(r[3][50:])
        finally:
            cl.shutdown()
