"""Per-core front door (ISSUE 17): the in-node slot→process map, the
SO_REUSEPORT probe + fallback, device-slice pinning, cross-worker
handoff semantics (forward / split / fan-out / CROSSSLOT), MULTI and
pub/sub across workers, chaos at the handoff leg, and the forked-worker
MulticoreNode suite with the K=4 differential soak.

The in-process tests run TWO RespServers in one process sharing a TCP
port via SO_REUSEPORT (each with its own engine), which exercises the
identical code path the forked workers run — the slow-marked tests at
the bottom fork real `python -m redisson_tpu` workers and are what the
CI multicore-smoke job runs.
"""

import logging
import os
import socket
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config, chaos
from redisson_tpu.serve import multicore, wireutil
from redisson_tpu.serve.multicore import (
    MulticoreNode,
    device_slice_for_worker,
    effective_processes,
    peer_sock_path,
    reuseport_available,
    worker_of_slot,
    worker_slot_range,
    worker_tag,
)
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from redisson_tpu.serve.resp import RespServer

pytestmark = pytest.mark.skipif(
    not reuseport_available(), reason="SO_REUSEPORT unavailable"
)


def _key(w, nworkers, suffix):
    """A key pinned to worker ``w`` via its hash tag."""
    return ("{%s}%s" % (worker_tag(w, nworkers), suffix)).encode()


def _recv_frames(sock, n, timeout=30.0):
    """Read exactly ``n`` raw reply frames (byte-identical checks)."""
    sock.settimeout(timeout)
    data = b""
    frames = []
    pos = 0
    while len(frames) < n:
        try:
            while len(frames) < n:
                end = wireutil.skip_reply_frame(data, pos)
                frames.append(data[pos:end])
                pos = end
        except IndexError:
            pass
        if len(frames) >= n:
            break
        chunk = sock.recv(1 << 16)
        assert chunk, f"connection closed with {len(frames)}/{n} replies"
        data += chunk
    assert data[pos:] == b"", "trailing bytes after expected replies"
    return frames


def _ask(sock, cmds):
    sock.sendall(b"".join(wireutil.wire_command(c) for c in cmds))
    return _recv_frames(sock, len(cmds))


# -- the in-node slot→process map (pure units) --------------------------------


@pytest.mark.parametrize("nworkers", [2, 3, 4, 5])
def test_worker_of_slot_contiguous_partition(nworkers):
    owners = [worker_of_slot(s, nworkers) for s in range(NSLOTS)]
    assert owners[0] == 0 and owners[-1] == nworkers - 1
    assert owners == sorted(owners), "partition must be contiguous"
    assert set(owners) == set(range(nworkers)), "every worker owns slots"
    for w in range(nworkers):
        lo, hi = worker_slot_range(w, nworkers)
        assert worker_of_slot(lo, nworkers) == w
        assert worker_of_slot(hi, nworkers) == w
        if lo > 0:
            assert worker_of_slot(lo - 1, nworkers) == w - 1
        if hi < NSLOTS - 1:
            assert worker_of_slot(hi + 1, nworkers) == w + 1


@pytest.mark.parametrize("nworkers", [2, 4])
def test_worker_tag_pins_keys(nworkers):
    for w in range(nworkers):
        k = _key(w, nworkers, "anything")
        assert worker_of_slot(key_slot(k), nworkers) == w


def test_device_slice_for_worker_partitions_devices():
    slices = [device_slice_for_worker(i, 4, 8) for i in range(4)]
    assert slices == [[0, 1], [2, 3], [4, 5], [6, 7]]
    # Fewer devices than workers: no pinning (shared enumeration).
    assert device_slice_for_worker(0, 4, 1) is None
    # Uneven split still covers every device exactly once.
    got = [d for i in range(3) for d in device_slice_for_worker(i, 3, 8)]
    assert got == list(range(8))


# -- device-slice pinning (ISSUE 17 satellite, ROADMAP carry-over) ------------


def test_resolve_device_slice_fake_devices():
    from redisson_tpu.executor.tpu_executor import resolve_device_slice

    fake = ["dev0", "dev1", "dev2", "dev3"]
    assert resolve_device_slice(None, devices=fake) == fake
    # Order is the caller's, not the enumeration's.
    assert resolve_device_slice([2, 0], devices=fake) == ["dev2", "dev0"]
    with pytest.raises(ValueError, match="out of range"):
        resolve_device_slice([4], devices=fake)
    with pytest.raises(ValueError, match="repeated"):
        resolve_device_slice([1, 1], devices=fake)
    with pytest.raises(ValueError, match="empty"):
        resolve_device_slice([], devices=fake)


def test_executor_pins_device_slice():
    """An executor built with device_indices uses exactly that slice of
    the (fake-8-device) enumeration as its pool devices."""
    import jax

    cfg = Config().use_tpu_sketch(min_bucket=64)
    cfg.tpu_sketch.device_indices = [1, 3]
    client = redisson_tpu.create(cfg)
    try:
        ex = client._engine.executor
        assert ex.devices is not None and len(ex.devices) == 2
        assert list(ex.devices) == [jax.devices()[1], jax.devices()[3]]
        # The pinned executor still serves traffic.
        bf = client.get_bloom_filter("pin-bf")
        bf.try_init(10_000, 0.01)
        keys = np.arange(64, dtype=np.uint64)
        bf.add_all(keys)
        assert bool(np.all(bf.contains_each(keys)))
    finally:
        client.shutdown()


# -- SO_REUSEPORT probe + fallback (ISSUE 17 satellite) -----------------------


def test_reuseport_probe_is_a_real_setsockopt():
    # On this platform (the skipif gate passed) the probe must agree.
    assert reuseport_available() is True


def test_effective_processes_fallback_logs_and_degrades(monkeypatch, caplog):
    monkeypatch.setattr(multicore, "reuseport_available", lambda: False)
    with caplog.at_level(logging.INFO, logger="redisson_tpu.frontdoor"):
        assert effective_processes(4) == 1
    msgs = [r for r in caplog.records if "SO_REUSEPORT" in r.getMessage()]
    assert msgs, "fallback must log an INFO frontdoor line"
    assert msgs[0].levelno == logging.INFO
    # K=1 is not a fallback: no probe, no log line.
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="redisson_tpu.frontdoor"):
        assert effective_processes(1) == 1
        assert effective_processes(None) == 1
    assert not caplog.records


# -- in-process worker pair ---------------------------------------------------


NW = 2


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    """Two front-door workers in ONE process: same TCP port via
    SO_REUSEPORT, each with its own engine, handoff over the rundir's
    unix sockets."""
    rundir = str(tmp_path_factory.mktemp("frontdoor"))
    servers, clients = [], []
    port = 0
    try:
        for i in range(NW):
            cfg = Config().use_tpu_sketch(min_bucket=64)
            cfg.frontdoor_workers = NW
            cfg.frontdoor_index = i
            cfg.frontdoor_dir = rundir
            client = redisson_tpu.create(cfg)
            clients.append(client)
            server = RespServer(client, host="127.0.0.1", port=port)
            servers.append(server)
            port = server.port
        yield servers
    finally:
        for s in servers:
            s.close()
        for c in clients:
            c.shutdown()


def _tcp(pair):
    s = socket.create_connection(("127.0.0.1", pair[0].port))
    s.settimeout(30)
    return s


def _landed_index(sock):
    info = wireutil.exchange(sock, [[b"INFO", b"frontdoor"]])[0].decode()
    for line in info.splitlines():
        if line.startswith("frontdoor_worker_index:"):
            return int(line.split(":")[1])
    raise AssertionError(f"no frontdoor_worker_index in {info!r}")


def _peer_conn(pair, w):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.connect(peer_sock_path(pair[w].multicore.rundir, w))
    s.settimeout(30)
    return s


def test_pair_serves_keyless_where_landed(pair):
    s = _tcp(pair)
    try:
        assert wireutil.exchange(s, [[b"PING"], [b"ECHO", b"hi"]]) == [
            b"PONG", b"hi",
        ]
        assert _landed_index(s) in range(NW)
    finally:
        s.close()


def test_pair_cross_worker_forward_and_local(pair):
    s = _tcp(pair)
    try:
        me = _landed_index(s)
        other = (me + 1) % NW
        mine = _key(me, NW, "fwd")
        theirs = _key(other, NW, "fwd")
        assert wireutil.exchange(
            s, [[b"SET", mine, b"local"], [b"SET", theirs, b"remote"]]
        ) == [b"OK", b"OK"]
        assert wireutil.exchange(
            s, [[b"GET", mine], [b"GET", theirs]]
        ) == [b"local", b"remote"]
        # The landed worker counted the forwards; the in-node map never
        # surfaced a -MOVED to the client.
        lines = dict(
            ln.split(":", 1)
            for ln in wireutil.exchange(s, [[b"INFO", b"frontdoor"]])[0]
            .decode().splitlines()
            if ":" in ln
        )
        assert int(lines["frontdoor_handoffs_forward"]) >= 1
        assert int(lines["frontdoor_processes"]) == NW
    finally:
        s.close()


def test_pair_split_commands_merge_byte_identically(pair):
    s = _tcp(pair)
    try:
        k0 = _key(0, NW, "sp0")
        k1 = _key(1, NW, "sp1")
        k2 = _key(0, NW, "sp2")
        assert wireutil.exchange(
            s, [[b"MSET", k0, b"a", k1, b"b", k2, b"c"]]
        ) == [b"OK"]
        assert wireutil.exchange(
            s, [[b"MGET", k0, k1, k2, b"{missing}nope"]]
        ) == [[b"a", b"b", b"c", None]]
        assert wireutil.exchange(
            s, [[b"EXISTS", k0, k1, k2], [b"DEL", k0, k1]]
        ) == [3, 2]
        assert wireutil.exchange(s, [[b"MGET", k0, k1, k2]]) == [
            [None, None, b"c"],
        ]
        assert wireutil.exchange(s, [[b"DEL", k2]]) == [1]
    finally:
        s.close()


def test_pair_fanout_dbsize_keys_flushall(pair):
    s = _tcp(pair)
    try:
        wireutil.exchange(s, [[b"FLUSHALL"]])
        k0 = _key(0, NW, "fan0")
        k1 = _key(1, NW, "fan1")
        wireutil.exchange(s, [[b"SET", k0, b"x"], [b"SET", k1, b"y"]])
        assert wireutil.exchange(s, [[b"DBSIZE"]]) == [2]
        got = wireutil.exchange(s, [[b"KEYS", b"*"]])[0]
        assert sorted(got) == sorted([k0, k1])
        assert wireutil.exchange(s, [[b"FLUSHALL"]]) == [b"OK"]
        assert wireutil.exchange(s, [[b"DBSIZE"]]) == [0]
    finally:
        s.close()


def test_pair_cross_worker_multikey_gets_crossslot(pair):
    s = _tcp(pair)
    try:
        k0 = _key(0, NW, "ren")
        k1 = _key(1, NW, "ren")
        wireutil.exchange(s, [[b"SET", k0, b"v"]])
        err = wireutil.exchange(s, [[b"RENAME", k0, k1]])[0]
        assert isinstance(err, wireutil.ReplyError)
        assert err.code == "CROSSSLOT"
        # Same-worker multikey RENAME is untouched by the map.
        k0b = _key(0, NW, "ren2")
        assert wireutil.exchange(s, [[b"RENAME", k0, k0b]]) == [b"OK"]
        wireutil.exchange(s, [[b"DEL", k0b]])
    finally:
        s.close()


def test_pair_multi_exec_across_handoff(pair):
    s = _tcp(pair)
    try:
        me = _landed_index(s)
        theirs = _key((me + 1) % NW, NW, "tx")
        frames = _ask(s, [
            [b"MULTI"],
            [b"SET", theirs, b"txv"],
            [b"GET", theirs],
            [b"EXEC"],
        ])
        assert frames[0] == b"+OK\r\n"
        assert frames[1] == frames[2] == b"+QUEUED\r\n"
        assert frames[3] == b"*2\r\n+OK\r\n$3\r\ntxv\r\n"
        wireutil.exchange(s, [[b"DEL", theirs]])
    finally:
        s.close()


def test_pair_publish_fans_out_to_both_workers(pair):
    # One subscriber parked on EACH worker (the unix door serves normal
    # dispatch and lets a test pick its worker); a TCP publisher's
    # PUBLISH fans out: the reply sums receivers across workers and
    # both buses deliver, in order.
    sub0 = _peer_conn(pair, 0)
    sub1 = _peer_conn(pair, 1)
    pub = _tcp(pair)
    try:
        for sub in (sub0, sub1):
            assert wireutil.exchange(sub, [[b"SUBSCRIBE", b"mc-chan"]]) == [
                [b"subscribe", b"mc-chan", 1],
            ]
        assert wireutil.exchange(pub, [[b"PUBLISH", b"mc-chan", b"m1"]]) == [2]
        assert wireutil.exchange(pub, [[b"PUBLISH", b"mc-chan", b"m2"]]) == [2]
        for sub in (sub0, sub1):
            got = _recv_frames(sub, 2)
            assert got[0] == (
                b"*3\r\n$7\r\nmessage\r\n$7\r\nmc-chan\r\n$2\r\nm1\r\n"
            )
            assert got[1] == (
                b"*3\r\n$7\r\nmessage\r\n$7\r\nmc-chan\r\n$2\r\nm2\r\n"
            )
        # Nobody listening on a foreign channel: the fan-out sum is 0.
        assert wireutil.exchange(pub, [[b"PUBLISH", b"mc-none", b"x"]]) == [0]
    finally:
        sub0.close()
        sub1.close()
        pub.close()


def test_pair_chaos_at_handoff_leg_surfaces_handoffbroken(pair):
    s = _tcp(pair)
    chaos.inject("handoff.leg", kind="error", rate=1.0, seed=3)
    try:
        me = _landed_index(s)
        theirs = _key((me + 1) % NW, NW, "chaos")
        err = wireutil.exchange(s, [[b"GET", theirs]])[0]
        assert isinstance(err, wireutil.ReplyError)
        assert err.code == "HANDOFFBROKEN"
        assert b"retry" in str(err).encode()
    finally:
        chaos.clear()
    try:
        # The failed leg was never repooled (RT013): the next handoff
        # rides a fresh socket and succeeds.
        me = _landed_index(s)
        theirs = _key((me + 1) % NW, NW, "chaos")
        assert wireutil.exchange(
            s, [[b"SET", theirs, b"ok"], [b"GET", theirs]]
        ) == [b"OK", b"ok"]
        wireutil.exchange(s, [[b"DEL", theirs]])
        lines = dict(
            ln.split(":", 1)
            for ln in wireutil.exchange(s, [[b"INFO", b"frontdoor"]])[0]
            .decode().splitlines()
            if ":" in ln
        )
        assert int(lines["frontdoor_handoff_errors"]) >= 1
    finally:
        s.close()


def test_pair_gauges_and_info(pair):
    for i, srv in enumerate(pair):
        reg = srv.obs.registry if hasattr(srv.obs, "registry") else None
        assert srv.multicore is not None
        assert srv.multicore.nworkers == NW
        assert srv.multicore.index == i
    # The gauge the fallback satellite pins to 1 reads K here.
    sample = pair[0].obs.frontdoor_processes
    assert sample is not None


# -- forked-worker suite (CI multicore-smoke job) -----------------------------


def _node_conn(node):
    s = socket.create_connection((node.host, node.port))
    s.settimeout(60)
    return s


@pytest.mark.slow
def test_multicore_node_k2_smoke():
    """The MulticoreNode parent forks K=2 real workers on one port,
    serves cross-worker traffic, and SIGTERM-reaps them cleanly (the
    pgrep no-orphans gate in CI counts the survivors)."""
    node = MulticoreNode(2, platform="cpu")
    try:
        s = _node_conn(node)
        k0 = _key(0, 2, "a")
        k1 = _key(1, 2, "b")
        assert wireutil.exchange(s, [[b"PING"]]) == [b"PONG"]
        assert wireutil.exchange(
            s, [[b"SET", k0, b"v0"], [b"SET", k1, b"v1"]]
        ) == [b"OK", b"OK"]
        assert wireutil.exchange(s, [[b"MGET", k0, k1]]) == [[b"v0", b"v1"]]
        assert wireutil.exchange(s, [[b"DBSIZE"]]) == [2]
        info = wireutil.exchange(s, [[b"INFO", b"frontdoor"]])[0].decode()
        assert "frontdoor_processes:2" in info
        assert "frontdoor_native_tick:1" in info
        s.close()
    finally:
        assert node.shutdown() is True, "workers must exit from SIGTERM"
    for p in node.procs:
        assert p.poll() is not None


def _rand_cmds(rng, conn_id, n_ops, nworkers):
    """A randomized per-connection command stream over a PRIVATE
    keyspace (disjoint across connections, so replies are independent
    of cross-connection interleaving), pinned across both doors."""
    cmds = []
    mine = [
        _key(w, nworkers, "c%d-k%d" % (conn_id, i))
        for w in range(nworkers) for i in range(4)
    ]
    in_multi = False
    for _ in range(n_ops):
        roll = int(rng.integers(10))
        k = mine[int(rng.integers(len(mine)))]
        if roll <= 3:
            cmds.append([b"SET", k, b"v%d" % int(rng.integers(1000))])
        elif roll <= 5:
            cmds.append([b"GET", k])
        elif roll == 6:
            ks = [mine[int(rng.integers(len(mine)))] for _ in range(3)]
            cmds.append([b"MGET"] + ks)
        elif roll == 7:
            cmds.append([b"INCR", _key(
                int(rng.integers(nworkers)), nworkers, "c%d-ctr" % conn_id
            )])
        elif roll == 8:
            cmds.append([b"DEL", k])
        elif not in_multi:
            cmds.append([b"MULTI"])
            in_multi = True
        else:
            cmds.append([b"EXEC"])
            in_multi = False
    if in_multi:
        cmds.append([b"EXEC"])
    return cmds


@pytest.mark.slow
def test_differential_soak_k4_byte_identical():
    """Satellite 4: K=4 multicore vs the single-process door — every
    connection's reply stream is byte-identical, including MULTI/EXEC
    spanning workers and ordered pub/sub delivery."""
    nworkers = 4
    cfg = Config().use_tpu_sketch(min_bucket=64)
    ref_client = redisson_tpu.create(cfg)
    ref = RespServer(ref_client, host="127.0.0.1", port=0)
    node = MulticoreNode(nworkers, platform="cpu")
    try:
        rng = np.random.default_rng(170)
        streams = [
            _rand_cmds(rng, c, 80, nworkers) for c in range(6)
        ]
        for conn_id, cmds in enumerate(streams):
            sm = _node_conn(node)
            sr = socket.create_connection((ref.host, ref.port))
            sr.settimeout(60)
            got_m = _ask(sm, cmds)
            got_r = _ask(sr, cmds)
            assert got_m == got_r, (
                f"conn {conn_id}: reply stream diverged\n"
                f"multicore: {got_m}\nreference: {got_r}"
            )
            sm.close()
            sr.close()
        # Ordered pub/sub across doors: N sequential publishes arrive
        # as N ordered pushes, byte-identical on both doors.
        for srv_kind, (host, port) in (
            ("multicore", (node.host, node.port)),
            ("reference", (ref.host, ref.port)),
        ):
            sub = socket.create_connection((host, port))
            pub = socket.create_connection((host, port))
            sub.settimeout(60)
            pub.settimeout(60)
            subf = _ask(sub, [[b"SUBSCRIBE", b"soak-chan"]])
            pushes = []
            for i in range(8):
                assert wireutil.exchange(
                    pub, [[b"PUBLISH", b"soak-chan", b"m%d" % i]]
                ) == [1], srv_kind
            pushes = _recv_frames(sub, 8)
            if srv_kind == "multicore":
                want_sub, want_pushes = subf, pushes
            else:
                assert subf == want_sub
                assert pushes == want_pushes, "pub/sub streams diverged"
            sub.close()
            pub.close()
    finally:
        node.shutdown()
        ref.close()
        ref_client.shutdown()


@pytest.mark.slow
def test_chaos_soak_handoff_legs_fail_clean():
    """Chaos armed at the handoff leg via env (the forked workers read
    RTPU_CHAOS_HANDOFF at router init): every reply is either the
    correct value or -HANDOFFBROKEN, the stream never desyncs, and the
    connection survives."""
    node = MulticoreNode(
        2, platform="cpu",
        env_extra={
            "RTPU_CHAOS_HANDOFF": "0.4",
            "RTPU_CHAOS_HANDOFF_SEED": "17",
        },
    )
    try:
        s = _node_conn(node)
        me = _landed_index(s)
        theirs = _key((me + 1) % 2, 2, "soak")
        ok = broken = 0
        for i in range(40):
            rep = wireutil.exchange(s, [[b"SET", theirs, b"v%d" % i]])[0]
            if isinstance(rep, wireutil.ReplyError):
                assert rep.code == "HANDOFFBROKEN", rep
                broken += 1
            else:
                assert rep == b"OK"
                ok += 1
        assert ok > 0, "some legs must survive at rate 0.4"
        assert broken > 0, "some legs must fail at rate 0.4"
        # The stream is still framed and the conn still serves.
        assert wireutil.exchange(s, [[b"PING"]]) == [b"PONG"]
        s.close()
    finally:
        node.shutdown()
