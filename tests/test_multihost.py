"""Two-process multi-host integration test (docs/MULTIHOST.md).

The reference scales past one server with cluster topology over TCP
(→ org/redisson/cluster/ClusterConnectionManager.java); the TPU-native
equivalent is the standard JAX multi-controller runtime: every host joins
via ``jax.distributed.initialize`` (the engine's ``coordinator_address``
config arms this, objects/engines.py) and the device mesh spans all
processes, with XLA routing inter-process legs over DCN.

This test runs the REAL thing in miniature: two OS processes, 4 virtual
CPU devices each, one 8-shard global mesh, identical SPMD op streams
through the full client → sharded-executor path.  It validates that
pool state, partition-by-owner dispatch, and result fetches all work
when half the mesh lives in another process — the property MULTIHOST.md
claims makes multi-host a deployment step rather than a rewrite.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).with_name("multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.xfail(
    "cpu" in os.environ.get("JAX_PLATFORMS", "").lower(),
    reason="jax.distributed multi-process init over the CPU collectives "
    "backend is unsupported in this container (the seed baseline fails "
    "here too); runs for real on TPU pods",
    strict=False,
)
def test_two_process_engine_lockstep():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=str(WORKER.parent.parent),
            env={
                **os.environ,
                "PYTHONPATH": str(WORKER.parent.parent)
                + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-4000:]}"
    oks = [
        line
        for out in outs
        for line in out.splitlines()
        if line.startswith("MH-OK")
    ]
    assert len(oks) == 2, outs
    # Both controllers must compute identical results (SPMD determinism).
    assert oks[0] == oks[1], oks
