"""Native RESP codec (native/resp_codec.c via serve/native_codec.py).

Parity discipline mirrors the kernel/golden-twin strategy (SURVEY.md §4):
the C parser must frame byte streams exactly like the pure-Python
``_Reader`` path, across pipelining, arbitrary chunk splits, binary
payloads, and malformed input.
"""

import os
import random
import socket
import threading

import pytest

from redisson_tpu.serve import native_codec
from redisson_tpu.serve.native_codec import get_parser
from redisson_tpu.serve.resp import _Reader


def _wire(args):
    out = b"*%d\r\n" % len(args)
    for a in args:
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


@pytest.fixture(scope="module")
def parser():
    p = get_parser()
    assert p is not None, "native codec must build in this image (cc present)"
    return p


def test_parse_pipeline(parser):
    cmds = [
        [b"PING"],
        [b"SET", b"k", b"v" * 100],
        [b"GET", b""],  # empty bulk
        [b"BF.MADD", b"f"] + [b"item%d" % i for i in range(50)],
        [b"SET", b"bin", bytes(range(256)) + b"\r\n$9\r\n*3\r\n"],  # wire bytes inside payload
    ]
    buf = b"".join(_wire(c) for c in cmds)
    frames, consumed, err = parser.parse(buf)
    assert err == native_codec.PARSE_OK
    assert consumed == len(buf)
    assert frames == cmds


def test_parse_incomplete_then_complete(parser):
    cmd = [b"SET", b"key", b"value"]
    buf = _wire(cmd)
    for cut in range(len(buf)):
        frames, consumed, err = parser.parse(buf[:cut])
        assert frames == [] and consumed == 0
        assert err == native_codec.PARSE_OK, (cut, err)
    frames, consumed, err = parser.parse(buf)
    assert frames == [cmd] and consumed == len(buf)


def test_parse_trailing_partial(parser):
    full = _wire([b"PING"]) * 3
    tail = _wire([b"SET", b"a", b"b"])[:7]
    frames, consumed, err = parser.parse(full + tail)
    assert len(frames) == 3 and consumed == len(full)
    assert err == native_codec.PARSE_OK


def test_parse_inline_fallback(parser):
    frames, consumed, err = parser.parse(b"PING\r\n")
    assert frames == [] and consumed == 0
    assert err == native_codec.PARSE_FALLBACK
    # Pipelined frames BEFORE the inline command still parse.
    frames, consumed, err = parser.parse(_wire([b"PING"]) + b"QUIT\r\n")
    assert frames == [[b"PING"]]
    assert err == native_codec.PARSE_FALLBACK


def test_parse_protocol_errors(parser):
    for bad in (
        b"*2\r\nPING\r\n",  # missing $ header
        b"*x\r\n",  # non-numeric argc
        b"*1\r\n$3\r\nabcd\r\n",  # bulk length mismatch (no CRLF at end)
        b"*1\r\n$x\r\n",  # non-numeric bulk len
    ):
        frames, consumed, err = parser.parse(bad)
        assert err == native_codec.PARSE_PROTO_ERROR, bad
        assert frames == []


def test_parse_first_frame_exceeds_arg_capacity(parser):
    # A COMPLETE frame with more args than the descriptor capacity must
    # signal fallback (the slow path has no argc cap) — not read as
    # "incomplete", which would block the connection forever.
    big = [b"HSET", b"h"] + [b"f%d" % i for i in range(parser.MAX_ARGS)]
    frames, consumed, err = parser.parse(_wire(big))
    assert frames == [] and consumed == 0
    assert err == native_codec.PARSE_FALLBACK
    # Frames before the oversized one still parse; capacity stops cleanly.
    frames, consumed, err = parser.parse(_wire([b"PING"]) + _wire(big))
    assert frames == [[b"PING"]]
    assert err == native_codec.PARSE_OK


def test_reader_handles_oversized_frame(parser):
    big = [b"HSET", b"h"] + [b"f%d" % i for i in range(parser.MAX_ARGS)]
    payload = _wire([b"PING"]) + _wire(big) + _wire([b"PING"])
    got = _drive_reader(payload, 65536, native=True)
    assert got == [[b"PING"], big, [b"PING"]]


def test_encode_array_int_fast_path(parser):
    from redisson_tpu.serve.resp import _encode_array

    vals = list(range(50)) + [-3, 10**12]
    expect = b"*%d\r\n" % len(vals) + b"".join(b":%d\r\n" % v for v in vals)
    assert _encode_array(vals) == expect
    # Mixed arrays keep the general path.
    assert _encode_array([1, b"x"]) == b"*2\r\n:1\r\n$1\r\nx\r\n"


def test_encode_ints(parser):
    vals = [0, 1, -1, 42, -42, 10**17, -(10**17)]
    assert parser.encode_ints(vals) == b"".join(
        b":%d\r\n" % v for v in vals
    )


def _reader_pair():
    a, b = socket.socketpair()
    return _Reader(a), a, b


def _drive_reader(payload, chunks, native: bool):
    """Feed ``payload`` to a _Reader in ``chunks``-byte slices; collect
    every command it frames."""
    if native:
        os.environ.pop("RTPU_NO_NATIVE_RESP", None)
    else:
        os.environ["RTPU_NO_NATIVE_RESP"] = "1"
    try:
        reader, a, b = _reader_pair()
        assert (reader._native is not None) == native
    finally:
        os.environ.pop("RTPU_NO_NATIVE_RESP", None)

    def feed():
        for i in range(0, len(payload), chunks):
            b.sendall(payload[i : i + chunks])
        b.shutdown(socket.SHUT_WR)

    t = threading.Thread(target=feed)
    t.start()
    got = []
    while True:
        cmd = reader.read_command()
        if cmd is None:
            break
        got.append(cmd)
    t.join()
    a.close()
    b.close()
    return got


@pytest.mark.parametrize("chunks", [1, 3, 64, 65536])
def test_reader_parity_native_vs_python(parser, chunks):
    rng = random.Random(42)
    cmds = []
    for _ in range(40):
        n = rng.randint(1, 6)
        cmds.append(
            [bytes(rng.randrange(256) for _ in range(rng.randint(0, 40))) for _ in range(n)]
        )
    cmds.append([b"INLINE", b"CMD"])  # sent inline (no * framing)
    payload = b"".join(_wire(c) for c in cmds[:-1]) + b"INLINE CMD\r\n"
    native = _drive_reader(payload, chunks, native=True)
    pure = _drive_reader(payload, chunks, native=False)
    assert native == pure == cmds


def test_reader_fallback_on_malformed(parser):
    # A malformed frame must produce the same outcome on both paths:
    # a typed ProtocolError (the serve loop replies '-ERR Protocol
    # error' and closes — never an unhandled thread crash).
    from redisson_tpu.serve.resp import ProtocolError

    payload = _wire([b"PING"]) + b"*1\r\n$x\r\n"
    for native in (True, False):
        if native:
            os.environ.pop("RTPU_NO_NATIVE_RESP", None)
        else:
            os.environ["RTPU_NO_NATIVE_RESP"] = "1"
        try:
            reader, a, b = _reader_pair()
        finally:
            os.environ.pop("RTPU_NO_NATIVE_RESP", None)
        b.sendall(payload)
        b.shutdown(socket.SHUT_WR)
        assert reader.read_command() == [b"PING"]
        with pytest.raises(ProtocolError):
            reader.read_command()
        a.close()
        b.close()


def test_encode_bulks_native(parser):
    from redisson_tpu.serve.resp import _encode_array, _encode_bulk

    vals = [b"abc", None, b"", b"x" * 4096, None, b"\r\n$5\r\n", b"1",
            b"tail"]
    want = b"".join(
        b"$-1\r\n" if v is None else b"$%d\r\n%s\r\n" % (len(v), v)
        for v in vals
    )
    assert parser.encode_bulks(vals) == want
    # The array encoder rides it for >=8 all-bytes/None items...
    assert _encode_array(vals) == b"*8\r\n" + want
    # ...and still matches the per-item Python path exactly.
    py = b"*8\r\n" + b"".join(_encode_bulk(v) for v in vals)
    assert _encode_array(vals) == py


def test_require_native_guard():
    # The CI job that exercises the native parser sets
    # RTPU_REQUIRE_NATIVE_RESP=1: the suite must FAIL (not silently
    # fall back to the Python parser) when the codec did not build.
    if os.environ.get("RTPU_REQUIRE_NATIVE_RESP"):
        assert get_parser() is not None
        assert native_codec.get_ticker() is not None, (
            "require mode: the tick entry point must be active too"
        )


# -- rtpu_resp_tick (ISSUE 17): the fused drain loop --------------------------


def test_ticker_drains_frames_and_classifies(parser):
    ticker = native_codec.get_ticker()
    assert ticker is not None, "fresh .so must carry rtpu_resp_tick"
    a, b = socket.socketpair()
    try:
        b.sendall(
            _wire([b"GET", b"k"]) + _wire([b"BF.ADD", b"f", b"x"])
            + _wire([b"PING"])
        )
        a.setblocking(False)
        tbuf = ticker.new_buf()
        out = []
        nread, eof, err = ticker.tick(a.fileno(), tbuf, out)
        assert err == native_codec.PARSE_OK
        assert not eof
        assert [(f, cmd) for f, cmd in out] == [
            (3, [b"GET", b"k"]),
            (1, [b"BF.ADD", b"f", b"x"]),
            (0, [b"PING"]),
        ]
        assert tbuf.have == 0
    finally:
        a.close()
        b.close()


def test_ticker_partial_frame_stays_buffered(parser):
    ticker = native_codec.get_ticker()
    assert ticker is not None
    a, b = socket.socketpair()
    try:
        whole = _wire([b"SET", b"k", b"v"])
        b.sendall(whole[: len(whole) - 3])
        a.setblocking(False)
        tbuf = ticker.new_buf()
        out = []
        ticker.tick(a.fileno(), tbuf, out)
        assert out == [] and tbuf.have == len(whole) - 3
        b.sendall(whole[len(whole) - 3:])
        ticker.tick(a.fileno(), tbuf, out)
        assert out == [(0, [b"SET", b"k", b"v"])]
        assert tbuf.have == 0
    finally:
        a.close()
        b.close()


def test_no_native_tick_env_disables_only_the_ticker(parser, monkeypatch):
    # The A/B lever: RTPU_NO_NATIVE_TICK turns off the fused drain loop
    # while the per-frame parser stays native.
    monkeypatch.setenv("RTPU_NO_NATIVE_TICK", "1")
    assert native_codec.get_ticker() is None
    assert get_parser() is not None


class _HidingLib:
    """A .so proxy that pretends chosen symbols were never exported —
    the stale-library simulation (an old _resp_codec.so with no
    compiler available to rebuild it)."""

    def __init__(self, real, hidden):
        self._real = real
        self._hidden = frozenset(hidden)

    def __getattr__(self, name):
        if name in self._hidden:
            raise AttributeError(name)
        return getattr(self._real, name)


def test_stale_so_missing_tick_symbol_fails_hard(parser, monkeypatch):
    """Satellite: RTPU_REQUIRE_NATIVE_RESP must fail hard — not
    silently drop to the Python drain loop — when the loaded .so
    predates rtpu_resp_tick."""
    stale = type(parser)(parser._lib)  # fresh instance over the same lib
    stale._lib = _HidingLib(parser._lib, ("rtpu_resp_tick",))
    monkeypatch.setattr(native_codec, "get_parser", lambda: stale)
    monkeypatch.delenv("RTPU_NO_NATIVE_TICK", raising=False)
    monkeypatch.delenv("RTPU_NO_NATIVE_RESP", raising=False)
    # Without require mode: quiet degrade to the Python tick loop.
    monkeypatch.delenv("RTPU_REQUIRE_NATIVE_RESP", raising=False)
    assert native_codec.get_ticker() is None
    # With it: a hard error naming the stale symbol.
    monkeypatch.setenv("RTPU_REQUIRE_NATIVE_RESP", "1")
    with pytest.raises(RuntimeError, match="rtpu_resp_tick"):
        native_codec.get_ticker()


def test_stale_so_missing_encode_bulks_fails_hard(parser, monkeypatch):
    """Same contract for rtpu_resp_encode_bulks: parser construction
    refuses a stale .so under require mode, degrades one call without."""
    hidden = _HidingLib(parser._lib, ("rtpu_resp_encode_bulks",))
    monkeypatch.delenv("RTPU_NO_NATIVE_RESP", raising=False)
    monkeypatch.delenv("RTPU_REQUIRE_NATIVE_RESP", raising=False)
    p = type(parser)(hidden)
    assert p._enc_bulks is None  # quiet degrade of that one call
    monkeypatch.setenv("RTPU_REQUIRE_NATIVE_RESP", "1")
    with pytest.raises(RuntimeError, match="rtpu_resp_encode_bulks"):
        type(parser)(hidden)
