"""Sketch near cache (ISSUE 4): the epoch-guarded host read tier.

Covers the shared sharded-LRU store (bounds, tenant fairness, eviction),
the epoch discipline (monotone positives vs write-tagged results, the
capture-before-submit install guard), the engine read/write integration
(partial-hit splitting, invalidation on every mutating path, delete /
rename / restore identity changes), the RESP surface (INFO section +
live CONFIG SET), the LocalCachedMap refactor onto the shared store, and
the randomized differential soak against the host golden engine —
interleaved adds/clears/resizes/degradations with every read compared,
the acceptance criterion's zero-stale-reads evidence.
"""

import time

import numpy as np
import pytest

from redisson_tpu import chaos
from redisson_tpu.cache import MISS, ShardedLRUStore, SketchNearCache
from redisson_tpu.chaos import ChaosSchedule
from redisson_tpu.config import Config


@pytest.fixture(autouse=True)
def _chaos_off():
    chaos.clear()
    chaos.reset_counts()
    yield
    chaos.clear()
    chaos.reset_counts()


def make_client(**tpu_kw):
    from redisson_tpu.client import RedissonTpuClient

    tpu_kw.setdefault("batch_window_us", 100)
    cfg = Config().use_tpu_sketch(**tpu_kw)
    cfg.retry_attempts = 2
    cfg.retry_interval_ms = 5
    return RedissonTpuClient(cfg)


def _await(cond, timeout=8.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.02)
    return True


def _flap(fn, attempts=8):
    """Ride out breaker flaps (see test_chaos): a degraded-window op may
    fail typed while the breaker re-opens; retrying resumes from the
    mirror."""
    for _ in range(attempts - 1):
        try:
            return fn()
        except Exception:
            time.sleep(0.05)
    return fn()


# -- shared sharded-LRU store ------------------------------------------------


class TestShardedLRUStore:
    def test_put_get_miss_and_lru_promotion(self):
        s = ShardedLRUStore(max_bytes=1 << 20, nshards=1)
        s.set_tenant_limits("t", max_entries=2)
        assert s.get("t", "a") is MISS
        s.put("t", "a", 1, 100)
        s.put("t", "b", 2, 100)
        assert s.get("t", "a") == 1  # promotes a to MRU
        s.put("t", "c", 3, 100)      # entry bound 2: evicts LRU = b
        assert s.get("t", "b") is MISS
        assert s.get("t", "a") == 1
        assert s.get("t", "c") == 3

    def test_tenant_byte_quota_is_fair(self):
        # One hot tenant fills its OWN quota and recycles its OWN tail —
        # the cold tenant's entries survive untouched.
        s = ShardedLRUStore(max_bytes=10_000, nshards=2,
                            tenant_quota_bytes=1_000)
        for i in range(5):
            s.put("cold", f"c{i}", i, 100)
        for i in range(50):
            s.put("hot", f"h{i}", i, 100)
        assert s.tenant_bytes("hot") <= 1_000
        assert s.tenant_entry_count("cold") == 5
        assert all(s.get("cold", f"c{i}") == i for i in range(5))

    def test_global_budget_bounds_total(self):
        s = ShardedLRUStore(max_bytes=1_000, nshards=2,
                            tenant_quota_bytes=1_000)
        for i in range(40):
            s.put(f"t{i % 4}", f"k{i}", i, 100)
        assert s.bytes() <= 1_000

    def test_oversized_entry_refused(self):
        s = ShardedLRUStore(max_bytes=500, nshards=1)
        assert s.put("t", "big", 1, 600) is False
        assert s.entries() == 0

    def test_oversized_replace_discards_stale_entry(self):
        # A refused replace must still drop the OLD cached value — the
        # caller installed a new one and the old is stale now.
        s = ShardedLRUStore(max_bytes=500, nshards=1,
                            tenant_quota_bytes=500)
        s.put("t", "k", "old", 100)
        assert s.put("t", "k", "new-but-huge", 600) is False
        assert s.get("t", "k") is MISS

    def test_discard_and_invalidate_tenant(self):
        s = ShardedLRUStore(max_bytes=1 << 20, nshards=4)
        for i in range(10):
            s.put("a", i, i, 50)
            s.put("b", i, i, 50)
        s.discard("a", 3)
        assert s.get("a", 3) is MISS
        assert s.invalidate_tenant("a") == 9
        assert s.tenant_entry_count("a") == 0
        assert s.tenant_bytes("a") == 0
        assert s.tenant_entry_count("b") == 10

    def test_on_evict_hook_and_stats(self):
        evicted = []
        s = ShardedLRUStore(max_bytes=300, nshards=1,
                            tenant_quota_bytes=300,
                            on_evict=lambda t, nb: evicted.append((t, nb)))
        for i in range(5):
            s.put("t", i, i, 100)
        assert s.evictions >= 2
        assert len(evicted) == s.evictions
        st = s.stats()
        assert st["bytes"] <= 300 and st["entries"] <= 3

    def test_eviction_rotates_and_keeps_recent_keys(self):
        # Quota-pressure eviction must spread across shards and respect
        # recency: with a fixed start shard, survivors piled into one
        # shard and freshly installed keys in the others died instantly.
        s = ShardedLRUStore(max_bytes=1 << 20, nshards=8,
                            tenant_quota_bytes=10_000)
        for i in range(1000):
            s.put("hot", f"k{i}", i, 100)
        survivors_per_shard = [len(sh.entries) for sh in s._shards]
        assert sum(1 for n in survivors_per_shard if n > 0) >= 4, (
            survivors_per_shard
        )
        recent_alive = sum(
            1 for i in range(990, 1000) if s.get("hot", f"k{i}") is not MISS
        )
        assert recent_alive >= 8, recent_alive

    def test_resize_live(self):
        s = ShardedLRUStore(max_bytes=1 << 20, nshards=1)
        s.put("t", "k", 1, 100)
        s.resize(max_bytes=400)  # trims lazily on the next put
        s.put("t", "k2", 2, 40)  # under the re-derived 400/8 quota
        assert s.bytes() <= 400
        s.resize(max_bytes=120)
        s.put("t", "k3", 3, 10)
        assert s.bytes() <= 120


# -- epoch discipline --------------------------------------------------------


def _nc(**kw):
    return SketchNearCache(
        ShardedLRUStore(max_bytes=1 << 20, nshards=2), **kw
    )


class TestEpochDiscipline:
    def test_tagged_entry_dies_on_write(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.install("o", "k", 7, captured=cap, monotone=False)
        assert nc.probe("o", "k") == 7
        nc.note_write("o")
        assert nc.probe("o", "k") is MISS  # and discarded

    def test_monotone_positive_survives_writes_dies_structural(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.install("o", "k", True, captured=cap, monotone=True)
        nc.note_write("o")
        assert nc.probe("o", "k") is True  # adds never retire a positive
        nc.note_structural("o")
        assert nc.probe("o", "k") is MISS

    def test_monotone_negative_is_write_tagged(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.install("o", "k", False, captured=cap, monotone=True)
        nc.note_write("o")
        assert nc.probe("o", "k") is MISS  # an in-flight add invalidates

    def test_install_blocked_when_capture_stale(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.note_write("o")  # a write landed after the reader captured
        nc.install("o", "k", 5, captured=cap, monotone=False)
        assert nc.probe("o", "k") is MISS

    def test_monotone_positive_installs_across_write_not_structural(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.note_write("o")  # ordinary write: a positive still installs
        nc.install("o", "k", True, captured=cap, monotone=True)
        assert nc.probe("o", "k") is True
        nc.note_structural("o")
        cap2 = cap  # captured before the structural change: blocked
        nc.install("o", "k2", True, captured=cap2, monotone=True)
        assert nc.probe("o", "k2") is MISS

    def test_drop_object_advances_epochs_forever(self):
        nc = _nc()
        cap = nc.epochs("o")
        nc.install("o", "k", 1, captured=cap, monotone=False)
        nc.drop_object("o")
        assert nc.probe("o", "k") is MISS
        # A successor object under the same name continues the sequence:
        # the old capture can never install as fresh.
        nc.install("o", "k", 1, captured=cap, monotone=False)
        assert nc.probe("o", "k") is MISS

    def test_invalidate_all_retires_never_mutated_names(self):
        # A name with NO per-name epoch entry (never written in this
        # process — e.g. restored from a snapshot) must also stop
        # matching captures taken before invalidate_all: the floor moves.
        nc = _nc()
        cap = nc.epochs("restored-only")  # floor pair
        nc.invalidate_all()
        nc.install("restored-only", "k", 7, captured=cap, monotone=False)
        assert nc.probe("restored-only", "k") is MISS
        nc.install("restored-only", "p", True, captured=cap, monotone=True)
        assert nc.probe("restored-only", "p") is MISS

    def test_resize_recomputes_defaulted_tenant_quota(self):
        s = ShardedLRUStore(max_bytes=64 << 20)  # quota defaults to /8
        assert s.tenant_quota_bytes == 8 << 20
        s.resize(max_bytes=1 << 30)
        assert s.tenant_quota_bytes == (1 << 30) // 8
        s.resize(tenant_quota_bytes=123456)  # explicit: sticks
        s.resize(max_bytes=64 << 20)
        assert s.tenant_quota_bytes == 123456
        s.resize(tenant_quota_bytes=0)  # back to defaulted
        assert s.tenant_quota_bytes == 8 << 20

    def test_invalidate_all_and_set_enabled(self):
        nc = _nc()
        nc.install("o", "k", 1, captured=nc.epochs("o"), monotone=False)
        nc.invalidate_all()
        assert nc.probe("o", "k") is MISS
        nc.install("o", "k", 2, captured=nc.epochs("o"), monotone=False)
        nc.set_enabled(False)
        assert nc.store.entries() == 0
        nc.set_enabled(True)
        assert nc.probe("o", "k") is MISS

    def test_active_respects_max_batch(self):
        nc = _nc(max_batch=8)
        assert nc.active(8) and not nc.active(9) and not nc.active(0)

    def test_disabled_cache_refuses_installs(self):
        # A future created before CONFIG SET nearcache no resolves after
        # it: the install must bail, or the "disabled" store holds bytes
        # nothing will ever evict.
        nc = _nc()
        captured = nc.epochs("o")
        nc.set_enabled(False)
        nc.install("o", "k", 1, captured=captured, monotone=False)
        nc.install("o", "p", True, captured=captured, monotone=True)
        assert nc.store.entries() == 0 and nc.store.bytes() == 0
        nc.set_enabled(True)
        assert nc.probe("o", "k") is MISS

    def test_epoch_dict_bounded_under_name_churn(self):
        # TTL'd per-session sketches mint names forever; the per-name
        # epoch dict must fold dead names into the floor, not leak one
        # entry per name for the process lifetime.
        nc = _nc()
        nc._epoch_cap = nc._epoch_prune_at = 32
        nc.note_write("live")  # mutated + live entries → survives prunes
        nc.install("live", "k", 7, captured=nc.epochs("live"),
                   monotone=False)
        live_epochs = nc.epochs("live")
        for i in range(1000):
            name = f"ephemeral-{i}"
            nc.note_write(name)
            nc.drop_object(name)
        assert len(nc._epochs) <= 2 * 32 + 2
        # Pruned names resume FROM the raised floor: strictly past any
        # epoch they ever held, so an in-flight pre-prune read can
        # neither serve nor install.
        assert nc.epochs("ephemeral-0") == nc._floor
        assert nc._floor > (1, 1)
        # The name with live cached entries kept its own sequence and
        # its entry still serves.
        assert nc.epochs("live") == live_epochs
        assert nc.probe("live", "k") == 7


# -- engine integration ------------------------------------------------------


class TestEngineIntegration:
    def setup_method(self):
        self.c = make_client()
        self.nc = self.c._engine.nearcache

    def teardown_method(self):
        self.c._engine.shutdown()

    def test_bloom_negative_invalidated_by_add(self):
        bf = self.c.get_bloom_filter("nc-bf")
        bf.try_init(10_000, 0.01)
        assert bf.contains("ghost") is False  # cached negative
        bf.add("ghost")  # submit-time bump: the negative must die NOW
        assert bf.contains("ghost") is True

    def test_bloom_positive_survives_other_adds_and_hits(self):
        bf = self.c.get_bloom_filter("nc-bf2")
        bf.try_init(10_000, 0.01)
        bf.add("hot")
        assert bf.contains("hot") is True  # installs monotone positive
        h0 = self.nc.hits
        bf.add("other-key")  # ordinary write: positive survives
        assert bf.contains("hot") is True
        assert self.nc.hits > h0

    def test_bloom_partial_hit_split(self):
        bf = self.c.get_bloom_filter("nc-bf3")
        bf.try_init(10_000, 0.01)
        keys = [f"k{i}" for i in range(10)]
        bf.add_all(keys[:5])
        got_warm = bf.contains_each(keys[:5])  # caches 5 positives
        assert all(got_warm)
        self.nc.hits = self.nc.misses = 0
        got = bf.contains_each(keys)
        assert self.nc.hits == 5 and self.nc.misses == 5
        # The assembled result must equal an uncached read bit-for-bit.
        self.nc.store.clear()
        want = bf.contains_each(keys)
        assert np.array_equal(np.asarray(got, bool), np.asarray(want, bool))

    def test_bitset_get_cached_and_clear_is_structural(self):
        bs = self.c.get_bit_set("nc-bs")
        bs.set(5)
        assert bs.get(5) is True
        h0 = self.nc.hits
        assert bs.get(5) is True  # hit
        assert self.nc.hits > h0
        bs.set(5, False)  # structural: retires the monotone positive
        assert bs.get(5) is False
        bs.flip(5)
        assert bs.get(5) is True

    def test_bitset_scalars_invalidate_on_write(self):
        bs = self.c.get_bit_set("nc-bs2")
        bs.set_many(np.array([1, 3, 5]))
        assert bs.cardinality() == 3
        assert bs.cardinality() == 3  # cached
        bs.set(7)
        assert bs.cardinality() == 4
        assert bs.length() == 8
        assert bs.first_set_bit() == 1

    def test_cms_estimate_invalidated_by_add(self):
        cms = self.c.get_count_min_sketch("nc-cms")
        cms.try_init(4, 256)
        cms.add("k", 3)
        assert cms.estimate("k") == 3
        assert cms.estimate("k") == 3  # cached
        cms.add("k", 2)
        assert cms.estimate("k") == 5

    def test_hll_count_invalidated_by_add(self):
        h = self.c.get_hyper_log_log("nc-hll")
        h.add_all([f"v{i}" for i in range(100)])
        n = h.count()
        assert h.count() == n  # cached
        h.add_all([f"w{i}" for i in range(100)])
        assert h.count() > n

    def test_delete_drops_cached_entries(self):
        bf = self.c.get_bloom_filter("nc-del")
        bf.try_init(10_000, 0.01)
        bf.add("x")
        assert bf.contains("x") is True  # cached positive
        bf.delete()
        bf.try_init(10_000, 0.01)
        assert bf.contains("x") is False  # successor: no stale positive

    def test_rename_drops_both_names(self):
        bf = self.c.get_bloom_filter("nc-rn")
        bf.try_init(10_000, 0.01)
        bf.add("x")
        assert bf.contains("x") is True
        bf.rename("nc-rn2")
        bf2 = self.c.get_bloom_filter("nc-rn2")
        assert bf2.contains("x") is True  # re-read from device, not cache

    def test_bitset_grow_is_structural(self):
        bs = self.c.get_bit_set("nc-grow")
        bs.set(1)
        assert bs.get(1) is True  # cached
        s_before = self.nc.epochs("nc-grow")[1]
        bs.set(300_000)  # size-class migration
        assert self.nc.epochs("nc-grow")[1] > s_before
        assert bs.get(1) is True and bs.get(300_000) is True

    def test_big_batches_bypass(self):
        bf = self.c.get_bloom_filter("nc-bulk")
        bf.try_init(100_000, 0.01)
        keys = np.arange(2048, dtype=np.uint64)  # > nearcache_max_batch
        bf.add_all(keys)
        bf.contains_each(keys)
        assert self.nc.store.entries() == 0

    def test_disabled_never_populates(self):
        c2 = make_client(nearcache=False)
        try:
            bf = c2.get_bloom_filter("nc-off")
            bf.try_init(10_000, 0.01)
            bf.add("x")
            assert bf.contains("x") is True
            nc = c2._engine.nearcache
            assert nc.store.entries() == 0 and nc.hits == 0
        finally:
            c2._engine.shutdown()

    def test_metrics_counters_and_gauges(self):
        bf = self.c.get_bloom_filter("nc-met")
        bf.try_init(10_000, 0.01)
        bf.add("x")
        bf.contains("x")
        bf.contains("x")
        text = self.c.render_prometheus()
        assert "rtpu_nearcache_hits" in text
        assert "rtpu_nearcache_bytes" in text
        st = self.nc.stats()
        assert st["hits"] >= 1 and st["entries"] >= 1


# -- RESP surface ------------------------------------------------------------


class TestRespSurface:
    def test_info_section_and_live_config_set(self):
        from redisson_tpu.serve.resp import RespServer

        c = make_client()
        server = RespServer(c, host="127.0.0.1", port=0)
        try:
            bf = c.get_bloom_filter("resp-bf")
            bf.try_init(10_000, 0.01)
            bf.add("x")
            bf.contains("x")
            bf.contains("x")
            info = server._cmd_INFO([b"nearcache"]).decode()
            assert "# Nearcache" in info
            assert "nearcache_enabled:1" in info
            assert "nearcache_hits:" in info
            out = server._cmd_CONFIG([b"GET", b"nearcache*"]).decode()
            assert "nearcache-max-bytes" in out
            # Live retune: byte budget + disable (drops every entry).
            server._cmd_CONFIG([b"SET", b"nearcache-max-bytes", b"1048576"])
            nc = c._engine.nearcache
            assert nc.store.max_bytes == 1 << 20
            server._cmd_CONFIG([b"SET", b"nearcache", b"no"])
            assert nc.enabled is False and nc.store.entries() == 0
            info = server._cmd_INFO([b"nearcache"]).decode()
            assert "nearcache_enabled:0" in info
            server._cmd_CONFIG([b"SET", b"nearcache", b"yes"])
            assert nc.enabled is True
            # Unknown-value rejection.
            from redisson_tpu.serve.resp import RespError

            with pytest.raises(RespError):
                server._cmd_CONFIG([b"SET", b"nearcache", b"maybe"])
        finally:
            server.close()
            c._engine.shutdown()

    def test_host_engine_has_no_nearcache_keys(self):
        import redisson_tpu
        from redisson_tpu.serve.resp import RespError, RespServer

        c = redisson_tpu.create(Config())
        server = RespServer(c, host="127.0.0.1", port=0)
        try:
            with pytest.raises(RespError):
                server._cmd_CONFIG([b"SET", b"nearcache", b"yes"])
            info = server._cmd_INFO([b"nearcache"]).decode()
            assert "# Nearcache" not in info  # honesty: no tier to report
        finally:
            server.close()
            c.shutdown()


# -- LocalCachedMap on the shared store --------------------------------------


class TestLocalCachedMapSharedStore:
    def test_byte_quota_and_stats(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-store", cache_size=128,
                                       cache_max_bytes=4096)
            for i in range(64):
                m.put(f"k{i}", "v" * 100)
            st = m.cache_stats()
            assert st["bytes"] <= 4096
            assert st["evictions"] > 0
            assert m.cached_size() == st["entries"]
            # Reads served from the near cache count as store hits.
            m.get("k63")
            assert m.cache_stats()["hits"] >= 1
        finally:
            c.shutdown()

    def test_oversized_overwrite_never_serves_stale(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-big", cache_max_bytes=1024)
            m.put("k", "small")
            big = "v" * 4096  # over the byte budget: uncacheable
            m.put("k", big)
            assert m.get("k") == big  # backing map, never the stale entry
        finally:
            c.shutdown()

    def test_cache_size_zero_disables_caching(self):
        # Seed semantics: cache_size=0 means NO near cache (the old
        # OrderedDict evicted down to the bound after every put).  The
        # store's max_entries=0 means "unbounded" — the handle must not
        # pass the caller's opt-out through as that inversion.
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-off", cache_size=0)
            for i in range(32):
                m.put(f"k{i}", f"v{i}")
                m.get(f"k{i}")
            assert m.cached_size() == 0
            assert m.cache_stats()["bytes"] == 0
            assert m.get("k7") == "v7"  # served by the backing map
        finally:
            c.shutdown()

    def test_single_tenant_owns_whole_byte_budget(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-budget", cache_size=10_000,
                                       cache_max_bytes=64 << 10)
            for i in range(200):
                m.put(f"k{i}", "v" * 100)
            # ~200 entries * ~220B ≈ 44KB fits the 64KB budget whole —
            # the old default-quota bug capped the tenant at budget/8.
            assert m.cache_stats()["evictions"] == 0
            assert m.cached_size() == 200
        finally:
            c.shutdown()

    def test_entry_bound_still_enforced(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-bound", cache_size=4)
            for i in range(10):
                m.put(f"k{i}", i)
            assert m.cached_size() <= 4
            # Backing map still holds everything.
            assert all(m.get(f"k{i}") == i for i in range(10))
        finally:
            c.shutdown()


# -- differential soak -------------------------------------------------------


BLOOM_POINTS = (
    "dispatch.bloom_mixed", "dispatch.bloom_mixed_keys",
    "dispatch.bloom_mixed_keys_runs",
)
BITSET_POINTS = ("dispatch.bitset_mixed", "dispatch.bitset_mixed_runs")


class TestDifferentialSoak:
    """Randomized cached-vs-golden interleaving (acceptance criterion):
    adds, clears, resizes and a full degradation/reconcile cycle, every
    read equality-checked against the host golden engine — one stale
    cached read anywhere fails the run."""

    def _mk_pair(self):
        import redisson_tpu

        gold = redisson_tpu.create(Config())
        c = make_client(breaker_failure_threshold=2, breaker_open_ms=600)
        return c, gold

    def _check_reads(self, rng, pairs, keyspace):
        (tb, gb), (tbs, gbs), (tcm, gcm), (th, gh) = pairs
        ks = rng.integers(0, keyspace, int(rng.integers(1, 24))).astype(
            np.uint64
        )
        got = _flap(lambda: tb.contains_each(ks))
        want = gb.contains_each(ks)
        assert np.array_equal(np.asarray(got, bool), np.asarray(want, bool))
        idx = rng.integers(0, 4096, int(rng.integers(1, 16)))
        got = _flap(lambda: tbs.get_many(idx))
        want = gbs.get_many(idx)
        assert np.array_equal(np.asarray(got, bool), np.asarray(want, bool))
        est_t = _flap(lambda: tcm.estimate_all(ks))
        est_g = gcm.estimate_all(ks)
        assert np.array_equal(
            np.asarray(est_t, np.int64), np.asarray(est_g, np.int64)
        )
        assert _flap(lambda: th.count()) == gh.count()
        assert _flap(lambda: tbs.cardinality()) == gbs.cardinality()

    def _mixed_writes(self, rng, pairs, keyspace):
        (tb, gb), (tbs, gbs), (tcm, gcm), (th, gh) = pairs
        op = int(rng.integers(0, 6))
        if op == 0:
            ks = rng.integers(0, keyspace, 8).astype(np.uint64)
            _flap(lambda: tb.add_all(ks))
            gb.add_all(ks)
        elif op == 1:
            idx = rng.integers(0, 4096, 8)
            val = bool(rng.integers(0, 2))
            _flap(lambda: tbs.set_many(idx, val))
            gbs.set_many(idx, val)
        elif op == 2:
            idx = int(rng.integers(0, 4096))
            _flap(lambda: tbs.flip(idx))
            gbs.flip(idx)
        elif op == 3:
            ks = rng.integers(0, keyspace, 8).astype(np.uint64)
            w = rng.integers(1, 5, 8)
            _flap(lambda: tcm.add_all(ks, w))
            gcm.add_all(ks, w)
        elif op == 4:
            ks = rng.integers(0, keyspace, 16).astype(np.uint64)
            _flap(lambda: th.add_all(ks))
            gh.add_all(ks)
        else:
            lo = int(rng.integers(0, 2048))
            hi = lo + int(rng.integers(1, 64))
            val = bool(rng.integers(0, 2))
            _flap(lambda: tbs.set_range(lo, hi)) if val else _flap(
                lambda: tbs.clear_range(lo, hi)
            )
            gbs.set_range(lo, hi) if val else gbs.clear_range(lo, hi)

    def test_zero_stale_reads_across_chaos(self):
        c, gold = self._mk_pair()
        eng = c._engine
        KEYSPACE = 2000
        try:
            pairs = []
            tb, gb = (x.get_bloom_filter("soak-bf") for x in (c, gold))
            for h in (tb, gb):
                h.try_init(20_000, 0.01)
            pairs.append((tb, gb))
            pairs.append(tuple(x.get_bit_set("soak-bs") for x in (c, gold)))
            tcm, gcm = (x.get_count_min_sketch("soak-cms") for x in (c, gold))
            for h in (tcm, gcm):
                h.try_init(4, 512)
            pairs.append((tcm, gcm))
            pairs.append(
                tuple(x.get_hyper_log_log("soak-hll") for x in (c, gold))
            )
            rng = np.random.default_rng(7)

            # Phase 1: healthy interleaving, incl. clears + a resize.
            for i in range(60):
                self._mixed_writes(rng, pairs, KEYSPACE)
                if i % 3 == 0:
                    self._check_reads(rng, pairs, KEYSPACE)
                if i == 30:  # size-class migration mid-soak (structural)
                    _flap(lambda: pairs[1][0].set(300_000))
                    pairs[1][1].set(300_000)
                if i == 40:
                    _flap(lambda: tcm.add("reset-probe", 3))
                    gcm.add("reset-probe", 3)
                    c._engine.cms_reset("soak-cms")
                    gcm._engine.cms_reset("soak-cms")
                    assert _flap(lambda: tcm.estimate("reset-probe")) == 0

            # Phase 2: breaker-open degradation — bloom + bitset serve
            # from host mirrors; mirror writes MUST keep bumping epochs.
            chaos.install(ChaosSchedule(
                seed=5, rate=1.0, points=BLOOM_POINTS + BITSET_POINTS
            ))
            for i in range(12):
                try:
                    tb.add(np.uint64(900_000 + i))
                    gb.add(np.uint64(900_000 + i))
                except Exception:
                    pass
                try:
                    pairs[1][0].set(int(4096 + i))
                    pairs[1][1].set(int(4096 + i))
                except Exception:
                    pass
                if eng.health.any_degraded:
                    break
            assert _await(lambda: eng.health.any_degraded)
            # Golden re-sync for the sacrificial ops whose TPU-side throw
            # prevented the paired golden apply: replay them on BOTH
            # sides (idempotent monotone ops — safe to double-apply).
            for i in range(12):
                _flap(lambda i=i: tb.add(np.uint64(900_000 + i)))
                gb.add(np.uint64(900_000 + i))
                _flap(lambda i=i: pairs[1][0].set(int(4096 + i)))
                pairs[1][1].set(int(4096 + i))
            for i in range(24):
                self._mixed_writes(rng, pairs, KEYSPACE)
                if i % 3 == 0:
                    self._check_reads(rng, pairs, KEYSPACE)

            # Phase 3: heal, reconcile, full comparison sweep.
            chaos.clear()
            assert _await(lambda: not eng.health.any_degraded)
            for i in range(24):
                self._mixed_writes(rng, pairs, KEYSPACE)
                if i % 3 == 0:
                    self._check_reads(rng, pairs, KEYSPACE)
            probe = np.arange(0, KEYSPACE, 7, dtype=np.uint64)
            for lo in range(0, len(probe), 512):
                ks = probe[lo : lo + 512]
                assert np.array_equal(
                    np.asarray(tb.contains_each(ks), bool),
                    np.asarray(gb.contains_each(ks), bool),
                )
            idx = np.arange(4096)
            for lo in range(0, 4096, 1024):
                assert np.array_equal(
                    np.asarray(pairs[1][0].get_many(idx[lo : lo + 1024]), bool),
                    np.asarray(pairs[1][1].get_many(idx[lo : lo + 1024]), bool),
                )
            assert pairs[3][0].count() == pairs[3][1].count()
        finally:
            chaos.clear()
            eng.shutdown()
            gold.shutdown()


class TestLocalCachedMapCrossHandleSharing:
    """ISSUE 6 satellite (ROADMAP near-cache-reach): map gets route
    through ONE per-client store, so two handles to one map share hits."""

    def test_two_handles_share_hits(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            a = c.get_local_cached_map("lcm-share")
            b = c.get_local_cached_map("lcm-share")
            assert a._cache is b._cache  # one store per client
            a.put("k", "v")
            # A's invalidation message asynchronously discards through
            # B's listener (the converging-writes rule): drain the bus,
            # then settle one read-through install via A.
            c._topic_bus.drain(timeout=10)
            assert a.get("k") == "v"
            h0 = b.cache_stats()["hits"]
            assert b.get("k") == "v"
            assert b.cache_stats()["hits"] >= h0 + 1
        finally:
            c.shutdown()

    def test_write_through_one_handle_invalidates_shared_entry(self):
        import time

        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            a = c.get_local_cached_map("lcm-coh")
            b = c.get_local_cached_map("lcm-coh")
            a.put("k", "v1")
            assert b.get("k") == "v1"
            b.put("k", "v2")  # writer maintains the shared store itself
            assert a.get("k") == "v2"
            a.remove("k")
            assert b.get("k") is None
        finally:
            c.shutdown()

    def test_generation_guard_blocks_stale_install(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            m = c.get_local_cached_map("lcm-gen")
            m.put("k", "v1")
            m.clear_local_cache()
            gen = m._hub.gen("lcm-gen")  # reader samples here...
            m.put("k", "v2")             # ...a write lands in between
            ok = m._hub.install_if(
                "lcm-gen", m._enc_key("k"), "v1", 64, gen
            )
            assert not ok               # the stale install is refused
            assert m.get("k") == "v2"
        finally:
            c.shutdown()

    def test_disabled_handle_neither_serves_nor_erases_peer_bound(self):
        # Review regression: a cache_size=0 handle must stay fully
        # opted out (read-through, no shared-store hits) and must NOT
        # pass its 0 into the shared tenant limits — the store reads
        # max_entries=0 as UNBOUNDED, erasing the enabled peer's bound.
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            a = c.get_local_cached_map("lcm-dis", cache_size=4)
            b = c.get_local_cached_map("lcm-dis", cache_size=0)
            for i in range(12):
                a.put(f"k{i}", i)
            c._topic_bus.drain(timeout=10)
            for i in range(12):
                a.get(f"k{i}")
            assert a.cached_size() <= 4  # peer bound survives b
            h0 = b.cache_stats()["hits"]
            assert b.get("k11") == 11    # reads through...
            assert b.cache_stats()["hits"] == h0  # ...never a shared hit
        finally:
            c.shutdown()

    def test_distinct_maps_keep_distinct_quotas(self):
        import redisson_tpu

        c = redisson_tpu.create(Config())
        try:
            small = c.get_local_cached_map("lcm-q-small",
                                           cache_max_bytes=2048)
            big = c.get_local_cached_map("lcm-q-big")
            for i in range(64):
                small.put(f"k{i}", "v" * 100)
                big.put(f"k{i}", "v" * 100)
            st = small.cache_stats()
            assert st["tenant_bytes"] <= 2048
            assert big.cache_stats()["tenant_bytes"] > 2048
        finally:
            c.shutdown()
